"""Headline benchmark: DeepDFA (FlowGNN) training throughput on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference trains DeepDFA in ~9 min on 1× RTX 3090 (paper
Table 5); with ~150k train graphs × 25 epochs / 540 s ≈ 7000 graphs/s
aggregate (BASELINE.md "north-star"). We measure sustained training
graphs/sec (forward+backward+update, published model config, batch 256) on
the available chip(s).
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def main() -> None:
    from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig, TrainConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import make_train_state, make_train_step
    from __graft_entry__ import _example_batch

    # The Pallas block-sparse tile SpMM path is ~30% faster end-to-end than
    # XLA segment ops on v5e (see ops/tile_spmm.py); it needs a TPU backend.
    impl = "tile" if jax.default_backend() == "tpu" else "segment"
    model_cfg = FlowGNNConfig(message_impl=impl)
    data_cfg = DataConfig(batch_size=256)
    train_cfg = TrainConfig()

    batch = _example_batch(data_cfg, model_cfg)
    model = FlowGNN(model_cfg)
    state, tx = make_train_state(model, batch, train_cfg)
    # Donation is load-bearing on the tunneled axon backend: without it the
    # train state round-trips per step and throughput drops ~10x. (lax.scan
    # chaining is NOT used — while-loops run pathologically slow through the
    # tunnel.)
    step = jax.jit(make_train_step(model, tx, train_cfg), donate_argnums=(0,))

    # Warmup: compile + 3 steps (reference skips 3 warmup batches,
    # base_module.py:240-243).
    for _ in range(3):
        state, loss, _ = step(state, batch)
    jax.block_until_ready(state)

    # Best of 3 trials damps tunnel/host jitter; steps within a trial are
    # serialized by the donated-state data dependence, so wall time over the
    # trial is true device throughput.
    n_steps = 100
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, loss, _ = step(state, batch)
        jax.block_until_ready(state)
        dt = min(dt, time.perf_counter() - t0)

    graphs_per_sec = n_steps * data_cfg.batch_size / dt
    baseline = 7000.0  # reference aggregate graphs/s on 1x RTX 3090
    print(
        json.dumps(
            {
                "metric": "deepdfa_train_graphs_per_sec",
                "value": round(graphs_per_sec, 1),
                "unit": "graphs/s",
                "vs_baseline": round(graphs_per_sec / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

"""Headline benchmarks on TPU. Stdout carries up to three JSON lines —
two early safety lines (metric names suffixed _provisional/_predecode,
partial: true, printed so a supervisor timeout mid-run still leaves a
parseable record) and the FINAL complete line, which is always printed
last and supersedes them.

The final line keeps the driver contract — {"metric", "value", "unit",
"vs_baseline"} for the primary metric (DeepDFA training throughput) — and
carries the transformer-family measurements in "extra", covering the
reference's paper-Table-5 efficiency axes (BASELINE.md):

  deepdfa_train_graphs_per_sec     vs ~7000 graphs/s aggregate on RTX 3090
                                   (9-min train, paper Table 5)
  combined_train_examples_per_sec  DeepDFA+LineVul training step (codebert
                                   shape, 512 tokens, batch 16 — the
                                   msr_train_combined.sh configuration) vs
                                   ~39 examples/s on RTX 3090 (10h40m for 10
                                   epochs over ~150k examples, Table 5)
  combined_infer_ms_per_example    vs 15.4 ms/example on RTX 3090 (Table 5)
  deepdfa_infer_ms_per_example     DeepDFA-standalone forward at the parity
                                   batch (256) vs 4.6 ms/example (Table 5)
  gen_decode_tokens_per_sec[_beam10]  codet5-base summarize-shape decode,
                                   greedy + beam-10 (no reference baseline)
  serve_p99_ms / serve_graphs_per_sec  the serving layer (deepdfa_tpu/serve)
                                   replayed over the seeded bursty trace —
                                   deadline-aware micro-batching, warmed
                                   buckets (compiles_after_warmup must stay
                                   0), content cache (no reference baseline:
                                   the paper never serves)
  serve_fleet_rps / serve_fleet_p99_ms  the replicated fleet
                                   (serve/fleet.py): 1 vs N replicas over
                                   the SAME open-loop saturation trace on
                                   per-replica busy timelines — saturation
                                   throughput and admitted-tail latency
                                   (speedup must clear 2x; compiles stay 0
                                   fleet-wide)

Measurement notes, learned the hard way on the tunneled axon backend:
- ``jax.block_until_ready`` returns optimistically there; the only reliable
  completion barrier is a host read (``jax.device_get``) of an output that
  data-depends on every timed step. All timings here end with one.
- Per-step Python dispatch through the tunnel costs ~4 ms, which would
  dominate the small GNN step; the GNN loop therefore runs K steps unrolled
  inside one XLA program (K dispatches fewer, no while_loop — scan/while
  run pathologically slow through the tunnel).
- Transformer compute runs bfloat16 — the TPU-native dtype (MXU) — with f32
  master weights; the reference's GPU numbers are fp32.
- The tunneled chip shows large run-to-run variance for the small GNN step
  (observed 154k-308k graphs/s across IDENTICAL code on one afternoon);
  every A/B cited below was measured back-to-back in one process, which is
  the only comparison this backend supports.

Where the time goes (round-3/4 ablations on v5e; /tmp harnesses
re-derivable from this file):
- GNN message_impl (round 4): the block-banded batched-matmul path
  (ops/band_spmm.py) replaces the Pallas tile-grid kernel as flagship —
  the tile kernel walks its 128-entry tile list with a *sequential* grid,
  one DMA-latency-bound 128x128 matmul per step, while the banded layout
  runs the whole adjacency as 2B+1 parallel [T,128,128] bmms (B=1 at CFG
  sparsity). Isolated A/B pre-pooling-fix: 145.7k vs 114.0k graphs/s; the
  tile A/B rides the extras every run (BENCH_r04: 308.3k vs 195.3k).
- GNN pooling (round 4): TPU scatters serialize — the traced step spent
  ~0.9 ms (of 1.76) in GlobalAttentionPool's scatter/gather fusions
  (60-190 us EACH, vs ~12 us for an equivalent dense dot). Routing every
  per-graph reduction and graph->node broadcast through one dense
  assignment matrix (segment_onehot, pool_impl="matmul") and the
  graph-label scatter-max through a masked row-max cut the step to
  0.83 ms: 308.3k graphs/s bf16, 2.7x round 3's 114.4k.
- GNN embeddings (round 4): the last scatters standing were the 4 tables'
  grad accumulations (~240 us/step). Estimating them as "a wash at vocab
  1002" was wrong in the scatter's favor — the whole-step A/B of the
  onehot-matmul backward (segment.onehot_take, embed_impl="matmul")
  measured 0.83 -> 0.61 ms/step: 419.6k graphs/s bf16 (3.7x round 3,
  59.9x the 3090 baseline), f32 238.6k. Moral, twice over: never trust a
  per-op estimate on this backend; only whole-step back-to-back A/Bs.
  Remaining profile: the 5-step scan fwd+bwd ~370 us, loss/opt/metrics,
  and the pooling/label dense ops.
- remat_steps stays on (281k vs 203k off in the harness A/B); bigger
  batches stay flat (band, pre-pooling-fix: 256 -> 145.7k, 512 -> 154k,
  1024 -> 152.5k); 256 is the parity shape and the headline.
- Combined model (round-4 state): the Pallas flash kernel WINS the
  512-token parity A/B — round 3's 2x loss was (a) a backward that
  recomputed through the blockwise lax.scan and (b) 128x128 tiles whose
  b·h×4×4 grid drowned in per-program overhead. Cumulative round-4 wins
  measured by whole-step A/B: flash-by-default + q tiles 512 (one program
  per head at the parity shape), rbg dropout keys (+7%), the FUSED
  single-pass backward kernel (dq accumulated in a full-length VMEM
  scratch inside the dk/dv sweep — every score tile computed once, not
  twice), and the GNN encoder's scatter-free paths. Standing: 225.5 ex/s
  bs16 (34.9%+ MFU, 5.8x the 3090) vs blockwise 200.8; bs64 225.5
  (bs128 regresses; remat at these sizes only costs). The blockwise A/B
  rides along in "extra" so a regression shows.
- Long context: at 4096 tokens the blockwise path cannot even compile a
  training step (its lax.scan backward saves per-block logits — O(T^2)
  across steps — measured 54.8G required), while the flash kernels train
  the full 12L combined model on one 16G chip: 43.1k tok/s, 26.2% MFU
  (the fused backward is worth +13% here — its dq pass elimination scales
  with the tile count). dense at 512 is also slower than blockwise.
  Defaults: flash everywhere on TPU, blockwise as the portable fallback,
  ring (parallel/ring.py) across chips.

Round-5 findings (all back-to-back whole-step A/Bs on v5e):
- Combined: the biggest remaining lever was the FFN activation — the
  exact erf gelu runs on the VPU's transcendental path in forward AND
  backward, ~11 ms of the 70 ms step. The ladder: baseline 227.1 ex/s;
  gelu tanh-approx 269.0 (+18.5%); LN-in-bf16 231.8 (+2%, numerics risk,
  not taken); both 272.4. Dropout costs ~3.7 ms (no-dropout step 236.1) —
  left in, it is the training semantics. The GNN branch costs ~0.4 ms
  (text-only 229.2 vs 228.0 combined) — nothing to win there. tanh gelu
  (|delta| < 1e-3 vs erf) is now the EncoderConfig default; converted HF
  checkpoints keep erf (models/pretrained.py). Sequence packing was
  REJECTED by arithmetic, not measurement: at bq=bk=512 a packed
  1024-token row runs the same diagonal tile count as two 512 rows, so
  there is no program-count win at the parity shape. With the gelu
  default: 271.1 ex/s bs16 (42.1% MFU), bs64 262.2, long-context 46.2k
  tok/s (28.0% MFU).
- GNN (attack-the-scan round): band tile 256 LOSES (349-352k vs 392-404k
  graphs/s interleaved — fewer, deeper bmms pay more in the 2x
  zero-padded diagonals than they save in program count); a fused
  2-matmul GRU cell LOSES (365k vs 375k — XLA already fuses the six gate
  matmuls' elementwise tails, and the concat adds traffic); UNROLLING the
  5-step nn.scan WINS (405-410k vs 392-394k, +3-4% — cross-step fusion
  the rolled carry forbids) and is now the model default (capped at 8).
  The unroll also CORRECTED the MFU accounting: XLA's cost analysis does
  not multiply a while-loop body by its trip count, so the rolled scan
  reported 14.6 GFLOP/step where the unrolled program counts the true
  54.7 G — round 4's "12.2% MFU, scan is the headroom" was an accounting
  artifact; the step actually runs at ~45% MFU and the scan was never
  the bottleneck it appeared to be. That IS the certification this round
  owed: at 45% MFU on a step dominated by [T,128,128] band bmms and
  128-wide GRU matmuls, the remaining gap to peak is tile-shape overhead,
  not a missing rewrite.
- Decode (first measured round): see bench_gen_decode's docstring —
  split cache layout, beam-deduped cross K/V, cross K/V out of the scan
  carry; greedy 14.2k tok/s, beam-10 1.0k tok/s. Unrolling the decode
  scan LOSES (unroll=4: 13.6k vs 14.3k greedy, 2x compile) — unlike the
  GNN's 5 steps, 128 decode iterations gain nothing from cross-step
  fusion and the program bloat hurts. Reordering the beam cache with a
  one-hot bmm instead of take_along_axis also LOSES (728 vs 1151 tok/s,
  sequences identical): unlike the GNN's scatter-adds, a LEADING-axis
  gather vectorizes fine on TPU and the bmm just doubles the traffic.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np


def _timed(call, warmup: int, calls: int, trials: int = 3) -> float:
    """Best-of-trials wall seconds for ``calls`` dispatches of ``call``.

    ``call()`` dispatches one (chained) step and returns an output array;
    the clock stops at a jax.device_get of the final output — the one
    barrier the tunneled backend honors (module docstring).
    """
    for _ in range(warmup):
        out = call()
    jax.device_get(out)
    dt = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(calls):
            out = call()
        jax.device_get(out)
        dt = min(dt, time.perf_counter() - t0)
    return dt


# Peak dense bf16 matmul throughput per chip, for MFU — ONE table shared
# with the roofline report (telemetry/costmodel.py), so bench MFU and
# `cli trace report` MFU cannot disagree on the ceiling. The tunneled
# device reports kind "TPU v5 lite" (v5e): 197 TFLOP/s bf16.
from deepdfa_tpu.telemetry.costmodel import PEAK_FLOPS as _PEAK_FLOPS


_warned_unknown_kind = False


def _peak_flops() -> float:
    global _warned_unknown_kind
    kind = jax.devices()[0].device_kind
    peak = _PEAK_FLOPS.get(kind, 0.0)
    if not peak and not _warned_unknown_kind:
        # Make a null mfu attributable instead of silently mysterious
        # (once — three diagnostics stages share this lookup).
        import sys

        print(f"bench: unknown device kind {kind!r} — no peak-FLOPs entry, "
              "mfu will report null", file=sys.stderr)
        _warned_unknown_kind = True
    return peak


def bench_deepdfa(dtype: str = "bfloat16", diagnostics: bool = False,
                  impl: "str | None" = None):
    """Training throughput at the published architecture (Table 2 config).

    ``dtype``: computation dtype for messages/GRU (params stay f32).
    bfloat16 is the TPU-native flagship — the MXU's dtype, with bf16-resident
    adjacency tiles; f32 is measured as the reference-dtype comparison point
    (its GPU baseline is fp32). Both train the synthetic task to the same F1
    (tests/test_train.py).

    ``impl``: message-passing implementation; default "band" (the block-
    banded batched-matmul path, the measured winner — module docstring) on
    TPU and "segment" elsewhere. "tile" rides the extras as the A/B;
    "fused" is the single-pass Pallas megakernel (ops/fused_gnn.py) over
    dense-slot-packed batches — the ISSUE-9 headline candidate;
    "persistent" is the K-step persistent megakernel (ISSUE 15) — the
    whole n_steps unroll as one pallas_call per direction, A/B'd against
    the fused rows.

    ``diagnostics``: also return {flops_per_step, mfu, ms_per_step} — the
    cost-model FLOPs and achieved MFU against the chip's peak. The fused
    program's Pallas calls are invisible to XLA's cost analysis, so their
    hand-counted FLOPs (fused_gnn.fused_step_cost) join the accounting
    and the capture, labelled analytic. The dispatch/device split is a
    one-off ablation finding (module docstring: dispatch ~0.13 ms/step
    amortized at K=10), not re-measured per run — a two-unroll fit at
    this granularity is noisier than the quantity.
    """
    from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig, TrainConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import make_train_state, make_train_step
    from __graft_entry__ import _example_batch

    if impl is None:
        # The banded path is pure XLA but its dense-diagonal zero-fill only
        # pays off where the MXU eats it; segment ops win on CPU hosts.
        impl = "band" if jax.default_backend() == "tpu" else "segment"
    model_cfg = FlowGNNConfig(message_impl=impl, dtype=dtype)
    data_cfg = DataConfig(batch_size=256)
    train_cfg = TrainConfig()

    batch = _example_batch(data_cfg, model_cfg,
                           slot_pack=impl in ("fused", "persistent"))
    model = FlowGNN(model_cfg)
    state, tx = make_train_state(model, batch, train_cfg)
    inner = make_train_step(model, tx, train_cfg)

    K = 10  # unrolled steps per dispatch; K=50 measures within 3% of K=10

    def make_step(k):
        def multi(state, batch):
            for _ in range(k):
                state, loss, stats = inner(state, batch)
            return state, loss, stats

        # Donation is load-bearing here: without it the train state
        # round-trips through the tunnel per call. AOT-compile so the same
        # executable serves timing AND cost analysis (a .lower().compile()
        # after the fact would compile the program twice).
        return jax.jit(multi, donate_argnums=(0,)).lower(state, batch).compile()

    step = make_step(K)

    def call():
        nonlocal state
        state, loss, _ = step(state, batch)
        return loss

    calls = 100  # 1000 steps
    dt = _timed(call, warmup=3, calls=calls)
    gps = calls * K * data_cfg.batch_size / dt
    if not diagnostics:
        return gps

    from deepdfa_tpu.eval.profiling import _costs_of_compiled
    from deepdfa_tpu.telemetry import costmodel

    # Pallas custom calls count as ZERO in XLA's cost model; the fused
    # program's kernel FLOPs enter the one shared accounting analytically
    # (forward + hand-derived backward, per model step, times K unrolls).
    # ONE helper (ops/fused_gnn.analytic_extra_cost) owns every
    # eligibility leg — band adjacency, backend (when the flag resolves
    # to the XLA band composition the executed program's FLOPs are
    # already in cost_analysis; adding the analytic count would double
    # them), and the persistent VMEM budget — so this accounting tracks
    # the program the model dispatch actually ran. Scaled by the K
    # timing unrolls of this bench's dispatch.
    from deepdfa_tpu.ops.fused_gnn import analytic_extra_cost

    extra_flops, extra_bytes = analytic_extra_cost(
        impl, batch.band_adj, model_cfg.ggnn_hidden, model_cfg.n_steps,
        dtype, include_bwd=True)
    extra_flops *= K
    extra_bytes *= K
    # Register the K-unrolled program in the cost-model registry (the
    # observatory's compiled-callable catalogue) — same executable that
    # was timed, so the roofline numbers describe the measured program.
    costmodel.capture_compiled(f"bench.ddfa_step.{dtype}.{impl}", step,
                               steps_per_call=K, extra_flops=extra_flops,
                               extra_bytes=extra_bytes)
    flops = (_costs_of_compiled(step)["flops"] + extra_flops) / K
    sec_per_step = dt / (calls * K)
    peak = _peak_flops()
    return gps, {
        "flops_per_step": flops,
        "mfu": (flops / sec_per_step) / peak if (flops and peak) else None,
        "ms_per_step": sec_per_step * 1e3,
    }




def bench_deepdfa_infer(batch_size: int = 256, dtype: str = "bfloat16",
                        impl: "str | None" = None) -> float:
    """DeepDFA-standalone inference latency (ms/example) at the published
    architecture — the comparison point for the paper's 4.6 ms/example
    (Table 5's DeepDFA row; the gap VERDICT.md round 5 called out).

    Forward-only FlowGNN over the 256-graph parity batch; ms/example =
    batch latency / batch size. ``impl`` selects the message path like
    bench_deepdfa (the flag-audit fix, ISSUE 9: this bench used to pin the
    band path no matter what the config said); default keeps band on TPU /
    segment elsewhere. The data-dependent chaining + final device_get
    mirror bench_combined_infer — the only completion barrier the
    tunneled backend honors (module docstring).
    """
    import jax.numpy as jnp

    from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from __graft_entry__ import _example_batch

    if impl is None:
        impl = "band" if jax.default_backend() == "tpu" else "segment"
    model_cfg = FlowGNNConfig(message_impl=impl, dtype=dtype)
    batch = _example_batch(DataConfig(batch_size=batch_size), model_cfg,
                           slot_pack=impl in ("fused", "persistent"))
    model = FlowGNN(model_cfg)
    params = model.init(jax.random.PRNGKey(0), batch)

    @jax.jit
    def infer(params, batch, prev):
        # Chain this call's input on the previous call's output (adds 0 to
        # a feature table the forward actually reads) so the timed sequence
        # cannot overlap or reorder on the device.
        feats = dict(batch.node_feats)
        k0 = sorted(feats)[0]
        feats[k0] = feats[k0].at[0].add((prev * 0).astype(feats[k0].dtype))
        logits = model.apply(params, batch.replace(node_feats=feats))
        return logits, logits.reshape(-1)[0]

    prev = jnp.zeros((), jnp.float32)

    def call():
        nonlocal prev
        out, prev = infer(params, batch, prev)
        return out

    n_steps = 30
    dt = _timed(call, warmup=3, calls=n_steps)
    return dt / (n_steps * batch_size) * 1000.0  # ms/example


def bench_checkpoint_resilience(reps: int = 3) -> dict:
    """The robustness tax, tracked per round (ISSUE 3).

    ``ckpt_save_ms`` / ``ckpt_restore_ms``: median wall time of one
    hardened snapshot write (orbax save + content checksum + atomic
    fsync'd meta) and one verified restore, on the published Table-2
    architecture's full trainer state — the per-epoch cost ``save_last``
    charges training under the SYNCHRONOUS manager.

    ``ckpt_async_blocking_ms`` (ISSUE 6): the step-loop stall of the same
    save under ``AsyncCheckpointManager`` — the ``save_last`` call
    returns after starting the device→host copy and enqueueing the
    write; serialization/fsync/checksum ride the writer thread. Measured
    back-to-back with the sync saves (alternated per rep, medians — the
    ``_timed`` variance protocol: same process, interleaved A/B), with a
    ``drain()`` between reps OUTSIDE the timed region so each submit
    lands on an idle writer. The acceptance gate is this number dropping
    materially below the sync ``ckpt_save_ms`` the r05 baseline charged
    every epoch.

    ``sigterm_to_durable_snapshot_ms`` (ISSUE 10): signal delivery →
    committed durable preempt snapshot. A REAL ``os.kill(self, SIGTERM)``
    lands on the lifecycle coordinator's flag-only handler, the main
    path polls the notice (the step loop's check), fires
    ``save_preempt`` on the async manager, and drains to the atomic
    meta commit — the clock stops when the snapshot is durable. Best-of
    ``reps`` per the ``_timed`` variance protocol, one fresh coordinator
    per rep.

    ``ckpt_redistribute_ms`` / ``ckpt_redistribute_fast_ms`` (ISSUE 18):
    rewriting one flagship-state snapshot for a different process count
    — the elastic-resume critical path. A sharded snapshot is fabricated
    in-process (N managers on one dir, ``set_host(i, N)``, non-primaries
    save first, the primary commits last — the same rendezvous a live
    fleet runs), then ``redistribute`` is timed: the headline number is
    the 2→1 ``consolidate`` rewrite (reassemble + plain orbax — the
    shrink-to-one path every single-process tool depends on), and the
    ``fast`` number is the 4→2 hardlink re-home (no byte copies; the
    nested-shard-sets fast path). Best-of ``reps`` per the ``_timed``
    variance protocol, a fresh fabricated snapshot per rep (the rewrite
    consumes its input).

    ``resume_overhead_s``: wall-clock delta of a kill-and-resume versus
    the uninterrupted fit on the synthetic dataset — a 3-epoch tiny fit,
    preempted by an injected epoch-start fault at epoch 1, resumed with
    ``resume=True``. Dominated by the resumed process's fresh jit
    compiles plus the snapshot restore: exactly what one preemption
    charges a run. The resumed history is also checked bit-for-bit
    against the uninterrupted run (the chaos gate, re-asserted in the
    bench lane); a mismatch raises rather than reporting a number for a
    broken property.
    """
    import shutil
    import tempfile

    from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig, TrainConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.resilience import inject
    from deepdfa_tpu.resilience.chaos import scenario_preempt_resume
    from deepdfa_tpu.train.checkpoint import AsyncCheckpointManager, CheckpointManager
    from deepdfa_tpu.train.loop import make_train_state
    from __graft_entry__ import _example_batch

    model_cfg = FlowGNNConfig()
    data_cfg = DataConfig(batch_size=256)
    batch = _example_batch(data_cfg, model_cfg)
    model = FlowGNN(model_cfg)
    state, _ = make_train_state(model, batch, TrainConfig())

    tmp = tempfile.mkdtemp(prefix="bench_ckpt_")
    tmp_async = tempfile.mkdtemp(prefix="bench_ckpt_async_")
    try:
        mgr = CheckpointManager(tmp)
        amgr = AsyncCheckpointManager(tmp_async)
        saves, async_blocks, restores = [], [], []
        for i in range(reps):
            t0 = time.perf_counter()
            mgr.save_last(state, epoch=i)
            saves.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            amgr.save_last(state, epoch=i)
            async_blocks.append(time.perf_counter() - t0)
            amgr.drain()  # outside the timed region: idle writer per rep
        if amgr.errors:
            raise AssertionError(
                f"async writer failed during bench: {amgr.errors}"
            )
        for _ in range(reps):
            t0 = time.perf_counter()
            restored = mgr.restore("last", state)
            jax.device_get(restored.params)
            restores.append(time.perf_counter() - t0)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.rmtree(tmp_async, ignore_errors=True)

    from deepdfa_tpu.benchwatch import sigterm_to_snapshot_ms

    sigterm_ms = sigterm_to_snapshot_ms(state, reps=reps)

    def _fabricate_sharded(directory: str, pc: int) -> CheckpointManager:
        """A committed pc-process sharded "last" snapshot, written the
        way a live fleet writes one: peers land shards + markers first,
        the primary rendezvouses and owns the commit."""
        mgrs = [CheckpointManager(directory) for _ in range(pc)]
        for i, m in enumerate(mgrs):
            m.set_host(i, pc)
        for m in mgrs[1:]:
            m.save_last(state, epoch=0)
        mgrs[0].save_last(state, epoch=0)
        return mgrs[0]

    redist_fast, redist_cons = [], []
    for _ in range(reps):
        for old_pc, new_pc, sink in ((4, 2, redist_fast),
                                     (2, 1, redist_cons)):
            d = tempfile.mkdtemp(prefix="bench_redist_")
            try:
                primary = _fabricate_sharded(d, old_pc)
                t0 = time.perf_counter()
                primary.redistribute("last", new_pc, target=state)
                sink.append(time.perf_counter() - t0)
            finally:
                shutil.rmtree(d, ignore_errors=True)

    tmp2 = tempfile.mkdtemp(prefix="bench_resume_")
    try:
        t0 = time.perf_counter()
        report = scenario_preempt_resume(tmp2, n_examples=48, epochs=3)
    finally:
        shutil.rmtree(tmp2, ignore_errors=True)
    if not report["ok"]:
        raise AssertionError(
            f"kill-and-resume determinism broke under bench: {report}"
        )
    # The scenario runs (uninterrupted) + (preempted + resumed) on the
    # same workload in-process; its overhead field isolates the delta.
    return {
        "ckpt_save_ms": float(np.median(saves) * 1000.0),
        "ckpt_async_blocking_ms": float(np.median(async_blocks) * 1000.0),
        "ckpt_restore_ms": float(np.median(restores) * 1000.0),
        "sigterm_to_durable_snapshot_ms": sigterm_ms,
        "ckpt_redistribute_ms": float(min(redist_cons) * 1000.0),
        "ckpt_redistribute_fast_ms": float(min(redist_fast) * 1000.0),
        "resume_overhead_s": float(report["resume_overhead_s"]),
        "resume_bitwise_match": bool(report["bitwise_match"]),
    }


def bench_ingest_validate(n_rows: int = 1500, reps: int = 5) -> dict:
    """The validation tax at the ingestion boundary (ISSUE 4 gate: < 5%).

    A/B over the same exported JSONL corpus (pipeline ``examples.jsonl``
    format, no row digests — the A/B isolates schema validation, not
    hashing): the pre-contracts raw loader (json.loads + asarray, exactly
    what ``cli.load_dataset`` used to inline) versus the contract-enforced
    ``contracts.load_examples_jsonl`` (type/shape/endpoint/domain checks +
    quarantine bookkeeping). Alternated back-to-back per rep, medians —
    the only comparison protocol this backend supports (module docstring).
    """
    import shutil
    import tempfile

    from deepdfa_tpu.contracts import Quarantine, load_examples_jsonl, write_examples_jsonl
    from deepdfa_tpu.core.config import ALL_SUBKEYS, FeatureSpec
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    examples = synthetic_bigvul(n_rows, FeatureSpec(),
                                positive_fraction=0.5, seed=0)
    tmp = tempfile.mkdtemp(prefix="bench_ingest_")
    path = os.path.join(tmp, "corpus.jsonl")
    try:
        write_examples_jsonl(examples, path, checksum=False)

        def load_raw():
            # The pre-contracts ingest loop, verbatim (the A/B baseline —
            # deliberately NOT routed through contracts).
            out = []
            with open(path) as f:
                for i, line in enumerate(f):
                    ex = json.loads(line)
                    for key in ("senders", "receivers", "vuln"):
                        ex[key] = np.asarray(ex[key], np.int32)
                    ex["feats"] = {k: np.asarray(v, np.int32)
                                   for k, v in ex["feats"].items()}
                    ex.setdefault("id", i)
                    ex.setdefault("label", int(ex["vuln"].max())
                                  if len(ex["vuln"]) else 0)
                    out.append(ex)
            return out

        def load_validated():
            exs, _ = load_examples_jsonl(
                path, ALL_SUBKEYS,
                quarantine=Quarantine(os.path.join(tmp, "quarantine")))
            return exs

        # Warm both paths (imports, allocator), then alternate.
        assert len(load_raw()) == len(load_validated()) == n_rows
        t_raw, t_val = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            load_raw()
            t_raw.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            load_validated()
            t_val.append(time.perf_counter() - t0)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    raw_s = float(np.median(t_raw))
    val_s = float(np.median(t_val))
    return {
        "overhead_pct": (val_s - raw_s) / raw_s * 100.0,
        "raw_rows_per_sec": n_rows / raw_s,
        "validated_rows_per_sec": n_rows / val_s,
        "n_rows": n_rows,
    }


def bench_telemetry_overhead(n_steps: int = 200, reps: int = 3,
                             gate_pct: float = 2.0) -> dict:
    """The observability tax (ISSUE 5 gate: < 2%).

    A/B over the SAME AOT-compiled train step at the bench parity batch
    (256 graphs): the instrumented loop carries exactly the train-loop
    instrumentation — a per-step span plus a fenced window span every 50
    steps, with an active telemetry run writing events.jsonl — versus the
    ``DEEPDFA_TELEMETRY=0`` loop, where every hook is a no-op. Alternated
    back-to-back per rep, BEST-of-reps on each side (the ``_timed``
    protocol: this backend's run-to-run variance dwarfs the quantity —
    measured A/A spread exceeds 10% on the shared-CPU container, while
    the per-step span cost is microseconds — and min is the estimator
    robust to contention outliers). Donated-state chaining serializes
    the steps; each rep ends on the device_get barrier.
    """
    import shutil
    import tempfile

    from deepdfa_tpu import telemetry
    from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig, TrainConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import make_train_state, make_train_step
    from __graft_entry__ import _example_batch

    impl = "band" if jax.default_backend() == "tpu" else "segment"
    model_cfg = FlowGNNConfig(message_impl=impl)
    batch = _example_batch(DataConfig(batch_size=256), model_cfg)
    model = FlowGNN(model_cfg)
    state, tx = make_train_state(model, batch, TrainConfig())
    inner = make_train_step(model, tx, TrainConfig())
    step = jax.jit(inner, donate_argnums=(0,)).lower(state, batch).compile()

    def run_loop(instrumented: bool) -> float:
        nonlocal state
        loss_sum = None
        t0 = time.perf_counter()
        for i in range(n_steps):
            with telemetry.span("train.step", step=i):
                state, loss, _ = step(state, batch)
            loss_sum = loss
            if (i + 1) % 50 == 0:
                with telemetry.span("train.window", steps=50) as w:
                    w.fence(loss_sum)
        jax.device_get(loss_sum)
        return time.perf_counter() - t0

    tmp = tempfile.mkdtemp(prefix="bench_telemetry_")
    t_on, t_off = [], []
    try:
        with telemetry.run_scope(tmp):
            run_loop(True)  # warm both code paths + the event machinery
            for _ in range(reps):
                t_on.append(run_loop(True))
                telemetry.set_enabled(False)
                try:
                    t_off.append(run_loop(False))
                finally:
                    telemetry.set_enabled(None)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    on_s, off_s = float(np.min(t_on)), float(np.min(t_off))
    pct = (on_s - off_s) / off_s * 100.0
    return {
        "overhead_pct": pct,
        "gate_pct": gate_pct,
        "gate_ok": pct < gate_pct,
        "instrumented_steps_per_sec": n_steps / on_s,
        "disabled_steps_per_sec": n_steps / off_s,
        "n_steps": n_steps,
    }


def bench_trace_propagation(n_requests: int = 256, batch_slots: int = 8,
                            reps: int = 3, gate_pct: float = 2.0) -> dict:
    """The distributed-trace tax (ISSUE 14 gate: < 2%, the PR-5
    observability discipline).

    A/B over the SAME warmed serve replay: the instrumented side submits
    every request with a trace id (the traceparent-continuation path —
    two extra attrs on every ``serve.request`` span) under an active
    telemetry run with the sharding/rotation machinery on the write path
    (an explicit flush per rep makes the events durable inside the timed
    region); the other side is ``DEEPDFA_TELEMETRY=0``, where every hook
    is a no-op. Alternated back-to-back per rep, BEST-of-reps per the
    ``_timed`` variance protocol. The cache is disabled so both sides do
    identical compute every rep; compiles after warmup must stay 0.
    """
    import shutil
    import tempfile

    from deepdfa_tpu import telemetry
    from deepdfa_tpu.core.config import FlowGNNConfig
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock
    from deepdfa_tpu.telemetry import context as trace_context

    on_tpu = jax.default_backend() == "tpu"
    model_cfg = FlowGNNConfig(
        message_impl="band" if on_tpu else "segment",
        dtype="bfloat16" if on_tpu else "float32",
    )
    config = ServeConfig(batch_slots=batch_slots, cache_capacity=0)
    model = FlowGNN(model_cfg)
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config, clock=VirtualClock())
    graphs = synthetic_bigvul(n_requests, model_cfg.feature,
                              positive_fraction=0.5, seed=0)
    trace_ids = [trace_context.new_trace_id() for _ in range(n_requests)]

    def run_replay(with_trace: bool) -> float:
        t0 = time.perf_counter()
        for i, g in enumerate(graphs):
            engine.submit(
                g, trace_id=trace_ids[i] if with_trace else None,
                trace_continued=with_trace)
        engine.drain()
        telemetry.flush()  # sharding on the measured path (no-op when off)
        return time.perf_counter() - t0

    tmp = tempfile.mkdtemp(prefix="bench_trace_prop_")
    t_on, t_off = [], []
    try:
        with telemetry.run_scope(tmp):
            engine.warmup()
            compiles0 = engine.stats.compiles
            run_replay(True)  # warm both code paths + the event machinery
            for _ in range(reps):
                t_on.append(run_replay(True))
                telemetry.set_enabled(False)
                try:
                    t_off.append(run_replay(False))
                finally:
                    telemetry.set_enabled(None)
            recompiled = engine.stats.compiles != compiles0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if recompiled:
        raise AssertionError(
            "trace-propagation bench recompiled after warmup")
    on_s, off_s = float(np.min(t_on)), float(np.min(t_off))
    pct = (on_s - off_s) / off_s * 100.0
    return {
        "overhead_pct": pct,
        "gate_pct": gate_pct,
        "gate_ok": pct < gate_pct,
        "instrumented_rps": n_requests / on_s,
        "disabled_rps": n_requests / off_s,
        "n_requests": n_requests,
    }


def bench_traffic_capture_overhead(n_requests: int = 256,
                                   batch_slots: int = 8, reps: int = 3,
                                   gate_pct: float = 2.0) -> dict:
    """The traffic-observatory tax (ISSUE 20 gate: < 2%, the same A/B
    protocol as ``bench_trace_propagation``).

    Both sides run the SAME warmed serve replay with telemetry ON — the
    only difference is the shape-capture kill switch
    (``telemetry.sketch.set_capture``), so the measurement isolates the
    cost the traffic observatory itself adds on the submit path: sketch
    binning per request (nodes + edges per graph), the per-(lane,bucket)
    element accounting per flush, and the pow2-scheduled
    ``traffic.shape`` mirror events. Alternated back-to-back per rep,
    best-of-reps, recompile-free by assertion.
    """
    import shutil
    import tempfile

    from deepdfa_tpu import telemetry
    from deepdfa_tpu.core.config import FlowGNNConfig
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock
    from deepdfa_tpu.telemetry import sketch as traffic_sketch

    on_tpu = jax.default_backend() == "tpu"
    model_cfg = FlowGNNConfig(
        message_impl="band" if on_tpu else "segment",
        dtype="bfloat16" if on_tpu else "float32",
    )
    config = ServeConfig(batch_slots=batch_slots, cache_capacity=0)
    model = FlowGNN(model_cfg)
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config, clock=VirtualClock())
    graphs = synthetic_bigvul(n_requests, model_cfg.feature,
                              positive_fraction=0.5, seed=0)

    def run_replay() -> float:
        t0 = time.perf_counter()
        for g in graphs:
            engine.submit(g)
        engine.drain()
        telemetry.flush()
        return time.perf_counter() - t0

    tmp = tempfile.mkdtemp(prefix="bench_traffic_cap_")
    t_on, t_off = [], []
    try:
        with telemetry.run_scope(tmp):
            engine.warmup()
            compiles0 = engine.stats.compiles
            run_replay()  # warm both code paths + the event machinery
            for _ in range(reps):
                t_on.append(run_replay())
                traffic_sketch.set_capture(False)
                try:
                    t_off.append(run_replay())
                finally:
                    traffic_sketch.set_capture(True)
            recompiled = engine.stats.compiles != compiles0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if recompiled:
        raise AssertionError(
            "traffic-capture bench recompiled after warmup")
    on_s, off_s = float(np.min(t_on)), float(np.min(t_off))
    pct = (on_s - off_s) / off_s * 100.0
    return {
        "overhead_pct": pct,
        "gate_pct": gate_pct,
        "gate_ok": pct < gate_pct,
        "captured_rps": n_requests / on_s,
        "uncaptured_rps": n_requests / off_s,
        "n_requests": n_requests,
    }


def bench_serve(n_requests: int = 512, batch_slots: int = 16,
                seed: int = 0) -> dict:
    """Serving-path latency/throughput on THE seeded bursty trace.

    The serving layer (deepdfa_tpu/serve) replayed over a deterministic
    CI-scan-shaped trace: seeded bursty arrivals + 25% duplicates on a
    virtual clock, with only measured micro-batch compute advancing it —
    no wall-clock randomness in the workload, so every round replays the
    identical request stream (serve/replay.py). Reported latency is
    queue wait + compute, end to end per request.

    Serving shape: the published GNN architecture at the flagship message
    impl (band on TPU, segment elsewhere) over the serving bucket ladder
    (slot buckets 1..batch_slots) — NOT the 256-graph training parity
    batch; 16 slots at a 100 ms deadline is the serving operating point.
    Random-init params: the machinery under test is batching + AOT bucket
    dispatch + caching, which is weight-independent.

    ``compiles_after_warmup`` must be 0 — the warmed-bucket invariant
    (every shape steady-state traffic can produce is compiled at
    startup); a nonzero value here is a regression even if throughput
    looks fine.
    """
    from deepdfa_tpu.core.config import FlowGNNConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock, bursty_trace, replay

    on_tpu = jax.default_backend() == "tpu"
    model_cfg = FlowGNNConfig(
        message_impl="band" if on_tpu else "segment",
        dtype="bfloat16" if on_tpu else "float32",
    )
    model = FlowGNN(model_cfg)
    config = ServeConfig(batch_slots=batch_slots)
    clock = VirtualClock()
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config, clock=clock)
    warm = engine.warmup()
    trace = bursty_trace(n_requests, model_cfg.feature, seed=seed)
    out = replay(engine, trace, clock)
    m = out["metrics"]
    return {
        "p50_ms": m["latency_p50_ms"],
        "p99_ms": m["latency_p99_ms"],
        "graphs_per_sec": m["graphs_per_sec"],
        "occupancy": m["batch_occupancy"],
        "cache_hit_rate": m["cache_hit_rate"],
        "compiles_after_warmup": m["compiles"] - warm,
        "warm_buckets": warm,
        "n_requests": n_requests,
        "dropped": m["dropped"],
    }


def bench_serve_fleet(n_requests: int = 640, replicas: int = 4,
                      batch_slots: int = 8, rps: float = 20000.0,
                      seed: int = 0) -> dict:
    """1 vs N engine replicas over THE SAME open-loop saturation trace.

    The fleet bench (ISSUE 12): a seeded open-loop arrival schedule hot
    enough to saturate both configurations (arrivals never wait on
    completions; the queue sheds via backpressure), replayed through the
    discrete-event fleet harness — every replica credits its *measured*
    micro-batch compute to its own busy timeline, so N replicas overlap
    exactly like N devices while one replica serializes. Measured
    throughput is completed/span: at overload that is service capacity,
    the honest 1-vs-N number (queue-limited vs hardware-limited is the
    whole point of the refactor). Adaptive flush runs ON — it is the
    shipped architecture — and ``compiles_after_warmup`` must be 0
    across every replica of both runs.
    """
    from deepdfa_tpu.core.config import FlowGNNConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve import ServeConfig, ServeFleet
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import (
        ReplicaTimeline,
        VirtualClock,
        open_loop_trace,
        replay_fleet,
    )

    on_tpu = jax.default_backend() == "tpu"
    model_cfg = FlowGNNConfig(
        message_impl="band" if on_tpu else "segment",
        dtype="bfloat16" if on_tpu else "float32",
    )
    model = FlowGNN(model_cfg)
    config = ServeConfig(batch_slots=batch_slots, deadline_ms=250.0,
                         queue_capacity=64, cache_capacity=0,
                         adaptive_flush=True)
    params = random_gnn_params(model, config)
    trace = open_loop_trace(n_requests, model_cfg.feature, seed=seed,
                            rps=rps, duplicate_fraction=0.0)
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    primer = synthetic_bigvul(sum(config.slot_buckets), model_cfg.feature,
                              positive_fraction=0.5, seed=seed + 1)

    def run(n: int) -> dict:
        clock = VirtualClock()
        timelines = [ReplicaTimeline(clock) for _ in range(n)]
        fleet = ServeFleet.build(model, params, config=config,
                                 n_replicas=n,
                                 clock_factory=lambda i: timelines[i])
        fleet.warmup()
        # First-execution cost per AOT executable is one-time setup, not
        # steady-state serving; prime it out so 1-vs-N compares capacity.
        fleet.prime(primer)
        rep = replay_fleet(fleet, trace, clock)
        assert rep["compiles_after_warmup"] == 0, \
            "fleet replay recompiled after warmup"
        return rep

    solo = run(1)
    multi = run(replicas)
    return {
        "serve_fleet_rps": multi["rps"],
        "serve_fleet_p99_ms": multi["latency_p99_ms"],
        "serve_fleet_p50_ms": multi["latency_p50_ms"],
        "single_replica_rps": solo["rps"],
        "single_replica_p99_ms": solo["latency_p99_ms"],
        "speedup": multi["rps"] / solo["rps"] if solo["rps"] else None,
        "replicas": replicas,
        "batch_slots": batch_slots,
        "offered_rps": multi["offered_rps"],
        "n_requests": n_requests,
        "completed": multi["completed"],
        "shed": multi["shed"],
        "compiles_after_warmup": multi["compiles_after_warmup"],
    }


def bench_serve_multiproc(n_requests: int = 512, processes: int = 3,
                          batch_slots: int = 8, calib_reps: int = 5,
                          seed: int = 0) -> dict:
    """1 vs N engine OS **processes** over the same open-loop trace —
    the shared-nothing serving tier's capacity evidence (ISSUE 17).

    Two-part protocol, honest about a 1-core CI container:

    1. **Calibrate against the real thing.** Spawn the REAL process
       fleet (N ``cli serve`` children, each AOT-warming its own
       engine) plus the router tier, prime every child's full bucket,
       then measure the wall cost of full ``batch_slots`` micro-batches
       over HTTP ``/score`` against the children — and assert zero
       post-warmup compiles through the router-side per-child
       baselines. Spawn, warmup handshake, routing, forwarding, and
       aggregation are all exercised for real.
    2. **Replay over process timelines.** The same seeded open-loop
       trace through ``replay_multiproc`` — the router's routing rules
       over N *independent* timelines at the measured cost. N real
       children on one core would timeslice that core and measure the
       scheduler, not the architecture; the per-process timeline is
       bench_serve_fleet's virtual-clock posture promoted across the
       process boundary, with real-child calibration keeping the cost
       grounded.

    The ISSUE-17 gate: N-process capacity must clear 2x single-process
    capacity with p99 under the configured deadline.
    """
    import statistics
    import threading
    import urllib.request

    from deepdfa_tpu.core.config import FeatureSpec
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.serve import ServeConfig
    from deepdfa_tpu.serve.procfleet import ProcFleet
    from deepdfa_tpu.serve.replay import open_loop_trace, replay_multiproc
    from deepdfa_tpu.serve.router import RouterHTTPServer

    deadline_ms = 500.0
    feature = FeatureSpec()
    child_args = ["--batch-slots", str(batch_slots),
                  "--deadline-ms", str(deadline_ms),
                  "--queue-capacity", "64",
                  # cache off: calibration measures compute, not lookups
                  # (bench_serve_fleet's posture).
                  "--cache-capacity", "0",
                  "--replicas", "1", "--processes", "1", "--slo", "none"]
    config = ServeConfig(batch_slots=batch_slots, deadline_ms=deadline_ms,
                         queue_capacity=64, cache_capacity=0)
    fleet = ProcFleet(processes, child_args=child_args)
    fleet.start()
    server = RouterHTTPServer(("127.0.0.1", 0), fleet, config)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        n_unique = (calib_reps + 2) * processes * batch_slots
        graphs = synthetic_bigvul(n_unique, feature, positive_fraction=0.5,
                                  seed=seed + 1)
        payload = [
            {"id": int(g["id"]),
             "graph": {"num_nodes": int(g["num_nodes"]),
                       "senders": np.asarray(g["senders"]).tolist(),
                       "receivers": np.asarray(g["receivers"]).tolist(),
                       "feats": {k: np.asarray(v).tolist()
                                 for k, v in g["feats"].items()}}}
            for g in graphs
        ]

        def post(port: int, chunk) -> None:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/score",
                data=json.dumps({"functions": chunk}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120.0) as resp:
                body = json.loads(resp.read())
            assert all("prob" in r for r in body["results"]), body

        ports = {rid: int(p["port"])
                 for rid, p in fleet.processes().items()}
        chunks = [payload[i:i + batch_slots]
                  for i in range(0, len(payload), batch_slots)]
        ci = iter(chunks)
        for port in ports.values():  # prime first-execution cost
            post(port, next(ci))
        costs = []
        for _ in range(calib_reps):
            for port in ports.values():
                t0 = time.perf_counter()
                post(port, next(ci))
                costs.append(time.perf_counter() - t0)
        cost = statistics.median(costs)

        # Through-router pass + aggregation, then the invariant: zero
        # compiles after each child's warmup baseline, fleet-wide.
        router_port = server.server_address[1]
        post(router_port, next(ci))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{router_port}/metrics",
                timeout=30.0) as resp:
            agg = json.loads(resp.read())
        caw = fleet.compiles_after_warmup()
        assert caw == 0, f"multiproc bench recompiled after warmup: {caw}"
    finally:
        server.shutdown()
        fleet.shutdown()

    deadline_s = deadline_ms / 1000.0
    # Queue depth sized so worst-case wait (queue ahead + own service)
    # stays under the deadline the children run at.
    queue_capacity = batch_slots * max(
        1, min(8, int(0.6 * deadline_s / cost)))
    offered = 2.5 * processes * batch_slots / cost
    trace = open_loop_trace(n_requests, feature, seed=seed, rps=offered,
                            duplicate_fraction=0.0)
    solo = replay_multiproc(trace, 1, batch_slots, cost,
                            queue_capacity=queue_capacity,
                            deadline_s=deadline_s)
    multi = replay_multiproc(trace, processes, batch_slots, cost,
                             queue_capacity=queue_capacity,
                             deadline_s=deadline_s)
    return {
        "serve_multiproc_rps": multi["rps"],
        "serve_multiproc_p99_ms": multi["latency_p99_ms"],
        "serve_multiproc_p50_ms": multi["latency_p50_ms"],
        "single_process_rps": solo["rps"],
        "single_process_p99_ms": solo["latency_p99_ms"],
        "speedup": multi["rps"] / solo["rps"] if solo["rps"] else None,
        "processes": processes,
        "batch_slots": batch_slots,
        "deadline_ms": deadline_ms,
        "cost_ms": cost * 1e3,
        "offered_rps": multi["offered_rps"],
        "completed": multi["completed"],
        "shed": multi["shed"],
        "n_requests": n_requests,
        "compiles_after_warmup": caw,
        "router_agg_processes": agg.get("n_processes"),
    }


def bench_scan(n_functions: int = 24, n_warm_requests: int = 96,
               reps: int = 3, seed: int = 0) -> dict:
    """Streaming scan service (deepdfa_tpu/scan): cold per-function cost
    and warm-cache hit rate under the seeded edit/repeat mix.

    Hermetic fake-Joern transport (a scripted subprocess speaking the
    real session protocol), so the number tracks the pool/featurize/
    score machinery and not a JVM install — the same measurement runs on
    the TPU host and a CI box. A/B per the ``_timed`` variance protocol:
    the **cold** side sweeps a fresh seeded corpus each rep (every
    function a cache miss: pooled Joern export + on-demand featurize +
    warmed-engine score), best-of-reps; the **warm** side replays the
    seeded edit/repeat trace (serve/replay.scan_trace — the PR-diff
    traffic shape) over its own corpus, disjoint from the cold sweeps'
    (disjoint seeds), so every warm hit comes from the trace's internal
    repeat structure and the realized hit count is checked against the
    trace's exact expectation — a cache regression fails the bench, not
    just the eyeball. ``compiles_after_warmup`` must be 0: scan requests
    reuse the serve engine's warmed (lane, slot-bucket) executables
    unchanged.
    """
    import shutil
    import tempfile

    from deepdfa_tpu.core.config import FlowGNNConfig
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.scan import ScanConfig, ScanService, fake_joern_command
    from deepdfa_tpu.scan.cache import ScanCache
    from deepdfa_tpu.scan.fake_joern import seeded_sources
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import replay_scan, scan_trace

    model_cfg = FlowGNNConfig()
    model = FlowGNN(model_cfg)
    config = ServeConfig(batch_slots=8)
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config)
    warm = engine.warmup()
    tmp = tempfile.mkdtemp(prefix="bench_scan_")
    try:
        with ScanService(engine, model_cfg.feature, workdir=tmp,
                         config=ScanConfig(pool_size=2, timeout_s=60.0),
                         command=fake_joern_command(),
                         cache=ScanCache(None)) as svc:
            cold_s = float("inf")
            for rep in range(reps):
                # A fresh corpus per rep (disjoint seeds): every item is
                # a genuine miss, no cache surgery between reps.
                sources = seeded_sources(n_functions,
                                         seed=seed + 101 * rep + 1)
                items = [{"id": i, "source": s}
                         for i, s in enumerate(sources)]
                t0 = time.perf_counter()
                out = svc.scan_sources(items)
                cold_s = min(cold_s, time.perf_counter() - t0)
                assert all("prob" in r for r in out), "cold sweep errored"
            trace = scan_trace(n_warm_requests, seed=seed,
                               n_functions=n_functions)
            warm_report = replay_scan(svc, trace, chunk=8)
            restarts = svc.pool.restarts
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    assert warm_report["errors"] == 0, "warm replay errored"
    assert warm_report["hits"] == warm_report["expected_hits"], (
        f"cache hit count {warm_report['hits']} != trace expectation "
        f"{warm_report['expected_hits']}")
    scanned = warm_report["n_requests"] - warm_report["errors"]
    return {
        "scan_cold_ms_per_func": cold_s * 1000.0 / n_functions,
        "scan_warm_cache_hit_pct": warm_report["hit_rate"] * 100.0,
        "expected_warm_hit_pct": (warm_report["expected_hits"] / scanned
                                  * 100.0) if scanned else 0.0,
        "warm_requests": warm_report["n_requests"],
        "warm_errors": warm_report["errors"],
        "n_functions": n_functions,
        "pool_restarts": restarts,
        "compiles_after_warmup": engine.stats.compiles - warm,
    }


def _combined_setup(batch_size: int = 16, seq_len: int = 512,
                    attention_impl: str = "blockwise", remat: bool = False):
    """DeepDFA+LineVul at published shape: codebert-base encoder (12L/768),
    encoder-mode FlowGNN (paper Table 2 config), 512-token inputs, batch 16
    (msr_train_combined.sh:12-30).

    ``attention_impl``: "flash" rides the headline (the Pallas fwd+bwd
    kernels win the A/B at 512 tokens since round 4, module docstring);
    "blockwise" is measured alongside so the standing is re-checked every
    run.
    """
    import dataclasses

    from deepdfa_tpu.core.config import FlowGNNConfig, subkeys_for
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.graphs.batch import batch_graphs, pad_budget_for
    from deepdfa_tpu.models.linevul import LineVul
    from deepdfa_tpu.models.transformer import EncoderConfig
    from deepdfa_tpu.train.text_loop import TextBatch

    enc_cfg = dataclasses.replace(
        EncoderConfig(), dtype="bfloat16", attention_impl=attention_impl,
        remat_layers=remat,
    )
    gnn_cfg = FlowGNNConfig(encoder_mode=True)
    model = LineVul(enc_cfg, graph_config=gnn_cfg)

    rng = np.random.RandomState(0)
    graphs = synthetic_bigvul(
        batch_size, gnn_cfg.feature, positive_fraction=0.5, seed=0
    )
    budget = pad_budget_for(graphs, batch_size)
    gbatch = batch_graphs(
        graphs, batch_size, budget["max_nodes"], budget["max_edges"],
        subkeys_for(gnn_cfg.feature),
    )
    batch = TextBatch(
        input_ids=rng.randint(
            2, enc_cfg.vocab_size, size=(batch_size, seq_len)
        ).astype(np.int32),
        labels=rng.randint(0, 2, size=batch_size).astype(np.int32),
        example_mask=np.ones(batch_size, bool),
        index=np.arange(batch_size),
        graphs=gbatch,
    )
    return model, batch


def bench_combined_train(
    batch_size: int = 16,
    attention_impl: str = "blockwise",
    n_steps: int = 60,
    diagnostics: bool = False,
    seq_len: int = 512,
    remat: bool = False,
):
    import jax.numpy as jnp

    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.train.text_loop import (
        make_text_train_state,
        make_text_train_step,
    )

    model, batch = _combined_setup(batch_size, seq_len=seq_len,
                                   attention_impl=attention_impl,
                                   remat=remat)
    cfg = TransformerTrainConfig()
    state, tx = make_text_train_state(model, batch, cfg, max_steps=1000)

    args = (
        jnp.asarray(batch.input_ids),
        jnp.asarray(batch.labels),
        jnp.asarray(batch.example_mask),
        batch.graphs,
    )
    step = (
        jax.jit(make_text_train_step(model, tx, cfg), donate_argnums=(0,))
        .lower(state, *args)
        .compile()
    )

    def call():
        nonlocal state
        state, loss, _ = step(state, *args)
        return loss

    # ~81 ms device time per step dwarfs the ~4 ms dispatch; no unroll
    # needed. Donated-state chaining serializes the steps.
    dt = _timed(call, warmup=3, calls=n_steps, trials=2)
    eps = n_steps * batch_size / dt
    if not diagnostics:
        return eps
    from deepdfa_tpu.eval.profiling import _costs_of_compiled
    from deepdfa_tpu.telemetry import costmodel

    costmodel.capture_compiled(
        f"bench.combined_step.{attention_impl}.t{seq_len}", step)
    flops = _costs_of_compiled(step)["flops"]
    if attention_impl == "flash":
        # XLA's cost analysis reports ~0 FLOPs for Pallas custom calls
        # (measured: 782 kFLOP vs 1.66 GFLOP for the identical dense grad),
        # so add the analytic attention count: per layer the fwd kernel
        # does 2 T×T×D matmuls and the dq + dkv backward kernels 7 more
        # (each recomputes S and dP, plus dq/dk/dv) — 9 × 2·B·H·T²·D.
        enc = model.encoder_config
        head_dim = enc.hidden_size // enc.num_heads
        flops += (9 * 2 * batch_size * enc.num_heads * seq_len**2
                  * head_dim * enc.num_layers)
    peak = _peak_flops()
    sec_per_step = dt / n_steps
    return eps, {
        "flops_per_step": flops,
        "mfu": (flops / sec_per_step) / peak if (flops and peak) else None,
    }


def _gen_decode_setup(batch_size: int = 48, src_len: int = 256):
    """(model, bf16 params, src) for bench_gen_decode — built once and
    shared between the greedy and beam runs (a full codet5-base init at
    this shape is expensive through the tunnel)."""
    import dataclasses

    import jax.numpy as jnp

    from deepdfa_tpu.models.t5 import T5Config, T5Model

    cfg = dataclasses.replace(T5Config.codet5_base(), dtype="bfloat16",
                              dropout_rate=0.0)
    model = T5Model(cfg)
    rng = np.random.RandomState(0)
    src = jnp.asarray(
        rng.randint(3, cfg.vocab_size, size=(batch_size, src_len))
        .astype(np.int32)
    )
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        src, jnp.zeros((batch_size, 4), jnp.int32),
    )
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
        params,
    )
    return model, params, src


def bench_gen_decode(beam_size: int = 1, batch_size: int = 48,
                     src_len: int = 256, max_len: int = 128,
                     n_calls: int = 3, setup=None,
                     beam_impl: str = "batched",
                     gather_impl: str = "take_along"):
    """Generation decode throughput at the summarize shape: codet5-base,
    256-token sources, 128 generated tokens, batch 48 (exp.resolve's
    reference table) — the loop the reference times in its generation eval
    (CodeT5/run_gen.py:104-123, model.generate with beams).

    tokens/s counts batch * max_len decode steps (the compute actually
    run; the scan is fixed-trip). Params are cast to bf16 for decode — the
    standard inference dtype, and the measured A/B: greedy 13.9k tok/s
    bf16 vs 11.4k f32 on v5e (beam-10 is cache-bound and indifferent).

    Round-5 findings baked into the defaults (each a back-to-back A/B on
    v5e; see models/t5.py and models/t5_generate.py):
    - decode_cache_layout="split": merged [B,T,768] storage relayouts on
      every attention read — greedy 10.0k vs split 13.9k tok/s, beam-10
      718 vs 1007.
    - Beam-deduped cross K/V (cross cache primed unreplicated, beam factor
      folded into the query axis): beam-10 went OOM -> 658 (merged layout)
      -> 1007 tok/s, and the per-step encoder K/V read dropped 10x.
    - Cross K/V out of the scan carry (closed-over constants): removes the
      risk of per-step copies of the largest buffers in the program.
    No MFU is reported: decode is HBM-bound by construction (arithmetic
    intensity ~1 FLOP/byte at batch 48 — each step re-reads the decoder
    params and the whole KV cache to produce one token per row); the
    greedy step's ~1 GB/step traffic at the measured rate is ~0.3-0.4 of
    the chip's HBM peak. Beam cache movement depends on ``beam_impl``:

    - "batched" (default, ISSUE 13): ONE physical [B*K] cache, ancestry
      resolved at attention-read time — per-step cache traffic is the
      read attention performs anyway (~2.3 GB at this shape); the
      reorder is a [B,K,T] int32 gather in the scan body.
    - "reference": the pre-ISSUE-13 formulation — the whole self cache
      take_along_axis-gathered through HBM every step (read + gather +
      write ≈ 3× the cache bytes, ~6.8 GB/step) — kept so the A/B that
      justifies the layout stays runnable per backend.

    ``gather_impl`` A/Bs how the batched read resolves ancestry
    ("take_along" vs "onehot"); the one-hot bmm reads K× the cache and
    measured a LOSS on both v5e and CPU, which is why take_along is the
    default (ISSUE 13 gate). Early exit is DISABLED here so tokens/s
    counts exactly batch * max_len steps of compute — comparable across
    impls and to the recorded trajectory.
    """
    import jax.numpy as jnp

    from deepdfa_tpu.models.t5_generate import (
        beam_search,
        beam_search_reference,
        greedy_decode,
    )

    model, params, src = setup or _gen_decode_setup(batch_size, src_len)
    # The setup's shapes are authoritative — a prebuilt setup at another
    # shape must not silently mislabel the per-example math.
    batch_size, src_len = src.shape
    if beam_impl not in ("batched", "reference"):
        raise ValueError(f"beam_impl {beam_impl!r}")

    def decode(params, src, prev):
        # Chain calls through a data dependency (the infer-bench barrier
        # pattern) so the timed sequence cannot overlap on the device.
        src = src.at[0, 0].add((prev * 0).astype(src.dtype))
        if beam_size <= 1:
            seq = greedy_decode(model, params, src, max_len)
        elif beam_impl == "reference":
            seq, _ = beam_search_reference(model, params, src, max_len,
                                           beam_size)
        else:
            seq, _ = beam_search(model, params, src, max_len, beam_size,
                                 gather_impl=gather_impl,
                                 early_exit=False)
        return seq, seq[0, 0]

    step = jax.jit(decode).lower(params, src, jnp.zeros((), jnp.int32)).compile()
    from deepdfa_tpu.telemetry import costmodel

    # Decode is HBM-bound by construction (docstring above); the capture
    # records the cost model's view of exactly that — bytes dominate.
    costmodel.capture_compiled(f"bench.gen_decode.beam{beam_size}", step,
                               steps_per_call=max_len)
    prev = jnp.zeros((), jnp.int32)

    def call():
        nonlocal prev
        out, prev = step(params, src, prev)
        return prev

    dt = _timed(call, warmup=1, calls=n_calls, trials=2)
    return batch_size * max_len * n_calls / dt


def bench_combined_infer(batch_size: int = 16) -> float:
    import jax.numpy as jnp

    # flash is the combined default since round 4; the headline inference
    # number must measure the implementation users get.
    model, batch = _combined_setup(batch_size, attention_impl="flash")
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(batch.input_ids),
        graphs=batch.graphs,
        deterministic=True,
    )

    @jax.jit
    def infer(params, ids, graphs, prev):
        # Data-depend this call's input on the previous call's output
        # (adds 0) so the timed sequence cannot overlap or reorder on the
        # device; folding it into the jitted program keeps the timed loop
        # at exactly one dispatch per step.
        ids = ids.at[0, 0].add((prev * 0).astype(ids.dtype))
        logits = model.apply(params, ids, graphs=graphs, deterministic=True)
        return logits, logits[0, 0]

    ids = jnp.asarray(batch.input_ids)
    prev = jnp.zeros((), jnp.float32)

    def call():
        nonlocal prev
        out, prev = infer(params, ids, batch.graphs, prev)
        return out

    n_steps = 30
    dt = _timed(call, warmup=3, calls=n_steps)
    return dt / (n_steps * batch_size) * 1000.0  # ms/example


# Reference hardware numbers (RTX 3090, paper Table 5 / BASELINE.md).
BASELINE_GNN_GRAPHS_PER_SEC = 7000.0
BASELINE_COMBINED_EXAMPLES_PER_SEC = 39.0
BASELINE_COMBINED_INFER_MS = 15.4
BASELINE_DEEPDFA_INFER_MS = 4.6


def bench_graftlint_full_repo(reps: int = 2) -> float:
    """Cold full-repo graftlint wall time in ms (per-file rules + the
    GL022-25 interprocedural phase; no incremental cache), best of
    ``reps``. Pure-CPU stdlib work — deterministic enough that two reps
    pin the floor."""
    from deepdfa_tpu.analysis.runner import run_analysis

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        report = run_analysis()
        dt = time.perf_counter() - t0
        assert report["files"] > 50  # measured the real package, not a stub
        best = min(best, dt)
    return best * 1e3


def main() -> None:
    graphs_per_sec, gnn_diag = bench_deepdfa("bfloat16", diagnostics=True)
    # Provisional line the moment the headline exists: the full run takes
    # ~12 min on the tunneled backend (five AOT compiles dominate), and a
    # supervisor timeout should cost the extras, not the primary metric.
    # The final complete line below is printed last and supersedes this one.
    print(
        json.dumps(
            {
                # Distinct name: a consumer grepping the headline metric
                # must never pick up or double-count the provisional line.
                "metric": "deepdfa_train_graphs_per_sec_provisional",
                "value": round(graphs_per_sec, 1),
                "unit": "graphs/s",
                "vs_baseline": round(
                    graphs_per_sec / BASELINE_GNN_GRAPHS_PER_SEC, 3
                ),
                "partial": True,
            }
        ),
        flush=True,
    )
    graphs_per_sec_f32 = bench_deepdfa("float32")
    # The tile-kernel A/B at the parity shape, re-checked every run (band
    # wins since round 4 — module docstring); on non-TPU hosts both
    # measurements fall back to segment and the A/B is a no-op.
    graphs_per_sec_tile = (
        bench_deepdfa("bfloat16", impl="tile")
        if jax.default_backend() == "tpu" else None
    )
    # The fused megakernel (ISSUE 9): one Pallas pass per gated step over
    # dense-slot-packed batches. bf16 challenges the band flagship; the
    # f32 variant is the successor the 15%-of-band acceptance gate names
    # (f32 ran at ~55% of the bf16 band path unfused). TPU-only — on CPU
    # "fused" resolves to the band composition and the A/B is a no-op.
    graphs_per_sec_fused = (
        bench_deepdfa("bfloat16", impl="fused", diagnostics=True)
        if jax.default_backend() == "tpu" else None
    )
    graphs_per_sec_fused_f32 = (
        bench_deepdfa("float32", impl="fused")
        if jax.default_backend() == "tpu" else None
    )
    # The persistent K-step megakernel (ISSUE 15): the whole n_steps
    # unroll as ONE pallas_call per direction — h VMEM-resident across
    # steps, h_0 in / h_K out the only per-unroll h HBM traffic. A/B'd
    # back-to-back against the PR-9 fused rows above under the same
    # _timed variance protocol (same process, interleaved measurements).
    # TPU-only — on CPU "persistent" resolves to the band composition
    # and the A/B is a no-op.
    graphs_per_sec_persistent = (
        bench_deepdfa("bfloat16", impl="persistent", diagnostics=True)
        if jax.default_backend() == "tpu" else None
    )
    graphs_per_sec_persistent_f32 = (
        bench_deepdfa("float32", impl="persistent")
        if jax.default_backend() == "tpu" else None
    )
    # DeepDFA-standalone inference: the paper's 4.6 ms/example finally gets
    # a comparison point (the round-5 VERDICT gap).
    deepdfa_infer_ms = bench_deepdfa_infer()
    # Serving path (deepdfa_tpu/serve): p99 + throughput on the seeded
    # bursty trace, so the request-serving trajectory is tracked like
    # training's. No reference baseline exists (the paper never serves).
    serve_report = bench_serve()
    # Replicated serving fleet (deepdfa_tpu/serve/fleet.py): 1 vs N
    # engine replicas over the same open-loop saturation trace — the
    # queue-limited -> hardware-limited throughput evidence (ISSUE 12).
    fleet_report = bench_serve_fleet()
    # Shared-nothing process fleet (deepdfa_tpu/serve/procfleet.py): 1
    # vs N engine OS processes — real spawn/warm/route/forward with the
    # per-batch cost calibrated against the live children, capacity
    # compared over independent process timelines (ISSUE 17).
    multiproc_report = bench_serve_multiproc()
    # Streaming scan path (deepdfa_tpu/scan): raw source -> pooled Joern
    # (hermetic fake transport) -> featurize -> warmed-engine score, cold
    # vs warm-cache A/B. No reference baseline (the paper never scans
    # live source).
    scan_report = bench_scan()
    # Robustness tax (deepdfa_tpu/resilience): hardened-checkpoint
    # save/restore latency and the kill-and-resume wall-clock delta —
    # tracked per round so resilience features never silently eat the
    # throughput wins above.
    ckpt_report = bench_checkpoint_resilience()
    # Data-contract tax (deepdfa_tpu/contracts): schema-validated ingestion
    # vs the raw pre-contracts loader over the same exported corpus — the
    # ISSUE-4 gate holds this under 5%.
    ingest_report = bench_ingest_validate()
    # Observability tax (deepdfa_tpu/telemetry): instrumented vs disabled
    # train loop over the same AOT step — the ISSUE-5 gate holds this
    # under 2%.
    telemetry_report = bench_telemetry_overhead()
    # Distributed-trace tax (ISSUE 14): propagation + sharding on vs
    # DEEPDFA_TELEMETRY=0 over the same warmed serve replay, same <2%
    # discipline.
    trace_prop_report = bench_trace_propagation()
    # Traffic-observatory tax (ISSUE 20): shape-sketch capture on vs the
    # capture kill switch, telemetry ON both sides, same <2% discipline.
    traffic_cap_report = bench_traffic_capture_overhead()
    combined_eps, comb_diag = bench_combined_train(attention_impl="flash",
                                                   diagnostics=True)
    # The A/B at the parity shape, re-checked every run (flash wins since
    # round 4, module docstring).
    combined_eps_blockwise = bench_combined_train(
        attention_impl="blockwise", n_steps=30
    )
    # Throughput optimum: the flash backward keeps no O(T^2) residuals, so
    # batch 64 fits one 16G chip (bs128 regresses — module docstring).
    combined_eps_bs64 = bench_combined_train(
        batch_size=64, attention_impl="flash", n_steps=30
    )
    # Long context is where the kernels earn their keep: blockwise's scan
    # backward saves per-block logits (O(T^2) across steps) and OOMs at
    # 4096 tokens (measured 54.8G needed vs 15.75G); the flash backward
    # kernels keep O(T) residuals, so the 12L combined model TRAINS at
    # 4096 on one chip — batch 8 is the measured optimum (33.8k tok/s vs
    # 30.7k at bs2 and 32.9k at bs16; remat only costs here, 24.6k).
    # No reference baseline exists — it truncates at 512 (SURVEY §5).
    # Positions past the 514-entry table clamp: a perf-shape benchmark.
    longctx_eps, longctx_diag = bench_combined_train(
        batch_size=8, attention_impl="flash", n_steps=20, seq_len=4096,
        diagnostics=True,
    )
    infer_ms = bench_combined_infer()

    baseline_gnn = BASELINE_GNN_GRAPHS_PER_SEC
    baseline_train = BASELINE_COMBINED_EXAMPLES_PER_SEC
    baseline_infer = BASELINE_COMBINED_INFER_MS

    def rnd(x, d=4):
        return None if x is None else round(x, d)

    extras = [
                    {
                        "metric": "deepdfa_train_graphs_per_sec_f32",
                        "value": round(graphs_per_sec_f32, 1),
                        "unit": "graphs/s",
                        "vs_baseline": round(graphs_per_sec_f32 / baseline_gnn, 3),
                    },
                    *(
                        [{
                            "metric": "deepdfa_train_graphs_per_sec_tile",
                            "value": round(graphs_per_sec_tile, 1),
                            "unit": "graphs/s",
                            "vs_baseline": round(
                                graphs_per_sec_tile / baseline_gnn, 3
                            ),
                            "message_impl": "tile",
                        }] if graphs_per_sec_tile is not None else []
                    ),
                    *(
                        [{
                            "metric": "deepdfa_train_graphs_per_sec_fused",
                            "value": round(graphs_per_sec_fused[0], 1),
                            "unit": "graphs/s",
                            "vs_baseline": round(
                                graphs_per_sec_fused[0] / baseline_gnn, 3
                            ),
                            "message_impl": "fused",
                            "mfu": rnd(graphs_per_sec_fused[1]["mfu"]),
                            "flops_per_step":
                                graphs_per_sec_fused[1]["flops_per_step"],
                            "ms_per_step": rnd(
                                graphs_per_sec_fused[1]["ms_per_step"]),
                        }] if graphs_per_sec_fused is not None else []
                    ),
                    *(
                        [{
                            "metric":
                                "deepdfa_train_graphs_per_sec_fused_f32",
                            "value": round(graphs_per_sec_fused_f32, 1),
                            "unit": "graphs/s",
                            "vs_baseline": round(
                                graphs_per_sec_fused_f32 / baseline_gnn, 3
                            ),
                            "message_impl": "fused",
                            "dtype": "float32",
                        }] if graphs_per_sec_fused_f32 is not None else []
                    ),
                    *(
                        [{
                            "metric":
                                "deepdfa_train_graphs_per_sec_persistent",
                            "value": round(graphs_per_sec_persistent[0], 1),
                            "unit": "graphs/s",
                            "vs_baseline": round(
                                graphs_per_sec_persistent[0] / baseline_gnn,
                                3,
                            ),
                            # The in-protocol A/B this row exists for:
                            # persistent vs the PR-9 per-step fused
                            # megakernel, measured back-to-back.
                            "vs_fused": round(
                                graphs_per_sec_persistent[0]
                                / graphs_per_sec_fused[0], 3
                            ) if graphs_per_sec_fused else None,
                            "message_impl": "persistent",
                            "mfu": rnd(graphs_per_sec_persistent[1]["mfu"]),
                            # The MFU's FLOPs numerator includes the
                            # hand-counted Pallas kernel work — say so
                            # (the roofline `source` discipline).
                            "flops_source": "xla+analytic",
                            "flops_per_step":
                                graphs_per_sec_persistent[1][
                                    "flops_per_step"],
                            "ms_per_step": rnd(
                                graphs_per_sec_persistent[1]["ms_per_step"]),
                        }] if graphs_per_sec_persistent is not None else []
                    ),
                    *(
                        [{
                            "metric":
                                "deepdfa_train_graphs_per_sec_persistent_f32",
                            "value": round(
                                graphs_per_sec_persistent_f32, 1),
                            "unit": "graphs/s",
                            "vs_baseline": round(
                                graphs_per_sec_persistent_f32
                                / baseline_gnn, 3
                            ),
                            "vs_fused": round(
                                graphs_per_sec_persistent_f32
                                / graphs_per_sec_fused_f32, 3
                            ) if graphs_per_sec_fused_f32 else None,
                            "message_impl": "persistent",
                            "dtype": "float32",
                        }] if graphs_per_sec_persistent_f32 is not None
                        else []
                    ),
                    {
                        "metric": "deepdfa_infer_ms_per_example",
                        "value": round(deepdfa_infer_ms, 4),
                        "unit": "ms",
                        # ratio >1 = faster than the 3090 here (time metric)
                        "vs_baseline": round(
                            BASELINE_DEEPDFA_INFER_MS / deepdfa_infer_ms, 3
                        ),
                        "batch_size": 256,
                    },
                    {
                        "metric": "serve_p99_ms",
                        "value": round(serve_report["p99_ms"], 3),
                        "unit": "ms",
                        "vs_baseline": None,  # the reference never serves
                        "p50_ms": round(serve_report["p50_ms"], 3),
                        "occupancy": round(serve_report["occupancy"], 3),
                        "cache_hit_rate": round(
                            serve_report["cache_hit_rate"], 3
                        ),
                        # MUST be 0: the warmed-bucket invariant.
                        "compiles_after_warmup":
                            serve_report["compiles_after_warmup"],
                        "n_requests": serve_report["n_requests"],
                        "batch_slots": 16,
                    },
                    {
                        "metric": "serve_graphs_per_sec",
                        "value": round(serve_report["graphs_per_sec"], 1),
                        "unit": "graphs/s",
                        "vs_baseline": None,
                        "n_requests": serve_report["n_requests"],
                        "dropped": serve_report["dropped"],
                    },
                    {
                        # N-replica saturation throughput over the same
                        # open-loop trace as single_replica_rps — the
                        # fleet's 1-vs-N evidence (ISSUE 12 gate: the
                        # speedup must clear 2x).
                        "metric": "serve_fleet_rps",
                        "value": round(fleet_report["serve_fleet_rps"], 1),
                        "unit": "req/s",
                        "vs_baseline": None,  # the reference never serves
                        "replicas": fleet_report["replicas"],
                        "single_replica_rps": round(
                            fleet_report["single_replica_rps"], 1),
                        "speedup": rnd(fleet_report["speedup"], 2),
                        "offered_rps": round(
                            fleet_report["offered_rps"], 1),
                        "shed": fleet_report["shed"],
                        # MUST be 0 fleet-wide: the warmed-bucket
                        # invariant holds per replica.
                        "compiles_after_warmup":
                            fleet_report["compiles_after_warmup"],
                    },
                    {
                        "metric": "serve_fleet_p99_ms",
                        "value": round(
                            fleet_report["serve_fleet_p99_ms"], 3),
                        "unit": "ms",
                        "vs_baseline": None,
                        "p50_ms": round(
                            fleet_report["serve_fleet_p50_ms"], 3),
                        "single_replica_p99_ms": round(
                            fleet_report["single_replica_p99_ms"], 3),
                        "replicas": fleet_report["replicas"],
                    },
                    {
                        # N-process capacity over the same open-loop
                        # trace as single_process_rps — the shared-
                        # nothing tier's 1-vs-N evidence (ISSUE 17
                        # gate: the speedup must clear 2x), calibrated
                        # against real spawned engine children.
                        "metric": "serve_multiproc_rps",
                        "value": round(
                            multiproc_report["serve_multiproc_rps"], 1),
                        "unit": "req/s",
                        "vs_baseline": None,  # the reference never serves
                        "processes": multiproc_report["processes"],
                        "single_process_rps": round(
                            multiproc_report["single_process_rps"], 1),
                        "speedup": rnd(multiproc_report["speedup"], 2),
                        "cost_ms": round(multiproc_report["cost_ms"], 2),
                        "offered_rps": round(
                            multiproc_report["offered_rps"], 1),
                        "shed": multiproc_report["shed"],
                        # MUST be 0 fleet-wide: each child's warmup
                        # baseline, audited through the router.
                        "compiles_after_warmup":
                            multiproc_report["compiles_after_warmup"],
                    },
                    {
                        "metric": "serve_multiproc_p99_ms",
                        "value": round(
                            multiproc_report["serve_multiproc_p99_ms"], 3),
                        "unit": "ms",
                        "vs_baseline": None,
                        "p50_ms": round(
                            multiproc_report["serve_multiproc_p50_ms"], 3),
                        "deadline_ms": multiproc_report["deadline_ms"],
                        "single_process_p99_ms": round(
                            multiproc_report["single_process_p99_ms"], 3),
                        "processes": multiproc_report["processes"],
                    },
                    {
                        "metric": "scan_cold_ms_per_func",
                        "value": round(
                            scan_report["scan_cold_ms_per_func"], 2),
                        "unit": "ms",
                        "vs_baseline": None,  # the reference never scans
                        "n_functions": scan_report["n_functions"],
                        "transport": "fake_joern",
                        # MUST be 0: scan reuses the warmed serve
                        # executables (the zero-new-compiles contract).
                        "compiles_after_warmup":
                            scan_report["compiles_after_warmup"],
                    },
                    {
                        "metric": "scan_warm_cache_hit_pct",
                        # Unit "hit%" (not "%"): benchwatch directions are
                        # unit-derived and a hit RATE regresses downward —
                        # plain "%" metrics are overheads (lower-better).
                        "value": round(
                            scan_report["scan_warm_cache_hit_pct"], 2),
                        "unit": "hit%",
                        "vs_baseline": None,
                        "expected_pct": round(
                            scan_report["expected_warm_hit_pct"], 2),
                        "warm_requests": scan_report["warm_requests"],
                        "warm_errors": scan_report["warm_errors"],
                        "pool_restarts": scan_report["pool_restarts"],
                    },
                    {
                        "metric": "ckpt_save_ms",
                        "value": round(ckpt_report["ckpt_save_ms"], 2),
                        "unit": "ms",
                        "vs_baseline": None,  # the reference never hardens
                    },
                    {
                        # Step-loop stall of one save under the async
                        # manager (submit + host-copy start) — the A/B
                        # against ckpt_save_ms above is what the async
                        # layer buys every epoch (ISSUE 6 gate).
                        "metric": "ckpt_async_blocking_ms",
                        "value": round(
                            ckpt_report["ckpt_async_blocking_ms"], 3),
                        "unit": "ms",
                        "vs_baseline": None,
                    },
                    {
                        "metric": "ckpt_restore_ms",
                        "value": round(ckpt_report["ckpt_restore_ms"], 2),
                        "unit": "ms",
                        "vs_baseline": None,
                    },
                    {
                        # Signal delivery -> committed durable preempt
                        # snapshot (ISSUE 10): the preemption drain's
                        # critical path, measured with a real
                        # self-SIGTERM through the lifecycle coordinator.
                        "metric": "sigterm_to_durable_snapshot_ms",
                        "value": round(
                            ckpt_report["sigterm_to_durable_snapshot_ms"],
                            2),
                        "unit": "ms",
                        "vs_baseline": None,  # the reference just dies
                    },
                    {
                        # One snapshot rewritten for a different process
                        # count (ISSUE 18): the elastic-resume critical
                        # path. Headline = 2→1 consolidate (plain-orbax
                        # rewrite); fast = 4→2 hardlink re-home.
                        "metric": "ckpt_redistribute_ms",
                        "value": round(
                            ckpt_report["ckpt_redistribute_ms"], 2),
                        "unit": "ms",
                        "vs_baseline": None,  # the reference can't shrink
                        "fast_4_to_2_ms": round(
                            ckpt_report["ckpt_redistribute_fast_ms"], 2),
                    },
                    {
                        "metric": "resume_overhead_s",
                        "value": round(ckpt_report["resume_overhead_s"], 2),
                        "unit": "s",
                        "vs_baseline": None,
                        # MUST be true: the kill-and-resume determinism
                        # invariant, re-asserted in the bench lane.
                        "bitwise_match": ckpt_report["resume_bitwise_match"],
                    },
                    {
                        "metric": "ingest_validate_overhead_pct",
                        "value": round(ingest_report["overhead_pct"], 2),
                        "unit": "%",
                        # new capability: the reference ingests unchecked
                        "vs_baseline": None,
                        "raw_rows_per_sec": round(
                            ingest_report["raw_rows_per_sec"], 1),
                        "validated_rows_per_sec": round(
                            ingest_report["validated_rows_per_sec"], 1),
                        "n_rows": ingest_report["n_rows"],
                    },
                    {
                        "metric": "telemetry_overhead_pct",
                        "value": round(telemetry_report["overhead_pct"], 2),
                        "unit": "%",
                        # new capability: the reference has no telemetry
                        "vs_baseline": None,
                        # MUST stay true: the <2% observability-tax gate.
                        "gate_ok": telemetry_report["gate_ok"],
                        "gate_pct": telemetry_report["gate_pct"],
                        "instrumented_steps_per_sec": round(
                            telemetry_report["instrumented_steps_per_sec"],
                            1),
                        "disabled_steps_per_sec": round(
                            telemetry_report["disabled_steps_per_sec"], 1),
                        "n_steps": telemetry_report["n_steps"],
                    },
                    {
                        # Distributed-trace tax (ISSUE 14): traceparent
                        # continuation + shard-writing on vs
                        # DEEPDFA_TELEMETRY=0, same warmed serve replay.
                        "metric": "trace_propagation_overhead_pct",
                        "value": round(
                            trace_prop_report["overhead_pct"], 2),
                        "unit": "%",
                        # new capability: the reference has no trace plane
                        "vs_baseline": None,
                        # MUST stay true: the <2% observability-tax gate.
                        "gate_ok": trace_prop_report["gate_ok"],
                        "gate_pct": trace_prop_report["gate_pct"],
                        "instrumented_rps": round(
                            trace_prop_report["instrumented_rps"], 1),
                        "disabled_rps": round(
                            trace_prop_report["disabled_rps"], 1),
                        "n_requests": trace_prop_report["n_requests"],
                    },
                    {
                        # Traffic-observatory tax (ISSUE 20): shape
                        # capture on vs the sketch kill switch, telemetry
                        # on both sides — isolates the observatory's own
                        # submit-path cost.
                        "metric": "traffic_capture_overhead_pct",
                        "value": round(
                            traffic_cap_report["overhead_pct"], 2),
                        "unit": "%",
                        # new capability: the reference has no observatory
                        "vs_baseline": None,
                        # MUST stay true: the <2% observability-tax gate.
                        "gate_ok": traffic_cap_report["gate_ok"],
                        "gate_pct": traffic_cap_report["gate_pct"],
                        "captured_rps": round(
                            traffic_cap_report["captured_rps"], 1),
                        "uncaptured_rps": round(
                            traffic_cap_report["uncaptured_rps"], 1),
                        "n_requests": traffic_cap_report["n_requests"],
                    },
                    {
                        "metric": "combined_train_examples_per_sec",
                        "value": round(combined_eps, 2),
                        "unit": "examples/s",
                        "vs_baseline": round(combined_eps / baseline_train, 3),
                        "mfu": rnd(comb_diag["mfu"]),
                        "flops_per_step": comb_diag["flops_per_step"],
                        "attention_impl": "flash",
                    },
                    {
                        "metric": "combined_train_examples_per_sec_blockwise",
                        "value": round(combined_eps_blockwise, 2),
                        "unit": "examples/s",
                        "vs_baseline": round(
                            combined_eps_blockwise / baseline_train, 3
                        ),
                        "attention_impl": "blockwise",
                    },
                    {
                        "metric": "combined_train_examples_per_sec_bs64",
                        "value": round(combined_eps_bs64, 2),
                        "unit": "examples/s",
                        "vs_baseline": round(
                            combined_eps_bs64 / baseline_train, 3
                        ),
                        "attention_impl": "flash",
                        "batch_size": 64,
                    },
                    {
                        "metric": "longcontext_train_tokens_per_sec",
                        "value": round(longctx_eps * 4096),
                        "unit": "tokens/s",
                        # the reference truncates at 512 tokens — no
                        # baseline exists for this capability
                        "vs_baseline": None,
                        # Efficiency context like every other headline
                        # (attention FLOPs counted analytically — Pallas
                        # kernels are invisible to XLA's cost analysis;
                        # the backward's recompute counts as real work).
                        "mfu": rnd(longctx_diag["mfu"]),
                        "flops_per_step": longctx_diag["flops_per_step"],
                        "attention_impl": "flash",
                        "seq_len": 4096,
                        "batch_size": 8,
                    },
                    {
                        "metric": "combined_infer_ms_per_example",
                        "value": round(infer_ms, 3),
                        "unit": "ms",
                        # ratio >1 = faster than the 3090 here (time metric)
                        "vs_baseline": round(baseline_infer / infer_ms, 3),
                        "attention_impl": "flash",
                    },
    ]

    def headline(extra, **flags):
        return {
            "metric": "deepdfa_train_graphs_per_sec",
            "value": round(graphs_per_sec, 1),
            "unit": "graphs/s",
            "vs_baseline": round(graphs_per_sec / baseline_gnn, 3),
            # Perf accounting for the headline: cost-model FLOPs and MFU
            # against the chip's bf16 peak. The step is fwd+bwd compute
            # (HBM-bound at hidden 128), NOT dispatch or optimizer
            # overhead — the ablation record is in the module docstring.
            "mfu": rnd(gnn_diag["mfu"]),
            "flops_per_step": gnn_diag["flops_per_step"],
            "ms_per_step": rnd(gnn_diag["ms_per_step"]),
            **flags,
            "extra": extra,
        }

    # Second safety line: everything above is measured; the decode stage
    # below adds a codet5-base init + two more compiles (~5 min through
    # the tunnel), and a supervisor timeout there must cost only the
    # decode extras, not the whole record. EVERY metric name in this line
    # (top-level and nested) carries the _predecode suffix so a consumer
    # aggregating stdout by name never double-counts anything.
    print(json.dumps(headline(
        [{**e, "metric": e["metric"] + "_predecode"} for e in extras],
        partial=True,
        metric="deepdfa_train_graphs_per_sec_predecode",
    )), flush=True)

    # Generation decode (round-5 addition): greedy + the reference's
    # beam-10 eval decoding at the summarize shape. No baseline number
    # exists (BASELINE.md has no decode measurement); HBM-bound — see
    # bench_gen_decode's docstring for the rationale and the layout/dedup
    # A/Bs behind the defaults. Since ISSUE 13 the beam metric measures
    # the batched ancestry-cache implementation; the _ref row is the same
    # shape on the old gather-every-step formulation, so the history
    # carries the A/B that justifies the layout (the pre-13 v5e rows of
    # gen_decode_tokens_per_sec_beam10 ARE the reference trajectory).
    decode_setup = _gen_decode_setup()
    decode_greedy = bench_gen_decode(beam_size=1, setup=decode_setup)
    decode_beam10 = bench_gen_decode(beam_size=10, n_calls=2,
                                     setup=decode_setup)
    decode_beam10_ref = bench_gen_decode(beam_size=10, n_calls=2,
                                         setup=decode_setup,
                                         beam_impl="reference")
    extras += [
        {
            "metric": "gen_decode_tokens_per_sec",
            "value": round(decode_greedy, 1),
            "unit": "tokens/s",
            "vs_baseline": None,  # no reference decode number
            "beam_size": 1,
            "batch_size": 48,
            "model": "codet5_base",
            "src_len": 256,
            "max_len": 128,
        },
        {
            "metric": "gen_decode_tokens_per_sec_beam10",
            "value": round(decode_beam10, 1),
            "unit": "tokens/s",
            "vs_baseline": None,
            "beam_size": 10,
            "batch_size": 48,
            "model": "codet5_base",
            "src_len": 256,
            "max_len": 128,
            "beam_impl": "batched",
            "vs_reference_impl": (round(decode_beam10 / decode_beam10_ref,
                                        3) if decode_beam10_ref else None),
        },
        {
            "metric": "gen_decode_tokens_per_sec_beam10_ref",
            "value": round(decode_beam10_ref, 1),
            "unit": "tokens/s",
            "vs_baseline": None,
            "beam_size": 10,
            "batch_size": 48,
            "model": "codet5_base",
            "src_len": 256,
            "max_len": 128,
            "beam_impl": "reference",
        },
    ]
    # graftlint cost trajectory: the analyzer just went interprocedural
    # (call graph + GL022-25 concurrency phase over every file), so its
    # full-repo cold wall time is gated like kernel perf — a rule that
    # quietly goes quadratic should fail bench diff, not CI patience.
    extras.append({
        "metric": "graftlint_full_repo_ms",
        "value": round(bench_graftlint_full_repo(), 1),
        "unit": "ms",
        "vs_baseline": None,
    })

    final = headline(extras)
    print(json.dumps(final))

    # Bench-regression observatory: every completed run appends one
    # env-fingerprinted row to benchmarks/history.jsonl, the trajectory
    # `cli bench diff` gates against. Never lets bookkeeping fail the
    # measurement that just finished printing.
    try:
        from deepdfa_tpu import benchwatch

        benchwatch.append_history(benchwatch.flatten_record(final),
                                  source="bench.py")
    except Exception:
        import traceback

        traceback.print_exc()


if __name__ == "__main__":
    main()

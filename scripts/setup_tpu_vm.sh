#!/bin/bash
# Provision a Cloud TPU VM for deepdfa_tpu (replaces the reference's
# SLURM/Singularity story, scripts/sbatch.sh + Dockerfile — TPU fleets are
# provisioned per-VM, not via a cluster scheduler).
#
# Usage: bash scripts/setup_tpu_vm.sh [v5litepod-8]
# Prereqs: gcloud configured with a project/zone that has TPU quota.
set -e
ACCEL="${1:-v5litepod-8}"
NAME="${TPU_NAME:-deepdfa-tpu}"
ZONE="${TPU_ZONE:-us-central1-a}"

gcloud compute tpus tpu-vm create "$NAME" \
  --zone "$ZONE" --accelerator-type "$ACCEL" \
  --version "${TPU_RUNTIME:-tpu-ubuntu2204-base}"

gcloud compute tpus tpu-vm ssh "$NAME" --zone "$ZONE" --command '
  sudo apt-get update -y && sudo apt-get install -y git openjdk-17-jdk-headless
  pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
  pip install flax optax orbax-checkpoint chex einops pandas pyyaml pytest
'
echo "TPU VM $NAME ready. Copy the repo and run: python -m pytest tests/ -q"
echo "Multi-host slices: run the same command on every worker; deepdfa_tpu"
echo "training loops detect jax.process_count()>1 and shard input per host."

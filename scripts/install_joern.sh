#!/bin/bash
# Install Joern v1.1.107 (the version the paper's artifact pins,
# reference scripts/install_joern.sh) into ./joern/. The ETL graphs stage
# (deepdfa_tpu/etl/joern_session.py) looks for `joern` on PATH; add
# $PWD/joern/joern to PATH or symlink it after install.
# Requires: JDK 11+, curl. Joern is CPU/JVM-side only — no TPU involvement.
set -e
mkdir -p joern
cd joern
curl -L "https://github.com/joernio/joern/releases/latest/download/joern-install.sh" -o joern-install.sh
chmod u+x joern-install.sh
printf "y\n$PWD/joern\nn\nv1.1.107\n" | ./joern-install.sh --interactive --without-plugins

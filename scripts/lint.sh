#!/bin/bash
# graftlint: dataflow-analysis-based static checking for JAX/TPU hazards
# (deepdfa_tpu/analysis/) over this repo's own sources. Exits nonzero on any
# finding not in configs/lint_baseline.json — the CI gate. Regenerate the
# baseline after a deliberate suppression with:
#   python -m deepdfa_tpu.cli analyze-code --write-baseline
# CI runs cold (full repo, every rule incl. the GL022-GL025 interprocedural
# concurrency phase). For fast local iteration pass --incremental: only
# changed files + their importers re-run the per-file phase, keyed on each
# file's sha256 in .graftlint_cache.json (gitignored):
#   scripts/lint.sh --incremental
set -e
cd "$(dirname "$0")/.."
# The analyzer is stdlib-only, but the CLI module imports jax-adjacent
# config; pin the CPU platform so a TPU plugin can never stall a lint.
JAX_PLATFORMS=cpu python -m deepdfa_tpu.cli analyze-code "$@"

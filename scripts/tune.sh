#!/bin/bash
# Hyperparameter search (the reference's NNI loop, main_cli.py:110-121).
set -e
cd "$(dirname "$0")/.."
python -m deepdfa_tpu.cli tune --config configs/default.yaml \
  --trials "${TRIALS:-8}" --epochs-per-trial "${EPOCHS:-3}" "$@"

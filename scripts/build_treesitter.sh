#!/bin/bash
# Build the tree-sitter grammar bundle the reference's CodeBLEU
# syntax/dataflow components parse with (CodeT5/evaluator/CodeBLEU/parser/
# build.sh:1-8 -> build.py:1-21 -> my-languages.so).
#
# The build image has neither network access nor the tree_sitter package,
# so the framework ships a self-contained parser
# (deepdfa_tpu/eval/codebleu/parser.py) whose syntax/dataflow semantics are
# pinned by hand-verified goldens (tests/test_codebleu.py) and a written
# divergence contract (deepdfa_tpu/eval/codebleu/DIVERGENCES.md). Run this
# script in an environment with git+pip to produce the real grammar bundle;
# wiring it in is then a parser swap behind the same metric surface.
set -e
cd "$(dirname "$0")/.."
DEST=${1:-deepdfa_tpu/eval/codebleu/ts}
python -c "import tree_sitter" 2>/dev/null || {
  echo "error: pip install tree_sitter first" >&2; exit 1; }
mkdir -p "$DEST"
cd "$DEST"
# The reference's grammar list (build.sh) plus c/cpp, which Big-Vul code
# actually is (the reference parses C through the java grammar's C-family
# tolerance; having the real grammars available is strictly better).
LANGS="go javascript python php java ruby c-sharp c cpp"
for lang in $LANGS; do
  [ -d "tree-sitter-$lang" ] || \
    git clone --depth 1 "https://github.com/tree-sitter/tree-sitter-$lang"
done
python - <<'PY'
from tree_sitter import Language

langs = ["go", "javascript", "python", "php", "java", "ruby", "c-sharp",
         "c", "cpp"]
Language.build_library("my-languages.so",
                       [f"tree-sitter-{l}" for l in langs])
print("built my-languages.so")
PY

#!/bin/bash
# Chaos soak (deepdfa_tpu/resilience): deterministic fault-injection run
# covering five fault classes — simulated preemption (kill-and-resume must
# be bit-for-bit deterministic), NaN loss (rollback self-healing),
# checkpoint corruption (checksum fallback), ETL item failure (attempt-cap
# requeue), serving flush failure (one flush fails alone). Exits nonzero on
# any missed recovery contract — the scripts/test.sh gate.
#
#   bash scripts/chaos.sh                      # the default soak
#   bash scripts/chaos.sh --epochs 4           # deeper training scenarios
# (custom fault plans arm via DEEPDFA_FAULT_PLAN against regular commands;
#  the soak's scenarios arm their own plans)
set -e
cd "$(dirname "$0")/.."
# CPU pin: the soak verifies *control-plane* behavior (resume, fallback,
# retry) and its determinism gate compares runs within one process; the
# tunneled TPU plugin adds nothing but variance here.
JAX_PLATFORMS=cpu python -m deepdfa_tpu.cli chaos \
  --out-dir "${CHAOS_DIR:-runs/chaos}" "$@"

#!/bin/bash
# Chaos soak (deepdfa_tpu/resilience): deterministic fault-injection run
# covering thirteen fault classes — simulated preemption (kill-and-resume
# must be bit-for-bit deterministic), NaN loss (rollback self-healing),
# checkpoint corruption (checksum fallback), ETL item failure (attempt-cap
# requeue), serving flush failure (one flush fails alone), corrupt-corpus
# quarantine, a mid-epoch kill under ASYNC checkpointing resumed on a
# different device count (elastic reshape), pooled Joern workers
# killed/hung mid-scan (fake transport; retry on a fresh worker +
# quarantine on attempt-cap, the sweep completes with an exact manifest),
# a REAL SIGTERM to a mid-epoch `cli fit` subprocess (preempt_drain:
# step-granular preempt snapshot, bit-continuous mid-epoch resume, and the
# hung-step watchdog forcing a durable exit out of a wedged step), a
# SIGTERM lame-duck drain of a live `cli serve` subprocess under load
# (serve_lame_duck: zero dropped admitted requests, 503 + Retry-After for
# new ones, drain inside the grace budget, compiles flat), and a rolling
# replica drain of a 3-replica serving fleet mid-load (fleet_roll: the
# rolled replica's admissions all answered, the other two keep serving,
# /healthz degrades then recovers, zero compiles across the roll), and a
# SIGKILL of one of three engine OS processes behind the router tier
# under live load (proc_crash: zero dropped admitted requests, the router
# sheds to siblings, a warmed replacement rejoins at a bumped generation,
# one merged trace shows kill/shed/rejoin across real pids), and a SIGTERM
# to one member of a live two-process `jax.distributed` training fleet
# (elastic_shrink: coordinated drain barrier — both processes exit
# preempted behind ONE sharded preempt snapshot — then a single-process
# --resume redistributes the checkpoint 2→1 and the loss history stays
# continuous with the uninterrupted fleet).
# Exits nonzero on any missed recovery contract — the scripts/test.sh gate.
#
#   bash scripts/chaos.sh                      # the default soak
#   bash scripts/chaos.sh --epochs 4           # deeper training scenarios
# (custom fault plans arm via DEEPDFA_FAULT_PLAN against regular commands;
#  the soak's scenarios arm their own plans)
set -e
cd "$(dirname "$0")/.."
# CPU pin: the soak verifies *control-plane* behavior (resume, fallback,
# retry) and its determinism gate compares runs within one process; the
# tunneled TPU plugin adds nothing but variance here. The virtual 8-device
# mesh (same recipe as tests/conftest.py) gives the elastic scenario a real
# 4 -> 2 data-parallel reshape to resume across.
_xla_flags="${XLA_FLAGS:-}"
case "$_xla_flags" in
  *xla_force_host_platform_device_count*) ;;
  *) _xla_flags="$_xla_flags --xla_force_host_platform_device_count=8" ;;
esac
JAX_PLATFORMS=cpu XLA_FLAGS="$_xla_flags" PALLAS_AXON_POOL_IPS= \
  python -m deepdfa_tpu.cli chaos \
  --out-dir "${CHAOS_DIR:-runs/chaos}" "$@"

#!/bin/bash
# DeepDFA evaluation from the best checkpoint (reference DDFA/scripts/test.sh).
set -e
cd "$(dirname "$0")/.."
python -m deepdfa_tpu.cli test --config configs/default.yaml \
  --checkpoint-dir "${CHECKPOINT_DIR:-runs/deepdfa}" --which best "$@"

#!/bin/bash
# DeepDFA evaluation from the best checkpoint (reference DDFA/scripts/test.sh).
set -e
cd "$(dirname "$0")/.."
# Static-analysis gate first: an eval run on code with a fresh TPU hazard
# (graftlint finding) should fail in seconds, not after the checkpoint load.
bash scripts/lint.sh
python -m deepdfa_tpu.cli test --config configs/default.yaml \
  --checkpoint-dir "${CHECKPOINT_DIR:-runs/deepdfa}" --which best "$@"

#!/bin/bash
# DeepDFA evaluation from the best checkpoint (reference DDFA/scripts/test.sh).
set -e
cd "$(dirname "$0")/.."
# Static-analysis gate first: an eval run on code with a fresh TPU hazard
# (graftlint finding) should fail in seconds, not after the checkpoint load.
bash scripts/lint.sh
# Serving smoke: the full HTTP stack (bucket warmup -> micro-batcher ->
# content cache) self-driven with synthetic requests on a tiny random-init
# model — seconds, and it fails before the slow eval does. The smoke is
# SLO-checked (ISSUE 7): its trace is gated on the built-in "smoke" spec,
# so a post-warmup recompile or a p99 blowout exits nonzero here, not as
# a log line. Checkpoint env vars are cleared: the smoke's tiny --set
# shapes must not try to load the eval checkpoint below. --gen-lane
# (ISSUE 13) warms the generation lane's (slot, src-length) decode
# ladder too, serves lane="gen" rounds over real HTTP, and the same SLO
# gate asserts compiles_after_warmup=0 ACROSS it. Every smoke POST
# carries a traceparent header (ISSUE 14): the smoke exits nonzero
# unless the merged-shard trace report shows propagation coverage > 0
# AND at least one client.request span joined to its serve.request span
# by trace id.
CHECKPOINT_DIR= COMBINED_DIR= GEN_DIR= bash scripts/serve.sh --smoke 8 \
  --batch-slots 4 --port 0 \
  --gen-lane --gen-src-len 32 --gen-max-len 8 --gen-beam 2 \
  --set model.hidden_dim=8 --set model.n_steps=2
# The same smoke with the observatory fully disabled: DEEPDFA_TELEMETRY=0
# must keep serving functional with no trace, no SLO gate, and no
# events.jsonl (the bit-identical-when-disabled contract; the training
# history half of it is asserted in tier-1 tests).
CHECKPOINT_DIR= COMBINED_DIR= DEEPDFA_TELEMETRY=0 bash scripts/serve.sh \
  --smoke 8 --batch-slots 4 --port 0 \
  --set model.hidden_dim=8 --set model.n_steps=2
# Bench-regression gate (deepdfa_tpu/benchwatch): the seconds-sized smoke
# benchmarks measured, compared variance-aware against the recorded
# trajectory for THIS environment fingerprint, and appended. First run in
# a fresh environment seeds the history; later runs fail on regressions.
# Base band 35%: the shared-CPU container's A/A spread exceeds 10% even
# best-of-reps (bench.py module docstring) — the gate is for mechanism
# regressions (a host sync in the step loop, a quadratic validator), not
# for chasing CI-box noise; the tolerance auto-widens further once the
# history shows more spread.
JAX_PLATFORMS=cpu python -m deepdfa_tpu.cli bench diff --smoke \
  --tolerance-pct 35
# Data-contract smoke (deepdfa_tpu/contracts): a seeded corrupt corpus is
# ingested and every corruption class must be repaired or quarantined
# under its expected reason code — seconds, fail-closed.
JAX_PLATFORMS=cpu python -m deepdfa_tpu.cli validate --smoke
# Telemetry smoke (deepdfa_tpu/telemetry): a tiny instrumented fit writes
# runs/<run>/telemetry/{events.jsonl,trace.json} and `trace report` must
# round-trip step timings, the host/device split, compile capture
# (post-warmup compiles 0), and a valid Perfetto-loadable trace.json.
# ISSUE 14: the smoke also forks a real pmap worker pool inside the run
# — the merged-shard report must show >= 2 named processes (the workers'
# events land in their own events-<proc>-<pid>.jsonl shards), the
# Chrome view must carry >= 2 emitter pids with M-phase process
# metadata, and zero torn rows.
JAX_PLATFORMS=cpu python -m deepdfa_tpu.cli trace --smoke
# Scan smoke (deepdfa_tpu/scan): hermetic fake-Joern end-to-end — sweep a
# seeded mini-corpus through the pooled-session → featurize → warmed-engine
# path, edit ONE function, re-scan, and assert exactly the changed function
# re-featurized (one cache miss), untouched verdicts byte-identical, and
# zero serve-engine compiles after warmup. No JVM, single device, seconds.
JAX_PLATFORMS=cpu python -m deepdfa_tpu.cli scan --smoke
# Elastic-fleet smoke (deepdfa_tpu/resilience/elastic): TWO real
# jax.distributed-joined `cli fit` processes on the virtual CPU mesh
# (gloo collectives) train one run dir of 2-process sharded snapshots —
# the multi-controller bring-up check (coordination service, collective
# step, sharded snapshot rendezvous) in under a minute, before the soak
# leans on the same harness to kill half the fleet.
JAX_PLATFORMS=cpu python -m deepdfa_tpu.resilience.elastic --smoke
# Chaos soak: thirteen injected fault classes against a tiny run — resume
# determinism, NaN rollback, checkpoint-corruption fallback, ETL requeue,
# serving flush isolation, corrupt-corpus quarantine+bitwise-clean
# training, a mid-epoch kill under async checkpointing resumed on a
# different device count, pooled Joern workers killed/hung mid-scan
# (retry + quarantine, the sweep still completes), a REAL SIGTERM to a
# mid-epoch fit subprocess (preempt_drain: step-granular snapshot,
# bit-continuous mid-epoch resume, hung-step watchdog; ISSUE 14: the
# fit children join the soak's trace plane via DEEPDFA_TRACE_CONTEXT —
# their drain/hang spans are asserted from the PARENT run's merged
# trace, which must render main + both children as distinct named
# processes in ONE trace.json), a SIGTERM
# lame-duck drain of a live serve subprocess (serve_lame_duck: zero
# dropped admitted requests, 503 for new ones), and a rolling replica
# drain of a 3-replica serving fleet mid-load (fleet_roll: admissions
# all answered, survivors keep serving, /healthz degrades-then-recovers,
# compiles flat), and a SIGTERM to one member of a two-process training
# fleet (elastic_shrink: coordinated drain, both exit preempted, 2→1
# checkpoint redistribution on resume, continuous loss history). Fails
# in minutes if a recovery contract regressed; the eval below would
# never notice.
bash scripts/chaos.sh
python -m deepdfa_tpu.cli test --config configs/default.yaml \
  --checkpoint-dir "${CHECKPOINT_DIR:-runs/deepdfa}" --which best "$@"

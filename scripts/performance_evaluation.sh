#!/bin/bash
# Three-model evaluation pipeline (reference scripts/performance_evaluation.sh):
# DeepDFA alone, then the combined transformer variants, then profiling.
set -e
cd "$(dirname "$0")/.."
DATASET="${DATASET:-synthetic:256}"

echo "== DeepDFA =="
python -m deepdfa_tpu.cli fit --config configs/default.yaml \
  --dataset "$DATASET" --set train.max_epochs="${EPOCHS:-5}" \
  --checkpoint-dir runs/perf_deepdfa

echo "== DeepDFA test (with Table-5 profiling) =="
python -m deepdfa_tpu.cli test --config configs/default.yaml \
  --dataset "$DATASET" --checkpoint-dir runs/perf_deepdfa --which best \
  --profile --time
python -m deepdfa_tpu.eval.report runs/perf_deepdfa/profiledata.jsonl \
  runs/perf_deepdfa/timedata.jsonl

echo "== bench =="
python bench.py

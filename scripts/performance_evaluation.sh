#!/bin/bash
# Three-model evaluation pipeline (reference scripts/performance_evaluation.sh):
# DeepDFA alone, the combined DeepDFA+LineVul model (its encoder loaded from
# the DeepDFA run), combined profiling, then the bench.
set -e
cd "$(dirname "$0")/.."
DATASET="${DATASET:-synthetic:256}"

echo "== DeepDFA =="
python -m deepdfa_tpu.cli fit --config configs/default.yaml \
  --dataset "$DATASET" --set train.max_epochs="${EPOCHS:-5}" \
  --checkpoint-dir runs/perf_deepdfa

echo "== DeepDFA test (with Table-5 profiling) =="
python -m deepdfa_tpu.cli test --config configs/default.yaml \
  --dataset "$DATASET" --checkpoint-dir runs/perf_deepdfa --which best \
  --profile --time
python -m deepdfa_tpu.eval.report runs/perf_deepdfa/profiledata.jsonl \
  runs/perf_deepdfa/timedata.jsonl

echo "== DeepDFA+LineVul combined (msr_train_combined.sh flow) =="
python -m deepdfa_tpu.cli fit-text --config configs/default.yaml \
  --model linevul --dataset "$DATASET" --graphs synthetic \
  --epochs "${EPOCHS:-5}" --checkpoint-dir runs/perf_combined \
  --ddfa-checkpoint runs/perf_deepdfa

echo "== combined test (with profiling) =="
python -m deepdfa_tpu.cli test-text --checkpoint-dir runs/perf_combined \
  --which best --profile --time
python -m deepdfa_tpu.eval.report runs/perf_combined/profiledata.jsonl \
  runs/perf_combined/timedata.jsonl

echo "== bench =="
python bench.py

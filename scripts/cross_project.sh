#!/bin/bash
# Cross-project generalization protocol (reference scripts/run_cross_project.sh
# for the GNN, LineVul/linevul/scripts/cross_project_{train,eval}_combined.sh
# for the combined model; paper Table 7): no project spans train/test.
#
# Extra args: FIT_ARGS apply to the GNN fit step, TEST_ARGS to the GNN test
# step, "$@" to both GNN steps (must be valid for both subcommands).
# COMBINED=0 skips the combined stage; COMBINED_ARGS feed fit-text;
# GRAPHS points the combined join at a real graph cache when DATASET is a
# CSV directory (synthetic graphs only pair with synthetic text).
set -e
cd "$(dirname "$0")/.."
DATASET="${DATASET:-synthetic:256}"
GRAPHS="${GRAPHS:-synthetic}"
CKPT="${CHECKPOINT_DIR:-runs/cross_project}"

python -m deepdfa_tpu.cli fit --config configs/default.yaml \
  --dataset "$DATASET" --split-mode cross-project \
  --checkpoint-dir "$CKPT" ${FIT_ARGS:-} "$@"
python -m deepdfa_tpu.cli test --config configs/default.yaml \
  --dataset "$DATASET" --split-mode cross-project \
  --checkpoint-dir "$CKPT" --which best \
  ${TEST_ARGS:-} "$@"

if [ "${COMBINED:-1}" = "1" ]; then
  echo "== combined DeepDFA+LineVul, cross-project =="
  python -m deepdfa_tpu.cli fit-text --config configs/default.yaml \
    --model linevul --dataset "$DATASET" --graphs "$GRAPHS" \
    --split-mode cross-project \
    --checkpoint-dir "${CKPT}_combined" \
    --ddfa-checkpoint "$CKPT" ${COMBINED_ARGS:-}
  python -m deepdfa_tpu.cli test-text \
    --checkpoint-dir "${CKPT}_combined" --which best
fi

#!/bin/bash
# Cross-project generalization protocol (reference scripts/run_cross_project.sh,
# paper Table 7): no project spans train/test.
#
# Extra args: FIT_ARGS apply to the fit step, TEST_ARGS to the test step,
# "$@" to both (must be valid for both subcommands).
set -e
cd "$(dirname "$0")/.."
python -m deepdfa_tpu.cli fit --config configs/default.yaml \
  --split-mode cross-project \
  --checkpoint-dir "${CHECKPOINT_DIR:-runs/cross_project}" ${FIT_ARGS:-} "$@"
python -m deepdfa_tpu.cli test --config configs/default.yaml \
  --split-mode cross-project \
  --checkpoint-dir "${CHECKPOINT_DIR:-runs/cross_project}" --which best \
  ${TEST_ARGS:-} "$@"

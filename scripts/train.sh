#!/bin/bash
# DeepDFA training (reference DDFA/scripts/train.sh).
set -e
cd "$(dirname "$0")/.."
python -m deepdfa_tpu.cli fit --config configs/default.yaml \
  --checkpoint-dir "${CHECKPOINT_DIR:-runs/deepdfa}" "$@"

#!/bin/bash
# Table-5 profiling flow (reference DDFA/scripts/run_profiling.sh:3-8):
# evaluate a checkpoint with the FLOPs + latency instruments, then aggregate
# the per-step JSONL records into GFLOPs/GMACs and ms-per-example
# (scripts/report_profiling.py:18-66 semantics).
#
# usage: run_profiling.sh <checkpoint-dir> [extra cli args...]
set -e
cd "$(dirname "$0")/.."
CKPT=${1:?usage: run_profiling.sh <checkpoint-dir> [extra cli args...]}
shift || true
case "$*" in *--dataset*) ;; *)
  # cli test defaults --dataset to synthetic:256 — profiling a checkpoint
  # against synthetic data is rarely what was meant; say so loudly.
  echo "run_profiling.sh: no --dataset given, profiling on synthetic:256" \
       "(pass --dataset <spec> to profile the checkpoint's real data)" >&2
;; esac
python -m deepdfa_tpu.cli test --config configs/default.yaml \
  --checkpoint-dir "$CKPT" --which best --profile --time "$@"
python -m deepdfa_tpu.eval.report "$CKPT/profiledata.jsonl" "$CKPT/timedata.jsonl"

#!/bin/bash
# One-command paper reproduction: preprocess -> DeepDFA fit/test ->
# combined DeepDFA+LineVul fit-text/test-text (+ optional cross-project
# and DbgBench stages), ending in ONE summary JSON with the Table
# 3b/5/7/8-equivalent numbers.
#
# Reference flows stitched together here: scripts/performance_evaluation.sh:1-9
# (DDFA -> combined -> profiling), LineVul/linevul/scripts/
# msr_train_combined.sh:12-30 (the combined training command),
# run_cross_project.sh + cross_project_{train,eval}_combined.sh (Table 7),
# and the DbgBench evaluation (Table 8).
#
# Usage:
#   scripts/reproduce_paper.sh                  # synthetic end-to-end dry-run
#   DATA=/data/MSR TEXT_DATA=/data/msr_csvs scripts/reproduce_paper.sh
#
# Env knobs:
#   DATA          raw dataset source for the ETL (Big-Vul csv / devign);
#                 unset => synthetic dry-run of every stage
#   TEXT_DATA     MSR csv directory for the combined model's text side
#                 (required with DATA; synthetic mode derives it)
#   DATASET_NAME  bigvul | devign (default bigvul)
#   WORKDIR       output root (default runs/reproduce)
#   EPOCHS        DeepDFA epochs (default 100 real / 5 synthetic — the
#                 reference's main_cli epoch budget)
#   TEXT_EPOCHS   combined epochs (default 10 real / 2 synthetic,
#                 msr_train_combined.sh --epochs 10)
#   SAMPLE        etl prepare --sample N (smoke a real dataset quickly)
#   SYNTHETIC_N   synthetic dataset size (default 256)
#   TINY=1        tiny text model (synthetic mode only; the CI size)
#   CROSS_PROJECT=1  add the Table-7 cross-project stage
#   DBGBENCH=bug_map.json  add the Table-8 DbgBench evaluation
set -euo pipefail
cd "$(dirname "$0")/.."
WORK="${WORKDIR:-runs/reproduce}"
LOGS="$WORK/logs"
mkdir -p "$LOGS"

if [ -n "${DATA:-}" ]; then
  # Validate EVERY required input before the hours-long ETL starts.
  TEXT_DATASET="${TEXT_DATA:?combined stage needs TEXT_DATA=<MSR csv dir>}"
  DSNAME="${DATASET_NAME:-bigvul}"
  echo "== preprocess ($DSNAME) =="
  python -m deepdfa_tpu.etl.pipeline prepare --dataset "$DSNAME" \
    --path "$DATA" --workdir "$WORK/etl" ${SAMPLE:+--sample "$SAMPLE"}
  python -m deepdfa_tpu.etl.pipeline graphs --workdir "$WORK/etl" \
    --workers "${WORKERS:-6}"
  python -m deepdfa_tpu.etl.pipeline export --workdir "$WORK/etl"
  DATASET="$WORK/etl/examples.jsonl"
  GRAPHS="$DATASET"
  EPOCHS="${EPOCHS:-100}"
  TEXT_EPOCHS="${TEXT_EPOCHS:-10}"
  TINYFLAG=""
else
  echo "== synthetic dry-run (set DATA=... to reproduce on real data) =="
  DATASET="synthetic:${SYNTHETIC_N:-256}"
  GRAPHS="synthetic"
  TEXT_DATASET="$DATASET"
  EPOCHS="${EPOCHS:-5}"
  TEXT_EPOCHS="${TEXT_EPOCHS:-2}"
  TINYFLAG="${TINY:+--tiny}"
fi

echo "== DeepDFA fit ($DATASET, $EPOCHS epochs) =="
python -m deepdfa_tpu.cli fit --config configs/default.yaml \
  --dataset "$DATASET" --set train.max_epochs="$EPOCHS" \
  --checkpoint-dir "$WORK/deepdfa" | tee "$LOGS/ddfa_fit.out"

echo "== DeepDFA test (Table 3b GNN row + Table 5 profiling) =="
python -m deepdfa_tpu.cli test --config configs/default.yaml \
  --dataset "$DATASET" --checkpoint-dir "$WORK/deepdfa" --which best \
  --profile --time | tee "$LOGS/ddfa_test.out"
python -m deepdfa_tpu.eval.report "$WORK/deepdfa/profiledata.jsonl" \
  "$WORK/deepdfa/timedata.jsonl" | tee "$LOGS/ddfa_profile.out"

echo "== combined fit-text (msr_train_combined.sh flow) =="
python -m deepdfa_tpu.cli fit-text --config configs/default.yaml \
  --model linevul --dataset "$TEXT_DATASET" --graphs "$GRAPHS" \
  --epochs "$TEXT_EPOCHS" --checkpoint-dir "$WORK/combined" \
  --ddfa-checkpoint "$WORK/deepdfa" $TINYFLAG | tee "$LOGS/combined_fit.out"

echo "== combined test-text (Table 3b combined row + Table 5) =="
python -m deepdfa_tpu.cli test-text --checkpoint-dir "$WORK/combined" \
  --which best --profile --time | tee "$LOGS/combined_test.out"
python -m deepdfa_tpu.eval.report "$WORK/combined/profiledata.jsonl" \
  "$WORK/combined/timedata.jsonl" | tee "$LOGS/combined_profile.out"

if [ "${CROSS_PROJECT:-0}" = "1" ]; then
  echo "== cross-project (Table 7) =="
  python -m deepdfa_tpu.cli fit --config configs/default.yaml \
    --dataset "$DATASET" --split-mode cross-project \
    --set train.max_epochs="$EPOCHS" \
    --checkpoint-dir "$WORK/cross_deepdfa" | tee "$LOGS/cross_fit.out"
  python -m deepdfa_tpu.cli test --config configs/default.yaml \
    --dataset "$DATASET" --split-mode cross-project \
    --checkpoint-dir "$WORK/cross_deepdfa" --which best \
    | tee "$LOGS/cross_test.out"
  python -m deepdfa_tpu.cli fit-text --config configs/default.yaml \
    --model linevul --dataset "$TEXT_DATASET" --graphs "$GRAPHS" \
    --split-mode cross-project --epochs "$TEXT_EPOCHS" \
    --checkpoint-dir "$WORK/cross_combined" \
    --ddfa-checkpoint "$WORK/cross_deepdfa" $TINYFLAG \
    | tee "$LOGS/cross_combined_fit.out"
  python -m deepdfa_tpu.cli test-text --checkpoint-dir "$WORK/cross_combined" \
    --which best | tee "$LOGS/cross_combined_test.out"
fi

if [ -n "${DBGBENCH:-}" ]; then
  echo "== DbgBench (Table 8) =="
  python -m deepdfa_tpu.cli test-text --checkpoint-dir "$WORK/combined" \
    --which best --dbgbench "$DBGBENCH" | tee "$LOGS/dbgbench.out"
fi

echo "== summary =="
WORK="$WORK" python - << 'PY'
import json, os

work = os.environ["WORK"]
logs = os.path.join(work, "logs")


def last_json(name):
    """Last parseable JSON line of a captured stage log (each CLI command
    prints its result record as its final stdout line)."""
    path = os.path.join(logs, name)
    if not os.path.exists(path):
        return None
    out = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("{"):
                try:
                    out = json.loads(line)
                except json.JSONDecodeError:
                    pass
    return out


summary = {
    "table3b": {
        "deepdfa": last_json("ddfa_test.out"),
        "combined": last_json("combined_test.out"),
    },
    "table5_profiling": {
        "deepdfa": last_json("ddfa_profile.out"),
        "combined": last_json("combined_profile.out"),
    },
    "table7_cross_project": {
        "deepdfa": last_json("cross_test.out"),
        "combined": last_json("cross_combined_test.out"),
    },
    "table8_dbgbench": last_json("dbgbench.out"),
}
fn = os.path.join(work, "reproduce_summary.json")
with open(fn, "w") as f:
    json.dump(summary, f, indent=1)
print(json.dumps({"summary": fn,
                  "stages": {k: v is not None if not isinstance(v, dict)
                             else {kk: vv is not None for kk, vv in v.items()}
                             for k, v in summary.items()}}))
PY

#!/bin/bash
# Serving endpoint (deepdfa_tpu/serve): deadline-aware bucketed
# micro-batching over AOT-warmed shapes, content-hash caching, 429
# backpressure — the checkpoint-to-responses path.
#
#   CHECKPOINT_DIR=runs/deepdfa bash scripts/serve.sh        # serve a run
#   COMBINED_DIR=runs/combined bash scripts/serve.sh          # + text lane
#   GEN_DIR=runs/summarize bash scripts/serve.sh              # + gen lane
#   bash scripts/serve.sh --smoke 8                           # self-test
#
# Extra flags pass through to `cli serve` (--port, --batch-slots,
# --deadline-ms, --queue-capacity, --cache-capacity, ...).
set -e
cd "$(dirname "$0")/.."
ARGS=()
if [ -n "${CHECKPOINT_DIR:-}" ]; then
  ARGS+=(--checkpoint-dir "$CHECKPOINT_DIR")
fi
if [ -n "${COMBINED_DIR:-}" ]; then
  ARGS+=(--combined-checkpoint-dir "$COMBINED_DIR")
fi
if [ -n "${GEN_DIR:-}" ]; then
  ARGS+=(--gen-checkpoint-dir "$GEN_DIR")
fi
python -m deepdfa_tpu.cli serve --config configs/default.yaml \
  "${ARGS[@]}" "$@"

#!/bin/bash
# Fetch the paper's public data archives (reference scripts/download_all.sh;
# same Figshare objects). Run from the repo root. The ETL consumes either
#   - the raw CSV via `python -m deepdfa_tpu.etl.pipeline prepare --dataset bigvul`
#   - or the preprocessed reference cache directly via
#     deepdfa_tpu.etl.legacy_cache.load_reference_cache (no Joern needed).
set -e
mkdir -p data

# Raw Big-Vul dataset (MSR_data_cleaned.csv)
curl -Lo data/MSR_data_cleaned.zip 'https://figshare.com/ndownloader/files/43990908'
unzip -o data/MSR_data_cleaned.zip -d data/

# LineVul split of Big-Vul (text training CSVs + linevul_splits.csv)
curl -Lo data/MSR_LineVul.zip 'https://figshare.com/ndownloader/files/43991823'
unzip -o data/MSR_LineVul.zip -d data/MSR

# Reference-preprocessed graph cache (nodes/edges/nodes_feat CSVs — the
# format legacy_cache reads)
curl -Lo data/preprocessed_data.zip 'https://figshare.com/ndownloader/files/43991910'
unzip -o data/preprocessed_data.zip -d data/

# Joern CFG exports for the before-functions
curl -Lo data/before.zip 'https://figshare.com/ndownloader/files/43916550'
unzip -o data/before.zip -d data/processed/bigvul

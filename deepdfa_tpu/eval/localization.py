"""Line-level vulnerability localization and the RQ2 effort/recall metrics.

Re-design of the UniXcoder-variant explanation stack
(LineVul/unixcoder/linevul_main.py:886-1380): per-token relevance scores
come from one of
  - ``attention``  — total attention each token receives in the FIRST
    encoder layer, summed over heads and query positions
    (linevul_main.py:1155-1170), special tokens zeroed;
  - ``saliency``   — |d logit_vuln / d embedding| summed over the hidden dim
    and L2-normalized (captum Saliency + summarize_attributions,
    linevul_main.py:946-949,1066-1078) — here a plain ``jax.grad``;
  - ``integrated_gradients`` — Riemann-sum IG against a pad-embedding
    baseline (captum LayerIntegratedGradients, linevul_main.py:1171-1186).

Token scores aggregate into per-line scores by splitting the decoded token
stream at newline markers (get_all_lines_score, linevul_main.py:1335-1363);
per-function evaluation ranks lines and reports Top-k accuracy, IFA, and
effort (line_level_evaluation, :1242-1332); corpus-level Effort@TopK% /
Recall@TopK% walk the ranked concatenation (:886-944).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Token-level scores
# ---------------------------------------------------------------------------

# The reference's full reasoning-method surface
# (linevul_main.py:514 all_reasoning_method). "attention" consumes encoder
# attention weights (attention_token_scores); the gradient family consumes
# (model, params, input_ids, embed_fn) — *_token_scores below.
REASONING_METHODS = (
    "attention",
    "saliency",
    "integrated_gradients",  # = the reference's "lig"
    "deeplift",
    "deeplift_shap",
    "gradient_shap",
)


def attention_token_scores(
    attentions: Sequence[jnp.ndarray], special_mask: np.ndarray
) -> np.ndarray:
    """attentions: per-layer [B, H, Q, K] weights (output_attentions=True).
    Score = attention received per key token in the first layer, summed over
    heads and queries; special/pad positions zeroed
    (linevul_main.py:1155-1170 uses attentions[0])."""
    att = np.asarray(attentions[0], np.float32)  # [B, H, Q, K]
    scores = att.sum(axis=(1, 2))  # [B, K]
    return np.where(special_mask, 0.0, scores)


def saliency_token_scores(
    model,
    params,
    input_ids: jnp.ndarray,
    embed_fn: Callable[[jnp.ndarray], jnp.ndarray],
    target: int = 1,
) -> np.ndarray:
    """|grad of logits[:, target] wrt input embeddings|, summed over hidden,
    L2-normalized per row (summarize_attributions semantics)."""
    embeds = embed_fn(input_ids)

    def logit_sum(e):
        logits = model.apply(params, input_ids, input_embeds=e)
        return logits[:, target].sum()

    grads = jax.grad(logit_sum)(embeds)
    attr = jnp.abs(grads).sum(axis=-1)
    norm = jnp.linalg.norm(attr, axis=-1, keepdims=True)
    return np.asarray(attr / jnp.maximum(norm, 1e-12))


def integrated_gradients_token_scores(
    model,
    params,
    input_ids: jnp.ndarray,
    embed_fn: Callable[[jnp.ndarray], jnp.ndarray],
    baseline_ids: Optional[jnp.ndarray] = None,
    pad_id: Optional[int] = None,
    target: int = 1,
    steps: int = 20,
) -> np.ndarray:
    """IG = (x - x0) * mean_alpha grad(f(x0 + alpha(x-x0))), summed over
    hidden and L2-normalized. Pass ``pad_id`` to use the reference's
    baseline — pad embeddings with the original first/last tokens kept
    (create_ref_input_ids, linevul_main.py:951-954); with neither
    ``baseline_ids`` nor ``pad_id`` the baseline is the zero embedding."""
    embeds = embed_fn(input_ids)
    if baseline_ids is None and pad_id is not None:
        mid = jnp.full_like(input_ids[:, 1:-1], pad_id)
        baseline_ids = jnp.concatenate(
            [input_ids[:, :1], mid, input_ids[:, -1:]], axis=1
        )
    if baseline_ids is None:
        base = jnp.zeros_like(embeds)
    else:
        base = embed_fn(baseline_ids)

    def logit_sum(e):
        logits = model.apply(params, input_ids, input_embeds=e)
        return logits[:, target].sum()

    grad_fn = jax.grad(logit_sum)
    delta = embeds - base

    def body(acc, alpha):
        return acc + grad_fn(base + alpha * delta), None

    alphas = (jnp.arange(steps, dtype=jnp.float32) + 0.5) / steps
    total, _ = jax.lax.scan(body, jnp.zeros_like(embeds), alphas)
    return _summarize(delta * total / steps)


def _summarize(attr: jnp.ndarray) -> np.ndarray:
    """summarize_attributions parity (linevul_main.py:945-948): sum over the
    hidden dim, L2-normalize per row — SIGNED, captum keeps the sign and the
    reference ranks lines by the raw scores."""
    attr = attr.sum(axis=-1)
    norm = jnp.linalg.norm(attr, axis=-1, keepdims=True)
    return np.asarray(attr / jnp.maximum(norm, 1e-12))


def _logit_grad_fn(model, params, input_ids, target):
    def logit_sum(e):
        logits = model.apply(params, input_ids, input_embeds=e)
        return logits[:, target].sum()

    return jax.grad(logit_sum)


def deeplift_token_scores(
    model,
    params,
    input_ids: jnp.ndarray,
    embed_fn: Callable[[jnp.ndarray], jnp.ndarray],
    baseline: Optional[jnp.ndarray] = None,
    target: int = 1,
) -> np.ndarray:
    """DeepLift against a zero-embedding baseline
    (linevul_main.py:1053-1056: ``DeepLift(model)`` with
    ``torch.zeros(1, 512, 768)``), computed as grad(x) × (x − baseline) —
    the gradient×Δinput form of the rescale rule."""
    embeds = embed_fn(input_ids)
    base = jnp.zeros_like(embeds) if baseline is None else baseline
    grads = _logit_grad_fn(model, params, input_ids, target)(embeds)
    return _summarize((embeds - base) * grads)


def deeplift_shap_token_scores(
    model,
    params,
    input_ids: jnp.ndarray,
    embed_fn: Callable[[jnp.ndarray], jnp.ndarray],
    baselines: Optional[jnp.ndarray] = None,
    target: int = 1,
) -> np.ndarray:
    """DeepLiftShap: DeepLift averaged over a baseline distribution
    (linevul_main.py:1057-1060; the reference passes 16 zero baselines, so
    its expectation degenerates to plain DeepLift — supported here, but any
    [N, T, H] baseline stack works)."""
    embeds = embed_fn(input_ids)
    if baselines is None:
        baselines = jnp.zeros((1,) + embeds.shape[-2:], embeds.dtype)
    # The gradient is taken at the input, not the baseline: one
    # forward+backward serves every baseline in the expectation.
    grads = _logit_grad_fn(model, params, input_ids, target)(embeds)
    attr = jax.vmap(lambda base: (embeds - base) * grads)(baselines).mean(axis=0)
    return _summarize(attr)


def gradient_shap_token_scores(
    model,
    params,
    input_ids: jnp.ndarray,
    embed_fn: Callable[[jnp.ndarray], jnp.ndarray],
    baselines: Optional[jnp.ndarray] = None,
    target: int = 1,
    n_samples: int = 8,
    stdev: float = 0.0,
    seed: int = 0,
) -> np.ndarray:
    """GradientShap (linevul_main.py:1061-1064): expectation over random
    interpolation points α·x + (1−α)·baseline (plus optional input noise) of
    grad × (x − baseline), zero baselines by default like the reference."""
    embeds = embed_fn(input_ids)
    if baselines is None:
        baselines = jnp.zeros((1,) + embeds.shape[-2:], embeds.dtype)
    grad_fn = _logit_grad_fn(model, params, input_ids, target)
    rng = jax.random.PRNGKey(seed)

    total = jnp.zeros_like(embeds)
    for i in range(n_samples):
        rng, k_alpha, k_base, k_noise = jax.random.split(rng, 4)
        alpha = jax.random.uniform(k_alpha)
        base = baselines[jax.random.randint(k_base, (), 0, baselines.shape[0])]
        x = embeds
        if stdev > 0.0:
            x = x + stdev * jax.random.normal(k_noise, embeds.shape)
        point = base + alpha * (x - base)
        total = total + grad_fn(point) * (x - base)
    return _summarize(total / n_samples)


# ---------------------------------------------------------------------------
# Line aggregation
# ---------------------------------------------------------------------------

NEWLINE_MARKERS = ("\n", " \n", "\n\n", " \n\n", "Ċ", " Ċ", "ĊĊ", " ĊĊ")


SPECIAL_TOKENS = ("<s>", "</s>", "<unk>", "<pad>", "<cls>", "<sep>")


def line_scores(
    tokens: Sequence[str], scores: Sequence[float],
    flaw_lines: Sequence[str] = (),
    special_tokens: Sequence[str] = SPECIAL_TOKENS,
) -> Tuple[List[float], List[int]]:
    """Accumulate token scores into line scores, splitting at newline
    markers; a line whose concatenated text equals a flaw line (whitespace-
    stripped) is marked (get_all_lines_score parity: lines with zero
    accumulated score do not emit). Special tokens contribute neither text
    nor score (clean_word_attr_scores, linevul_main.py:1196-1202)."""
    flaw = {"".join(l.split()) for l in flaw_lines}
    special = frozenset(special_tokens)
    all_lines: List[float] = []
    flaw_idx: List[int] = []
    acc = 0.0
    line = ""

    def emit():
        nonlocal acc, line
        all_lines.append(acc)
        if "".join(line.split()) in flaw:
            flaw_idx.append(len(all_lines) - 1)
        line = ""
        acc = 0.0

    for tok, sc in zip(tokens, scores):
        if tok in special:
            continue
        if tok in NEWLINE_MARKERS:
            if acc != 0.0:
                acc += float(sc)  # separator score joins its line (parity)
                emit()
            else:
                line = ""  # dead line: drop its text, don't leak it forward
        else:
            line += tok
            acc += float(sc)
    # Trailing line without a separator: the reference folds the last token
    # into the emit *condition* and drops its text (a latent quirk); here the
    # final line flushes completely so an end-of-function flaw line is
    # scored and matchable.
    if acc != 0.0:
        emit()
    return all_lines, flaw_idx


# ---------------------------------------------------------------------------
# Per-function evaluation
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FunctionLocalization:
    total_lines: int
    num_flaw_lines: int
    correct_at_k: Dict[float, int]  # top_k fraction -> flaw lines caught
    top_n_hit: Dict[int, bool]      # top-k constant (e.g. 10) -> any caught
    ifa: int                        # clean lines read before first flaw line
    all_effort: int                 # rank of the worst flaw line


def evaluate_function(
    all_lines_score: Sequence[float],
    flaw_line_indices: Sequence[int],
    top_k_loc: Sequence[float] = (0.01, 0.05, 0.1, 0.2),
    top_k_constant: Sequence[int] = (10,),
) -> Optional[FunctionLocalization]:
    """line_level_evaluation (true-positive path, linevul_main.py:1242-1332);
    None when the function has no verified flaw lines."""
    if not flaw_line_indices:
        return None
    ranking = sorted(
        range(len(all_lines_score)), key=lambda i: all_lines_score[i], reverse=True
    )
    positions = [ranking.index(i) for i in flaw_line_indices]
    correct_at_k = {}
    for k_frac in top_k_loc:
        k = int(len(all_lines_score) * k_frac)
        correct_at_k[k_frac] = sum(1 for i in flaw_line_indices if i in ranking[:k])
    top_n_hit = {
        k: any(i in ranking[:k] for i in flaw_line_indices) for k in top_k_constant
    }
    return FunctionLocalization(
        total_lines=len(all_lines_score),
        num_flaw_lines=len(flaw_line_indices),
        correct_at_k=correct_at_k,
        top_n_hit=top_n_hit,
        ifa=min(positions),
        all_effort=max(positions),
    )


def summarize_localizations(
    results: Sequence[FunctionLocalization],
    top_k_loc: Sequence[float] = (0.01, 0.05, 0.1, 0.2),
    top_k_constant: Sequence[int] = (10,),
) -> Dict[str, float]:
    """Corpus roll-up: Top-N accuracy (fraction of functions with any flaw
    line in the top N), recall@k% (caught / total flaw lines), mean IFA."""
    out: Dict[str, float] = {}
    n = max(len(results), 1)
    for k in top_k_constant:
        out[f"top_{k}_accuracy"] = sum(r.top_n_hit[k] for r in results) / n
    total_flaw = max(sum(r.num_flaw_lines for r in results), 1)
    for k_frac in top_k_loc:
        out[f"recall_at_{k_frac}"] = (
            sum(r.correct_at_k[k_frac] for r in results) / total_flaw
        )
    out["mean_ifa"] = float(np.mean([r.ifa for r in results])) if results else 0.0
    return out


# ---------------------------------------------------------------------------
# Corpus-level RQ2 Effort@TopK% / Recall@TopK% (linevul_main.py:886-944)
# ---------------------------------------------------------------------------


def top_k_effort(
    line_labels_ranked: Sequence[int], top_k: float = 0.2
) -> Tuple[float, int]:
    """Lines of the whole corpus ranked by score desc; effort = fraction of
    lines inspected until top_k of all flaw lines are caught."""
    total = len(line_labels_ranked)
    flaw_total = sum(line_labels_ranked)
    target = int(flaw_total * top_k)
    caught = inspected = 0
    for label in line_labels_ranked:
        if caught >= target:  # checked first: target 0 costs 0 inspections
            break
        inspected += 1
        caught += int(label == 1)
    return (inspected / total if total else 0.0), inspected


def top_k_recall(
    pos_labels_ranked: Sequence[int],
    neg_labels_ranked: Sequence[int],
    top_k: float = 0.01,
) -> float:
    """Recall of flaw lines within the top_k fraction of all lines: inspect
    predicted-positive functions' lines first, then negatives
    (linevul_main.py:912-931)."""
    total = len(pos_labels_ranked) + len(neg_labels_ranked)
    flaw_total = sum(pos_labels_ranked) + sum(neg_labels_ranked)
    budget = int(total * top_k)
    caught = inspected = 0
    for label in list(pos_labels_ranked) + list(neg_labels_ranked):
        inspected += 1
        if inspected > budget:
            break
        caught += int(label == 1)
    return caught / flaw_total if flaw_total else 0.0


# ---------------------------------------------------------------------------
# Prediction export (eval_export, linevul_main.py:742-830)
# ---------------------------------------------------------------------------


def export_predictions(
    path: str,
    index: Sequence[int],
    probs: Sequence[float],
    labels: Sequence[int],
    threshold: float = 0.5,
) -> None:
    """CSV dump of per-example predictions for downstream analysis."""
    import csv

    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["index", "prob", "pred", "label"])
        for i, p, l in zip(index, probs, labels):
            w.writerow([int(i), float(p), int(p >= threshold), int(l)])

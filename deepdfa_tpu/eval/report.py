"""Report aggregation and export.

Replaces scripts/report_profiling.py:18-66 (GFLOPs/GMACs + ms/example from
the JSONL records) and the test-epoch exports of base_module.py:348-383
(overall + positive-only/negative-only metrics, PR curves to ``pr.csv`` /
``pr_binned.csv``, confusion matrix, classification report).
"""

from __future__ import annotations

import csv
import json
import os
from typing import Dict, Optional

import numpy as np

from deepdfa_tpu.core.metrics import (
    classification_report_dict,
    pr_curve,
)


def _read_jsonl(path: str):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def aggregate_profile(path: str) -> Dict[str, float]:
    """GFLOPs / GMACs per example from ``profiledata.jsonl``
    (reference report_profiling.py:18-42)."""
    recs = _read_jsonl(path)
    if not recs:
        return {"gflops_per_example": 0.0, "gmacs_per_example": 0.0, "params": 0.0}
    flops = np.array([r["flops"] for r in recs], np.float64)
    macs = np.array([r["macs"] for r in recs], np.float64)
    bs = np.array([max(int(r["batch_size"]), 1) for r in recs], np.float64)
    return {
        "gflops_per_example": float(np.mean(flops / bs) / 1e9),
        "gmacs_per_example": float(np.mean(macs / bs) / 1e9),
        "params": float(recs[0].get("params", 0)),
    }


def aggregate_time(path: str) -> Dict[str, float]:
    """ms per example from ``timedata.jsonl``
    (reference report_profiling.py:44-66)."""
    recs = _read_jsonl(path)
    if not recs:
        return {"ms_per_example": 0.0, "examples_per_sec": 0.0}
    dur = np.array([r["duration"] for r in recs], np.float64)
    bs = np.array([max(int(r["batch_size"]), 1) for r in recs], np.float64)
    ms_per_ex = float(np.mean(dur / bs) * 1e3)
    return {
        "ms_per_example": ms_per_ex,
        "examples_per_sec": float(np.sum(bs) / np.sum(dur)) if np.sum(dur) else 0.0,
    }


def export_pr_csv(
    probs: np.ndarray,
    labels: np.ndarray,
    path: str,
    binned_path: Optional[str] = None,
    num_thresholds: int = 200,
    num_bins: int = 20,
) -> None:
    """Write precision/recall/threshold rows to ``path`` and a coarse binned
    variant, matching the reference's ``pr.csv`` / ``pr_binned.csv`` export
    (base_module.py:362-372)."""
    prec, rec, thr = pr_curve(probs, labels, num_thresholds=num_thresholds)

    def _write(p, ps, rs, ts):
        with open(p, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["precision", "recall", "threshold"])
            for a, b, c in zip(ps, rs, ts):
                w.writerow([f"{a:.6f}", f"{b:.6f}", f"{c:.6f}"])

    _write(path, prec, rec, thr)
    if binned_path is not None:
        idx = np.linspace(0, len(thr) - 1, num_bins).round().astype(int)
        _write(binned_path, prec[idx], rec[idx], thr[idx])


def _counts(pred: np.ndarray, lab: np.ndarray) -> Dict[str, float]:
    tp = float(np.sum(pred * lab))
    fp = float(np.sum(pred * (1 - lab)))
    tn = float(np.sum((1 - pred) * (1 - lab)))
    fn = float(np.sum((1 - pred) * lab))
    prec = tp / (tp + fp) if tp + fp else 0.0
    rec = tp / (tp + fn) if tp + fn else 0.0
    return {
        "acc": (tp + tn) / max(tp + fp + tn + fn, 1.0),
        "precision": prec,
        "recall": rec,
        "f1": 2 * prec * rec / (prec + rec) if prec + rec else 0.0,
    }


def test_report(
    probs: np.ndarray,
    labels: np.ndarray,
    out_dir: Optional[str] = None,
    threshold: float = 0.5,
) -> Dict[str, object]:
    """Full test-epoch report.

    Overall + positive-only + negative-only metric splits (the reference
    clones its MetricCollection three ways, base_module.py:55-58,348-361),
    confusion matrix, sklearn-style classification report; optionally writes
    ``pr.csv``/``pr_binned.csv`` and ``report.json`` into ``out_dir``.
    """
    probs = np.asarray(probs, np.float64)
    labels = np.asarray(labels, np.float64)
    pred = (probs >= threshold).astype(np.float64)

    pos, neg = labels == 1, labels == 0
    report = {
        "overall": _counts(pred, labels),
        # On a single-class slice recall-on-that-class is the only
        # informative number; the reference reports the full collection
        # anyway, so we do too.
        "positive_only": _counts(pred[pos], labels[pos]),
        "negative_only": _counts(pred[neg], labels[neg]),
        "confusion": {
            "tp": float(np.sum(pred * labels)),
            "fp": float(np.sum(pred * (1 - labels))),
            "tn": float(np.sum((1 - pred) * (1 - labels))),
            "fn": float(np.sum((1 - pred) * labels)),
        },
        "classification_report": classification_report_dict(
            probs, labels, threshold=threshold
        ),
    }
    if out_dir is not None:
        os.makedirs(out_dir, exist_ok=True)
        export_pr_csv(
            probs,
            labels,
            os.path.join(out_dir, "pr.csv"),
            os.path.join(out_dir, "pr_binned.csv"),
        )
        with open(os.path.join(out_dir, "report.json"), "w") as f:
            json.dump(report, f, indent=2)
    return report


def main(argv=None) -> Dict[str, float]:
    """``python -m deepdfa_tpu.eval.report <profiledata.jsonl>
    <timedata.jsonl>`` — the reference's scripts/report_profiling.py:18-66
    aggregation: GFLOPs/GMACs and ms per example (paper Table 5). Missing
    files are skipped so profile-only or time-only runs both report."""
    import argparse

    ap = argparse.ArgumentParser(prog="deepdfa_tpu.eval.report")
    ap.add_argument("profiledata", nargs="?", default="profiledata.jsonl")
    ap.add_argument("timedata", nargs="?", default="timedata.jsonl")
    args = ap.parse_args(argv)
    out: Dict[str, float] = {}
    if os.path.exists(args.profiledata):
        out.update(aggregate_profile(args.profiledata))
    if os.path.exists(args.timedata):
        out.update(aggregate_time(args.timedata))
    print(json.dumps(out))
    return out


def dbgbench_report(
    probs,
    example_bug_ids,
    threshold: float = 0.5,
) -> Dict[str, float]:
    """Bugs-detected metric over a DbgBench-style set (paper Table 8: 8.7/17
    bugs for DeepDFA; reference --dbgbench paths,
    unixcoder/linevul_main.py:1530-1555): each example belongs to one known
    bug, and a bug counts as detected when ANY of its functions is flagged.
    Returns {"bugs_total", "bugs_detected", "detection_rate"}."""
    flagged_by_bug: Dict[object, bool] = {}
    for p, bug in zip(probs, example_bug_ids):
        flagged_by_bug[bug] = flagged_by_bug.get(bug, False) or (float(p) >= threshold)
    total = len(flagged_by_bug)
    detected = sum(flagged_by_bug.values())
    return {
        "bugs_total": total,
        "bugs_detected": detected,
        "detection_rate": detected / total if total else 0.0,
    }


if __name__ == "__main__":
    main()

"""Corpus BLEU and the CodeBLEU weighted-recall variant.

Math parity with the reference's vendored nltk BLEU
(CodeT5/evaluator/CodeBLEU/bleu.py) and weighted variant
(weighted_ngram_match.py): clipped modified precision summed over the
corpus, geometric mean under uniform 4-gram weights, brevity penalty
exp(1 - r/h); the weighted variant is modified *recall* (denominator =
reference counts) with unigram counts scaled by per-token weights
(weighted_ngram_match.py ``modified_recall``). Zero precisions are floored
at a tiny epsilon (smoothing method-1 style) instead of zeroing the whole
corpus score.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Dict, List, Sequence, Tuple

_EPS = 1e-12


def ngrams(tokens: Sequence[str], n: int):
    return [tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1)]


def _closest_ref_length(refs: Sequence[Sequence[str]], hyp_len: int) -> int:
    return min((abs(len(r) - hyp_len), len(r)) for r in refs)[1]


def _brevity_penalty(ref_len: int, hyp_len: int) -> float:
    if hyp_len > ref_len:
        return 1.0
    if hyp_len == 0:
        return 0.0
    return math.exp(1 - ref_len / hyp_len)


def corpus_bleu(
    list_of_references: Sequence[Sequence[Sequence[str]]],
    hypotheses: Sequence[Sequence[str]],
    max_n: int = 4,
) -> float:
    """Standard corpus BLEU-N (uniform weights) with clipped counts against
    the per-example reference union."""
    num = [0] * max_n
    den = [0] * max_n
    ref_len = hyp_len = 0
    for refs, hyp in zip(list_of_references, hypotheses):
        hyp_len += len(hyp)
        ref_len += _closest_ref_length(refs, len(hyp))
        for n in range(1, max_n + 1):
            counts = Counter(ngrams(hyp, n))
            max_counts: Dict[Tuple, int] = {}
            for ref in refs:
                for ng, c in Counter(ngrams(ref, n)).items():
                    max_counts[ng] = max(max_counts.get(ng, 0), c)
            clipped = {ng: min(c, max_counts.get(ng, 0)) for ng, c in counts.items()}
            num[n - 1] += sum(clipped.values())
            den[n - 1] += max(1, sum(counts.values()))
    if hyp_len == 0:
        return 0.0
    if any(n == 0 for n in num):
        # The reference's vendored nltk corpus_bleu is unsmoothed
        # (CodeT5/evaluator/CodeBLEU/bleu.py, Fraction without smoothing):
        # any zero n-gram overlap zeroes the whole geometric mean. Match it
        # exactly — a tiny-positive floor here would deviate in the
        # CodeBLEU composite.
        return 0.0
    log_p = sum((1.0 / max_n) * math.log(num[i] / den[i]) for i in range(max_n))
    return _brevity_penalty(ref_len, hyp_len) * math.exp(log_p)


def corpus_weighted_recall(
    list_of_references: Sequence[Sequence[Tuple[Sequence[str], Dict[str, float]]]],
    hypotheses: Sequence[Sequence[str]],
    max_n: int = 4,
) -> float:
    """CodeBLEU's keyword-weighted modified recall: references arrive as
    (tokens, token->weight) pairs; at n=1 the clipped and total counts are
    weighted per token (weighted_ngram_match.py:96-120)."""
    num = [0.0] * max_n
    den = [0.0] * max_n
    ref_len = hyp_len = 0
    for refs, hyp in zip(list_of_references, hypotheses):
        hyp_len += len(hyp)
        ref_len += _closest_ref_length([r for r, _ in refs], len(hyp))
        for n in range(1, max_n + 1):
            counts = Counter(ngrams(hyp, n))
            for ref, weights in refs:
                ref_counts = Counter(ngrams(ref, n))
                clipped = {
                    ng: min(c, counts.get(ng, 0)) for ng, c in ref_counts.items()
                }
                if n == 1:
                    w = lambda ng: weights.get(ng[0], 1.0)
                    num[0] += sum(c * w(ng) for ng, c in clipped.items())
                    den[0] += max(
                        1.0, sum(c * w(ng) for ng, c in ref_counts.items())
                    )
                else:
                    num[n - 1] += sum(clipped.values())
                    den[n - 1] += max(1, sum(ref_counts.values()))
    if hyp_len == 0:
        return 0.0
    log_p = sum(
        (1.0 / max_n) * math.log(max(num[i], _EPS) / max(den[i], 1.0))
        for i in range(max_n)
    )
    return _brevity_penalty(ref_len, hyp_len) * math.exp(log_p)

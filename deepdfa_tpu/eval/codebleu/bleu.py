"""Corpus BLEU and the CodeBLEU weighted-recall variant.

Math parity with the reference's vendored nltk BLEU
(CodeT5/evaluator/CodeBLEU/bleu.py) and weighted variant
(weighted_ngram_match.py): clipped modified precision summed over the
corpus, geometric mean under uniform 4-gram weights, brevity penalty
exp(1 - r/h); the weighted variant is modified *recall* (denominator =
reference counts) with unigram counts scaled by per-token weights
(weighted_ngram_match.py ``modified_recall``). Zero precisions are floored
at a tiny epsilon (smoothing method-1 style) instead of zeroing the whole
corpus score.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Dict, List, Sequence, Tuple

# Chen & Cherry method1 epsilon — the reference's vendored nltk smooths
# zero-count precisions with SmoothingFunction().method1 by default
# (CodeT5/evaluator/CodeBLEU/bleu.py:190-199,475-484) and returns 0 outright
# when there are no unigram matches (:186-188).
_METHOD1_EPS = 0.1


def _nltk_geomean(num, den, max_n: int) -> float:
    """exp(mean log p_n) with the reference's exact zero handling."""
    if num[0] == 0:
        return 0.0
    log_p = sum(
        (1.0 / max_n)
        * math.log((n if n != 0 else _METHOD1_EPS) / d)
        for n, d in zip(num, den)
    )
    return math.exp(log_p)


def ngrams(tokens: Sequence[str], n: int):
    return [tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1)]


def _closest_ref_length(refs: Sequence[Sequence[str]], hyp_len: int) -> int:
    return min((abs(len(r) - hyp_len), len(r)) for r in refs)[1]


def _brevity_penalty(ref_len: int, hyp_len: int) -> float:
    if hyp_len > ref_len:
        return 1.0
    if hyp_len == 0:
        return 0.0
    return math.exp(1 - ref_len / hyp_len)


def corpus_bleu(
    list_of_references: Sequence[Sequence[Sequence[str]]],
    hypotheses: Sequence[Sequence[str]],
    max_n: int = 4,
) -> float:
    """Standard corpus BLEU-N (uniform weights) with clipped counts against
    the per-example reference union."""
    num = [0] * max_n
    den = [0] * max_n
    ref_len = hyp_len = 0
    for refs, hyp in zip(list_of_references, hypotheses):
        hyp_len += len(hyp)
        ref_len += _closest_ref_length(refs, len(hyp))
        for n in range(1, max_n + 1):
            counts = Counter(ngrams(hyp, n))
            max_counts: Dict[Tuple, int] = {}
            for ref in refs:
                for ng, c in Counter(ngrams(ref, n)).items():
                    max_counts[ng] = max(max_counts.get(ng, 0), c)
            clipped = {ng: min(c, max_counts.get(ng, 0)) for ng, c in counts.items()}
            num[n - 1] += sum(clipped.values())
            den[n - 1] += max(1, sum(counts.values()))
    if hyp_len == 0:
        return 0.0
    return _brevity_penalty(ref_len, hyp_len) * _nltk_geomean(num, den, max_n)


def corpus_weighted_recall(
    list_of_references: Sequence[Sequence[Tuple[Sequence[str], Dict[str, float]]]],
    hypotheses: Sequence[Sequence[str]],
    max_n: int = 4,
) -> float:
    """CodeBLEU's keyword-weighted modified recall: references arrive as
    (tokens, token->weight) pairs; at n=1 the clipped and total counts are
    weighted per token (weighted_ngram_match.py:96-120)."""
    num = [0.0] * max_n
    den = [0.0] * max_n
    ref_len = hyp_len = 0
    for refs, hyp in zip(list_of_references, hypotheses):
        hyp_len += len(hyp)
        # Replicated reference quirk: its closest_ref_length receives the
        # (tokens, weights) PAIRS, so every "reference length" is
        # len(pair) == 2 (weighted_ngram_match.py:270-286) and the brevity
        # penalty is effectively 1. Kept bug-for-bug — the CodeBLEU
        # composite must reproduce the reference's numbers.
        ref_len += 2
        for n in range(1, max_n + 1):
            counts = Counter(ngrams(hyp, n))
            for ref, weights in refs:
                ref_counts = Counter(ngrams(ref, n))
                clipped = {
                    ng: min(c, counts.get(ng, 0)) for ng, c in ref_counts.items()
                }
                if n == 1:
                    w = lambda ng: weights.get(ng[0], 1.0)
                    num[0] += sum(c * w(ng) for ng, c in clipped.items())
                    den[0] += max(
                        1.0, sum(c * w(ng) for ng, c in ref_counts.items())
                    )
                else:
                    num[n - 1] += sum(clipped.values())
                    den[n - 1] += max(1, sum(ref_counts.values()))
    if hyp_len == 0:
        return 0.0
    return _brevity_penalty(ref_len, hyp_len) * _nltk_geomean(num, den, max_n)

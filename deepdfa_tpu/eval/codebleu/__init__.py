"""CodeBLEU: composite code-generation metric.

Parity target: CodeT5/evaluator/CodeBLEU/calc_code_bleu.py —
``alpha*BLEU + beta*weighted-BLEU + gamma*syntax_match +
delta*dataflow_match`` with default weights 0.25 each, keyword token weight
1.0 vs 0.2 for the weighted component, syntax match = fraction of reference
AST subtrees found in the hypothesis AST, dataflow match = fraction of
normalized def-use edges matched.

The reference parses with tree-sitter grammars compiled into
``my-languages.so``; this image has no tree-sitter, so the syntax/dataflow
components run on a self-contained bracket/statement parser
(:mod:`deepdfa_tpu.eval.codebleu.parser`) that produces tree-sitter-like
s-expressions for the C-family languages (and a line/indent grouping for
Python). The ngram components are exact reimplementations of the reference
math.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple, Union

from deepdfa_tpu.eval.codebleu.bleu import corpus_bleu, corpus_weighted_recall
from deepdfa_tpu.eval.codebleu.dataflow import corpus_dataflow_match
from deepdfa_tpu.eval.codebleu.keywords import KEYWORDS
from deepdfa_tpu.eval.codebleu.syntax import corpus_syntax_match


def get_codebleu(
    references: Sequence[Union[str, Sequence[str]]],
    hypotheses: Sequence[str],
    lang: str = "java",
    weights: Tuple[float, float, float, float] = (0.25, 0.25, 0.25, 0.25),
) -> Dict[str, float]:
    """Compute CodeBLEU over parallel lists (references may be one string or
    a list of alternatives per hypothesis). Returns every component plus the
    composite under ``"codebleu"``."""
    refs: List[List[str]] = [
        [r] if isinstance(r, str) else list(r) for r in references
    ]
    if len(refs) != len(hypotheses):
        raise ValueError(f"{len(refs)} references vs {len(hypotheses)} hypotheses")

    tokenized_hyps = [h.split() for h in hypotheses]
    tokenized_refs = [[r.split() for r in group] for group in refs]

    ngram = corpus_bleu(tokenized_refs, tokenized_hyps)

    kw = KEYWORDS.get(lang, frozenset())
    weighted_refs = [
        [
            (toks, {t: 1.0 if t in kw else 0.2 for t in toks})
            for toks in group
        ]
        for group in tokenized_refs
    ]
    weighted = corpus_weighted_recall(weighted_refs, tokenized_hyps)

    syntax = corpus_syntax_match(refs, hypotheses, lang)
    dataflow = corpus_dataflow_match(refs, hypotheses, lang)

    a, b, c, d = weights
    return {
        "ngram_match": ngram,
        "weighted_ngram_match": weighted,
        "syntax_match": syntax,
        "dataflow_match": dataflow,
        "codebleu": a * ngram + b * weighted + c * syntax + d * dataflow,
    }


def get_codebleu_from_files(
    ref_files: Sequence[str], hyp_file: str, lang: str = "java",
    weights: Tuple[float, float, float, float] = (0.25, 0.25, 0.25, 0.25),
) -> Dict[str, float]:
    """File-based entry matching the reference CLI (one example per line;
    multiple reference files = multiple alternatives per example)."""
    ref_cols = [
        [line.strip() for line in open(f, encoding="utf-8")] for f in ref_files
    ]
    hyps = [line.strip() for line in open(hyp_file, encoding="utf-8")]
    for col in ref_cols:
        if len(col) != len(hyps):
            raise ValueError("reference/hypothesis line counts differ")
    refs = [[col[i] for col in ref_cols] for i in range(len(hyps))]
    return get_codebleu(refs, hyps, lang, weights)

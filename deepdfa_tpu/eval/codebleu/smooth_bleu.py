"""The two sentence/corpus BLEU variants the reference's generation trainer
evaluates with (CodeT5/run_gen.py:148-154):

- ``smooth_bleu_score``: per-example smoothed BLEU-4 averaged over the dev
  set — the CodeXGLUE summarization metric (evaluator/smooth_bleu.py:
  computeMaps + bleuFromMaps over splitPuncts'd lowercase text, each
  example scored by the MOSES ``score_cooked`` math with +1 smoothing on
  orders 2-4 and the soft ``min(0, 1-(r+1)/(h+1))`` brevity penalty).
- ``nmt_bleu``: corpus BLEU-4 with Lin & Och (2004) +1/+1 smoothing and
  ``exp(1-1/ratio)`` brevity penalty — the tensorflow-nmt ``compute_bleu``
  behind ``evaluator/bleu.py:_bleu`` used for translate/refine/concode.

Both are re-derived from the published algorithms; parity is pinned by
hand-computed goldens in tests/test_codebleu.py.
"""

from __future__ import annotations

import math
import re
import sys
from typing import List, Sequence

_MIN = sys.float_info.min

# mteval-v11a tokenization (smooth_bleu.py:31-45): join hyphenated line
# breaks, split out punctuation, isolate periods/commas not flanked by
# digits, split digit-dash.
_NORM1 = [(re.compile(p), r) for p, r in (
    (r"<skipped>", ""),
    (r"-\n", ""),
    (r"\n", " "),
)]
_NORM2 = [(re.compile(p), r) for p, r in (
    (r"([\{-\~\[-\` -\&\(-\+\:-\@\/])", r" \1 "),
    (r"([^0-9])([\.,])", r"\1 \2 "),
    (r"([\.,])([^0-9])", r" \1 \2"),
    (r"([0-9])(-)", r"\1 \2 "),
)]
_UNESCAPE = [("&quot;", '"'), ("&amp;", "&"), ("&lt;", "<"), ("&gt;", ">")]


def split_puncts(line: str) -> str:
    """computeMaps' pre-tokenization (smooth_bleu.py:160-161)."""
    return " ".join(re.findall(r"[\w]+|[^\s\w]", line))


def mteval_tokenize(s: str) -> List[str]:
    """``normalize`` (smooth_bleu.py:48-64): NIST mteval-v11a lowercased
    tokenization."""
    for pattern, replace in _NORM1:
        s = pattern.sub(replace, s)
    for entity, char in _UNESCAPE:
        s = s.replace(entity, char)
    s = f" {s} ".lower()
    for pattern, replace in _NORM2:
        s = pattern.sub(replace, s)
    return s.split()


def sentence_smooth_bleu(refs: Sequence[str], hyp: str, max_n: int = 4) -> float:
    """One segment's smoothed BLEU (smooth_bleu.py ``bleu(refs, cand)[0]``):
    +1 smoothing on orders >= 2, shortest-reference effective length, and
    the MOSES soft brevity penalty."""
    ref_tokens = [mteval_tokenize(r) for r in refs]
    hyp_tokens = mteval_tokenize(hyp)

    max_counts = {}
    for ref in ref_tokens:
        for n in range(1, max_n + 1):
            counts = {}
            for i in range(len(ref) - n + 1):
                ng = tuple(ref[i:i + n])
                counts[ng] = counts.get(ng, 0) + 1
            for ng, c in counts.items():
                max_counts[ng] = max(max_counts.get(ng, 0), c)

    log_bleu = 0.0
    for n in range(1, max_n + 1):
        guess = max(len(hyp_tokens) - n + 1, 0)
        counts = {}
        for i in range(len(hyp_tokens) - n + 1):
            ng = tuple(hyp_tokens[i:i + n])
            counts[ng] = counts.get(ng, 0) + 1
        correct = sum(min(c, max_counts.get(ng, 0)) for ng, c in counts.items())
        add = 1 if n > 1 else 0
        log_bleu += math.log(correct + add + _MIN) - math.log(guess + add + _MIN)
    log_bleu /= max_n

    ref_len = min((len(r) for r in ref_tokens), default=0)
    log_bleu += min(0.0, 1 - (ref_len + 1) / (len(hyp_tokens) + 1))
    return math.exp(log_bleu)


def smooth_bleu_score(golds: Sequence[str], preds: Sequence[str]) -> float:
    """Dev-set score (bleuFromMaps semantics): mean per-example smoothed
    BLEU x 100 over positionally-aligned (gold, pred) pairs, each side
    first ``splitPuncts``'d and lowercased (computeMaps)."""
    if not golds:
        return 0.0
    total = sum(
        sentence_smooth_bleu([split_puncts(g.strip().lower())],
                             split_puncts(p.strip().lower()))
        for g, p in zip(golds, preds)
    )
    return total * 100.0 / len(golds)


def nmt_bleu(
    references: Sequence[Sequence[Sequence[str]]],
    hypotheses: Sequence[Sequence[str]],
    max_n: int = 4,
) -> float:
    """Corpus BLEU with +1/+1 smoothing on every order (``compute_bleu``
    with smooth=True, x100 rounded to 2 — the ``_bleu`` file metric)."""
    matches = [0] * max_n
    possible = [0] * max_n
    ref_len = hyp_len = 0
    for refs, hyp in zip(references, hypotheses):
        ref_len += min((len(r) for r in refs), default=0)
        hyp_len += len(hyp)
        merged = {}
        for ref in refs:
            counts = {}
            for n in range(1, max_n + 1):
                for i in range(len(ref) - n + 1):
                    ng = tuple(ref[i:i + n])
                    counts[ng] = counts.get(ng, 0) + 1
            for ng, c in counts.items():
                merged[ng] = max(merged.get(ng, 0), c)
        counts = {}
        for n in range(1, max_n + 1):
            for i in range(len(hyp) - n + 1):
                ng = tuple(hyp[i:i + n])
                counts[ng] = counts.get(ng, 0) + 1
            if len(hyp) - n + 1 > 0:
                possible[n - 1] += len(hyp) - n + 1
        for ng, c in counts.items():
            matches[len(ng) - 1] += min(c, merged.get(ng, 0))

    precisions = [(m + 1.0) / (p + 1.0) for m, p in zip(matches, possible)]
    geo_mean = (
        math.exp(sum(math.log(p) for p in precisions) / max_n)
        if min(precisions) > 0 else 0.0
    )
    if ref_len == 0:
        return 0.0
    ratio = hyp_len / ref_len
    bp = 1.0 if ratio > 1.0 else math.exp(1 - 1.0 / max(ratio, 1e-12))
    return round(100 * geo_mean * bp, 2)

"""Dataflow match: fraction of the reference's normalized def-use edges
found in the hypothesis (CodeT5/evaluator/CodeBLEU/dataflow_match.py).

The reference extracts a DFG from the tree-sitter parse with per-language
extractors (parser/DFG.py); here edges come from a statement-level scan of
our own parse (parser.py): an assignment's left identifier receives a
``comesFrom`` edge when the RHS is a single identifier, else
``computedFrom`` from every RHS identifier (augmented assignments and
``++``/``--`` include the target itself); ``for x in expr`` (Python) is a
``comesFrom``. Variable names are normalized to ``var_i`` in first-use
order exactly like the reference's ``normalize_dataflow``
(dataflow_match.py:132-148), and matching removes each matched candidate
edge (multiset semantics, dataflow_match.py:63-70).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from deepdfa_tpu.eval.codebleu.parser import Token, iter_statements, parse

_ASSIGN_AUG = {
    "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "<<=", ">>=", "**=", "//=",
}

Edge = Tuple[str, str, Tuple[str, ...]]  # (target, relationship, parents)


def _idents(tokens: Sequence[Token]) -> List[str]:
    return [t.text for t in tokens if t.cat == "id"]


def extract_dataflow(code: str, lang: str) -> List[Edge]:
    edges: List[Edge] = []
    for stmt in iter_statements(parse(code, lang)):
        # increments/decrements anywhere in the statement
        for i, t in enumerate(stmt):
            if t.cat == "op" and t.text in ("++", "--"):
                nbr = None
                if i + 1 < len(stmt) and stmt[i + 1].cat == "id":
                    nbr = stmt[i + 1].text
                elif i > 0 and stmt[i - 1].cat == "id":
                    nbr = stmt[i - 1].text
                if nbr:
                    edges.append((nbr, "computedFrom", (nbr,)))

        # python for-in binding
        if (
            lang == "python"
            and len(stmt) >= 4
            and stmt[0].cat == "kw"
            and stmt[0].text == "for"
        ):
            try:
                in_pos = next(
                    i for i, t in enumerate(stmt) if t.cat == "kw" and t.text == "in"
                )
            except StopIteration:
                in_pos = None
            if in_pos:
                for tgt in _idents(stmt[1:in_pos]):
                    src = tuple(_idents(stmt[in_pos + 1 :]))
                    if src:
                        edges.append((tgt, "comesFrom", src))
            continue

        # first top-level assignment operator in the statement
        for i, t in enumerate(stmt):
            if t.cat != "op":
                continue
            if t.text == "=" or t.text in _ASSIGN_AUG:
                lhs_ids = _idents(stmt[:i])
                if not lhs_ids:
                    break
                target = lhs_ids[-1]
                rhs = stmt[i + 1 :]
                parents = _idents(rhs)
                if t.text in _ASSIGN_AUG:
                    parents = [target] + parents
                    edges.append((target, "computedFrom", tuple(parents)))
                elif len(rhs) == 1 and rhs[0].cat == "id":
                    edges.append((target, "comesFrom", tuple(parents)))
                elif parents:
                    edges.append((target, "computedFrom", tuple(parents)))
                else:
                    edges.append((target, "comesFrom", ()))
                break
    return edges


def normalize_dataflow(edges: Sequence[Edge]) -> List[Edge]:
    """First-appearance var_i renaming, parents before target per edge
    (dataflow_match.py:132-148)."""
    names = {}

    def norm(v: str) -> str:
        if v not in names:
            names[v] = f"var_{len(names)}"
        return names[v]

    out: List[Edge] = []
    for target, rel, parents in edges:
        np = tuple(norm(p) for p in parents)
        out.append((norm(target), rel, np))
    return out


def corpus_dataflow_match(
    references: Sequence[Sequence[str]], hypotheses: Sequence[str], lang: str
) -> float:
    match = total = 0
    for refs, hyp in zip(references, hypotheses):
        cand = normalize_dataflow(extract_dataflow(hyp, lang))
        for ref in refs:
            ref_dfg = normalize_dataflow(extract_dataflow(ref, lang))
            pool = list(cand)
            for edge in ref_dfg:
                if edge in pool:
                    match += 1
                    pool.remove(edge)
            total += len(ref_dfg)
    return match / total if total else 0.0

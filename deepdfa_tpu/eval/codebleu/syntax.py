"""Syntax match: fraction of reference AST subtrees present in the
hypothesis AST (CodeT5/evaluator/CodeBLEU/syntax_match.py:26-75, with our
parser's s-expressions standing in for tree-sitter's)."""

from __future__ import annotations

from typing import List, Sequence

from deepdfa_tpu.eval.codebleu.parser import Node, parse


def all_subtree_sexps(root: Node) -> List[str]:
    """Every internal node's s-expression (the reference pushes only nodes
    with children, syntax_match.py:57-60)."""
    out: List[str] = []
    stack = [root]
    while stack:
        n = stack.pop()
        out.append(n.sexp())
        for c in n.children:
            if isinstance(c, Node) and c.children:
                stack.append(c)
    return out


def corpus_syntax_match(
    references: Sequence[Sequence[str]], hypotheses: Sequence[str], lang: str
) -> float:
    match = total = 0
    for refs, hyp in zip(references, hypotheses):
        cand_sexps = set(all_subtree_sexps(parse(hyp, lang)))
        for ref in refs:
            for sexp in all_subtree_sexps(parse(ref, lang)):
                if sexp in cand_sexps:
                    match += 1
                total += 1
    return match / total if total else 0.0

"""Per-language reserved-word lists for the weighted ngram component
(CodeBLEU keyword weight 1.0 vs 0.2 for other tokens; reference keyword
files: CodeT5/evaluator/CodeBLEU/keywords/)."""

from __future__ import annotations

_C_COMMON = """
auto break case char const continue default do double else enum extern float
for goto if int long register return short signed sizeof static struct switch
typedef union unsigned void volatile while
"""

_JAVA = """
abstract assert boolean break byte case catch char class const continue
default do double else enum extends final finally float for goto if
implements import instanceof int interface long native new package private
protected public return short static strictfp super switch synchronized this
throw throws transient try void volatile while
"""

_C_SHARP = """
abstract as base bool break byte case catch char checked class const continue
decimal default delegate do double else enum event explicit extern false
finally fixed float for foreach goto if implicit in int interface internal is
lock long namespace new null object operator out override params private
protected public readonly ref return sbyte sealed short sizeof stackalloc
static string struct switch this throw true try typeof uint ulong unchecked
unsafe ushort using virtual void volatile while
add alias ascending async await by descending dynamic equals from get global
group into join let nameof notnull on orderby partial remove select set
unmanaged value var when where yield
"""

_PYTHON = """
False None True and as assert async await break class continue def del elif
else except finally for from global if import in is lambda nonlocal not or
pass raise return try while with yield
"""

_JS = """
await break case catch class const continue debugger default delete do else
export extends false finally for function if import in instanceof new null
return super switch this throw true try typeof var void while with yield let
static async of
"""

_GO = """
break case chan const continue default defer else fallthrough for func go
goto if import interface map package range return select struct switch type
var
"""

_PHP = """
abstract and array as break callable case catch class clone const continue
declare default die do echo else elseif empty enddeclare endfor endforeach
endif endswitch endwhile eval exit extends final finally fn for foreach
function global goto if implements include include_once instanceof insteadof
interface isset list match namespace new or print private protected public
readonly require require_once return static switch throw trait try unset use
var while xor yield true false null
"""

_RUBY = """
BEGIN END alias and begin break case class def defined? do else elsif end
ensure false for if in module next nil not or redo rescue retry return self
super then true undef unless until when while yield
"""


def _set(text: str) -> frozenset:
    return frozenset(text.split())


KEYWORDS = {
    "c": _set(_C_COMMON),
    "cpp": _set(_C_COMMON) | _set("class namespace template new delete try catch throw public private protected virtual"),
    "java": _set(_JAVA),
    "c_sharp": _set(_C_SHARP),
    "python": _set(_PYTHON),
    "js": _set(_JS),
    "javascript": _set(_JS),
    "go": _set(_GO),
    "php": _set(_PHP),
    "ruby": _set(_RUBY),
}

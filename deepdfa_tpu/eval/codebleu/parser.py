"""Self-contained code parser for the CodeBLEU syntax/dataflow components.

The reference uses tree-sitter grammars compiled to ``my-languages.so``
(CodeT5/evaluator/CodeBLEU/parser/build.py); that toolchain is unavailable
here, so this module provides the same *metric surface* with a lightweight
parser: a language-aware tokenizer (comments/strings/numbers/operators) and
a bracket/statement tree for C-family languages, plus an indentation-based
grouping for Python. Serialized s-expressions play the role of tree-sitter's
``node.sexp()``: token *categories* appear (keywords literally, ``id`` /
``num`` / ``str`` placeholders, operator literals), so syntax match is
structure-sensitive but identifier-name-insensitive — the property the
CodeBLEU paper wants from its syntax component.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Sequence, Union

from deepdfa_tpu.eval.codebleu.keywords import KEYWORDS


@dataclasses.dataclass
class Token:
    cat: str  # "kw" | "id" | "num" | "str" | "op"
    text: str

    def sexp(self) -> str:
        if self.cat == "kw":
            return self.text
        if self.cat == "op":
            return self.text
        return self.cat  # id / num / str placeholders


@dataclasses.dataclass
class Node:
    kind: str  # "program" | "block" | "parens" | "brackets" | "stmt"
    children: List[Union["Node", Token]]

    def sexp(self) -> str:
        inner = " ".join(c.sexp() for c in self.children)
        return f"({self.kind} {inner})" if inner else f"({self.kind})"


_TOKEN_RE = re.compile(
    r"""
    (?P<comment>//[^\n]*|\#[^\n]*|/\*.*?\*/)
  | (?P<str>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*'|`(?:\\.|[^`\\])*`)
  | (?P<num>0[xX][0-9a-fA-F]+|\d+\.?\d*(?:[eE][+-]?\d+)?[fFlLuU]*)
  | (?P<id>[A-Za-z_$][A-Za-z0-9_$]*)
  | (?P<op><<=|>>=|===|!==|\*\*=|//=|<<|>>|<=|>=|==|!=|&&|\|\||->|=>|\+\+|--|\+=|-=|\*=|/=|%=|&=|\|=|\^=|::|[{}()\[\];,.:?~!@%^&*\-+=<>/|])
  | (?P<ws>\s+)
    """,
    re.VERBOSE | re.DOTALL,
)

# '#' starts a comment only in these languages ('#include' etc. make it a
# preprocessor line in C — dropping it is fine for metric purposes).
_HASH_COMMENT_LANGS = {"python", "ruby", "php"}


def tokenize(code: str, lang: str = "java") -> List[Token]:
    kws = KEYWORDS.get(lang, frozenset())
    out: List[Token] = []
    pos = 0
    while pos < len(code):
        m = _TOKEN_RE.match(code, pos)
        if not m:
            pos += 1  # unknown byte: skip
            continue
        pos = m.end()
        if m.lastgroup in ("ws",):
            continue
        if m.lastgroup == "comment":
            text = m.group()
            if text.startswith("#") and lang not in _HASH_COMMENT_LANGS:
                continue  # preprocessor/other: drop either way
            continue
        text = m.group()
        if m.lastgroup == "id":
            out.append(Token("kw" if text in kws else "id", text))
        elif m.lastgroup == "num":
            out.append(Token("num", text))
        elif m.lastgroup == "str":
            out.append(Token("str", text))
        else:
            out.append(Token("op", text))
    return out


_OPEN = {"(": "parens", "[": "brackets", "{": "block"}
_CLOSE = {")": "(", "]": "[", "}": "{"}
_CONTINUATIONS = {"else", "catch", "finally", "while"}


def _parse_group(tokens: List[Token], i: int, kind: str, closer: str):
    """Parse until ``closer`` (or EOF); returns (Node, next_i). Statements
    split at ';'; a trailing block ends the statement unless the next token
    continues it (else/catch/finally/do-while)."""
    children: List[Union[Node, Token]] = []
    stmt: List[Union[Node, Token]] = []

    def flush():
        nonlocal stmt
        if stmt:
            children.append(Node("stmt", stmt))
            stmt = []

    while i < len(tokens):
        t = tokens[i]
        if t.cat == "op" and t.text == closer:
            flush()
            return Node(kind, children), i + 1
        if t.cat == "op" and t.text in _OPEN:
            sub, i = _parse_group(tokens, i + 1, _OPEN[t.text], {v: k for k, v in _CLOSE.items()}[t.text])
            stmt.append(sub)
            if sub.kind == "block":
                nxt = tokens[i] if i < len(tokens) else None
                if not (nxt and nxt.cat == "kw" and nxt.text in _CONTINUATIONS):
                    flush()
            continue
        if t.cat == "op" and t.text in _CLOSE:
            # stray closer (unbalanced code): treat as end of this group
            flush()
            return Node(kind, children), i + 1
        i += 1
        if t.cat == "op" and t.text == ";":
            flush()
        else:
            stmt.append(t)
    flush()
    return Node(kind, children), i


def _parse_python(code: str) -> Node:
    """Indentation blocks: logical lines (joined inside brackets) become
    stmts; deeper indent after a ':'-ended line opens a nested block."""
    lines: List[tuple] = []  # (indent, tokens)
    buf: List[Token] = []
    depth = 0
    indent = 0
    for raw in code.split("\n"):
        stripped = raw.strip()
        if not stripped:
            continue
        toks = tokenize(raw, "python")
        if not toks:
            continue
        if depth == 0:
            indent = len(raw) - len(raw.lstrip())
            buf = []
        buf.extend(toks)
        depth += sum(1 for t in toks if t.cat == "op" and t.text in _OPEN)
        depth -= sum(1 for t in toks if t.cat == "op" and t.text in _CLOSE)
        depth = max(depth, 0)
        if depth == 0:
            lines.append((indent, buf))

    def build(start: int, level: int) -> tuple:
        children: List[Union[Node, Token]] = []
        i = start
        while i < len(lines):
            ind, toks = lines[i]
            if ind < level:
                break
            if ind > level:
                block, i = build(i, ind)
                if children and isinstance(children[-1], Node):
                    children[-1].children.append(block)
                else:
                    children.append(block)
                continue
            children.append(Node("stmt", list(toks)))
            i += 1
        return Node("block" if level > 0 else "program", children), i

    root, _ = build(0, 0)
    return root


def parse(code: str, lang: str = "java") -> Node:
    if lang == "python":
        return _parse_python(code)
    tokens = tokenize(code, lang)
    node, _ = _parse_group(tokens, 0, "program", "\x00")
    return node


def _is_inline_group(n: Node) -> bool:
    """Expression-grouping parens/brackets (at most one stmt inside) inline
    into the enclosing statement; statement-holding groups (a for-header's
    ``( init ; cond ; update )``) are separate statements."""
    if n.kind not in ("parens", "brackets"):
        return False
    return sum(
        1 for c in n.children if isinstance(c, Node) and c.kind == "stmt"
    ) <= 1


def iter_statements(root: Node):
    """Yield every logical statement's flat token list, each exactly once.

    - Expression parens/brackets inline into their enclosing statement
      (``x = ( a + b )`` is ONE statement with rhs ids a, b).
    - Statement-holding parens (for-headers) and blocks are excluded from
      the enclosing flat and yielded as their own statements — flattening a
      for-header into one pseudo-assignment would fabricate edges, and
      yielding paren contents both inline and standalone would double-count
      under the metric's multiset matching.
    - SOURCE order (pre-order): dataflow normalization renames variables in
      first-appearance order (dataflow_match.py:132-148), so the statement
      stream's order is part of the metric's semantics.
    """

    def flat(n: Union[Node, Token], excluded: List[Node]):
        if isinstance(n, Token):
            return [n]
        if n.kind == "stmt" or _is_inline_group(n):
            out = []
            for c in n.children:
                out.extend(flat(c, excluded))
            return out
        excluded.append(n)  # block or statement-holding group
        return []

    stack = [root]
    while stack:
        n = stack.pop()
        if isinstance(n, Token):
            continue
        if n.kind == "stmt":
            excluded: List[Node] = []
            toks = flat(n, excluded)
            yield toks
            # Descend only into the parts excluded from this statement's
            # flat view (blocks, multi-stmt parens) — anything inlined is
            # already accounted for.
            stack.extend(reversed(excluded))
        else:
            stack.extend(
                reversed([c for c in n.children if isinstance(c, Node)])
            )

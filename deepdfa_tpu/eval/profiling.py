"""FLOPs / timing instrumentation on XLA.

The reference measures MACs/FLOPs with DeepSpeed's FlopsProfiler and latency
with CUDA events + explicit synchronize, skipping 3 warmup batches and
appending one JSON record per step to ``profiledata.jsonl`` /
``timedata.jsonl`` (reference: DDFA/code_gnn/models/base_module.py:238-291,
LineVul/linevul/linevul_main.py:332-394). The TPU-native instruments:

- **FLOPs**: XLA's own cost model via ``jit(fn).lower(...).compile()
  .cost_analysis()`` — the compiler counts post-fusion FLOPs for the exact
  HLO it will run, which is *more* faithful than framework-level hooks.
- **Timing**: host wall clock around ``jax.block_until_ready`` — the
  dispatch+execute boundary on TPU (there is no CUDA-event analogue; XLA
  executes asynchronously until blocked).
- **Deep traces**: ``jax.profiler.trace`` for TensorBoard-viewable device
  traces when a step needs microscope-level attribution.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from deepdfa_tpu import telemetry
# ONE flops accounting for the whole stack (ISSUE 7): this module,
# bench.py's diagnostics, and the roofline report all read
# telemetry.costmodel.costs_of_compiled, so their numbers cannot drift.
from deepdfa_tpu.telemetry.costmodel import costs_of_compiled as _costs_of_compiled
from deepdfa_tpu.telemetry.export import append_jsonl


def count_params(params: Any) -> int:
    """Total parameter count (reference reports ``params`` per profile record,
    base_module.py:282-287)."""
    return int(
        sum(np.prod(np.asarray(x).shape) for x in jax.tree_util.tree_leaves(params))
    )


def cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """Compile ``fn`` for the given example args and return XLA's cost model.

    Returns at least ``{"flops": ..., "macs": ...}`` — ``macs`` is flops/2 by
    the usual convention (one multiply-accumulate = 2 flops), matching how the
    reference compares DeepSpeed MACs against FLOPs (paper Table 5).
    Additional backend-provided keys (bytes accessed, utilization) pass
    through when present.
    """
    return _costs_of_compiled(jax.jit(fn).lower(*args, **kwargs).compile())


def time_steps(
    step: Callable[[], Any],
    n_steps: int,
    n_warmup: int = 3,
) -> List[float]:
    """Per-step wall-clock seconds with ``n_warmup`` discarded warmup runs.

    Matches the reference's warmup-3-then-measure protocol
    (base_module.py:240-243). ``step`` must return a value to block on.
    """
    for _ in range(n_warmup):
        jax.block_until_ready(step())
    times: List[float] = []
    for _ in range(n_steps):
        t0 = time.perf_counter()
        jax.block_until_ready(step())
        times.append(time.perf_counter() - t0)
    return times


class ProfileRecorder:
    """Append-per-step JSONL writer for profile/time records.

    Produces the same record shapes the reference writes
    (base_module.py:282-291): profile records
    ``{"step", "flops", "params", "macs", "batch_size"}`` and time records
    ``{"step", "duration", "batch_size"}``.

    One clock, one writer (ISSUE 5): every record goes through the
    telemetry JSONL writer AND is mirrored verbatim into the active
    telemetry run (``profile.step`` / ``profile.time`` events), so
    ``profiledata.jsonl``/``timedata.jsonl`` and ``events.jsonl`` carry
    the SAME measured values — they cannot disagree.
    """

    def __init__(
        self,
        profile_path: Optional[str] = None,
        time_path: Optional[str] = None,
    ):
        self.profile_path = profile_path
        self.time_path = time_path
        self._step = 0

    def record_profile(
        self, flops: float, macs: float, params: int, batch_size: int
    ) -> None:
        if self.profile_path is None:
            return
        rec = {
            "step": self._step,
            "flops": flops,
            "params": params,
            "macs": macs,
            "batch_size": batch_size,
        }
        append_jsonl(self.profile_path, rec)
        telemetry.event("profile.step", **rec)

    def record_time(self, duration_s: float, batch_size: int) -> None:
        if self.time_path is None:
            return
        rec = {"step": self._step, "duration": duration_s,
               "batch_size": batch_size}
        append_jsonl(self.time_path, rec)
        telemetry.event("profile.time", **rec)

    def next_step(self) -> None:
        self._step += 1


@contextlib.contextmanager
def device_trace(log_dir: str):
    """TensorBoard-viewable device trace around a block (the deep-dive
    instrument; TB logging parity with MyTensorBoardLogger, my_tb.py:5-8)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profile_eval(
    step: Callable[[Any], Any],
    batches: Sequence[Any],
    params: Any,
    batch_size_of: Callable[[Any], int],
    recorder: ProfileRecorder,
    n_warmup: int = 3,
) -> Dict[str, float]:
    """Run ``step`` over ``batches`` recording per-step FLOPs + latency.

    The FLOPs figure comes from one compile-time cost analysis (identical for
    every static-shape batch); latency is measured per step after warmup,
    mirroring the reference's test-loop instrumentation
    (base_module.py:238-291).
    """
    n_params = count_params(params)
    jstep = jax.jit(step)
    if batches:
        # One jit wrapper serves both the cost analysis and the timed runs,
        # so the model compiles exactly once.
        costs = _costs_of_compiled(jstep.lower(batches[0]).compile())
        # The AOT lower/compile above does NOT seed jit's dispatch cache:
        # execute once untimed so the first measured step never includes
        # compilation (matters when n_warmup is 0 on tiny test sets).
        jax.block_until_ready(jstep(batches[0]))
    else:
        costs = {"flops": 0.0, "macs": 0.0}
    total_time, measured = 0.0, 0
    for i, batch in enumerate(batches):
        t0 = time.perf_counter()
        jax.block_until_ready(jstep(batch))
        dt = time.perf_counter() - t0
        if i >= n_warmup:
            bs = batch_size_of(batch)
            recorder.record_profile(costs["flops"], costs["macs"], n_params, bs)
            recorder.record_time(dt, bs)
            total_time += dt
            measured += 1
        recorder.next_step()
    return {
        "flops_per_batch": costs["flops"],
        "macs_per_batch": costs["macs"],
        "params": float(n_params),
        "mean_step_s": total_time / measured if measured else 0.0,
    }

"""Evaluation, profiling and reporting subsystem.

TPU-native replacement for the reference's two instruments — DeepSpeed
``FlopsProfiler`` and CUDA-event timing (reference:
DDFA/code_gnn/models/base_module.py:238-323,
LineVul/linevul/linevul_main.py:332-394) — plus the report aggregation of
scripts/report_profiling.py:18-66 and the PR-curve / classification-report
exports of base_module.py:348-383.
"""

from deepdfa_tpu.eval.profiling import (
    ProfileRecorder,
    count_params,
    cost_analysis,
    time_steps,
)
from deepdfa_tpu.eval.report import (
    aggregate_profile,
    aggregate_time,
    export_pr_csv,
    test_report,
)

__all__ = [
    "ProfileRecorder",
    "cost_analysis",
    "count_params",
    "time_steps",
    "aggregate_profile",
    "aggregate_time",
    "export_pr_csv",
    "test_report",
]

"""Structured configuration for the framework.

The reference encodes the abstract-dataflow feature choice in a string like
``_ABS_DATAFLOW_datatype_all_limitall_1000_limitsubkeys_1000`` that is parsed
ad hoc (reference: DDFA/sastvd/helpers/datasets.py:560-585 ``parse_limits``).
Here the feature choice is a dataclass, with a parser kept for legacy names so
caches produced by the reference pipeline remain loadable.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence

# The four abstract-dataflow subkeys mined from each definition node
# (reference: DDFA/sastvd/scripts/abstract_dataflow_full.py:54-201 and
# DDFA/code_gnn/models/flow_gnn/ggnn.py:17-19 ``allfeats``).
ALL_SUBKEYS = ("api", "datatype", "literal", "operator")


@dataclasses.dataclass(frozen=True)
class FeatureSpec:
    """Which abstract-dataflow embedding feeds the GNN.

    ``limit_all`` caps the overall vocabulary of hashed (api, datatype,
    literal, operator) feature sets; ``limit_subkeys`` caps each subkey's
    per-key vocabulary during hashing. Index 0 is reserved for
    "not a definition" and index 1 for the UNKNOWN hash, hence
    ``input_dim == limit_all + 2`` (reference:
    DDFA/sastvd/linevd/datamodule.py:87-96).
    """

    subkey: str = "datatype"  # one of ALL_SUBKEYS, or "all" in legacy names
    limit_all: int = 1000
    limit_subkeys: int = 1000
    # When true the model embeds each of the four subkeys with its own table
    # and concatenates (reference: ggnn.py:47-54 ``concat_all_absdf``).
    concat_all: bool = True

    @property
    def input_dim(self) -> int:
        return self.limit_all + 2

    @property
    def legacy_name(self) -> str:
        return (
            f"_ABS_DATAFLOW_{self.subkey}_all"
            f"_limitall_{self.limit_all}_limitsubkeys_{self.limit_subkeys}"
        )

    @classmethod
    def parse_legacy(cls, name: str, concat_all: bool = True) -> "FeatureSpec":
        """Parse a reference-style feature name.

        Mirrors ``parse_limits`` (reference datasets.py:560-585): missing
        limits default to no cap (represented as a large sentinel there; here
        we default to 1000 which is the published configuration).
        """
        m = re.match(
            r"_ABS_DATAFLOW_(?P<subkey>\w+?)_all"
            r"(?:_limitall_(?P<la>\d+))?(?:_limitsubkeys_(?P<ls>\d+))?$",
            name,
        )
        if not m:
            raise ValueError(f"unparseable legacy feature name: {name!r}")
        return cls(
            subkey=m.group("subkey"),
            limit_all=int(m.group("la") or 1000),
            limit_subkeys=int(m.group("ls") or 1000),
            concat_all=concat_all,
        )


@dataclasses.dataclass(frozen=True)
class FlowGNNConfig:
    """FlowGNN GGNN hyperparameters.

    Defaults reproduce the published configuration (reference:
    DDFA/configs/config_ggnn.yaml + paper Table 2): 5 gated steps, hidden 32,
    3 output layers, per-subkey embedding tables concatenated.
    """

    feature: FeatureSpec = dataclasses.field(default_factory=FeatureSpec)
    hidden_dim: int = 32
    n_steps: int = 5
    num_output_layers: int = 3
    # "graph" (per-function logit) or "node" (per-statement logit). The
    # reference's experimental dataflow_solution_{in,out} styles land with
    # the ETL that produces the solution labels.
    label_style: str = "graph"
    encoder_mode: bool = False
    # Computation dtype for messages/GRU; params stay float32.
    dtype: str = "float32"
    # "segment": XLA gather/scatter-add; "tile": Pallas block-sparse tile
    # SpMM (requires batches built with build_tile_adj=True); "band":
    # block-banded batched matmul (build_band_adj=True) — fully parallel
    # MXU work (bench.py); "fused": the single-pass Pallas megakernel
    # (ops/fused_gnn.py — edge message + band SpMM + GRU gate in one
    # pallas_call, band adjacency required; degrades to the bitwise band
    # composition off-TPU and on sharded batches); "persistent": the
    # K-step megakernel (ISSUE 15) — the WHOLE n_steps unroll as one
    # pallas_call per direction with h VMEM-resident across steps
    # (degrades to the scan of fused steps, and from there to the bitwise
    # band composition, off-TPU and on sharded batches).
    message_impl: str = "segment"
    # Rematerialize the gated steps in the backward pass. The step is
    # HBM-bound, so recomputing activations beats storing them: ~7% higher
    # training throughput on v5e (110.8k vs 103.1k graphs/s at batch 256)
    # AND less memory. Gradients are mathematically identical.
    remat_steps: bool = True
    # Attention-pooling implementation: "matmul" computes the per-graph
    # softmax reductions/broadcasts as dense assignment-matrix matmuls (TPU
    # scatters serialize — the measured win, bench.py); "segment" keeps the
    # scatter formulation (the oracle); "auto" picks matmul on TPU and
    # segment elsewhere (CPU hosts pay real FLOPs for the zero-fill).
    pool_impl: str = "auto"
    # Embedding-lookup implementation: "matmul" accumulates table gradients
    # via an assignment-matrix matmul (graphs/segment.py:onehot_take —
    # measured 0.83 -> 0.61 ms/step, bench.py); "take" keeps the gather +
    # scatter-add backward (the oracle); "auto" = matmul on TPU only.
    embed_impl: str = "auto"

    @property
    def input_dim(self) -> int:
        return self.feature.input_dim

    @property
    def uses_band_adj(self) -> bool:
        """Batches for this model must carry the band adjacency — the ONE
        predicate every lane (train loops, bench, serve engine, CLI eval)
        keys batch construction on. "fused" consumes the band adjacency
        too; before this property existed, lanes testing
        ``message_impl == "band"`` literally would silently mis-build
        batches for new band-family impls."""
        return self.message_impl in ("band", "fused", "persistent")

    @property
    def uses_tile_adj(self) -> bool:
        return self.message_impl == "tile"

    @property
    def embedding_dim(self) -> int:
        n = len(ALL_SUBKEYS) if self.feature.concat_all else 1
        return self.hidden_dim * n

    @property
    def ggnn_hidden(self) -> int:
        # Reference multiplies hidden_dim by the number of concatenated
        # subkeys (ggnn.py:50-52).
        n = len(ALL_SUBKEYS) if self.feature.concat_all else 1
        return self.hidden_dim * n

    @property
    def out_dim(self) -> int:
        # skip-concat of [ggnn_out, feat_embed] (ggnn.py:62,98)
        return self.embedding_dim + self.ggnn_hidden


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset/batching configuration.

    ``batch_size`` graphs per step (256 train / 16 test in the reference,
    DDFA/sastvd/linevd/datamodule.py:110-141). Static-shape padding budgets
    replace DGL's dynamic batching: a batch always carries exactly
    ``batch_size`` graph slots, ``max_nodes`` node slots and ``max_edges``
    edge slots; unused slots are masked.
    """

    batch_size: int = 256
    eval_batch_size: int = 16
    # Padding budgets per batch; Big-Vul graphs average ~40 nodes after
    # filtering, so 64 nodes/graph and 4 edges/node of headroom.
    max_nodes_per_graph: int = 64
    max_edges_per_node: int = 4
    undersample_factor: Optional[float] = 1.0  # "v1.0" semantics: nonvul = 1.0*len(vul)
    oversample_factor: Optional[float] = None
    seed: int = 0

    @property
    def max_nodes(self) -> int:
        return self.batch_size * self.max_nodes_per_graph

    @property
    def max_edges(self) -> int:
        return self.max_nodes * self.max_edges_per_node


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Optimizer/trainer configuration.

    Defaults are the published DeepDFA settings (reference:
    DDFA/configs/config_default.yaml:43-47 — Adam lr 1e-3, weight decay 1e-2,
    25 epochs, batch 256).
    """

    learning_rate: float = 1e-3
    weight_decay: float = 1e-2
    max_epochs: int = 25
    grad_clip_norm: Optional[float] = None
    positive_weight: Optional[float] = None
    seed: int = 1
    # When set, fit() checkpoints best/last here and a periodic snapshot
    # every N epochs (reference config_default.yaml:20-29 semantics).
    checkpoint_dir: Optional[str] = None
    checkpoint_every_epochs: int = 25
    # Per-step loss finiteness check (the Lightning ``detect_anomaly: true``
    # of config_default.yaml:40): synchronizes every step when on, so it
    # costs throughput — a debugging aid, not a production default.
    detect_anomaly: bool = False
    # What a detected non-finite loss does. "raise" is the fail-fast parity
    # path (FloatingPointError, today's behavior). "rollback" self-heals:
    # restore the last good state, skip the offending batch window, keep
    # training — at most ``anomaly_retry_budget`` times per fit before the
    # run fails anyway (a persistently-diverging run must still die).
    # "rollback" implies detection even with detect_anomaly=False.
    anomaly_policy: str = "raise"
    anomaly_retry_budget: int = 3
    # Optional TensorBoard event directory (MyTensorBoardLogger parity).
    tensorboard_dir: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class TransformerTrainConfig:
    """LineVul/CodeT5-style fine-tune settings (reference:
    LineVul/linevul/scripts/msr_train_combined.sh + CodeT5/sh/exp_with_args.sh).
    """

    learning_rate: float = 2e-5
    adam_epsilon: float = 1e-8
    weight_decay: float = 0.0
    max_epochs: int = 10
    batch_size: int = 16
    eval_batch_size: int = 16
    block_size: int = 512
    warmup_fraction: float = 0.2  # linear warmup over 20% of steps
    grad_clip_norm: float = 1.0
    seed: int = 1
    early_stop_patience: Optional[int] = None  # CodeT5 uses patience on eval F1
    # Non-finite-loss handling, mirroring TrainConfig: detection is one
    # host check per epoch (the loss transfer already happens there), and
    # "rollback" restores the epoch-start state and moves on — at most
    # ``anomaly_retry_budget`` times per fit. Default keeps fail-fast
    # parity ("raise" — and detection off unless opted in).
    detect_anomaly: bool = False
    anomaly_policy: str = "raise"
    anomaly_retry_budget: int = 3


def subkeys_for(spec: FeatureSpec) -> Sequence[str]:
    return ALL_SUBKEYS if spec.concat_all else (spec.subkey,)

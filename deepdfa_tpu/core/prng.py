"""Per-step dropout keys, TPU-tuned.

The train states carry a raw uint32[2] threefry key (checkpoint-friendly,
stable across backends); each step folds the step index in for the stream
position. On TPU the folded key is re-wrapped as an ``rbg`` key before it
reaches the dropout masks: XLA lowers threefry bit generation to a long
scalar hash chain that drags every dropout-fused matmul with it, while rbg
rides the hardware RNG — measured +7% combined-model training throughput
(195.4 -> 209.0 ex/s back-to-back, bench.py). Elsewhere (CPU test meshes)
the threefry key passes through unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepdfa_tpu.core.backend import tpu_backend


def fold_in_dropout(base_rng: jnp.ndarray, step: jnp.ndarray):
    """fold_in(base, step), re-wrapped for fast TPU bit generation.

    The fold itself stays threefry (one cheap hash of two words, and the
    train-state key keeps its uint32[2] layout for checkpoints); only the
    mask-generation impl changes, so the dropout stream is deterministic
    per (seed, step) on every backend — but not bit-identical across
    backends, which nothing depends on.
    """
    k = jax.random.fold_in(base_rng, step)
    if not tpu_backend():
        return k
    data = jnp.concatenate([jnp.ravel(k), jnp.ravel(k)]).astype(jnp.uint32)
    return jax.random.wrap_key_data(data, impl="rbg")

"""The one backend gate for "auto" implementation choices.

Several ops keep two formulations — a dense matmul/bmm form whose zero-fill
is free on the MXU, and a segment/gather form that wins elsewhere — and
resolve "auto" by backend. The rule lives here once so the sites
(GlobalAttentionPool, EmbedTable, flash-vs-blockwise attention, rbg dropout
keys) can never drift apart.
"""

from __future__ import annotations

import jax


def tpu_backend() -> bool:
    return jax.default_backend() == "tpu"


def resolve_auto(impl: str, tpu: str, other: str) -> str:
    """Map "auto" to the backend's choice; pass any explicit impl through."""
    if impl != "auto":
        return impl
    return tpu if tpu_backend() else other

"""Pure-JAX classification metrics.

Replaces the reference's torchmetrics MetricCollection
(DDFA/code_gnn/models/base_module.py:35-68): Accuracy/Precision/Recall/F1 as
jit-friendly count accumulators that compose across sharded steps via psum,
plus PR-curve points from stored prediction scores. All metrics accept a mask
so padded graph slots never contribute (the static-shape replacement for
dynamic batching).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.telemetry.registry import REGISTRY, sanitize


class BinaryStats(NamedTuple):
    """Sufficient statistics for binary classification metrics.

    Summable across batches and across devices (psum over the data axis), so
    a metric epoch is just a fold of these.
    """

    tp: jnp.ndarray
    fp: jnp.ndarray
    tn: jnp.ndarray
    fn: jnp.ndarray

    def __add__(self, other: "BinaryStats") -> "BinaryStats":  # type: ignore[override]
        return BinaryStats(
            self.tp + other.tp,
            self.fp + other.fp,
            self.tn + other.tn,
            self.fn + other.fn,
        )

    @staticmethod
    def zeros() -> "BinaryStats":
        z = jnp.zeros((), jnp.float32)
        return BinaryStats(z, z, z, z)


def binary_stats(
    probs: jnp.ndarray,
    labels: jnp.ndarray,
    mask: jnp.ndarray,
    threshold: float = 0.5,
) -> BinaryStats:
    """Confusion counts at ``threshold`` over masked entries.

    ``threshold=0.5`` matches the reference's ``class_threshold``
    (base_module.py:32).
    """
    pred = (probs >= threshold).astype(jnp.float32)
    lab = labels.astype(jnp.float32)
    m = mask.astype(jnp.float32)
    return BinaryStats(
        tp=jnp.sum(m * pred * lab),
        fp=jnp.sum(m * pred * (1.0 - lab)),
        tn=jnp.sum(m * (1.0 - pred) * (1.0 - lab)),
        fn=jnp.sum(m * (1.0 - pred) * lab),
    )


def compute_metrics(stats: BinaryStats) -> Dict[str, jnp.ndarray]:
    """Accuracy / Precision / Recall / F1 from counts.

    Division-by-zero yields 0, matching torchmetrics' default behavior on
    empty denominators.
    """
    tp, fp, tn, fn = stats.tp, stats.fp, stats.tn, stats.fn

    def _safe(n, d):
        return jnp.where(d > 0, n / jnp.where(d > 0, d, 1.0), 0.0)

    acc = _safe(tp + tn, tp + fp + tn + fn)
    prec = _safe(tp, tp + fp)
    rec = _safe(tp, tp + fn)
    f1 = _safe(2 * prec * rec, prec + rec)
    return {"acc": acc, "precision": prec, "recall": rec, "f1": f1}


def pr_curve(
    probs: np.ndarray, labels: np.ndarray, num_thresholds: int = 200
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Precision/recall arrays over a threshold sweep (host-side).

    Equivalent to the reference's ``torchmetrics.PrecisionRecallCurve`` export
    to ``pr.csv`` (base_module.py:59,362-372); a fixed grid of thresholds
    keeps the output size bounded like the binned variant.
    """
    probs = np.asarray(probs, np.float64)
    labels = np.asarray(labels, np.float64)
    thresholds = np.linspace(0.0, 1.0, num_thresholds)
    precisions, recalls = [], []
    for t in thresholds:
        pred = probs >= t
        tp = float(np.sum(pred * labels))
        fp = float(np.sum(pred * (1 - labels)))
        fn = float(np.sum((~pred) * labels))
        precisions.append(tp / (tp + fp) if tp + fp > 0 else 1.0)
        recalls.append(tp / (tp + fn) if tp + fn > 0 else 0.0)
    return np.array(precisions), np.array(recalls), thresholds


def latency_quantile(samples_ms, q: float) -> float:
    """Nearest-rank quantile over latency samples (host-side, ms).

    Nearest-rank (not interpolated) so the reported p99 is a latency some
    request actually experienced — the convention serving dashboards use.
    Empty samples report 0.0.
    """
    xs = np.sort(np.asarray(samples_ms, np.float64))
    if xs.size == 0:
        return 0.0
    rank = min(int(np.ceil(q * xs.size)) - 1, xs.size - 1)
    return float(xs[max(rank, 0)])


class ServingStats:
    """Host-side accumulator for the serving layer (deepdfa_tpu/serve).

    The serving siblings of :class:`BinaryStats`: counters and sums that
    fold across micro-batches, snapshotted into the ``/metrics`` endpoint
    and the bench report. Everything here is plain Python/numpy — these
    numbers are assembled from values that already crossed to the host
    (response assembly), never from in-flight device buffers, so updating
    them adds no device sync.

    Latencies keep a bounded ring of the most recent ``latency_window``
    samples; p50/p99 are over that window (a serving dashboard's rolling
    quantile, bounded memory under sustained traffic).

    Thread-safe: every mutation is a read-modify-write invoked from many
    transport threads (submit) plus the pump thread (completion), so a
    lock serializes them — without it, concurrent bumps lose increments
    and /metrics drifts.
    """

    COUNTERS = (
        "submitted", "completed", "rejected", "oversized", "cache_hits",
        "cache_misses", "degraded", "batches", "compiles", "failures",
    )

    def __init__(self, latency_window: int = 8192,
                 replica: "str | None" = None):
        import threading

        self._lock = threading.Lock()
        for name in self.COUNTERS:
            setattr(self, name, 0)
        # Fleet identity: when set (a member of serve/config.py's
        # statically-enumerated REPLICA_IDS), every bump also lands on
        # this replica's own registry series (serve_<rid>_*) alongside
        # the fleet-wide serve_* totals. The names are formatted from a
        # code-enumerated id, never from runtime data — the GL014
        # bounded-cardinality discipline; serve/fleet.py predeclares the
        # full set at init so the exposition carries every replica's
        # counters from the first scrape.
        self._replica = replica
        self.occupancy_used = 0   # real requests over all flushed batches
        self.occupancy_slots = 0  # padded slots over all flushed batches
        # Per-(lane, bucket) flush shapes (ISSUE 17): how much of each
        # compiled bucket's slot budget real traffic actually fills —
        # the measured input the traffic-shaped dynamic-batching work
        # needs. Keys are (lane, n_slots); both come from code-
        # enumerated sets (lane names, the config slot ladder), so the
        # derived gauge names stay GL014-bounded.
        self._padding: Dict[tuple, Dict[str, int]] = {}
        self._latency_window = latency_window
        self._latencies_ms = np.zeros(latency_window, np.float64)
        self._latency_count = 0  # total ever observed (ring write cursor)

    def bump(self, counter: str, by: int = 1) -> None:
        if counter not in self.COUNTERS:
            raise ValueError(f"unknown serving counter {counter!r}")
        with self._lock:
            setattr(self, counter, getattr(self, counter) + by)
        # Publish into the process-wide telemetry registry (this snapshot
        # API stays the per-engine view; the registry aggregates across
        # engines for Prometheus and the offline report).
        REGISTRY.counter(f"serve_{counter}_total").inc(by)
        if self._replica is not None:
            REGISTRY.counter(
                f"serve_{self._replica}_{counter}_total").inc(by)

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies_ms[
                self._latency_count % self._latency_window
            ] = seconds * 1000.0
            self._latency_count += 1
        REGISTRY.histogram("serve_latency_ms").observe(seconds * 1000.0)
        if self._replica is not None:
            REGISTRY.histogram(
                f"serve_{self._replica}_latency_ms").observe(
                    seconds * 1000.0)

    def record_batch(self, n_real: int, n_slots: int,
                     lane: "str | None" = None,
                     elems_used: "int | None" = None,
                     elems_per_slot: "int | None" = None,
                     elems_budget: "int | None" = None) -> None:
        """Fold one flushed micro-batch into the per-(lane, bucket) cells.

        The slot axis (``n_real`` of ``n_slots``) is PR-17's accounting.
        The element axis (ISSUE 20) decomposes the bucket's padded
        element budget (graph lanes: nodes; gen lane: source tokens)
        into three exactly-summing waste components:

        * ``slot_underfill``  — empty slots x per-slot share (the same
          waste the slot axis reports, in element units);
        * ``inslot_pad``      — occupied slots' pad up to the per-slot
          cap (the ``select_bucket`` node / src-length ladder's cost);
        * ``flush_overhead``  — the bucket budget's own pow2/tile
          rounding above ``n_slots * elems_per_slot``.

        ``slot_underfill + inslot_pad + flush_overhead ==
        elems_budget - elems_used`` by construction, so the element
        decomposition ties exactly to the slot-axis cells it extends.
        """
        elems = (elems_used is not None and elems_per_slot is not None
                 and elems_budget is not None)
        if lane is not None:
            with self._lock:
                self.batches += 1
                self.occupancy_used += n_real
                self.occupancy_slots += n_slots
                cell = self._padding.setdefault(
                    (lane, int(n_slots)),
                    {"used": 0, "slots": 0, "flushes": 0},
                )
                cell["used"] += n_real
                cell["slots"] += n_slots
                cell["flushes"] += 1
                if elems:
                    cell["elems_used"] = (
                        cell.get("elems_used", 0) + int(elems_used))
                    cell["elems_budget"] = (
                        cell.get("elems_budget", 0) + int(elems_budget))
                    cell["elems_slot_underfill"] = (
                        cell.get("elems_slot_underfill", 0)
                        + (n_slots - n_real) * int(elems_per_slot))
                    cell["elems_inslot_pad"] = (
                        cell.get("elems_inslot_pad", 0)
                        + n_real * int(elems_per_slot) - int(elems_used))
                    cell["elems_flush_overhead"] = (
                        cell.get("elems_flush_overhead", 0)
                        + int(elems_budget)
                        - n_slots * int(elems_per_slot))
                    elem_waste = 100.0 * (
                        1.0 - cell["elems_used"] / cell["elems_budget"])
                waste_pct = 100.0 * (1.0 - cell["used"] / cell["slots"])
            # Gauge name formatted from the lane parameter, the config
            # slot ladder, and the statically-enumerated replica id —
            # never from per-request data (GL014).
            suffix = f"_{self._replica}" if self._replica else ""
            REGISTRY.gauge(
                f"serve_padding_waste_pct_{lane}_b{int(n_slots)}{suffix}"
            ).set(round(waste_pct, 4))
            if elems:
                REGISTRY.gauge(
                    f"serve_elem_waste_pct_{lane}_b{int(n_slots)}{suffix}"
                ).set(round(elem_waste, 4))
        else:
            with self._lock:
                self.batches += 1
                self.occupancy_used += n_real
                self.occupancy_slots += n_slots
        REGISTRY.counter("serve_batches_total").inc()
        REGISTRY.counter("serve_slots_occupied_total").inc(n_real)
        REGISTRY.counter("serve_slots_padded_total").inc(n_slots - n_real)
        if elems:
            REGISTRY.counter("serve_elems_used_total").inc(int(elems_used))
            REGISTRY.counter("serve_elems_budget_total").inc(
                int(elems_budget))
        if self._replica is not None:
            REGISTRY.counter(f"serve_{self._replica}_batches_total").inc()

    @property
    def latencies_ms(self) -> np.ndarray:
        with self._lock:
            n = min(self._latency_count, self._latency_window)
            return self._latencies_ms[:n].copy()

    @property
    def occupancy(self) -> float:
        return (self.occupancy_used / self.occupancy_slots
                if self.occupancy_slots else 0.0)

    @property
    def cache_hit_rate(self) -> float:
        looked = self.cache_hits + self.cache_misses
        return self.cache_hits / looked if looked else 0.0

    def snapshot(self, queue_depth: int = 0) -> Dict[str, float]:
        """One JSON-able dict: the /metrics endpoint body and the bench
        record."""
        out: Dict[str, float] = {name: getattr(self, name)
                                 for name in self.COUNTERS}
        lat = self.latencies_ms
        out.update(
            queue_depth=queue_depth,
            batch_occupancy=self.occupancy,
            cache_hit_rate=self.cache_hit_rate,
            latency_p50_ms=latency_quantile(lat, 0.50),
            latency_p99_ms=latency_quantile(lat, 0.99),
            latency_samples=int(lat.size),
            padding_waste_pct=round(100.0 * (1.0 - self.occupancy), 4)
            if self.occupancy_slots else 0.0,
        )
        with self._lock:
            padding = {}
            for (lane, slots), cell in sorted(self._padding.items()):
                c = {"used": cell["used"], "slots": cell["slots"],
                     "waste_pct": round(
                         100.0 * (1.0 - cell["used"] / cell["slots"]), 2),
                     "flushes": cell["flushes"]}
                if cell.get("elems_budget"):
                    b = cell["elems_budget"]
                    c.update(
                        elems_used=cell.get("elems_used", 0),
                        elems_budget=b,
                        elems_slot_underfill=cell.get(
                            "elems_slot_underfill", 0),
                        elems_inslot_pad=cell.get("elems_inslot_pad", 0),
                        elems_flush_overhead=cell.get(
                            "elems_flush_overhead", 0),
                        elem_waste_pct=round(
                            100.0 * (1.0 - cell.get("elems_used", 0) / b),
                            2),
                        slot_underfill_pct=round(
                            100.0 * cell.get("elems_slot_underfill", 0)
                            / b, 2),
                        inslot_pad_pct=round(
                            100.0 * cell.get("elems_inslot_pad", 0) / b,
                            2),
                        flush_overhead_pct=round(
                            100.0 * cell.get("elems_flush_overhead", 0)
                            / b, 2),
                    )
                padding[f"{lane}:b{slots}"] = c
            e_used = sum(c.get("elems_used", 0)
                         for c in self._padding.values())
            e_budget = sum(c.get("elems_budget", 0)
                           for c in self._padding.values())
        if e_budget:
            out["elem_waste_pct"] = round(
                100.0 * (1.0 - e_used / e_budget), 4)
        if padding:
            out["padding_waste"] = padding
        return out


# Everything exactly summable across replicas / router processes in a
# padding cell; derived pct keys are recomputed after the merge.
_PADDING_SUM_KEYS = (
    "used", "slots", "flushes", "elems_used", "elems_budget",
    "elems_slot_underfill", "elems_inslot_pad", "elems_flush_overhead",
)


def merge_padding_cells(cell_maps) -> Dict[str, Dict[str, float]]:
    """Exact aggregation of per-(lane, bucket) padding cells across
    engine snapshots — the ONE merge the fleet front-end and the router
    tier both use (it was copy-pasted in serve/fleet.py and
    serve/router.py before ISSUE 20).

    ``cell_maps`` is an iterable of ``snapshot()["padding_waste"]``
    maps (None/missing entries tolerated). Counts sum exactly;
    ``waste_pct`` and the element-axis pct columns are recomputed from
    the merged counts, so the output for slot-only cells is
    byte-identical to what the two former copies produced.
    """
    merged: Dict[str, Dict[str, float]] = {}
    for cells in cell_maps:
        for key, cell in (cells or {}).items():
            acc = merged.setdefault(key, {"used": 0, "slots": 0})
            for k in _PADDING_SUM_KEYS:
                if k in cell:
                    acc[k] = acc.get(k, 0) + cell[k]
    for cell in merged.values():
        cell["waste_pct"] = round(
            100.0 * (1.0 - cell["used"] / cell["slots"]), 2
        ) if cell["slots"] else 0.0
        if cell.get("elems_budget"):
            b = cell["elems_budget"]
            cell["elem_waste_pct"] = round(
                100.0 * (1.0 - cell.get("elems_used", 0) / b), 2)
            cell["slot_underfill_pct"] = round(
                100.0 * cell.get("elems_slot_underfill", 0) / b, 2)
            cell["inslot_pad_pct"] = round(
                100.0 * cell.get("elems_inslot_pad", 0) / b, 2)
            cell["flush_overhead_pct"] = round(
                100.0 * cell.get("elems_flush_overhead", 0) / b, 2)
    return merged


class IngestStats:
    """Per-boundary ingestion counters (the data-contract siblings of
    :class:`ServingStats`, consumed by ``deepdfa_tpu/contracts``).

    Boundaries are free-form strings ("joern", "cache", "serve", ...);
    fields are ``seen`` / ``valid`` / ``rejected`` / ``repaired`` plus
    dynamic ``reason:<code>`` and ``repair:<code>`` taxonomy counters.
    Everything here is host-side Python on values that already crossed to
    the host (ingestion runs before any device work), so bumping adds no
    device sync. Thread-safe for the same reason ServingStats is: serve
    admission validates on many transport threads at once.
    """

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._counts: Dict[str, Dict[str, int]] = {}

    def bump(self, boundary: str, field: str, by: int = 1) -> None:
        with self._lock:
            b = self._counts.setdefault(boundary, {})
            b[field] = b.get(field, 0) + by
        # Mirror into the process registry (reason-code fields like
        # "reason:v1" sanitize into legal metric names); the per-boundary
        # snapshot stays this class's view.
        REGISTRY.counter(
            f"ingest_{sanitize(boundary)}_{sanitize(field)}_total"
        ).inc(by)

    def get(self, boundary: str, field: str) -> int:
        with self._lock:
            return self._counts.get(boundary, {}).get(field, 0)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """JSON-able per-boundary counter map (the ``cli validate`` /
        metrics-endpoint body)."""
        with self._lock:
            return {b: dict(sorted(fields.items()))
                    for b, fields in sorted(self._counts.items())}


def classification_report_dict(
    probs: np.ndarray, labels: np.ndarray, threshold: float = 0.5
) -> Dict[str, Dict[str, float]]:
    """sklearn-style per-class report (host-side), matching the reference's
    ``classification_report`` usage (base_module.py:376-383)."""
    pred = (np.asarray(probs) >= threshold).astype(np.int64)
    lab = np.asarray(labels).astype(np.int64)
    out: Dict[str, Dict[str, float]] = {}
    for cls in (0, 1):
        tp = float(np.sum((pred == cls) & (lab == cls)))
        fp = float(np.sum((pred == cls) & (lab != cls)))
        fn = float(np.sum((pred != cls) & (lab == cls)))
        prec = tp / (tp + fp) if tp + fp > 0 else 0.0
        rec = tp / (tp + fn) if tp + fn > 0 else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec > 0 else 0.0
        out[str(cls)] = {
            "precision": prec,
            "recall": rec,
            "f1-score": f1,
            "support": float(np.sum(lab == cls)),
        }
    out["accuracy"] = {"accuracy": float(np.mean(pred == lab))}
    return out

"""Shared retry/backoff utility: jittered exponential backoff, a wall-clock
deadline, and a *typed* giveup.

The ETL layer (Joern REPLs, forked pool workers) and any future external
dependency share one retry discipline instead of ad-hoc sleep loops:

* exponential backoff with full jitter — retries from many workers
  de-synchronize instead of stampeding a recovering dependency;
* a deadline — a retry loop may never hold a multi-hour pipeline hostage;
* ``giveup_on`` — errors that retrying cannot fix (bad input, missing
  binary) re-raise immediately instead of burning the attempt budget;
* :class:`GiveUp` — callers distinguish "retries exhausted" from the
  underlying error type, with the last error chained as ``__cause__``.

Determinism: pass a seeded ``rng`` (and a virtual ``sleep``/``clock``) to
make backoff schedules replayable in tests and fault-plan soaks.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Iterator, Optional, Tuple

_RNG = random.Random()


class GiveUp(Exception):
    """Retries exhausted (attempts or deadline). The last underlying
    exception is chained as ``__cause__`` and kept as ``.last``."""

    def __init__(self, message: str, last: BaseException, attempts: int,
                 elapsed_s: float):
        super().__init__(message)
        self.last = last
        self.attempts = attempts
        self.elapsed_s = elapsed_s


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """``max_attempts`` total calls (1 = no retry). Delay before retry k
    (1-based) is ``base_delay_s * multiplier**(k-1)`` capped at
    ``max_delay_s``, then jittered down to ``delay * (1 - jitter * u)``
    with ``u ~ U[0, 1)`` (full-jitter style: never longer than the
    deterministic schedule, so deadlines stay honest)."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 10.0
    jitter: float = 0.5
    deadline_s: Optional[float] = None
    retry_on: Tuple[type, ...] = (Exception,)
    giveup_on: Tuple[type, ...] = ()

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")


def backoff_delays(policy: RetryPolicy,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """The jittered delay schedule (one entry per retry, i.e.
    ``max_attempts - 1`` entries)."""
    rng = rng or _RNG
    delay = policy.base_delay_s
    for _ in range(policy.max_attempts - 1):
        capped = min(delay, policy.max_delay_s)
        yield capped * (1.0 - policy.jitter * rng.random())
        delay *= policy.multiplier


def retry_call(
    fn: Callable[..., Any],
    args: Tuple = (),
    kwargs: Optional[dict] = None,
    policy: RetryPolicy = RetryPolicy(),
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: Optional[random.Random] = None,
) -> Any:
    """Call ``fn(*args, **kwargs)`` under ``policy``.

    ``on_retry(attempt, exc, delay)`` runs before each sleep — the hook
    where callers repair state (e.g. restart a crashed Joern session)
    before the next attempt. Exceptions in ``giveup_on`` re-raise
    untouched; exhausting attempts or the deadline raises :class:`GiveUp`
    with the last error chained.
    """
    kwargs = kwargs or {}
    start = clock()
    delays = backoff_delays(policy, rng)
    last: Optional[BaseException] = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except policy.giveup_on:
            raise
        except policy.retry_on as exc:
            last = exc
            elapsed = clock() - start
            delay = next(delays, None)
            over_deadline = (
                policy.deadline_s is not None
                and delay is not None
                and elapsed + delay > policy.deadline_s
            )
            # Telemetry (import deferred: core.retry must stay importable
            # with zero package siblings loaded): each retry and each
            # giveup is an event in the run trace and a registry counter.
            from deepdfa_tpu import telemetry

            fn_name = getattr(fn, "__name__", "call")
            if delay is None or over_deadline:
                why = ("deadline exceeded" if over_deadline
                       else "attempts exhausted")
                telemetry.REGISTRY.counter("retry_giveups_total").inc()
                telemetry.event("retry.giveup", fn=fn_name, attempts=attempt,
                                why=why, error=type(exc).__name__)
                raise GiveUp(
                    f"{fn_name} failed after "
                    f"{attempt} attempt(s) in {elapsed:.2f}s ({why}): "
                    f"{type(exc).__name__}: {exc}",
                    last=exc, attempts=attempt, elapsed_s=elapsed,
                ) from exc
            telemetry.REGISTRY.counter("retry_attempts_total").inc()
            telemetry.event("retry", fn=fn_name, attempt=attempt,
                            delay_s=delay, error=type(exc).__name__)
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover

from deepdfa_tpu.core.config import (
    DataConfig,
    FeatureSpec,
    FlowGNNConfig,
    TrainConfig,
)
from deepdfa_tpu.core.metrics import (
    BinaryStats,
    binary_stats,
    compute_metrics,
)

__all__ = [
    "DataConfig",
    "FeatureSpec",
    "FlowGNNConfig",
    "TrainConfig",
    "BinaryStats",
    "binary_stats",
    "compute_metrics",
]

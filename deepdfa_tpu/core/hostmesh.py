"""Environment recipe for a virtual multi-device CPU mesh.

Real multi-chip hardware is unavailable in CI and in the driver environment;
sharding correctness is validated on XLA's host platform with
``--xla_force_host_platform_device_count=N`` (same program, same collectives,
CPU execution). The platform choice must be in the environment *before* the
interpreter starts: this image's sitecustomize registers the axon TPU PJRT
plugin at startup, and flipping ``JAX_PLATFORMS`` afterwards stalls the
process. Every consumer (tests/conftest.py, __graft_entry__.dryrun_multichip)
therefore re-execs into a fresh interpreter whose environment this one helper
produces — keep the protocol here, in one place.

This module must stay import-light (no jax): it runs pre-re-exec in
processes whose platform is still wrong.
"""

from __future__ import annotations

from typing import Mapping, MutableMapping

DEVICE_COUNT_FLAG = "xla_force_host_platform_device_count"


def cpu_mesh_env(
    env: Mapping[str, str], n_devices: int, force_count: bool = True
) -> MutableMapping[str, str]:
    """Copy of ``env`` configured for an ``n_devices`` virtual CPU mesh.

    ``force_count=True`` replaces any existing device-count flag (callers
    that need *exactly* n devices, e.g. the multi-chip dry run);
    ``force_count=False`` keeps a caller-provided count (tests, where an
    outer harness may have picked its own).
    """
    out = dict(env)
    out["JAX_PLATFORMS"] = "cpu"
    out["PALLAS_AXON_POOL_IPS"] = ""  # skip axon TPU plugin registration
    flags = out.get("XLA_FLAGS", "").split()
    if force_count:
        flags = [f for f in flags if DEVICE_COUNT_FLAG not in f]
    if not any(DEVICE_COUNT_FLAG in f for f in flags):
        flags.append(f"--{DEVICE_COUNT_FLAG}={n_devices}")
    out["XLA_FLAGS"] = " ".join(flags)
    return out

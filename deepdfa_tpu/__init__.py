"""deepdfa_tpu: a TPU-native (JAX/XLA/Pallas/pjit) vulnerability-detection framework.

A from-scratch rebuild of the capability surface of the DeepDFA reference stack
(ICSE'24, "Dataflow Analysis-Inspired Deep Learning for Efficient Vulnerability
Detection"): the FlowGNN gated graph network over program CFGs with abstract
dataflow embeddings, the LineVul (RoBERTa/UniXcoder) sequence classifiers, the
CodeT5 defect classifier, combined graph+text models, the Joern-based ETL
pipeline, and the evaluation/profiling subsystem — all designed TPU-first:

- static-shape bucketed graph batching instead of dynamic `dgl.batch`
- segment-op message passing on XLA (with a Pallas kernel for the hot loop)
  instead of DGL's CUDA kernels
- `jax.sharding.Mesh` + jit-sharded data parallelism instead of
  DataParallel/DDP+NCCL
- orbax checkpointing, HLO cost analysis instead of DeepSpeed FlopsProfiler

Subpackages:
  core      config dataclasses, pure-JAX metrics
  graphs    padded graph batches, segment ops, bucketing
  models    flowgnn / linevul / codet5 model families
  ops       Pallas TPU kernels
  parallel  mesh + sharding helpers
  train     jit-sharded training loops, checkpointing
  data      datasets, splits, host input pipeline
  etl       Joern output parsing, reaching-definitions, abstract dataflow
  eval      reports, PR curves, profiling
"""

__version__ = "0.1.0"

"""Command-line entry point: fit / test / analyze / tune.

Replaces the reference's three coexisting config systems (SURVEY §5 —
LightningCLI+YAML with link_arguments, plain argparse, and NNI injection)
with one structured CLI over the dataclass configs:

  python -m deepdfa_tpu.cli fit  --config cfg.yaml --set train.max_epochs=5
  python -m deepdfa_tpu.cli test --checkpoint-dir runs/x --which best
  python -m deepdfa_tpu.cli analyze --dataset synthetic:256
  python -m deepdfa_tpu.cli tune --trials 8 --dataset synthetic:256

Reference semantics carried over:
  - layered ``--config`` YAML files, later files override earlier
    (main_cli.py:315-321 config chains);
  - ``--set section.key=value`` overrides anything (NNI param injection,
    main_cli.py:110-121 — also honored from the ``DEEPDFA_TUNE_PARAMS``
    env var as JSON);
  - data→model linking: the model's ``input_dim`` derives from the feature
    spec (link_arguments, main_cli.py:73-99) by construction here;
  - crash handling renames the run log to ``.error`` and re-raises
    (main_cli.py:324-336);
  - after fit, the best-val-loss state is evaluated and reported
    (main_cli.py:167-184) — tracked explicitly, not re-parsed from
    checkpoint filenames;
  - ``analyze`` reports abstract-dataflow feature coverage like
    ``--analyze_dataset`` (get_coverage, main_cli.py:192-313).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepdfa_tpu.core.config import (
    DataConfig,
    FeatureSpec,
    FlowGNNConfig,
    TrainConfig,
    subkeys_for,
)

logger = logging.getLogger("deepdfa_tpu")


# ---------------------------------------------------------------------------
# Config assembly
# ---------------------------------------------------------------------------

_SECTIONS = {"model": FlowGNNConfig, "data": DataConfig, "train": TrainConfig}


def _coerce(value: str, field_type: Any):
    if field_type is bool or str(field_type) == "bool":
        return value.lower() in ("1", "true", "yes")
    for cast in (int, float):
        try:
            return cast(value)
        except (TypeError, ValueError):
            continue
    return value


def build_configs(
    config_files: List[str], overrides: List[str],
    inject_service_params: bool = False,
) -> Dict[str, Any]:
    """Layered YAML + key=value overrides -> {"model", "data", "train"}.

    ``inject_service_params``: also pull one parameter set from an attached
    NNI service (nni.get_next_parameter is one-call-per-trial, so only the
    trial entrypoint — cmd_fit — may set this)."""
    import yaml

    def deep_update(dst: Dict, src: Dict) -> None:
        for k, v in src.items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                deep_update(dst[k], v)
            else:
                dst[k] = v

    merged: Dict[str, Dict[str, Any]] = {k: {} for k in _SECTIONS}
    for path in config_files:
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        for section, values in doc.items():
            if section not in merged:
                raise ValueError(f"unknown config section {section!r} in {path}")
            deep_update(merged[section], values or {})

    # Injected tune params apply before explicit --set: the command line
    # always wins. Order: nni service < DEEPDFA_TUNE_PARAMS env < --set
    # (the reference mutates the parsed config from nni.get_next_parameter,
    # main_cli.py:110-121).
    injected: List[str] = []
    if inject_service_params:
        from deepdfa_tpu.train.tune import nni_next_parameters

        nni_params = nni_next_parameters()
        if nni_params:
            injected += [
                f"{dotted}={value}" for dotted, value in nni_params.items()
            ]
    env_params = os.environ.get("DEEPDFA_TUNE_PARAMS")
    if env_params:
        injected += [
            f"{dotted}={value}"
            for dotted, value in json.loads(env_params).items()
        ]
    overrides = injected + list(overrides)
    for item in overrides:
        dotted, _, value = item.partition("=")
        section, _, key = dotted.partition(".")
        if section not in merged or not key:
            raise ValueError(f"override must be section.key=value, got {item!r}")
        merged[section][key] = value

    out: Dict[str, Any] = {}
    for section, cls in _SECTIONS.items():
        kwargs = dict(merged[section])
        if section == "model" and "feature" in kwargs:
            feat = kwargs["feature"]
            kwargs["feature"] = (
                FeatureSpec.parse_legacy(feat) if isinstance(feat, str)
                else FeatureSpec(**feat)
            )
        fields = {f.name: f for f in dataclasses.fields(cls)}
        for k in list(kwargs):
            if k not in fields:
                raise ValueError(f"unknown {section} option {k!r}")
            if isinstance(kwargs[k], str) and fields[k].type not in (str, "str"):
                kwargs[k] = _coerce(kwargs[k], fields[k].type)
        out[section] = cls(**kwargs)
    return out


# ---------------------------------------------------------------------------
# Datasets
# ---------------------------------------------------------------------------


def _read_pinned_split(path: str) -> Optional[Dict[int, str]]:
    """Read a splits.json in either layout: {"<id>": "train", ...} (current)
    or {"train": [ids], ...} (pre-pinning exports). None when absent."""
    if not os.path.exists(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    if not doc:
        # {} would sniff as the legacy layout and pin every partition empty;
        # treat it as no pinned split.
        return None
    if set(doc) <= {"train", "val", "test"}:  # legacy layout
        return {int(i): part for part, ids in doc.items() for i in ids}
    return {int(k): v for k, v in doc.items()}


def load_dataset(spec: str, feature: FeatureSpec, seed: int = 0,
                 split_mode: str = "random"):
    """"synthetic[:N]" for the built-in sample generator, or a ``.jsonl``
    of exported graph examples (the etl/export.py ``cpg_to_example``
    format: num_nodes/senders/receivers/vuln/feats/label/id per line)."""
    from deepdfa_tpu.data.splits import make_splits

    if spec.startswith("synthetic"):
        from deepdfa_tpu.data.synthetic import synthetic_bigvul

        n = int(spec.split(":")[1]) if ":" in spec else 256
        examples = synthetic_bigvul(
            n, feature, positive_fraction=0.5, seed=seed
        )
        for i, ex in enumerate(examples):
            ex["label"] = int(np.asarray(ex["vuln"]).max())
            ex["id"] = i
        splits = make_splits(examples, mode=split_mode, seed=seed)
        return examples, splits
    if spec.endswith(".jsonl") and os.path.exists(spec):
        examples = []
        with open(spec) as f:
            for i, line in enumerate(f):
                ex = json.loads(line)
                for key in ("senders", "receivers", "vuln"):
                    ex[key] = np.asarray(ex[key], np.int32)
                ex["feats"] = {
                    k: np.asarray(v, np.int32) for k, v in ex["feats"].items()
                }
                ex.setdefault("id", i)
                ex.setdefault("label", int(ex["vuln"].max()) if len(ex["vuln"]) else 0)
                examples.append(ex)
        # A sibling splits.json (written by etl.pipeline export) pins the
        # partition the abstract-dataflow vocab was built on; re-splitting
        # would leak vocab-defining train examples into test.
        sibling = os.path.join(os.path.dirname(spec) or ".", "splits.json")
        fixed = _read_pinned_split(sibling)
        if split_mode == "random" and fixed is not None:
            logger.info("using pinned split %s", sibling)
            splits = make_splits(examples, mode="fixed", fixed=fixed)
        else:
            if fixed is not None:
                logger.warning(
                    "overriding the pinned split %s with --split-mode=%s: "
                    "the abstract-dataflow vocab was built on the pinned "
                    "train partition, so re-splitting risks vocab leakage "
                    "into test — re-export with the matching --split-mode",
                    sibling, split_mode,
                )
            splits = make_splits(examples, mode=split_mode, seed=seed)
        return examples, splits
    raise ValueError(f"unknown dataset spec {spec!r}")


# ---------------------------------------------------------------------------
# Logging + crash handling (main_cli.py:31-65,324-336)
# ---------------------------------------------------------------------------


def _setup_run_logging(run_dir: str):
    os.makedirs(run_dir, exist_ok=True)
    log_path = os.path.join(run_dir, f"run_{time.strftime('%Y%m%d_%H%M%S')}.log")
    handler = logging.FileHandler(log_path)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s")
    )
    logging.getLogger().addHandler(handler)
    logging.getLogger().setLevel(logging.INFO)
    return log_path, handler


class _CrashLog:
    """Rename the run log to ``.error`` on crash (main_cli.py:324-336) and
    detach its handler either way (repeat invocations must not stack)."""

    def __init__(self, log_path: str, handler: logging.Handler):
        self.log_path = log_path
        self.handler = handler

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        logging.getLogger().removeHandler(self.handler)
        self.handler.close()
        if exc_type is not None and os.path.exists(self.log_path):
            os.replace(self.log_path, self.log_path + ".error")
        return False


# ---------------------------------------------------------------------------
# Commands
# ---------------------------------------------------------------------------


def cmd_fit(args) -> Dict[str, Any]:
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit
    from deepdfa_tpu.train.tune import TrialReporter

    cfgs = build_configs(args.config, args.set, inject_service_params=True)
    model_cfg, data_cfg = cfgs["model"], cfgs["data"]
    train_cfg = cfgs["train"]
    # One run directory for checkpoints, log, and history: CLI flag beats
    # YAML beats the default — and checkpoints are always written.
    run_dir = args.checkpoint_dir or train_cfg.checkpoint_dir or "runs/default"
    train_cfg = dataclasses.replace(train_cfg, checkpoint_dir=run_dir)
    log_path, handler = _setup_run_logging(run_dir)
    with _CrashLog(log_path, handler):
        examples, splits = load_dataset(args.dataset, model_cfg.feature,
                                        seed=train_cfg.seed,
                                        split_mode=args.split_mode)
        model = FlowGNN(model_cfg)
        mesh = None
        if args.n_devices > 1:
            from deepdfa_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(n_data=args.n_devices)
        # Under a real NNI trial the reporter streams per-epoch val F1 and
        # the final best (base_module.py:346, main_cli.py:184); otherwise
        # both calls are no-ops.
        reporter = TrialReporter()

        def report_epoch(epoch, record):
            reporter.intermediate(record["val_metrics"].get("f1", 0.0))
            return False  # reporting only; the service decides terminations

        on_epoch = report_epoch if reporter.attached else None
        state, history = fit(model, examples, splits, train_cfg, data_cfg,
                             mesh=mesh, resume=getattr(args, "resume", False),
                             on_epoch_end=on_epoch)
        result = {
            "best_epoch": history["best_epoch"],
            "best_val_loss": history["best_val_loss"],
            "final_val_metrics": history["epochs"][-1]["val_metrics"]
            if history["epochs"] else {},
        }
        if reporter.attached and history["epochs"]:
            reporter.final(max(
                e["val_metrics"].get("f1", 0.0) for e in history["epochs"]
            ))
        with open(os.path.join(run_dir, "history.json"), "w") as f:
            json.dump(history, f, indent=1)
        print(json.dumps(result))
        return result


def cmd_test(args) -> Dict[str, Any]:
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.checkpoint import CheckpointManager
    from deepdfa_tpu.train.loop import (
        evaluate,
        make_eval_step,
        make_train_state,
        _batches,
    )

    cfgs = build_configs(args.config, args.set)
    model_cfg, data_cfg, train_cfg = cfgs["model"], cfgs["data"], cfgs["train"]
    examples, splits = load_dataset(args.dataset, model_cfg.feature,
                                    seed=train_cfg.seed,
                                    split_mode=args.split_mode)
    model = FlowGNN(model_cfg)
    subkeys = subkeys_for(model_cfg.feature)
    use_tile = model_cfg.message_impl == "tile"
    use_df = model_cfg.label_style.startswith("dataflow_solution")
    example_batch = next(
        _batches(examples, splits["test"][: data_cfg.eval_batch_size], data_cfg,
                 subkeys, data_cfg.eval_batch_size, build_tile_adj=use_tile,
                 with_dataflow=use_df)
    )
    state, _ = make_train_state(model, example_batch, train_cfg)
    ckpt = CheckpointManager(args.checkpoint_dir)
    state = ckpt.restore(args.which, state)

    import jax

    eval_step = jax.jit(make_eval_step(model, train_cfg))
    res = evaluate(eval_step, state, examples, splits["test"], data_cfg, subkeys,
                   build_tile_adj=use_tile, with_dataflow=use_df)
    report = {"loss": res.loss, **res.metrics}

    if getattr(args, "profile", False) or getattr(args, "time", False):
        # run_profiling.sh parity: re-run the test batches under the
        # FLOPs/latency instruments (base_module.py:238-291) and aggregate
        # like scripts/report_profiling.py:18-66.
        from deepdfa_tpu.eval.profiling import ProfileRecorder, profile_eval
        from deepdfa_tpu.eval.report import aggregate_profile, aggregate_time

        out_dir = args.profile_dir or args.checkpoint_dir
        os.makedirs(out_dir, exist_ok=True)
        profile_path = (
            os.path.join(out_dir, "profiledata.jsonl") if args.profile else None
        )
        time_path = os.path.join(out_dir, "timedata.jsonl") if args.time else None
        for p in (profile_path, time_path):
            if p and os.path.exists(p):
                os.remove(p)  # fresh run, not an append to a stale one
        batches = list(
            _batches(examples, splits["test"], data_cfg, subkeys,
                     data_cfg.eval_batch_size, build_tile_adj=use_tile,
                     with_dataflow=use_df)
        )
        recorder = ProfileRecorder(profile_path, time_path)
        summary = profile_eval(
            lambda b: eval_step(state, b),
            batches,
            state.params,
            lambda b: int(np.asarray(b.graph_mask).sum()),
            recorder,
            # warmup-3 protocol (base_module.py:240-243), but always keep at
            # least one measured step on tiny test sets
            n_warmup=min(3, max(len(batches) - 1, 0)),
        )
        report["profiling"] = summary
        if profile_path:
            report["profiling"].update(aggregate_profile(profile_path))
        if time_path:
            report["profiling"].update(aggregate_time(time_path))

    print(json.dumps(report))
    return report


def cmd_analyze(args) -> Dict[str, Any]:
    """Feature coverage: share of definition nodes whose abstract-dataflow
    index is known vs UNKNOWN (index 1) vs not-a-definition (index 0) —
    get_coverage semantics (main_cli.py:192-313, paper Table 2 ~79% at
    k=1000)."""
    cfgs = build_configs(args.config, args.set)
    model_cfg = cfgs["model"]
    examples, _ = load_dataset(args.dataset, model_cfg.feature)
    subkeys = subkeys_for(model_cfg.feature)
    report: Dict[str, Any] = {"n_examples": len(examples)}
    for k in subkeys:
        known = unknown = nondef = 0
        for ex in examples:
            feats = np.asarray(ex["feats"][k])
            nondef += int((feats == 0).sum())
            unknown += int((feats == 1).sum())
            known += int((feats > 1).sum())
        defs = known + unknown
        report[k] = {
            "definitions": defs,
            "coverage": known / defs if defs else 0.0,
            "nondef_nodes": nondef,
        }
    print(json.dumps(report))
    return report


def cmd_tune(args) -> Dict[str, Any]:
    """Random hyperparameter search (the NNI replacement): samples the
    published search space (paper Table 2 context), runs short fits, ranks
    by best val F1, writes tune_results.jsonl.

    Per-epoch val F1 feeds a median-stop assessor (NNI's early-termination
    rule, train/tune.py): once enough trials completed, a trial whose best
    F1 trails the median of completed running-averages is cut short — its
    record carries ``epochs_run`` < epochs_per_trial."""
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit
    from deepdfa_tpu.train.tune import MedianStopAssessor

    cfgs = build_configs(args.config, args.set)
    base_model, base_data, base_train = cfgs["model"], cfgs["data"], cfgs["train"]
    rng = np.random.RandomState(base_train.seed)
    space = {
        "train.learning_rate": [1e-4, 5e-4, 1e-3, 5e-3],
        "train.weight_decay": [0.0, 1e-3, 1e-2],
        "model.hidden_dim": [16, 32, 64],
        "model.n_steps": [3, 5, 7],
    }
    examples, splits = load_dataset(args.dataset, base_model.feature,
                                    seed=base_train.seed,
                                    split_mode=args.split_mode)
    results = []
    out_path = os.path.join(args.out_dir, "tune_results.jsonl")
    os.makedirs(args.out_dir, exist_ok=True)
    open(out_path, "w").close()  # fresh file per run: no stale trials
    assessor = MedianStopAssessor(warmup_steps=args.assessor_warmup)
    for trial in range(args.trials):
        pick = {k: v[rng.randint(len(v))] for k, v in space.items()}
        model_cfg = dataclasses.replace(
            base_model,
            hidden_dim=int(pick["model.hidden_dim"]),
            n_steps=int(pick["model.n_steps"]),
        )
        train_cfg = dataclasses.replace(
            base_train,
            learning_rate=float(pick["train.learning_rate"]),
            weight_decay=float(pick["train.weight_decay"]),
            max_epochs=args.epochs_per_trial,
        )

        def on_epoch(epoch, record, trial=trial):
            assessor.report(trial, record["val_metrics"].get("f1", 0.0))
            return assessor.should_stop(trial)

        _, history = fit(FlowGNN(model_cfg), examples, splits, train_cfg,
                         base_data, on_epoch_end=on_epoch)
        assessor.complete(trial)
        best_f1 = max(
            (e["val_metrics"].get("f1", 0.0) for e in history["epochs"]),
            default=0.0,
        )
        record = {"trial": trial, "params": pick, "best_val_f1": best_f1,
                  "best_val_loss": history["best_val_loss"],
                  "epochs_run": len(history["epochs"]),
                  "early_stopped": bool(history.get("early_stopped", False))}
        results.append(record)
        with open(out_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        logger.info("trial %d: f1=%.4f epochs=%d%s %s", trial, best_f1,
                    record["epochs_run"],
                    " (assessor-stopped)" if record["early_stopped"] else "",
                    pick)
    best = max(results, key=lambda r: r["best_val_f1"])
    print(json.dumps(best))
    return best


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="deepdfa_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    def common(p):
        p.add_argument("--config", action="append", default=[],
                       help="YAML config file (repeatable; later overrides)")
        p.add_argument("--set", action="append", default=[], metavar="S.K=V",
                       help="override any config value")
        p.add_argument("--dataset", default="synthetic:256")
        p.add_argument("--split-mode", default="random",
                       choices=["random", "cross-project"],
                       help="cross-project = the Table 7 protocol")

    p_fit = sub.add_parser("fit")
    common(p_fit)
    p_fit.add_argument("--checkpoint-dir", default=None)
    p_fit.add_argument("--n-devices", type=int, default=1)
    p_fit.add_argument("--resume", action="store_true",
                       help="continue from the run dir's 'last' checkpoint")
    p_fit.set_defaults(func=cmd_fit)

    p_test = sub.add_parser("test")
    common(p_test)
    p_test.add_argument("--checkpoint-dir", required=True)
    p_test.add_argument("--which", default="best", help="best | last | epoch_N")
    # The reference's profiling flow (scripts/run_profiling.sh ->
    # --model.profile/--model.time, base_module.py:238-291): per-step
    # FLOPs/latency JSONL plus an aggregated Table-5-style summary.
    p_test.add_argument("--profile", action="store_true",
                        help="record per-step FLOPs/MACs to profiledata.jsonl")
    p_test.add_argument("--time", action="store_true",
                        help="record per-step latency to timedata.jsonl")
    p_test.add_argument("--profile-dir", default=None,
                        help="where the JSONL records land (default: "
                             "checkpoint dir)")
    p_test.set_defaults(func=cmd_test)

    p_an = sub.add_parser("analyze")
    common(p_an)
    p_an.set_defaults(func=cmd_analyze)

    p_tune = sub.add_parser("tune")
    common(p_tune)
    p_tune.add_argument("--trials", type=int, default=8)
    p_tune.add_argument("--epochs-per-trial", type=int, default=3)
    p_tune.add_argument("--out-dir", default="runs/tune")
    p_tune.add_argument("--assessor-warmup", type=int, default=1,
                        help="epochs before the median-stop assessor may "
                             "terminate a trial (NNI start_step; with the "
                             "3-epoch trial default, 1 leaves epochs 2-3 "
                             "cuttable)")
    p_tune.set_defaults(func=cmd_tune)

    args = parser.parse_args(argv)
    args.func(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Command-line entry point: fit / test / analyze / tune.

Replaces the reference's three coexisting config systems (SURVEY §5 —
LightningCLI+YAML with link_arguments, plain argparse, and NNI injection)
with one structured CLI over the dataclass configs:

  python -m deepdfa_tpu.cli fit  --config cfg.yaml --set train.max_epochs=5
  python -m deepdfa_tpu.cli test --checkpoint-dir runs/x --which best
  python -m deepdfa_tpu.cli analyze --dataset synthetic:256
  python -m deepdfa_tpu.cli analyze-code          # graftlint over our sources
  python -m deepdfa_tpu.cli tune --trials 8 --dataset synthetic:256

Reference semantics carried over:
  - layered ``--config`` YAML files, later files override earlier
    (main_cli.py:315-321 config chains);
  - ``--set section.key=value`` overrides anything (NNI param injection,
    main_cli.py:110-121 — also honored from the ``DEEPDFA_TUNE_PARAMS``
    env var as JSON);
  - data→model linking: the model's ``input_dim`` derives from the feature
    spec (link_arguments, main_cli.py:73-99) by construction here;
  - crash handling renames the run log to ``.error`` and re-raises
    (main_cli.py:324-336);
  - after fit, the best-val-loss state is evaluated and reported
    (main_cli.py:167-184) — tracked explicitly, not re-parsed from
    checkpoint filenames;
  - ``analyze`` reports abstract-dataflow feature coverage like
    ``--analyze_dataset`` (get_coverage, main_cli.py:192-313).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepdfa_tpu import telemetry
from deepdfa_tpu.core.config import (
    DataConfig,
    FeatureSpec,
    FlowGNNConfig,
    TrainConfig,
    subkeys_for,
)

logger = logging.getLogger("deepdfa_tpu")


# ---------------------------------------------------------------------------
# Config assembly
# ---------------------------------------------------------------------------

_SECTIONS = {"model": FlowGNNConfig, "data": DataConfig, "train": TrainConfig}


def _coerce(value: str, field_type: Any):
    if field_type is bool or str(field_type) == "bool":
        return value.lower() in ("1", "true", "yes")
    for cast in (int, float):
        try:
            return cast(value)
        except (TypeError, ValueError):
            continue
    return value


def env_injected_overrides() -> List[str]:
    """DEEPDFA_TUNE_PARAMS (JSON {dotted: value}) as ``section.key=value``
    items — THE parse of the env injection; build_configs and the fit-text
    override guard must agree on it."""
    env_params = os.environ.get("DEEPDFA_TUNE_PARAMS")
    if not env_params:
        return []
    return [f"{dotted}={value}"
            for dotted, value in json.loads(env_params).items()]


def build_configs(
    config_files: List[str], overrides: List[str],
    inject_service_params: bool = False,
) -> Dict[str, Any]:
    """Layered YAML + key=value overrides -> {"model", "data", "train"}.

    ``inject_service_params``: also pull one parameter set from an attached
    NNI service (nni.get_next_parameter is one-call-per-trial, so only the
    trial entrypoint — cmd_fit — may set this)."""
    import yaml

    def deep_update(dst: Dict, src: Dict) -> None:
        for k, v in src.items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                deep_update(dst[k], v)
            else:
                dst[k] = v

    merged: Dict[str, Dict[str, Any]] = {k: {} for k in _SECTIONS}
    for path in config_files:
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        for section, values in doc.items():
            if section not in merged:
                raise ValueError(f"unknown config section {section!r} in {path}")
            deep_update(merged[section], values or {})

    # Injected tune params apply before explicit --set: the command line
    # always wins. Order: nni service < DEEPDFA_TUNE_PARAMS env < --set
    # (the reference mutates the parsed config from nni.get_next_parameter,
    # main_cli.py:110-121).
    injected: List[str] = []
    if inject_service_params:
        from deepdfa_tpu.train.tune import nni_next_parameters

        nni_params = nni_next_parameters()
        if nni_params:
            injected += [
                f"{dotted}={value}" for dotted, value in nni_params.items()
            ]
    injected += env_injected_overrides()
    overrides = injected + list(overrides)
    for item in overrides:
        dotted, _, value = item.partition("=")
        section, _, key = dotted.partition(".")
        if section not in merged or not key:
            raise ValueError(f"override must be section.key=value, got {item!r}")
        merged[section][key] = value

    out: Dict[str, Any] = {}
    for section, cls in _SECTIONS.items():
        kwargs = dict(merged[section])
        if section == "model" and "feature" in kwargs:
            feat = kwargs["feature"]
            kwargs["feature"] = (
                FeatureSpec.parse_legacy(feat) if isinstance(feat, str)
                else FeatureSpec(**feat)
            )
        fields = {f.name: f for f in dataclasses.fields(cls)}
        for k in list(kwargs):
            if k not in fields:
                raise ValueError(f"unknown {section} option {k!r}")
            if isinstance(kwargs[k], str) and fields[k].type not in (str, "str"):
                kwargs[k] = _coerce(kwargs[k], fields[k].type)
        out[section] = cls(**kwargs)
    return out


# ---------------------------------------------------------------------------
# Datasets
# ---------------------------------------------------------------------------


def _read_pinned_split(path: str) -> Optional[Dict[int, str]]:
    """Read a splits.json in either layout: {"<id>": "train", ...} (current)
    or {"train": [ids], ...} (pre-pinning exports). None when absent."""
    if not os.path.exists(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    if not doc:
        # {} would sniff as the legacy layout and pin every partition empty;
        # treat it as no pinned split.
        return None
    if set(doc) <= {"train", "val", "test"}:  # legacy layout
        return {int(i): part for part, ids in doc.items() for i in ids}
    return {int(k): v for k, v in doc.items()}


def load_dataset(spec: str, feature: FeatureSpec, seed: int = 0,
                 split_mode: str = "random"):
    """"synthetic[:N]" for the built-in sample generator, or a ``.jsonl``
    of exported graph examples (the etl/export.py ``cpg_to_example``
    format: num_nodes/senders/receivers/vuln/feats/label/id per line)."""
    from deepdfa_tpu.data.splits import make_splits

    if spec.startswith("synthetic"):
        from deepdfa_tpu.data.synthetic import synthetic_bigvul

        n = int(spec.split(":")[1]) if ":" in spec else 256
        examples = synthetic_bigvul(
            n, feature, positive_fraction=0.5, seed=seed
        )
        for i, ex in enumerate(examples):
            ex["label"] = int(np.asarray(ex["vuln"]).max())
            ex["id"] = i
        splits = make_splits(examples, mode=split_mode, seed=seed)
        return examples, splits
    if spec.endswith(".jsonl") and os.path.exists(spec):
        # Schema-validated ingestion (deepdfa_tpu/contracts): rows that
        # violate the example contract are moved to the corpus's
        # quarantine/ sibling (manifest.jsonl records item id, boundary,
        # reason code, offending fragment) and skipped — fail-closed, so a
        # poisoned cache row can never reach batch_graphs or the model.
        from deepdfa_tpu.contracts import load_examples_jsonl

        examples, ingest_report = load_examples_jsonl(
            spec, subkeys_for(feature))
        if ingest_report["quarantined"]:
            logger.warning(
                "dataset %s: %d row(s) quarantined (%s) -> %s", spec,
                ingest_report["quarantined"], ingest_report["by_reason"],
                ingest_report["dir"],
            )
        # A sibling splits.json (written by etl.pipeline export) pins the
        # partition the abstract-dataflow vocab was built on; re-splitting
        # would leak vocab-defining train examples into test.
        sibling = os.path.join(os.path.dirname(spec) or ".", "splits.json")
        fixed = _read_pinned_split(sibling)
        if split_mode == "random" and fixed is not None:
            logger.info("using pinned split %s", sibling)
            splits = make_splits(examples, mode="fixed", fixed=fixed)
        else:
            if fixed is not None:
                logger.warning(
                    "overriding the pinned split %s with --split-mode=%s: "
                    "the abstract-dataflow vocab was built on the pinned "
                    "train partition, so re-splitting risks vocab leakage "
                    "into test — re-export with the matching --split-mode",
                    sibling, split_mode,
                )
            splits = make_splits(examples, mode=split_mode, seed=seed)
        return examples, splits
    raise ValueError(f"unknown dataset spec {spec!r}")


# ---------------------------------------------------------------------------
# Logging + crash handling (main_cli.py:31-65,324-336)
# ---------------------------------------------------------------------------


def _setup_run_logging(run_dir: str):
    os.makedirs(run_dir, exist_ok=True)
    log_path = os.path.join(run_dir, f"run_{time.strftime('%Y%m%d_%H%M%S')}.log")
    handler = logging.FileHandler(log_path)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s")
    )
    logging.getLogger().addHandler(handler)
    logging.getLogger().setLevel(logging.INFO)
    return log_path, handler


class _CrashLog:
    """Rename the run log to ``.error`` on crash (main_cli.py:324-336) and
    detach its handler either way (repeat invocations must not stack)."""

    def __init__(self, log_path: str, handler: logging.Handler):
        self.log_path = log_path
        self.handler = handler

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        logging.getLogger().removeHandler(self.handler)
        self.handler.close()
        if exc_type is not None and os.path.exists(self.log_path):
            os.replace(self.log_path, self.log_path + ".error")
        return False


# ---------------------------------------------------------------------------
# Commands
# ---------------------------------------------------------------------------


def cmd_fit(args) -> Dict[str, Any]:
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.resilience import lifecycle
    from deepdfa_tpu.train.loop import fit
    from deepdfa_tpu.train.tune import TrialReporter

    # Preemption lifecycle (ISSUE 10): SIGTERM/SIGINT becomes a typed
    # notice the step loop drains on — an immediate preempt_<epoch>_<step>
    # snapshot, writer drained, exit EXIT_PREEMPTED (75). --resume then
    # restarts MID-epoch from it.
    coordinator = lifecycle.fresh()
    cfgs = build_configs(args.config, args.set, inject_service_params=True)
    model_cfg, data_cfg = cfgs["model"], cfgs["data"]
    train_cfg = cfgs["train"]
    # One run directory for checkpoints, log, and history: CLI flag beats
    # YAML beats the default — and checkpoints are always written.
    run_dir = args.checkpoint_dir or train_cfg.checkpoint_dir or "runs/default"
    train_cfg = dataclasses.replace(train_cfg, checkpoint_dir=run_dir)
    log_path, handler = _setup_run_logging(run_dir)
    # Telemetry rides the run dir: runs/<run>/telemetry/{events.jsonl,
    # trace.json}, summarized offline by `cli trace report <run>`.
    with _CrashLog(log_path, handler), telemetry.run_scope(run_dir):
        examples, splits = load_dataset(args.dataset, model_cfg.feature,
                                        seed=train_cfg.seed,
                                        split_mode=args.split_mode)
        model = FlowGNN(model_cfg)
        mesh = None
        if args.n_devices > 1:
            from deepdfa_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(n_data=args.n_devices)
        # Under a real NNI trial the reporter streams per-epoch val F1 and
        # the final best (base_module.py:346, main_cli.py:184); otherwise
        # both calls are no-ops.
        reporter = TrialReporter()

        def report_epoch(epoch, record):
            reporter.intermediate(record["val_metrics"].get("f1", 0.0))
            return False  # reporting only; the service decides terminations

        on_epoch = report_epoch if reporter.attached else None
        try:
            state, history = fit(model, examples, splits, train_cfg, data_cfg,
                                 mesh=mesh,
                                 resume=getattr(args, "resume", False),
                                 on_epoch_end=on_epoch)
        except lifecycle.Preempted as p:
            # The graceful-drain exit: the snapshot is durable (the loop
            # drained the writer before raising), the partial history is
            # recorded, and the process reports the distinct preemption
            # exit code so orchestrators reschedule instead of alerting.
            history = p.history or {"epochs": []}
            with open(os.path.join(run_dir, "history.json"), "w") as f:
                json.dump(history, f, indent=1)
            result = {
                "preempted": True,
                "reason": p.notice.reason,
                "epoch": p.epoch,
                "step": p.step,
                "snapshot": p.snapshot,
                "resume_hint": f"--resume --checkpoint-dir {run_dir}",
                "exit_code": lifecycle.EXIT_PREEMPTED,
            }
            coordinator.complete()
            print(json.dumps(result))
            return result
        finally:
            coordinator.uninstall()
        result = {
            "best_epoch": history["best_epoch"],
            "best_val_loss": history["best_val_loss"],
            "final_val_metrics": history["epochs"][-1]["val_metrics"]
            if history["epochs"] else {},
        }
        if reporter.attached and history["epochs"]:
            reporter.final(max(
                e["val_metrics"].get("f1", 0.0) for e in history["epochs"]
            ))
        with open(os.path.join(run_dir, "history.json"), "w") as f:
            json.dump(history, f, indent=1)
        print(json.dumps(result))
        return result


def _eval_mesh(args):
    """(mesh, host, n_shards) for ``--n-devices`` eval sharding, plus the
    fail-early --profile/--time multi-controller guard — one source of
    truth for cmd_test AND cmd_test_text (the reference's DataParallel
    eval, linevul_main.py:259-260, run_defect.py:427-429)."""
    import jax

    mesh, host, n_shards = None, None, 1
    if getattr(args, "n_devices", 1) > 1:
        from deepdfa_tpu.parallel.mesh import DATA_AXIS, make_mesh

        mesh = make_mesh(n_data=args.n_devices)
        n_shards = int(mesh.shape[DATA_AXIS])
        host = ((jax.process_index(), jax.process_count())
                if jax.process_count() > 1 else None)
    if (getattr(args, "profile", False) or getattr(args, "time", False)) \
            and host is not None:
        # Fail before the pod-scale eval runs, not after.
        raise ValueError(
            "--profile/--time instrument a single process; run them "
            "without multi-controller (they work with --n-devices on "
            "one host)"
        )
    return mesh, host, n_shards


def cmd_test(args) -> Dict[str, Any]:
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.checkpoint import CheckpointManager
    from deepdfa_tpu.train.loop import (
        evaluate,
        make_eval_step,
        make_train_state,
        _batches,
    )

    import jax

    cfgs = build_configs(args.config, args.set)
    model_cfg, data_cfg, train_cfg = cfgs["model"], cfgs["data"], cfgs["train"]
    examples, splits = load_dataset(args.dataset, model_cfg.feature,
                                    seed=train_cfg.seed,
                                    split_mode=args.split_mode)
    model = FlowGNN(model_cfg)
    subkeys = subkeys_for(model_cfg.feature)
    use_tile = model_cfg.uses_tile_adj
    use_band = model_cfg.uses_band_adj
    use_df = model_cfg.label_style.startswith("dataflow_solution")
    example_batch = next(
        _batches(examples, splits["test"][: data_cfg.eval_batch_size], data_cfg,
                 subkeys, data_cfg.eval_batch_size, build_tile_adj=use_tile,
                 build_band_adj=use_band, with_dataflow=use_df)
    )
    state, _ = make_train_state(model, example_batch, train_cfg)
    ckpt = CheckpointManager(args.checkpoint_dir)
    state = ckpt.restore(args.which, state)
    restored = ckpt.last_restored or {}

    # --n-devices: dp-shard the eval batches over a mesh, like fit.
    # Per-example outputs replicate, so metrics, prediction dumps, and
    # profiling behave identically (the per-graph node caps make the
    # per-shard budget exact here, unlike the combined join).
    mesh, host, n_shards = _eval_mesh(args)
    if mesh is not None:
        from deepdfa_tpu.parallel.mesh import batch_sharding, replicated

        # The sharded tile kernel runs under shard_map and needs the mesh
        # on the model (the fit contract, train/loop.py).
        eval_model = model.clone(mesh=mesh)
        eval_step = jax.jit(
            make_eval_step(eval_model, train_cfg),
            in_shardings=(replicated(mesh), batch_sharding(mesh)),
            out_shardings=(replicated(mesh),) * 4,
        )
    else:
        eval_step = jax.jit(make_eval_step(model, train_cfg))
    res = evaluate(eval_step, state, examples, splits["test"], data_cfg, subkeys,
                   n_shards=n_shards, build_tile_adj=use_tile,
                   build_band_adj=use_band, with_dataflow=use_df,
                   host=host, mesh=mesh)
    report = {"loss": res.loss, **res.metrics}
    if restored.get("fallback"):
        # The requested snapshot was damaged and an older intact one was
        # loaded: these metrics describe THAT model — a report silently
        # labelled with --which would misattribute them.
        report["restored_snapshot"] = restored["name"]
        report["restored_fallback"] = True
        logger.error(
            "test: snapshot %r was damaged; metrics below are for the "
            "fallback snapshot %r (epoch %s)", args.which,
            restored["name"], restored.get("epoch"),
        )

    if getattr(args, "profile", False) or getattr(args, "time", False):
        # run_profiling.sh parity: re-run the test batches under the
        # FLOPs/latency instruments (base_module.py:238-291) and aggregate
        # like scripts/report_profiling.py:18-66.
        from deepdfa_tpu.eval.profiling import ProfileRecorder, profile_eval
        from deepdfa_tpu.eval.report import aggregate_profile, aggregate_time

        out_dir = args.profile_dir or args.checkpoint_dir
        os.makedirs(out_dir, exist_ok=True)
        profile_path = (
            os.path.join(out_dir, "profiledata.jsonl") if args.profile else None
        )
        time_path = os.path.join(out_dir, "timedata.jsonl") if args.time else None
        for p in (profile_path, time_path):
            if p and os.path.exists(p):
                os.remove(p)  # fresh run, not an append to a stale one
        batches = list(
            _batches(examples, splits["test"], data_cfg, subkeys,
                     data_cfg.eval_batch_size, n_shards,
                     build_tile_adj=use_tile,
                     build_band_adj=use_band, with_dataflow=use_df)
        )
        recorder = ProfileRecorder(profile_path, time_path)
        summary = profile_eval(
            lambda b: eval_step(state, b),
            batches,
            state.params,
            lambda b: int(np.asarray(b.graph_mask).sum()),
            recorder,
            # warmup-3 protocol (base_module.py:240-243), but always keep at
            # least one measured step on tiny test sets
            n_warmup=min(3, max(len(batches) - 1, 0)),
        )
        report["profiling"] = summary
        if profile_path:
            report["profiling"].update(aggregate_profile(profile_path))
        if time_path:
            report["profiling"].update(aggregate_time(time_path))

    print(json.dumps(report))
    return report


# ---------------------------------------------------------------------------
# Combined DeepDFA+transformer training (fit-text / test-text)
# ---------------------------------------------------------------------------


def _text_model_and_tokenizer(args, combined: bool, graph_cfg):
    """(model, tokenizer, pad_id, style, descriptor) for fit-text/test-text.

    Mirrors the reference's model assembly: ``--model linevul`` is
    linevul_main.py:576-621 (RoBERTa classifier + optional FlowGNN encoder),
    ``--model codet5`` is run_defect.py:208-246 (DefectModel + optional
    FlowGNN)."""
    from deepdfa_tpu.data.text import HashingCodeTokenizer, HashingT5Tokenizer

    gcfg = graph_cfg if combined else None
    if args.model == "codet5":
        from deepdfa_tpu.models.t5 import DefectModel, T5Config

        if (getattr(args, "attention_impl", "auto") != "auto"
                or getattr(args, "remat", False)):
            # The T5 stack has its own attention; silently recording
            # settings that were never in effect would poison test-text's
            # reconstruction.
            raise ValueError(
                "--attention-impl/--remat configure the RoBERTa encoder "
                "(--model linevul); the codet5 stack does not take them"
            )
        t5cfg = T5Config.tiny() if args.tiny else T5Config.codet5_base()
        model = DefectModel(t5cfg, graph_config=gcfg)
        vocab, pad_id, style = t5cfg.vocab_size, t5cfg.pad_token_id, "t5"
        eos_id = t5cfg.eos_token_id
        tok_cls = HashingT5Tokenizer
    else:
        from deepdfa_tpu.models.linevul import LineVul
        from deepdfa_tpu.models.transformer import EncoderConfig

        enc = EncoderConfig.tiny() if args.tiny else EncoderConfig()
        if args.tiny:
            # The tiny position table (66) must still cover --block-size
            # (default 512): undersized tables used to NaN-fill silently.
            enc = dataclasses.replace(
                enc,
                max_position_embeddings=max(
                    enc.max_position_embeddings,
                    args.block_size + enc.pad_token_id + 1,
                ),
            )
        enc = dataclasses.replace(
            enc,
            # "auto" = the measured champion per backend (flash kernels on
            # TPU, blockwise elsewhere); "dense" remains available for the
            # localization/attribution flows that need attention weights.
            attention_impl=getattr(args, "attention_impl", "auto"),
            remat_layers=getattr(args, "remat", False),
            # Recorded in model.json so test-text rebuilds the SAME
            # activation (tanh default since round 5; pre-round-5
            # checkpoints read back as erf).
            gelu_approximate=getattr(args, "gelu_approximate",
                                     enc.gelu_approximate),
        )
        model = LineVul(enc, graph_config=gcfg)
        vocab, pad_id, style = enc.vocab_size, enc.pad_token_id, "roberta"
        eos_id = None
        tok_cls = HashingCodeTokenizer
    if getattr(args, "tokenizer", None):
        from deepdfa_tpu.data.text import check_tok_vocab, load_bpe_tokenizer

        tok = load_bpe_tokenizer(args.tokenizer)
        check_tok_vocab(tok, vocab, pad_id=pad_id, eos_id=eos_id)
    else:
        tok = tok_cls(vocab)
    return model, tok, pad_id, style


def _restore_ddfa_encoder(ckpt_dir: str, which: str) -> Dict[str, Any]:
    """DDFA checkpoint -> init_params for the combined model's ``flowgnn``
    submodule (main_cli.py:136-144: load the trained graph model, strip
    head/pooling, graft into the encoder slot)."""
    import orbax.checkpoint as ocp

    from deepdfa_tpu.train.checkpoint import load_encoder_params

    path = os.path.join(os.path.abspath(ckpt_dir), which)
    restored = ocp.StandardCheckpointer().restore(path)
    kept = load_encoder_params(restored["params"])
    return {"params": {"flowgnn": kept["params"]}}


def cmd_fit_text(args) -> Dict[str, Any]:
    """Train LineVul/CodeT5-defect, optionally combined with the FlowGNN
    encoder — the reference's one-command combined training
    (msr_train_combined.sh → linevul_main.py:421-668, run_defect.py:160-246),
    with ``--ddfa-checkpoint``/``--freeze-graph`` covering the pretrained
    graph-encoder flow (main_cli.py:136-144)."""
    import dataclasses as _dc

    from deepdfa_tpu.core.config import TransformerTrainConfig
    from deepdfa_tpu.data.combined import load_combined_dataset
    from deepdfa_tpu.train.checkpoint import make_checkpoint_manager
    from deepdfa_tpu.train.text_loop import (
        evaluate_text,
        fit_text,
        make_text_eval_step,
    )

    for item in env_injected_overrides() + list(args.set):
        if not item.startswith("model."):
            # fit-text's trainer settings come from its own flags
            # (--epochs/--batch-size/...); silently ignoring a train./data.
            # override — explicit or DEEPDFA_TUNE_PARAMS-injected — would
            # train something other than what was asked.
            raise ValueError(
                f"fit-text --set only configures the graph encoder "
                f"(model.*); use the native flags instead of {item!r}"
            )
    cfgs = build_configs(args.config, args.set)
    graph_cfg = _dc.replace(cfgs["model"], encoder_mode=True,
                            label_style="graph")
    combined = args.graphs is not None
    run_dir = args.checkpoint_dir
    log_path, handler = _setup_run_logging(run_dir)
    with _CrashLog(log_path, handler), telemetry.run_scope(run_dir):
        tcfg = TransformerTrainConfig(
            learning_rate=args.learning_rate,
            max_epochs=args.epochs,
            batch_size=args.batch_size,
            eval_batch_size=args.eval_batch_size or args.batch_size,
            block_size=args.block_size,
            seed=args.seed,
        )
        model, tok, pad_id, style = _text_model_and_tokenizer(
            args, combined, graph_cfg
        )
        data, splits, graphs_by_id = load_combined_dataset(
            args.dataset, graph_cfg.feature, tok, tcfg.block_size,
            style=style, graphs=args.graphs, seed=args.seed,
            split_mode=args.split_mode,
        )
        subkeys = subkeys_for(graph_cfg.feature) if combined else None
        budget = None
        if combined:
            from deepdfa_tpu.data.combined import graph_join_and_budget

            graphs_by_id, budget = graph_join_and_budget(
                list(graphs_by_id.values()),
                max(tcfg.batch_size, tcfg.eval_batch_size),
                max_nodes=args.max_nodes, max_edges=args.max_edges,
            )
        init_params = None
        if args.ddfa_checkpoint:
            if not combined:
                raise ValueError("--ddfa-checkpoint needs --graphs (the "
                                 "encoder slot only exists combined)")
            init_params = _restore_ddfa_encoder(args.ddfa_checkpoint,
                                                args.which)
        if args.freeze_graph and not args.ddfa_checkpoint:
            raise ValueError(
                "--freeze-graph without --ddfa-checkpoint would freeze a "
                "random-init encoder (the reference freezes a LOADED one, "
                "main_cli.py:136-144)"
            )
        mesh = None
        if args.n_devices > 1:
            from deepdfa_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(n_data=args.n_devices)
        # One manager for the whole run (async by default): fit_text
        # snapshots ``last`` per epoch so a preempted fine-tune resumes,
        # and the final ``best`` write below rides the same writer.
        ckpt = make_checkpoint_manager(run_dir)
        from deepdfa_tpu.resilience import lifecycle

        coordinator = lifecycle.fresh()
        try:
            best_state, history = fit_text(
                model, data, splits, tcfg, graphs_by_id=graphs_by_id,
                subkeys=subkeys, graph_budget=budget,
                init_params=init_params,
                mesh=mesh, pad_id=pad_id,
                freeze_submodules=("flowgnn",) if args.freeze_graph else (),
                checkpointer=ckpt,
            )
        except lifecycle.Preempted as p:
            # SIGTERM mid-fine-tune: the loop drained a durable
            # preempt_<epoch>_<step> snapshot; record what happened and
            # exit with the distinct preemption code.
            history = p.history or {"epochs": []}
            with open(os.path.join(run_dir, "history.json"), "w") as f:
                json.dump(history, f, indent=1)
            result = {"preempted": True, "reason": p.notice.reason,
                      "epoch": p.epoch, "step": p.step,
                      "snapshot": p.snapshot,
                      "exit_code": lifecycle.EXIT_PREEMPTED}
            coordinator.complete()
            print(json.dumps(result))
            return result
        finally:
            coordinator.uninstall()
        # Params only: the eval-time restore must not depend on the
        # optimizer tree, whose structure changes with --freeze-graph.
        ckpt.save_best({"params": best_state.params}, history["best_epoch"],
                       metrics={"val_f1": history["best_val_f1"]})
        ckpt.drain()
        descriptor = {
            "model": args.model,
            "tiny": args.tiny,
            "attention_impl": args.attention_impl,
            "remat": args.remat,
            # Record the activation the model ACTUALLY used (linevul's
            # encoder; the codet5 stack is relu and ignores this on
            # reconstruction) — never a second copy of the default.
            "gelu_approximate": getattr(
                getattr(model, "encoder_config", None),
                "gelu_approximate", True,
            ),
            "combined": combined,
            "block_size": tcfg.block_size,
            "dataset": args.dataset,
            "split_mode": args.split_mode,
            "graphs": args.graphs,
            "tokenizer": args.tokenizer,
            "batch_size": max(tcfg.batch_size, tcfg.eval_batch_size),
            "graph_budget": budget,
            "graph_config": _dc.asdict(graph_cfg),
            "seed": args.seed,
        }
        with open(os.path.join(run_dir, "model.json"), "w") as f:
            json.dump(descriptor, f, indent=1)
        result: Dict[str, Any] = {
            "best_epoch": history["best_epoch"],
            "best_val_f1": history["best_val_f1"],
        }
        if not args.no_test and len(splits.get("test", ())):
            import jax

            eval_step = jax.jit(make_text_eval_step(model))
            test = evaluate_text(
                eval_step, best_state, data, splits["test"], tcfg,
                graphs_by_id, subkeys, budget, pad_id=pad_id,
            )
            result["test"] = {"loss": test["loss"], **test["metrics"],
                              "num_missing": test["num_missing"]}
            _dump_predictions(run_dir, test)
        with open(os.path.join(run_dir, "history.json"), "w") as f:
            json.dump(history, f, indent=1)
        print(json.dumps(result))
        return result


def _dump_predictions(run_dir: str, eval_out: Dict[str, Any],
                      name: str = "predictions.csv") -> None:
    """Per-example prediction dump (the reference writes predictions.txt of
    ``index\\tprob`` rows after --do_test, linevul_main.py:968-987)."""
    os.makedirs(run_dir, exist_ok=True)
    path = os.path.join(run_dir, name)
    with open(path, "w") as f:
        f.write("index,prob,label\n")
        for i, p, l in zip(eval_out["index"], eval_out["probs"],
                           eval_out["labels"]):
            f.write(f"{int(i)},{float(p):.6f},{int(l)}\n")


def cmd_test_text(args) -> Dict[str, Any]:
    """Evaluate (and optionally profile) a fit-text checkpoint on the test
    split — the --do_test-only flow plus the profiling instruments."""
    import jax

    from deepdfa_tpu.core.config import (
        FeatureSpec,
        FlowGNNConfig,
        TransformerTrainConfig,
    )
    from deepdfa_tpu.data.combined import load_combined_dataset
    from deepdfa_tpu.train.checkpoint import CheckpointManager
    from deepdfa_tpu.train.text_loop import (
        evaluate_text,
        make_text_eval_step,
        make_text_train_state,
        text_graph_batches,
    )

    with open(os.path.join(args.checkpoint_dir, "model.json")) as f:
        desc = json.load(f)
    gdict = dict(desc["graph_config"])
    gdict["feature"] = FeatureSpec(**gdict["feature"])
    graph_cfg = FlowGNNConfig(**gdict)
    ns = argparse.Namespace(
        model=desc["model"], tiny=desc["tiny"],
        tokenizer=args.tokenizer or desc.get("tokenizer"),
        attention_impl=desc.get("attention_impl", "auto"),
        remat=desc.get("remat", False),
        block_size=desc["block_size"],
        # Pre-round-5 checkpoints trained with the (then hardcoded) exact
        # erf gelu; absent key => erf, so their eval numerics reproduce.
        gelu_approximate=desc.get("gelu_approximate", False),
    )
    combined = desc["combined"]
    model, tok, pad_id, style = _text_model_and_tokenizer(ns, combined,
                                                          graph_cfg)
    tcfg = TransformerTrainConfig(
        block_size=desc["block_size"],
        eval_batch_size=args.eval_batch_size,
        batch_size=args.eval_batch_size,
        seed=desc["seed"],
    )
    dataset = args.dataset or desc["dataset"]
    graphs = (args.graphs or desc["graphs"]) if combined else None
    data, splits, graphs_by_id = load_combined_dataset(
        dataset, graph_cfg.feature, tok, tcfg.block_size, style=style,
        graphs=graphs, seed=desc["seed"],
        # The recorded split protocol: re-splitting differently would leak
        # fit-time train examples into the reported test metric.
        split_mode=desc.get("split_mode", "random"),
    )
    subkeys = subkeys_for(graph_cfg.feature) if combined else None
    budget = desc["graph_budget"]
    source_override = bool(args.dataset or args.graphs)
    if combined and (source_override
                     or args.eval_batch_size > desc.get("batch_size", 0)):
        # The fit-time budget was sized for the fit-time graphs and batch
        # size; a swapped graph source or bigger eval batch packs more than
        # it covers. Re-derive so no test graph is dropped (keeping any
        # larger recorded budget when the source is unchanged).
        from deepdfa_tpu.data.combined import graph_join_and_budget

        graphs_by_id, rebudget = graph_join_and_budget(
            list(graphs_by_id.values()),
            max(desc.get("batch_size", 0), args.eval_batch_size),
        )
        budget = (rebudget if source_override
                  else {k: max(budget[k], rebudget[k]) for k in budget})
    split_used = "test" if len(splits.get("test", ())) else "val"
    indices = splits[split_used]
    example = next(
        text_graph_batches(data, indices[: tcfg.eval_batch_size],
                           tcfg.eval_batch_size, graphs_by_id, subkeys,
                           budget, pad_id=pad_id)
    )
    # --n-devices: dp-shard the eval batches, like fit-text. Outputs
    # replicate, so the report matches the single-device one.
    mesh, host, n_shards = _eval_mesh(args)
    if mesh is not None:
        model = model.clone(mesh=mesh)
        if combined:
            # The shard packer divides the batch budget by n_shards, so a
            # graph that fits the single-device budget could overflow its
            # shard and be silently masked — widen the budget to cover
            # per-shard packing of the worst rows.
            from deepdfa_tpu.data.combined import graph_join_and_budget

            rows_per_shard = max(args.eval_batch_size // n_shards, 1)
            _, shard_b = graph_join_and_budget(
                list(graphs_by_id.values()), rows_per_shard
            )
            budget = {k: max(budget[k], shard_b[k] * n_shards)
                      for k in budget}
    state, _ = make_text_train_state(model, example, tcfg, max_steps=1)
    restored = CheckpointManager(args.checkpoint_dir).restore(
        args.which, {"params": state.params}
    )
    state = state.replace(params=restored["params"])
    if mesh is not None:
        from deepdfa_tpu.parallel.mesh import jit_dp_step

        eval_step = jit_dp_step(make_text_eval_step(model), mesh,
                                n_batch_args=4, n_out=2, donate=())
    else:
        eval_step = jax.jit(make_text_eval_step(model))
    res = evaluate_text(eval_step, state, data, indices, tcfg, graphs_by_id,
                        subkeys, budget, pad_id=pad_id, n_shards=n_shards,
                        host=host, mesh=mesh)
    report: Dict[str, Any] = {"loss": res["loss"], **res["metrics"],
                              "num_missing": res["num_missing"],
                              "split": split_used}
    # Distinct filename: must not clobber the fit-time test predictions
    # (this run may cover an overridden dataset or the val fallback).
    _dump_predictions(args.profile_dir or args.checkpoint_dir, res,
                      name="test_predictions.csv")
    if args.dbgbench:
        # DbgBench protocol (paper Table 8; the reference's eval-export +
        # bugs-detected analysis, unixcoder/linevul_main.py:742-857,
        # run_all_eval_export_dbgbench_combined.sh): each example belongs
        # to one known bug; a bug counts as detected when ANY of its
        # functions is flagged.
        from deepdfa_tpu.eval.report import dbgbench_report

        with open(args.dbgbench) as f:
            bug_of = {int(k): v for k, v in json.load(f).items()}
        pairs = [(p, bug_of[int(i)])
                 for p, i in zip(res["probs"], res["index"])
                 if int(i) in bug_of]
        if not pairs:
            raise ValueError(
                f"no evaluated example ids appear in {args.dbgbench} — "
                "wrong bug map for this dataset?"
            )
        report["dbgbench"] = dbgbench_report(
            [p for p, _ in pairs], [b for _, b in pairs],
            threshold=args.dbgbench_threshold,
        )

    if args.profile or args.time:
        from deepdfa_tpu.eval.profiling import ProfileRecorder, profile_eval
        from deepdfa_tpu.eval.report import aggregate_profile, aggregate_time

        out_dir = args.profile_dir or args.checkpoint_dir
        os.makedirs(out_dir, exist_ok=True)
        profile_path = (
            os.path.join(out_dir, "profiledata.jsonl") if args.profile else None
        )
        time_path = os.path.join(out_dir, "timedata.jsonl") if args.time else None
        for p in (profile_path, time_path):
            if p and os.path.exists(p):
                os.remove(p)
        # profile_eval jits over the batch, so hand it pytrees: (ids,
        # labels, mask, graphs) tuples instead of the host-side TextBatch.
        # The text arrays stay numpy until each dispatch — materializing
        # the whole test set on device would OOM real-sized splits.
        batches = [
            (np.asarray(b.input_ids), np.asarray(b.labels),
             np.asarray(b.example_mask), b.graphs)
            for b in text_graph_batches(data, indices, tcfg.eval_batch_size,
                                        graphs_by_id, subkeys, budget,
                                        pad_id=pad_id, n_shards=n_shards)
        ]
        recorder = ProfileRecorder(profile_path, time_path)
        summary = profile_eval(
            lambda b: eval_step(state, *b),
            batches,
            state.params,
            lambda b: int(np.asarray(b[2]).sum()),
            recorder,
            n_warmup=min(3, max(len(batches) - 1, 0)),
        )
        report["profiling"] = summary
        if profile_path:
            report["profiling"].update(aggregate_profile(profile_path))
        if time_path:
            report["profiling"].update(aggregate_time(time_path))

    print(json.dumps(report))
    return report


# ---------------------------------------------------------------------------
# Serving (serve / score — the checkpoint-to-responses path, deepdfa_tpu/serve)
# ---------------------------------------------------------------------------


def _serve_config(args, block_size: Optional[int] = None):
    from deepdfa_tpu.serve import ServeConfig

    kw: Dict[str, Any] = dict(
        batch_slots=args.batch_slots,
        deadline_ms=args.deadline_ms,
        queue_capacity=args.queue_capacity,
        cache_capacity=args.cache_capacity,
        replicas=getattr(args, "replicas", 1),
        adaptive_flush=bool(getattr(args, "adaptive_flush", False)),
    )
    if getattr(args, "gen_src_len", None) is not None:
        kw["gen_src_len"] = args.gen_src_len
        kw["gen_src_min_bucket"] = min(
            ServeConfig.gen_src_min_bucket, args.gen_src_len)
    if getattr(args, "gen_max_len", None) is not None:
        kw["gen_max_len"] = args.gen_max_len
    if getattr(args, "gen_beam", None) is not None:
        kw["gen_beam_size"] = args.gen_beam
    if block_size is not None:
        kw["block_size"] = block_size
    return ServeConfig(**kw)


def _build_gen_lane(args, serve_cfg):
    """(gen_model, gen_params, gen_tokenizer) for the generation lane, or
    (None, None, None) when not requested. ``--gen-checkpoint-dir``
    restores a fit-gen run's params for the ``--gen-model`` shape;
    ``--gen-lane`` alone serves RANDOM-INIT weights (smoke mode — the
    decode stack is real, the tokens are not). ``--gen-tokenizer`` loads
    the run's trained BPE assets; without it the hashing tokenizer is
    only correct for hashing-encoded (synthetic) runs, and serving a
    BPE-trained checkpoint through it would return confidently-wrong
    tokens — hence the loud warning below."""
    import dataclasses as _dc

    if not (getattr(args, "gen_lane", False)
            or getattr(args, "gen_checkpoint_dir", None)):
        return None, None, None
    import jax

    from deepdfa_tpu.data.text import HashingT5Tokenizer
    from deepdfa_tpu.models.t5 import T5Config, T5Model
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    name = getattr(args, "gen_model", "tiny") or "tiny"
    if name == "tiny":
        tcfg = T5Config.tiny(vocab_size=256)
    elif name == "codet5-small":
        tcfg = T5Config.codet5_small()
    elif name == "codet5-base":
        tcfg = T5Config.codet5_base()
    else:
        raise ValueError(f"--gen-model {name!r}: expected tiny, "
                         "codet5-small or codet5-base")
    tcfg = _dc.replace(tcfg, dropout_rate=0.0)
    if getattr(args, "gen_tokenizer", None):
        from deepdfa_tpu.data.text import check_tok_vocab, load_bpe_tokenizer

        tokenizer = load_bpe_tokenizer(args.gen_tokenizer)
        check_tok_vocab(tokenizer, tcfg.vocab_size,
                        pad_id=tcfg.pad_token_id, eos_id=tcfg.eos_token_id)
    else:
        tokenizer = HashingT5Tokenizer(vocab_size=tcfg.vocab_size)
        if getattr(args, "gen_checkpoint_dir", None):
            logger.warning(
                "gen lane: restoring %s with the HASHING tokenizer — "
                "correct only for checkpoints trained on hashing-encoded "
                "(synthetic) data; a BPE-trained run needs its assets via "
                "--gen-tokenizer or the served tokens are garbage",
                args.gen_checkpoint_dir)
    model = T5Model(tcfg)
    if getattr(args, "gen_checkpoint_dir", None):
        params = CheckpointManager(args.gen_checkpoint_dir).restore_params(
            getattr(args, "gen_which", "best") or "best")
    else:
        logger.warning(
            "gen lane on RANDOM-INIT weights (smoke mode — the decode "
            "stack is real, the tokens are not)")
        import numpy as _np

        src = _np.zeros((1, serve_cfg.gen_src_len), _np.int32)
        params = model.init(jax.random.PRNGKey(0), src, src[:, :4])
    return model, params, tokenizer


def _build_serve_engine(args):
    """(engine, model_cfg): the serving engine from checkpoints.

    Without ``--checkpoint-dir`` the GNN lane runs on random-init params —
    smoke mode for exercising the serving stack itself (scripts/serve.sh
    from scripts/test.sh); scores are meaningless and the log says so.
    ``--combined-checkpoint-dir`` (a fit-text linevul run dir) attaches
    the combined DDFA+LineVul lane; its recorded block_size wins.
    """
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve import ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    cfgs = build_configs(args.config, args.set)
    model_cfg = cfgs["model"]
    if model_cfg.label_style != "graph":
        raise ValueError("serving scores functions; use label_style=graph")
    model = FlowGNN(model_cfg)

    combined_model = combined_params = tokenizer = None
    block_size = None
    if getattr(args, "combined_checkpoint_dir", None):
        with open(os.path.join(args.combined_checkpoint_dir,
                               "model.json")) as f:
            desc = json.load(f)
        if desc["model"] != "linevul" or not desc["combined"]:
            raise ValueError(
                "--combined-checkpoint-dir must hold a combined linevul "
                "fit-text run (model.json says otherwise)"
            )
        gdict = dict(desc["graph_config"])
        gdict["feature"] = FeatureSpec(**gdict["feature"])
        ns = argparse.Namespace(
            model=desc["model"], tiny=desc["tiny"],
            tokenizer=desc.get("tokenizer"),
            attention_impl=desc.get("attention_impl", "auto"),
            remat=desc.get("remat", False),
            block_size=desc["block_size"],
            gelu_approximate=desc.get("gelu_approximate", False),
        )
        combined_model, tokenizer, _, _ = _text_model_and_tokenizer(
            ns, True, FlowGNNConfig(**gdict)
        )
        combined_params = CheckpointManager(
            args.combined_checkpoint_dir
        ).restore_params(args.combined_which)
        block_size = desc["block_size"]

    serve_cfg = _serve_config(args, block_size=block_size)
    if args.checkpoint_dir:
        gnn_params = CheckpointManager(args.checkpoint_dir).restore_params(
            args.which
        )
    else:
        logger.warning(
            "no --checkpoint-dir: serving RANDOM-INIT weights (smoke mode "
            "— the serving stack is real, the scores are not)"
        )
        gnn_params = random_gnn_params(model, serve_cfg)

    gen_model, gen_params, gen_tokenizer = _build_gen_lane(args, serve_cfg)

    if serve_cfg.replicas > 1:
        # The replicated fleet (deepdfa_tpu/serve/fleet.py): N engines,
        # each pinned to its shard of the device mesh and AOT-warmed
        # independently, behind the content-affine router. The fleet
        # speaks the single-engine surface, so serve/score/scan drive
        # either shape through the same code below.
        import jax

        from deepdfa_tpu.serve import ServeFleet

        fleet = ServeFleet.build(
            model, gnn_params, config=serve_cfg,
            combined_model=combined_model,
            combined_params=combined_params, tokenizer=tokenizer,
            gen_model=gen_model, gen_params=gen_params,
            gen_tokenizer=gen_tokenizer,
        )
        logger.info("serving fleet: %d replicas over %d device(s)",
                    fleet.size, jax.device_count())
        return fleet, model_cfg

    policy = None
    if serve_cfg.adaptive_flush:
        from deepdfa_tpu.serve import AdaptiveFlushPolicy

        policy = AdaptiveFlushPolicy(serve_cfg)
    engine = ServeEngine(
        model, gnn_params, config=serve_cfg,
        combined_model=combined_model, combined_params=combined_params,
        tokenizer=tokenizer, policy=policy,
        gen_model=gen_model, gen_params=gen_params,
        gen_tokenizer=gen_tokenizer,
    )
    return engine, model_cfg


def _smoke_http(engine, host: str, port: int, n: int,
                feature, slo_monitor=None,
                scan_service=None) -> Dict[str, Any]:
    """Self-drive the full HTTP stack with ``n`` synthetic functions
    (chunks exercise batching; a duplicated chunk exercises the cache).
    With a scan service attached, one ``POST /scan`` round proves the
    raw-source edge end-to-end over real HTTP.

    Every POST carries a traceparent header and records a
    ``client.request`` span under the same trace id (ISSUE 14), so the
    smoke trace demonstrates the client↔server join the report's
    ``propagation`` section audits — coverage on the smoke must be
    complete, and cmd_serve gates on it."""
    import threading
    import urllib.request

    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.serve.http import ServeHTTPServer
    from deepdfa_tpu.telemetry import context as trace_context

    server = ServeHTTPServer((host, port), engine, slo_monitor=slo_monitor,
                             scan_service=scan_service)
    server.start_pump()
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    bound_port = server.server_address[1]
    base = f"http://{host}:{bound_port}"

    def post(doc, path="/score"):
        trace_id = trace_context.new_trace_id()
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json",
                     trace_context.TRACEPARENT_HEADER:
                         trace_context.make_traceparent(trace_id)},
        )
        t0 = telemetry.now()
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return json.loads(resp.read())
        finally:
            telemetry.record_span("client.request", t0,
                                  trace_id=trace_id, path=path,
                                  n=len(doc.get("functions", [])))

    try:
        graphs = synthetic_bigvul(n, feature, positive_fraction=0.5, seed=0)
        payload = [
            {"id": int(g["id"]),
             "graph": {"num_nodes": int(g["num_nodes"]),
                       "senders": np.asarray(g["senders"]).tolist(),
                       "receivers": np.asarray(g["receivers"]).tolist(),
                       "feats": {k: np.asarray(v).tolist()
                                 for k, v in g["feats"].items()}}}
            for g in graphs
        ]
        results = []
        chunk = max(engine.config.batch_slots // 2, 1)
        for start in range(0, n, chunk):
            results += post(
                {"functions": payload[start:start + chunk]}
            )["results"]
        # Duplicate the first chunk: CI-scan traffic, must hit the cache.
        dup = post({"functions": payload[:chunk]})["results"]
        gen_ok = None
        if getattr(server.fleet, "has_gen_lane", False):
            # Generation-lane round (ISSUE 13): lane="gen" entries over
            # real HTTP — tokens come back, and a byte-identical replay
            # must answer from the content cache with zero new compiles
            # (the SLO gate on the trace asserts the compile half).
            gdoc = {"functions": [
                {"id": i, "lane": "gen",
                 "code": f"int gen_{i}(char *p) {{ return p[{i}]; }}"}
                for i in range(3)
            ]}
            first_gen = post(gdoc)["results"]
            replay_gen = post(gdoc)["results"]
            gen_ok = (all("tokens" in r for r in first_gen)
                      and all(r.get("cached") for r in replay_gen))
        scan_ok = None
        if scan_service is not None:
            # One POST /scan round-trip over real HTTP (raw source ->
            # pooled Joern -> featurize -> the same warmed engine), then
            # a replay that must come back entirely from the scan cache.
            from deepdfa_tpu.scan.fake_joern import seeded_sources

            sdoc = {"functions": [{"id": i, "source": s} for i, s in
                                  enumerate(seeded_sources(3, seed=7))]}
            first_scan = post(sdoc, path="/scan")["results"]
            replay_scan = post(sdoc, path="/scan")["results"]
            scan_ok = (all("prob" in r for r in first_scan)
                       and all(r.get("cached") for r in replay_scan))
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            metrics = json.loads(resp.read())
        ok = (all("prob" in r for r in results)
              and all(r.get("cached") for r in dup)
              and scan_ok is not False
              and gen_ok is not False)
        report = {"smoke": n, "ok": ok, "cached_replay": len(dup),
                  "metrics": metrics}
        if gen_ok is not None:
            report["gen_ok"] = gen_ok
        if scan_ok is not None:
            report["scan_ok"] = scan_ok
            report["scan"] = scan_service.snapshot()
        return report
    finally:
        server.shutdown()


def _build_scan_service(engine, model_cfg, args):
    """The streaming scan service behind ``POST /scan`` and ``cli scan``,
    from the ``--scan-*`` knobs. ``--scan-transport none`` (the serve
    default) returns None — /scan answers 501; ``fake`` is the hermetic
    scripted subprocess (no JVM, the tier-1/smoke transport); anything
    else is the Joern binary name/path. Env knobs DEEPDFA_SCAN_TRANSPORT /
    DEEPDFA_SCAN_POOL override the argparse defaults (README "Streaming
    scan service")."""
    transport = getattr(args, "scan_transport", "none")
    if transport == "none":
        return None
    from deepdfa_tpu.scan import ScanConfig, ScanService, fake_joern_command

    command = fake_joern_command() if transport == "fake" else transport
    config = ScanConfig(pool_size=args.scan_pool_size,
                        timeout_s=args.scan_timeout_s,
                        attempts=args.scan_attempts)
    vocabs = None
    vocabs_path = getattr(args, "scan_vocabs", None)
    if vocabs_path:
        # Checkpoint-faithful scan vocabularies (the ROADMAP gap): load
        # the ETL export's persisted vocabs so live sweeps index features
        # exactly as the checkpoint trained — replacing the deterministic
        # hashing fallback.
        from deepdfa_tpu.etl.export import load_vocabs

        vocabs = load_vocabs(vocabs_path)
        logger.info("scan: loaded export vocabs from %s (%s)", vocabs_path,
                    ", ".join(sorted(vocabs)))
    return ScanService(engine, model_cfg.feature,
                       workdir=args.scan_workdir, config=config,
                       command=command, cache_path=args.scan_cache,
                       vocabs=vocabs)


def _apply_slo_gate(report: Dict[str, Any], trace_rep: Dict[str, Any],
                    spec: str) -> Dict[str, Any]:
    """The offline SLO gate shared by serve ``--smoke``, ``chaos``, and
    ``trace report --slo``: evaluate ``spec`` against a trace report and
    fold the verdict into ``report`` — a breach flips ``ok`` and sets
    the nonzero ``exit_code``, so CI gates on the trace, not on
    log-grepping. The verdict rides under ``slo_gate``: a trace report's
    own ``slo`` section (the run's *live* breach summary) must survive
    being gated."""
    from deepdfa_tpu.telemetry import slo as slo_mod

    result = slo_mod.evaluate_report(trace_rep, spec)
    report["slo_gate"] = result
    report["ok"] = bool(report.get("ok", True) and result["ok"])
    if not report["ok"]:
        report["exit_code"] = 1
    return result


def cmd_serve(args) -> Dict[str, Any]:
    """Serve scoring requests over HTTP (deepdfa_tpu/serve): deadline-aware
    bucketed micro-batching, AOT bucket warmup (zero steady-state
    recompiles), content-hash caching, 429 backpressure, GNN-only
    degradation. ``--smoke N`` self-drives the full stack with N synthetic
    requests, checks the run's trace against the SLO spec (post-warmup
    recompiles and p99 blowouts fail the smoke with a nonzero exit, not a
    log line), and exits — the scripts/test.sh gate. Live serving runs
    the same spec as a burn-rate monitor degrading ``/healthz``."""
    import contextlib

    from deepdfa_tpu.serve.http import serve_forever
    from deepdfa_tpu.telemetry import slo as slo_mod

    if int(args.processes) > 1:
        # Shared-nothing multi-process serving (ISSUE 17): N engine OS
        # processes behind the router tier. --processes 1 (the default)
        # never reaches this branch, so the single-process server — and
        # its /metrics JSON body — stays byte-for-byte the historic path.
        return _cmd_serve_multiproc(args, int(args.processes))

    # Telemetry sink: --run-dir (default runs/serve_smoke under --smoke);
    # without one, live serving runs untraced (hooks stay no-ops).
    run_dir = args.run_dir or ("runs/serve_smoke"
                               if args.smoke is not None else None)
    scope = (telemetry.run_scope(run_dir) if run_dir
             else contextlib.nullcontext())
    slo_monitor = (slo_mod.SLOMonitor(args.slo)
                   if args.slo != "none" else None)
    with scope:
        engine, model_cfg = _build_serve_engine(args)
        if not args.no_warmup:
            n = engine.warmup()
            logger.info("warmed %d bucket shapes", n)
        scan_service = _build_scan_service(engine, model_cfg, args)
        try:
            if args.smoke is not None:
                report = _smoke_http(engine, args.host, args.port,
                                     args.smoke, model_cfg.feature,
                                     slo_monitor=slo_monitor,
                                     scan_service=scan_service)
            else:
                # Live serving registers with the preemption lifecycle:
                # SIGTERM/SIGINT → lame-duck (admission 503 +
                # Retry-After, partial buckets flush now, every admitted
                # request answered, scan pool drained via the session
                # protocol) → clean telemetry close → EXIT_PREEMPTED.
                from deepdfa_tpu.resilience import lifecycle

                coordinator = lifecycle.fresh()
                try:
                    notice = serve_forever(
                        engine, args.host, args.port,
                        slo_monitor=slo_monitor,
                        scan_service=scan_service,
                        port_file=getattr(args, "port_file", None))
                finally:
                    coordinator.uninstall()
                if notice is not None:
                    coordinator.complete()
                    return {"preempted": True, "reason": notice.reason,
                            "exit_code": lifecycle.EXIT_PREEMPTED}
                return {}
        finally:
            if scan_service is not None:
                scan_service.close()
    # Smoke path, run closed (events.jsonl complete): the offline SLO
    # gate over the trace the smoke just produced. DEEPDFA_TELEMETRY=0
    # leaves no trace — the observatory is fully disabled, and the smoke
    # reports only its own functional checks.
    return _serve_smoke_gates(report, run_dir, args.slo)


def _serve_smoke_gates(report: Dict[str, Any], run_dir: Optional[str],
                       slo_spec: str) -> Dict[str, Any]:
    """The serve-smoke trace gates shared by the single-process and
    multi-process paths: the offline SLO gate over the run the smoke
    just produced, plus the trace-plane propagation gate (ISSUE 14) —
    every smoke POST sent a traceparent, so coverage must be complete
    and at least one trace id must join a client span to its
    serve.request (across the process boundary in the multiproc case)."""
    if run_dir:
        report["telemetry"] = os.path.join(run_dir, "telemetry")
        if telemetry.enabled():
            from deepdfa_tpu.telemetry.report import trace_report

            trace_rep = trace_report(run_dir)
            if slo_spec != "none":
                _apply_slo_gate(report, trace_rep, slo_spec)
            prop = trace_rep.get("propagation") or {}
            report["propagation"] = {
                k: prop.get(k)
                for k in ("coverage", "continued_requests",
                          "joined_traces", "client_ms_p50",
                          "server_ms_p50", "client_minus_server_ms_p50")
            }
            report["trace_processes"] = sorted(
                trace_rep.get("processes") or {})
            if not (prop.get("continued_requests")
                    and prop.get("joined_traces")):
                logger.error("serve smoke: no propagated traces in the "
                             "report (propagation=%s)", prop)
                report["ok"] = False
            # Traffic-observatory gate (ISSUE 20): every lane the smoke
            # exercised must have captured raw shape samples — a lane
            # serving traffic with an empty sketch means an admission
            # edge lost its capture hook.
            traffic = trace_rep.get("traffic") or {}
            shapes = traffic.get("shapes") or {}
            lanes = sorted((trace_rep.get("serve") or {})
                           .get("lanes") or {})
            missing = []
            for lane in lanes:
                series = ("traffic_shape_serve_gen_src_tokens"
                          if lane == "gen"
                          else f"traffic_shape_serve_{lane}_nodes")
                if not (shapes.get(series) or {}).get("count"):
                    missing.append(lane)
            report["traffic"] = {
                "samples": traffic.get("samples", 0),
                "lanes": lanes,
                "elem_waste_pct": traffic.get("elem_waste_pct"),
            }
            if missing:
                logger.error("serve smoke: no traffic shape samples for "
                             "active lanes %s", missing)
                report["ok"] = False
    if not report["ok"]:
        report["exit_code"] = 1
    print(json.dumps(report))
    return report


def _multiproc_child_args(args, run_dir: Optional[str]) -> List[str]:
    """The argv tail every engine child gets: the parent's model/
    checkpoint/batching/lane knobs forwarded verbatim, pinned to one
    replica and one process (no recursive fleets), child-level SLO off
    (the router owns fleet health), and the parent's run dir so an
    untraced parent still yields traced children."""
    out: List[str] = []
    for c in args.config or []:
        out += ["--config", c]
    for s in args.set or []:
        out += ["--set", s]
    if args.checkpoint_dir:
        out += ["--checkpoint-dir", args.checkpoint_dir,
                "--which", args.which]
    if args.combined_checkpoint_dir:
        out += ["--combined-checkpoint-dir", args.combined_checkpoint_dir,
                "--combined-which", args.combined_which]
    out += ["--batch-slots", str(args.batch_slots),
            "--deadline-ms", str(args.deadline_ms),
            "--queue-capacity", str(args.queue_capacity),
            "--cache-capacity", str(args.cache_capacity),
            "--replicas", "1", "--processes", "1", "--slo", "none"]
    if args.adaptive_flush:
        out += ["--adaptive-flush"]
    if args.gen_lane or args.gen_checkpoint_dir:
        out += ["--gen-lane", "--gen-model", args.gen_model]
        if args.gen_checkpoint_dir:
            out += ["--gen-checkpoint-dir", args.gen_checkpoint_dir,
                    "--gen-which", args.gen_which]
        if args.gen_tokenizer:
            out += ["--gen-tokenizer", args.gen_tokenizer]
        for flag, value in (("--gen-src-len", args.gen_src_len),
                            ("--gen-max-len", args.gen_max_len),
                            ("--gen-beam", args.gen_beam)):
            if value is not None:
                out += [flag, str(value)]
    if getattr(args, "scan_transport", "none") != "none":
        out += ["--scan-transport", args.scan_transport,
                "--scan-pool-size", str(args.scan_pool_size),
                "--scan-timeout-s", str(args.scan_timeout_s),
                "--scan-attempts", str(args.scan_attempts),
                "--scan-workdir", args.scan_workdir]
        if args.scan_cache:
            out += ["--scan-cache", args.scan_cache]
        if getattr(args, "scan_vocabs", None):
            out += ["--scan-vocabs", args.scan_vocabs]
    if run_dir:
        # Joined to the parent's run via DEEPDFA_TRACE_CONTEXT (the env
        # wins inside the child); the flag covers the untraced-parent
        # case so children never scatter default run dirs.
        out += ["--run-dir", run_dir]
    return out


def _cmd_serve_multiproc(args, processes: int) -> Dict[str, Any]:
    """``serve --processes N``: spawn N engine OS processes (each a
    plain ``cli serve`` child with its own warmed engine and lifecycle)
    and run the router tier in THIS process — the shared-nothing fleet
    of ISSUE 17. ``--smoke N`` self-drives the router surface and runs
    the same trace gates as the single-process smoke."""
    import contextlib

    from deepdfa_tpu.serve import router as router_mod
    from deepdfa_tpu.serve.config import ServeConfig
    from deepdfa_tpu.serve.procfleet import ProcFleet

    run_dir = args.run_dir or ("runs/serve_smoke"
                               if args.smoke is not None else None)
    scope = (telemetry.run_scope(run_dir) if run_dir
             else contextlib.nullcontext())
    with scope:
        config = ServeConfig(batch_slots=args.batch_slots,
                             deadline_ms=args.deadline_ms,
                             queue_capacity=max(args.queue_capacity,
                                                args.batch_slots),
                             cache_capacity=args.cache_capacity)
        fleet = ProcFleet(processes,
                          child_args=_multiproc_child_args(args, run_dir),
                          host=args.host)
        with telemetry.span("procfleet.start", n=processes):
            fleet.start()
        logger.info("engine fleet live: %d processes, pids %s", processes,
                    [p["pid"] for p in fleet.processes().values()])
        try:
            if args.smoke is not None:
                report = _smoke_multiproc(fleet, config, args.host,
                                          args.smoke, args)
            else:
                from deepdfa_tpu.resilience import lifecycle

                coordinator = lifecycle.fresh()
                try:
                    notice = router_mod.serve_forever_router(
                        fleet, config, args.host, args.port,
                        port_file=getattr(args, "port_file", None))
                finally:
                    coordinator.uninstall()
                if notice is not None:
                    coordinator.complete()
                    return {"preempted": True, "reason": notice.reason,
                            "exit_code": lifecycle.EXIT_PREEMPTED}
                return {}
        finally:
            fleet.shutdown()
    return _serve_smoke_gates(report, run_dir, args.slo)


def _smoke_multiproc(fleet, config, host: str, n: int,
                     args) -> Dict[str, Any]:
    """Self-drive the multi-process stack: synthetic chunks through the
    router (batching + rendezvous affinity), a duplicated chunk that
    must answer from the children's content caches, then the aggregated
    /metrics — all processes live, zero post-warmup compiles through
    the router."""
    import threading
    import urllib.request

    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.serve.router import RouterHTTPServer
    from deepdfa_tpu.telemetry import context as trace_context

    model_cfg = build_configs(args.config, args.set)["model"]
    server = RouterHTTPServer((host, 0), fleet, config)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://{host}:{server.server_address[1]}"

    def post(doc, path="/score"):
        trace_id = trace_context.new_trace_id()
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json",
                     trace_context.TRACEPARENT_HEADER:
                         trace_context.make_traceparent(trace_id)},
        )
        t0 = telemetry.now()
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return json.loads(resp.read())
        finally:
            telemetry.record_span("client.request", t0,
                                  trace_id=trace_id, path=path,
                                  n=len(doc.get("functions", [])))

    try:
        graphs = synthetic_bigvul(n, model_cfg.feature,
                                  positive_fraction=0.5, seed=0)
        payload = [
            {"id": int(g["id"]),
             "graph": {"num_nodes": int(g["num_nodes"]),
                       "senders": np.asarray(g["senders"]).tolist(),
                       "receivers": np.asarray(g["receivers"]).tolist(),
                       "feats": {k: np.asarray(v).tolist()
                                 for k, v in g["feats"].items()}}}
            for g in graphs
        ]
        results = []
        chunk = max(config.batch_slots // 2, 1)
        for start in range(0, n, chunk):
            results += post(
                {"functions": payload[start:start + chunk]}
            )["results"]
        # Duplicate the first chunk: rendezvous affinity must land each
        # function on the process whose cache already holds it.
        dup = post({"functions": payload[:chunk]})["results"]
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            metrics = json.loads(resp.read())
        caw = fleet.compiles_after_warmup()
        live = sum(1 for p in fleet.processes().values()
                   if p["state"] == "live")
        ok = (all("prob" in r for r in results)
              and all(r.get("cached") for r in dup)
              and live == fleet.n and caw == 0)
        return {"smoke": n, "ok": ok, "cached_replay": len(dup),
                "processes": live, "compiles_after_warmup": caw,
                "metrics": metrics}
    finally:
        server.shutdown()


def cmd_score(args) -> Dict[str, Any]:
    """Offline batch client of the serving path: scores a dataset through
    the same cache + micro-batcher + bucketed executables the HTTP
    endpoint uses, and writes the predictions CSV (the cmd_test_text
    writer)."""
    engine, model_cfg = _build_serve_engine(args)
    engine.warmup()
    examples, splits = load_dataset(args.dataset, model_cfg.feature,
                                    split_mode=args.split_mode)
    indices = (np.arange(len(examples)) if args.split == "all"
               else np.asarray(splits[args.split]))
    chosen = [examples[int(i)] for i in indices]
    results = engine.score_sync(chosen)
    # Admission failures (oversize/malformed rows) come back inline; they
    # are counted and skipped, not allowed to abort the batch run.
    probs, labels, ids, errors = [], [], [], []
    for i, (ex, r) in enumerate(zip(chosen, results)):
        if "error" in r:
            errors.append({"id": int(ex.get("id", i)), **r})
            continue
        probs.append(r["prob"])
        labels.append(int(ex.get("label", 0)))
        ids.append(int(ex.get("id", i)))
    os.makedirs(args.out_dir, exist_ok=True)
    _dump_predictions(args.out_dir, {"index": ids, "probs": probs,
                                     "labels": labels},
                      name="score_predictions.csv")
    report = {"n_scored": len(probs), "n_errors": len(errors),
              "errors": errors[:10], "split": args.split,
              "out": os.path.join(args.out_dir, "score_predictions.csv"),
              "serving": engine.snapshot()}
    print(json.dumps(report))
    return report


def _scan_smoke(engine, model_cfg, args, compiles0: int) -> Dict[str, Any]:
    """The hermetic scan self-test (scripts/test.sh gate): sweep a seeded
    mini-corpus through the full pool/featurize/score machinery on the
    fake-Joern transport, edit ONE function, re-scan — exactly the
    changed function may re-featurize (one cache miss), every untouched
    verdict must come back cached and byte-identical, and the warmed
    serve engine must not compile anything new."""
    import shutil
    import tempfile

    from deepdfa_tpu.scan import ScanConfig, ScanService, fake_joern_command
    from deepdfa_tpu.scan.fake_joern import edit_source, seeded_sources

    n = args.smoke
    tmp = tempfile.mkdtemp(prefix="scan_smoke_")
    try:
        corpus = os.path.join(tmp, "corpus")
        os.makedirs(corpus)
        paths = []
        for i, source in enumerate(seeded_sources(n, seed=args.seed)):
            p = os.path.join(corpus, f"fn_{i:03d}.c")
            with open(p, "w", encoding="utf-8") as f:
                f.write(source)
            paths.append(p)
        config = ScanConfig(pool_size=args.scan_pool_size,
                            timeout_s=args.scan_timeout_s,
                            attempts=args.scan_attempts)
        with ScanService(engine, model_cfg.feature,
                         workdir=os.path.join(tmp, "scan"), config=config,
                         command=fake_joern_command()) as svc:
            first = svc.scan_files(paths)
            edited = paths[n // 2]
            with open(edited, encoding="utf-8") as f:
                text = f.read()
            with open(edited, "w", encoding="utf-8") as f:
                f.write(edit_source(text))
            second = svc.scan_files(paths)
            snap = svc.snapshot()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    misses = [r for r in second if not r.get("cached")]
    stable = all(
        b.get("cached") and a.get("prob") == b.get("prob")
        and a.get("key") == b.get("key")
        for a, b in zip(first, second) if a["id"] != edited
    )
    compiles_after = int(engine.snapshot()["compiles"]) - compiles0
    ok = bool(
        all("prob" in r for r in first)
        and len(misses) == 1
        and misses[0]["id"] == edited
        and misses[0].get("featurized")
        and stable
        and compiles_after == 0
    )
    return {
        "smoke": n, "ok": ok,
        "first_misses": sum(1 for r in first if not r.get("cached")),
        "rescan_misses": len(misses),
        "changed_only_refeaturized":
            len(misses) == 1 and misses[0]["id"] == edited,
        "untouched_verdicts_stable": stable,
        "compiles_after_warmup": compiles_after,
        "scan": snap,
    }


def cmd_scan(args) -> Dict[str, Any]:
    """Offline scan sweep (deepdfa_tpu/scan): raw C source files ->
    pooled persistent Joern workers -> on-demand featurize -> the warmed
    serve engine, with the incremental content-hash verdict cache.
    Targets are files, directories (every ``*.c`` under them), or
    ``--diff FILE`` (a unified diff — the post-image ``.c`` paths are the
    work-list, the PR-diff mode). With a persistent ``--scan-cache``, a
    re-sweep after a one-line edit re-analyzes ~one function.

    ``--smoke N`` is the hermetic self-test on the fake-Joern transport
    (no JVM, single device): seeded corpus, one edit, re-scan, exactly
    the changed function re-featurized — the scripts/test.sh gate."""
    import contextlib

    from deepdfa_tpu.scan import changed_paths_from_diff

    run_dir = args.run_dir or ("runs/scan_smoke"
                               if args.smoke is not None else None)
    scope = (telemetry.run_scope(run_dir) if run_dir
             else contextlib.nullcontext())
    with scope:
        engine, model_cfg = _build_serve_engine(args)
        engine.warmup()
        # snapshot()["compiles"], not engine.stats: _build_serve_engine
        # returns a ServeFleet under --replicas, and the snapshot key is
        # the one surface both shapes share (fleet: summed per-replica).
        compiles0 = int(engine.snapshot()["compiles"])
        if args.smoke is not None:
            report = _scan_smoke(engine, model_cfg, args, compiles0)
        else:
            paths: List[str] = []
            for target in args.targets:
                if os.path.isdir(target):
                    for root, _, names in sorted(os.walk(target)):
                        paths += [os.path.join(root, x)
                                  for x in sorted(names)
                                  if x.endswith(".c")]
                else:
                    paths.append(target)
            if args.diff:
                text = (sys.stdin.read() if args.diff == "-"
                        else open(args.diff, encoding="utf-8").read())
                paths += [os.path.join(args.root, rel)
                          for rel in changed_paths_from_diff(text)
                          if rel.endswith(".c")]
            if not paths:
                raise ValueError(
                    "scan: nothing to scan (pass files/dirs, --diff, or "
                    "--smoke)")
            svc = _build_scan_service(engine, model_cfg, args)
            if svc is None:
                raise ValueError("scan: --scan-transport none makes no "
                                 "sense here (use 'fake' or a joern "
                                 "binary)")
            with svc:
                verdicts = svc.scan_files(paths)
                snap = svc.snapshot()
            if args.out:
                os.makedirs(os.path.dirname(args.out) or ".",
                            exist_ok=True)
                with open(args.out, "w", encoding="utf-8") as f:
                    for r in verdicts:
                        f.write(json.dumps(r) + "\n")
            n_errors = sum(1 for r in verdicts if "error" in r)
            report = {
                "n_scanned": len(verdicts),
                "n_errors": n_errors,
                "cache_hits":
                    sum(1 for r in verdicts if r.get("cached")),
                "compiles_after_warmup":
                    int(engine.snapshot()["compiles"]) - compiles0,
                "scan": snap,
                "results": verdicts if not args.out else None,
                "out": args.out,
                # A poisoned function is an inline error and costs
                # itself; a sweep where NOTHING scored (e.g. no usable
                # Joern — every worker dead) must not exit 0, or a CI
                # gate passes while zero functions were analyzed.
                "ok": n_errors < len(verdicts),
            }
    if run_dir:
        report["telemetry"] = os.path.join(run_dir, "telemetry")
    if not report.get("ok", True):
        report["exit_code"] = 1
    print(json.dumps(report))
    return report


def cmd_analyze(args) -> Dict[str, Any]:
    """Feature coverage: share of definition nodes whose abstract-dataflow
    index is known vs UNKNOWN (index 1) vs not-a-definition (index 0) —
    get_coverage semantics (main_cli.py:192-313, paper Table 2 ~79% at
    k=1000)."""
    cfgs = build_configs(args.config, args.set)
    model_cfg = cfgs["model"]
    examples, _ = load_dataset(args.dataset, model_cfg.feature)
    subkeys = subkeys_for(model_cfg.feature)
    report: Dict[str, Any] = {"n_examples": len(examples)}
    for k in subkeys:
        known = unknown = nondef = 0
        for ex in examples:
            feats = np.asarray(ex["feats"][k])
            nondef += int((feats == 0).sum())
            unknown += int((feats == 1).sum())
            known += int((feats > 1).sum())
        defs = known + unknown
        report[k] = {
            "definitions": defs,
            "coverage": known / defs if defs else 0.0,
            "nondef_nodes": nondef,
        }
    print(json.dumps(report))
    return report


def cmd_analyze_code(args) -> Dict[str, Any]:
    """graftlint: the dataflow-analysis-based static checker for JAX/TPU
    hazards (host syncs in jitted/step-loop code, tracer control flow,
    recompilation triggers, impurity under jit, PRNG key reuse) over our own
    sources — the paper's core idea, dogfooded (analysis/ package). Reports
    only findings not in the committed baseline; exits nonzero when any
    exist (the scripts/lint.sh CI contract)."""
    from deepdfa_tpu.analysis.runner import format_report, run_analysis

    report = run_analysis(
        paths=args.paths or None,
        baseline_path=args.baseline,
        write_baseline_file=args.write_baseline,
        incremental=args.incremental,
    )
    if args.sarif:
        from deepdfa_tpu.analysis.sarif import write_sarif

        write_sarif(report, args.sarif)
    if args.json:
        # new_findings holds Finding objects for the text formatter only
        print(json.dumps({k: v for k, v in report.items()
                          if k != "new_findings"}))
    else:
        print(format_report(report, verbose=args.verbose))
    return report


def cmd_chaos(args) -> Dict[str, Any]:
    """Chaos soak (deepdfa_tpu/resilience): provoke thirteen fault
    classes — simulated preemption, NaN loss, checkpoint corruption, ETL
    item failure, serving flush failure, corrupt-corpus poisoning, a
    mid-epoch kill under async checkpointing resumed on a different
    device count, pooled Joern workers killed mid-scan, a REAL SIGTERM
    to a mid-epoch training subprocess (step-granular preempt snapshot,
    mid-epoch resume, hung-step watchdog), a SIGTERM lame-duck drain
    of a live serve subprocess under load, a rolling replica drain of a
    3-replica serving fleet mid-load, a SIGKILLed engine process under
    the multi-process router, and a SIGTERM to one member of a live
    two-process ``jax.distributed`` training fleet (coordinated drain
    barrier, both exit preempted, 2→1 checkpoint redistribution on
    resume) — against a tiny synthetic workload and verify every
    recovery contract, including the bit-for-bit kill-and-resume
    determinism gate. Exits nonzero on any miss.

    (Custom fault plans don't belong here — the soak's scenarios arm
    their own; arm ``DEEPDFA_FAULT_PLAN`` against a regular command
    (``fit``, ``serve``, ...) to drive arbitrary fault sites by hand.)"""
    from deepdfa_tpu.resilience import chaos

    if args.epochs < 2:
        # The preemption scenario kills epoch >= 1 and resumes; with one
        # epoch it can never fire and the soak would report a missed
        # recovery contract instead of the actual argument error.
        raise ValueError("chaos: --epochs must be >= 2 (the preemption "
                         "scenario interrupts a later epoch)")
    n = 48
    if args.dataset.startswith("synthetic") and ":" in args.dataset:
        n = int(args.dataset.split(":")[1])
    # The soak runs instrumented: every scenario's spans/faults land in
    # one run, and the SLO gate below checks the observability substrate
    # held up under fault load (nothing dropped, serve latency bounded).
    with telemetry.run_scope(args.out_dir):
        report = chaos.run_soak(out_dir=args.out_dir, n_examples=n,
                                epochs=args.epochs)
    if telemetry.enabled() and args.slo != "none":
        from deepdfa_tpu.telemetry.report import trace_report

        _apply_slo_gate(report, trace_report(args.out_dir), args.slo)
    print(json.dumps(report))
    return report


def cmd_validate(args) -> Dict[str, Any]:
    """Schema-validate a cached corpus (deepdfa_tpu/contracts): every
    ``*.jsonl`` under the cache dir runs through the example contract;
    violating rows are quarantined under ``<cache>/quarantine/`` with a
    reason-coded manifest and the command exits nonzero (fail-closed — a
    dirty cache should fail a pipeline gate, not pass silently).

    ``--smoke``: self-test instead — poison a tiny synthetic corpus across
    every corruption class in the gauntlet and assert each one is repaired
    or quarantined under its expected reason code (the scripts/test.sh
    gate; seconds on CPU)."""
    from deepdfa_tpu.contracts import gauntlet

    if args.smoke:
        report = gauntlet.smoke(seed=args.seed)
        print(json.dumps(report))
        return report
    if not args.cache_dir:
        raise ValueError("validate needs a cache dir/corpus (or --smoke)")
    # Required subkeys follow the export's FeatureSpec: a single-subkey
    # corpus (concat_all=False exports) must not quarantine for lacking
    # the other three.
    subkeys = (subkeys_for(FeatureSpec.parse_legacy(args.feature))
               if args.feature else None)
    report = gauntlet.validate_corpus(
        args.cache_dir, max_nodes=args.max_nodes,
        **({"subkeys": subkeys} if subkeys else {}))
    # Contract taxonomy counters ride along: the per-boundary IngestStats
    # snapshot is the machine-readable face of the validation pass.
    from deepdfa_tpu.contracts import STATS

    report["ingest_stats"] = STATS.snapshot()
    print(json.dumps({k: v for k, v in report.items() if k != "reports"}))
    return report


def cmd_trace(args) -> Dict[str, Any]:
    """Telemetry tooling (deepdfa_tpu/telemetry).

    ``cli trace report <run>`` summarizes ``runs/<run>/telemetry/
    events.jsonl`` offline: step-time p50/p99, host-dispatch vs
    device-execute split, post-warmup compile count, retry/fault/
    quarantine totals. ``cli trace --smoke`` runs a tiny instrumented fit
    and asserts the report round-trips — the scripts/test.sh gate.
    """
    from deepdfa_tpu.telemetry.report import trace_report

    if args.smoke:
        from deepdfa_tpu.core.config import DataConfig, TrainConfig
        from deepdfa_tpu.data.splits import make_splits
        from deepdfa_tpu.data.synthetic import synthetic_bigvul
        from deepdfa_tpu.models.flowgnn import FlowGNN
        from deepdfa_tpu.train.loop import fit

        run_dir = args.out_dir
        model_cfg = FlowGNNConfig(hidden_dim=8, n_steps=2)
        examples = synthetic_bigvul(32, model_cfg.feature,
                                    positive_fraction=0.5, seed=args.seed)
        for i, ex in enumerate(examples):
            ex["label"] = int(np.asarray(ex["vuln"]).max())
            ex["id"] = i
        splits = make_splits(examples, seed=args.seed)
        with telemetry.run_scope(run_dir):
            # Cross-process leg (ISSUE 14): a real forked pmap pool whose
            # workers emit events from their own processes — each lands
            # in its own shard of THIS run, and the merged report must
            # see them under a distinct process name. Forked BEFORE the
            # fit dispatches anything: os.fork() from a process whose
            # JAX thread pools are already hot risks the classic
            # fork-while-a-thread-holds-a-lock wedge — forking first
            # keeps the smoke's fork window as single-threaded as this
            # process gets.
            from deepdfa_tpu.etl.parallel import pmap

            def _probe(i):
                telemetry.event("smoke.child_work", item=int(i))
                return int(i)

            child_ok = pmap(_probe, list(range(4)), workers=2,
                            desc="trace-smoke") == [0, 1, 2, 3]
            fit(FlowGNN(model_cfg), examples, splits,
                TrainConfig(max_epochs=2, seed=args.seed),
                DataConfig(batch_size=8, eval_batch_size=8), log_every=2)
        report = trace_report(run_dir)
        trace_json = os.path.join(run_dir, "telemetry", "trace.json")
        with open(trace_json) as f:
            trace_doc = json.load(f)
        procs = report.get("processes") or {}
        child_procs = [p for p in procs if p != "main"]
        proc_meta = [e for e in trace_doc.get("traceEvents", [])
                     if e.get("ph") == "M"
                     and e.get("name") == "process_name"]
        checks = {
            "steps_recorded": report["train"]["steps"] > 0,
            "fenced_windows": report["train"]["fenced_windows"] > 0,
            "host_device_split": report["train"]["host_frac"] is not None,
            "compiles_captured": report["compiles"]["total"] > 0,
            "warmup_marker": report["compiles"]["warmup_marker"],
            "no_faults": report["faults"]["total"] == 0,
            "no_drops": report["telemetry_drops"] == 0,
            "trace_json_valid": bool(trace_doc.get("traceEvents")),
            # Merged-shard round-trip: child processes' events survived
            # into the one report/trace under their own identity.
            "child_items_ok": child_ok,
            "cross_process_shards": len(child_procs) >= 1,
            "child_events_merged": any(procs[p]["events"] > 0
                                       for p in child_procs),
            "merged_trace_processes":
                len({m.get("pid") for m in proc_meta}) >= 2,
            "no_torn_rows": all(p.get("torn_rows", 0) == 0
                                for p in procs.values()),
        }
        out = {"smoke": True, "ok": all(checks.values()), "checks": checks,
               "report": report}
        print(json.dumps(out))
        if not out["ok"]:
            out["exit_code"] = 1
        return out
    if args.action == "recommend-buckets":
        # The offline ladder recommender (ISSUE 20): report-only replay
        # of the run's traffic shape sketches against fitted ladders.
        if not args.run_dir:
            raise ValueError(
                "usage: cli trace recommend-buckets <run-dir>")
        from deepdfa_tpu.telemetry.report import recommend_buckets

        kw: Dict[str, Any] = {}
        if getattr(args, "quantiles", None):
            kw["quantiles"] = tuple(
                float(q) for q in args.quantiles.split(","))
        rec = recommend_buckets(args.run_dir, **kw)
        print(json.dumps(rec))
        return rec
    if args.action != "report" or not args.run_dir:
        raise ValueError("usage: cli trace report <run-dir> | "
                         "cli trace recommend-buckets <run-dir> | "
                         "cli trace --smoke")
    report = trace_report(args.run_dir)
    if args.slo:
        _apply_slo_gate(report, report, args.slo)
    print(json.dumps(report))
    return report


def cmd_bench(args) -> Dict[str, Any]:
    """Bench-regression observatory (deepdfa_tpu/benchwatch).

    ``cli bench diff --smoke`` runs the seconds-sized smoke measurement,
    compares it variance-aware against ``benchmarks/history.jsonl`` rows
    from the same environment fingerprint, appends the new row, and
    exits nonzero on a regression — the scripts/test.sh gate. ``--current
    FILE`` diffs an existing bench artifact (raw stdout or a driver
    BENCH_r*.json) against the trajectory instead."""
    from deepdfa_tpu import benchwatch

    if args.action != "diff":
        raise ValueError("usage: cli bench diff [--smoke | --current FILE]")
    history = benchwatch.read_history(args.history)
    fingerprint = benchwatch.env_fingerprint()
    if args.smoke:
        metrics = benchwatch.bench_smoke()
        source = "bench_smoke"
    elif args.current:
        metrics = benchwatch.parse_bench_file(args.current)
        source = os.path.basename(args.current)
    else:
        raise ValueError("bench diff needs --smoke or --current FILE")
    report = benchwatch.diff(metrics, history, fingerprint,
                             base_tolerance_pct=args.tolerance_pct)
    report["metrics"] = {k: v["value"] for k, v in metrics.items()}
    report["history"] = args.history
    # Append AFTER the comparison (a row must never compare against
    # itself); only measurements append — replaying an artifact with
    # --current is a query, not a new datapoint.
    if args.smoke and not args.no_append:
        benchwatch.append_history(metrics, fingerprint, source=source,
                                  path=args.history)
        report["appended"] = True
    if not report["ok"]:
        report["exit_code"] = 1
    print(json.dumps(report))
    return report


def cmd_tune(args) -> Dict[str, Any]:
    """Random hyperparameter search (the NNI replacement): samples the
    published search space (paper Table 2 context), runs short fits, ranks
    by best val F1, writes tune_results.jsonl.

    Per-epoch val F1 feeds a median-stop assessor (NNI's early-termination
    rule, train/tune.py): once enough trials completed, a trial whose best
    F1 trails the median of completed running-averages is cut short — its
    record carries ``epochs_run`` < epochs_per_trial."""
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit
    from deepdfa_tpu.train.tune import MedianStopAssessor

    cfgs = build_configs(args.config, args.set)
    base_model, base_data, base_train = cfgs["model"], cfgs["data"], cfgs["train"]
    rng = np.random.RandomState(base_train.seed)
    # --space FILE: arbitrary search spaces the way the reference's NNI
    # flow takes a search-space config (DDFA nni config yamls) — a JSON
    # object of "model.<field>"/"train.<field>" -> candidate list. The
    # baked-in default is the published four-axis space (paper Table 2
    # context).
    if getattr(args, "space", None):
        with open(args.space) as f:
            space = json.load(f)
        if not (isinstance(space, dict) and space and all(
                isinstance(v, list) and v for v in space.values())):
            raise ValueError(
                f"{args.space}: search space must be a non-empty JSON "
                "object mapping 'model.<field>'/'train.<field>' to "
                "non-empty candidate lists"
            )
        # Validate every key now, before the dataset loads and trial state
        # is created — a bad key must not waste a trial's worth of setup.
        fields = {
            "model": {f.name for f in dataclasses.fields(base_model)},
            "train": {f.name for f in dataclasses.fields(base_train)},
        }
        for key, cands in space.items():
            scope, _, field = key.partition(".")
            if scope not in fields:
                raise ValueError(
                    f"search-space key {key!r}: scope must be 'model.' or "
                    "'train.'"
                )
            if field not in fields[scope]:
                raise ValueError(
                    f"search-space key {key!r}: no such {scope} config "
                    f"field"
                )
            # Coerce candidates to the field's current type now — a
            # "64"-for-int or unparseable value must fail here, not after
            # a trial's worth of dataset/assessor setup.
            cur = getattr(base_model if scope == "model" else base_train,
                          field)
            if isinstance(cur, bool):
                def caster(v):
                    # bool("false") is True — parse, don't cast.
                    if isinstance(v, bool):
                        return v
                    if isinstance(v, str) and v.lower() in ("true", "false"):
                        return v.lower() == "true"
                    raise ValueError(f"not a boolean: {v!r}")
            elif isinstance(cur, int):
                def caster(v):
                    if isinstance(v, float) and not v.is_integer():
                        raise ValueError(f"non-integral for int field: {v!r}")
                    return int(v)
            elif isinstance(cur, float):
                caster = float
            else:
                caster = lambda v: v
            try:
                space[key] = [caster(v) for v in cands]
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"search-space key {key!r}: candidate not coercible to "
                    f"{type(cur).__name__}: {e}"
                )
    else:
        space = {
            "train.learning_rate": [1e-4, 5e-4, 1e-3, 5e-3],
            "train.weight_decay": [0.0, 1e-3, 1e-2],
            "model.hidden_dim": [16, 32, 64],
            "model.n_steps": [3, 5, 7],
        }
    examples, splits = load_dataset(args.dataset, base_model.feature,
                                    seed=base_train.seed,
                                    split_mode=args.split_mode)
    results = []
    out_path = os.path.join(args.out_dir, "tune_results.jsonl")
    os.makedirs(args.out_dir, exist_ok=True)
    open(out_path, "w").close()  # fresh file per run: no stale trials
    assessor = MedianStopAssessor(warmup_steps=args.assessor_warmup,
                                  min_trials=args.assessor_min_trials)
    for trial in range(args.trials):
        pick = {k: v[rng.randint(len(v))] for k, v in space.items()}
        # Keys were validated at load time; plain partition by scope. The
        # per-trial epoch budget is authoritative over the space.
        model_over = {k.partition(".")[2]: v for k, v in pick.items()
                      if k.startswith("model.")}
        train_over = {k.partition(".")[2]: v for k, v in pick.items()
                      if k.startswith("train.")}
        model_cfg = dataclasses.replace(base_model, **model_over)
        train_cfg = dataclasses.replace(
            base_train,
            **{**train_over, "max_epochs": args.epochs_per_trial},
        )

        def on_epoch(epoch, record, trial=trial):
            assessor.report(trial, record["val_metrics"].get("f1", 0.0))
            return assessor.should_stop(trial)

        _, history = fit(FlowGNN(model_cfg), examples, splits, train_cfg,
                         base_data, on_epoch_end=on_epoch)
        assessor.complete(trial)
        best_f1 = max(
            (e["val_metrics"].get("f1", 0.0) for e in history["epochs"]),
            default=0.0,
        )
        record = {"trial": trial, "params": pick, "best_val_f1": best_f1,
                  "best_val_loss": history["best_val_loss"],
                  "epochs_run": len(history["epochs"]),
                  "early_stopped": bool(history.get("early_stopped", False))}
        results.append(record)
        with open(out_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        logger.info("trial %d: f1=%.4f epochs=%d%s %s", trial, best_f1,
                    record["epochs_run"],
                    " (assessor-stopped)" if record["early_stopped"] else "",
                    pick)
    best = max(results, key=lambda r: r["best_val_f1"])
    print(json.dumps(best))
    return best


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="deepdfa_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    def common(p):
        p.add_argument("--config", action="append", default=[],
                       help="YAML config file (repeatable; later overrides)")
        p.add_argument("--set", action="append", default=[], metavar="S.K=V",
                       help="override any config value")
        p.add_argument("--dataset", default="synthetic:256")
        p.add_argument("--split-mode", default="random",
                       choices=["random", "cross-project"],
                       help="cross-project = the Table 7 protocol")

    p_fit = sub.add_parser("fit")
    common(p_fit)
    p_fit.add_argument("--checkpoint-dir", default=None)
    p_fit.add_argument("--n-devices", type=int, default=1)
    p_fit.add_argument("--resume", action="store_true",
                       help="continue from the run dir's 'last' checkpoint")
    p_fit.set_defaults(func=cmd_fit)

    p_test = sub.add_parser("test")
    common(p_test)
    p_test.add_argument("--checkpoint-dir", required=True)
    p_test.add_argument("--which", default="best", help="best | last | epoch_N")
    p_test.add_argument("--n-devices", type=int, default=1,
                        help="dp-shard eval batches over a mesh (the "
                             "reference's DataParallel eval)")
    # The reference's profiling flow (scripts/run_profiling.sh ->
    # --model.profile/--model.time, base_module.py:238-291): per-step
    # FLOPs/latency JSONL plus an aggregated Table-5-style summary.
    p_test.add_argument("--profile", action="store_true",
                        help="record per-step FLOPs/MACs to profiledata.jsonl")
    p_test.add_argument("--time", action="store_true",
                        help="record per-step latency to timedata.jsonl")
    p_test.add_argument("--profile-dir", default=None,
                        help="where the JSONL records land (default: "
                             "checkpoint dir)")
    p_test.set_defaults(func=cmd_test)

    # Combined DeepDFA+transformer training: the msr_train_combined.sh /
    # run_defect.py --flowgnn_* surface.
    p_ft = sub.add_parser(
        "fit-text", help="train LineVul/CodeT5-defect, optionally combined "
                         "with the FlowGNN graph encoder")
    common(p_ft)
    p_ft.add_argument("--model", choices=["linevul", "codet5"],
                      default="linevul")
    p_ft.add_argument("--graphs", default=None,
                      help="graph source: synthetic | dbize cache dir | "
                           "etl export .jsonl (omit for text-only)")
    p_ft.add_argument("--checkpoint-dir", required=True)
    p_ft.add_argument("--ddfa-checkpoint", default=None,
                      help="DDFA (cli fit) run dir; its graph encoder is "
                           "loaded into the combined model")
    p_ft.add_argument("--which", default="best",
                      help="which DDFA checkpoint to load (best|last|epoch_N)")
    p_ft.add_argument("--freeze-graph", action="store_true",
                      help="freeze the loaded graph encoder (main_cli.py "
                           "--freeze_graph)")
    p_ft.add_argument("--tiny", action="store_true",
                      help="tiny encoder shapes (smoke tests)")
    p_ft.add_argument("--attention-impl", default="auto",
                      choices=["auto", "dense", "blockwise", "flash"],
                      help="encoder attention (auto = flash kernels on TPU, "
                           "blockwise elsewhere; dense for attribution; "
                           "ring needs a seq-axis mesh — library surface)")
    p_ft.add_argument("--remat", action="store_true",
                      help="rematerialize encoder layers (shapes beyond the "
                           "measured 16G envelope — costs throughput inside "
                           "it)")
    p_ft.add_argument("--tokenizer", default=None,
                      help="trained BPE assets (defaults to the hashing "
                           "tokenizer)")
    p_ft.add_argument("--epochs", type=int, default=10)
    p_ft.add_argument("--batch-size", type=int, default=16)
    p_ft.add_argument("--eval-batch-size", type=int, default=None)
    p_ft.add_argument("--learning-rate", type=float, default=2e-5)
    p_ft.add_argument("--block-size", type=int, default=512)
    p_ft.add_argument("--seed", type=int, default=1)
    p_ft.add_argument("--n-devices", type=int, default=1)
    p_ft.add_argument("--max-nodes", type=int, default=None,
                      help="graph batch node budget (default: sized from "
                           "the data)")
    p_ft.add_argument("--max-edges", type=int, default=None)
    p_ft.add_argument("--no-test", action="store_true",
                      help="skip the post-training test-split evaluation")
    p_ft.set_defaults(func=cmd_fit_text)

    p_tt = sub.add_parser(
        "test-text", help="evaluate/profile a fit-text checkpoint")
    p_tt.add_argument("--checkpoint-dir", required=True)
    p_tt.add_argument("--which", default="best")
    p_tt.add_argument("--dataset", default=None,
                      help="override the dataset recorded at fit time")
    p_tt.add_argument("--graphs", default=None)
    p_tt.add_argument("--tokenizer", default=None)
    p_tt.add_argument("--eval-batch-size", type=int, default=16)
    p_tt.add_argument("--n-devices", type=int, default=1,
                      help="dp-shard eval batches over a mesh (the "
                           "reference's DataParallel eval)")
    p_tt.add_argument("--profile", action="store_true")
    p_tt.add_argument("--time", action="store_true")
    p_tt.add_argument("--profile-dir", default=None)
    p_tt.add_argument("--dbgbench", default=None, metavar="BUG_MAP.json",
                      help="JSON {example_index: bug_id}; adds the Table-8 "
                           "bugs-detected report over the evaluated split")
    p_tt.add_argument("--dbgbench-threshold", type=float, default=0.5)
    p_tt.set_defaults(func=cmd_test_text)

    # Serving: the checkpoint-to-responses path (deepdfa_tpu/serve).
    def serve_knobs(p):
        p.add_argument("--batch-slots", type=int, default=16,
                       help="largest micro-batch (slot-bucket ladder top)")
        p.add_argument("--deadline-ms", type=float, default=100.0,
                       help="per-request latency budget; a bucket flushes "
                            "once the oldest request has spent half of it")
        p.add_argument("--queue-capacity", type=int, default=256,
                       help="pending requests before 429-style rejection")
        p.add_argument("--cache-capacity", type=int, default=4096,
                       help="content-hash result cache entries (0 = off)")
        # String default: argparse runs type= over string defaults at
        # parse time, so a malformed DEEPDFA_SERVE_REPLICAS is a clean
        # parser error on the serve-family command — never an import-time
        # crash of unrelated subcommands.
        p.add_argument("--replicas", type=int,
                       default=os.environ.get(
                           "DEEPDFA_SERVE_REPLICAS", "1"),
                       help="engine replicas, each pinned to its shard of "
                            "the device mesh with its own micro-batcher "
                            "and pump thread (env DEEPDFA_SERVE_REPLICAS; "
                            "bounded by the static replica-id set, max 8)")
        # Same string-default discipline for the engine-PROCESS count
        # (ISSUE 17): a malformed DEEPDFA_SERVE_PROCESSES surfaces as a
        # clean parser error, never an import-time crash.
        p.add_argument("--processes", type=int,
                       default=os.environ.get(
                           "DEEPDFA_SERVE_PROCESSES", "1"),
                       help="engine OS processes behind an in-process "
                            "router tier: each child owns its own AOT-"
                            "warmed engine, batcher, and lifecycle; the "
                            "router preserves content-affine routing and "
                            "re-routes around dead children (env "
                            "DEEPDFA_SERVE_PROCESSES; bounded by the "
                            "static process-id set, max 8; 1 = the "
                            "historic single-process server)")
        p.add_argument("--adaptive-flush", action="store_true",
                       default=os.environ.get(
                           "DEEPDFA_ADAPTIVE_FLUSH", "") not in ("", "0"),
                       help="telemetry-driven flush policy: each replica "
                            "tunes its deadline-fraction/fill thresholds "
                            "from its own p99/occupancy (clamped, with "
                            "hysteresis; every decision is a "
                            "serve.flush_policy trace event; env "
                            "DEEPDFA_ADAPTIVE_FLUSH=1)")
        # Generation lane (ISSUE 13): batched-beam CodeT5 decode served
        # under the same AOT-warmup/zero-recompile discipline.
        p.add_argument("--gen-lane", action="store_true",
                       default=os.environ.get(
                           "DEEPDFA_GEN_LANE", "") not in ("", "0"),
                       help="attach the generation lane (lane='gen' on "
                            "POST /score); without --gen-checkpoint-dir "
                            "it serves random-init weights (smoke mode; "
                            "env DEEPDFA_GEN_LANE=1)")
        p.add_argument("--gen-model", default="tiny",
                       choices=("tiny", "codet5-small", "codet5-base"),
                       help="gen-lane model shape")
        p.add_argument("--gen-checkpoint-dir", default=None,
                       help="fit-gen run dir to restore gen params from "
                            "(implies --gen-lane)")
        p.add_argument("--gen-which", default="best")
        p.add_argument("--gen-tokenizer", default=None, metavar="ASSETS",
                       help="trained tokenizer assets for the gen lane "
                            "(tokenizer.json / vocab+merges dir) — "
                            "required for BPE-trained checkpoints; "
                            "omitted: the hashing tokenizer (synthetic/"
                            "smoke runs only)")
        p.add_argument("--gen-src-len", type=int, default=None,
                       help="gen-lane source-token cap / length-bucket "
                            "ladder top (default ServeConfig)")
        p.add_argument("--gen-max-len", type=int, default=None,
                       help="generated tokens per request (static decode "
                            "shape)")
        p.add_argument("--gen-beam", type=int, default=None,
                       help="beam width (1 = greedy)")

    # Streaming scan: the raw-source edge (deepdfa_tpu/scan). Shared by
    # `serve` (attaches POST /scan) and `scan` (offline sweeps). Env
    # knobs override the defaults so a deployment can size the pool
    # without re-plumbing flags (README "Streaming scan service").
    def scan_knobs(p, default_transport):
        p.add_argument(
            "--scan-transport",
            default=os.environ.get("DEEPDFA_SCAN_TRANSPORT",
                                   default_transport),
            help="CPG transport: 'fake' (hermetic scripted subprocess — "
                 "no JVM, the tier-1/smoke transport), 'none' (serve "
                 "only: POST /scan answers 501), or a joern binary "
                 "name/path (env DEEPDFA_SCAN_TRANSPORT)")
        p.add_argument("--scan-pool-size", type=int,
                       default=int(os.environ.get("DEEPDFA_SCAN_POOL",
                                                  "2")),
                       help="persistent Joern workers (env "
                            "DEEPDFA_SCAN_POOL)")
        p.add_argument("--scan-timeout-s", type=float, default=120.0,
                       help="per-REPL-command read deadline; a hung "
                            "Joern is restarted when it fires")
        p.add_argument("--scan-attempts", type=int, default=3,
                       help="tries per function (session restart "
                            "between) before the item fails typed")
        p.add_argument("--scan-workdir", default="runs/scan",
                       help="scan scratch: function files, Joern "
                            "workspaces, quarantine, default cache")
        p.add_argument("--scan-cache", default=None, metavar="FILE",
                       help="persistent verdict cache JSONL (default "
                            "<scan-workdir>/verdicts.jsonl); re-scans "
                            "hit it across restarts")
        p.add_argument("--scan-vocabs",
                       default=os.environ.get("DEEPDFA_SCAN_VOCABS"),
                       metavar="FILE",
                       help="vocabs.json persisted by the ETL export "
                            "(checkpoint-faithful feature indices; env "
                            "DEEPDFA_SCAN_VOCABS). Omitted: the "
                            "deterministic hashing vocabulary")

    p_srv = sub.add_parser(
        "serve", help="HTTP scoring endpoint: deadline-aware bucketed "
                      "micro-batching over AOT-warmed shapes")
    p_srv.add_argument("--config", action="append", default=[])
    p_srv.add_argument("--set", action="append", default=[], metavar="S.K=V")
    p_srv.add_argument("--checkpoint-dir", default=None,
                       help="cli fit run dir (omit for random-init smoke "
                            "mode)")
    p_srv.add_argument("--which", default="best")
    p_srv.add_argument("--combined-checkpoint-dir", default=None,
                       help="fit-text combined linevul run dir: attaches "
                            "the DDFA+LineVul lane for requests with code")
    p_srv.add_argument("--combined-which", default="best")
    p_srv.add_argument("--host", default="127.0.0.1")
    p_srv.add_argument("--port", type=int, default=8321)
    p_srv.add_argument("--port-file", default=None, metavar="FILE",
                       help="write the bound port here after bind (how "
                            "drivers find an ephemeral --port 0)")
    p_srv.add_argument("--no-warmup", action="store_true",
                       help="skip AOT bucket warmup (first requests then "
                            "pay the compiles)")
    p_srv.add_argument("--smoke", type=int, default=None, metavar="N",
                       help="self-drive the full HTTP stack with N "
                            "synthetic requests, print the report, exit")
    p_srv.add_argument("--run-dir", default=None,
                       help="telemetry sink directory (events.jsonl + "
                            "trace.json; --smoke defaults to "
                            "runs/serve_smoke)")
    p_srv.add_argument("--slo", default="smoke",
                       help="SLO spec: JSON file, built-in name (smoke/"
                            "chaos/default), or 'none'. Live serving runs "
                            "it as a burn-rate monitor degrading /healthz; "
                            "--smoke additionally gates the run's trace "
                            "(post-warmup recompiles, p99) with a nonzero "
                            "exit")
    serve_knobs(p_srv)
    scan_knobs(p_srv, default_transport="none")
    p_srv.set_defaults(func=cmd_serve)

    p_scan = sub.add_parser(
        "scan", help="streaming scan: raw C source -> pooled persistent "
                     "Joern -> DDFA verdicts through the warmed serving "
                     "engine, with incremental content-hash caching")
    p_scan.add_argument("targets", nargs="*",
                        help="files and/or directories (every *.c under "
                             "a directory, recursively)")
    p_scan.add_argument("--diff", default=None, metavar="FILE",
                        help="unified diff ('-' = stdin): scan its "
                             "post-image .c paths (the PR-diff mode)")
    p_scan.add_argument("--root", default=".",
                        help="prefix for --diff paths")
    p_scan.add_argument("--config", action="append", default=[])
    p_scan.add_argument("--set", action="append", default=[],
                        metavar="S.K=V")
    p_scan.add_argument("--checkpoint-dir", default=None,
                        help="cli fit run dir (omit for random-init "
                             "smoke mode)")
    p_scan.add_argument("--which", default="best")
    p_scan.add_argument("--combined-checkpoint-dir", default=None,
                        help="fit-text combined linevul run dir: scores "
                             "through the DDFA+LineVul lane")
    p_scan.add_argument("--combined-which", default="best")
    p_scan.add_argument("--out", default=None, metavar="FILE",
                        help="write per-function verdicts JSONL here "
                             "instead of inlining them in the report")
    p_scan.add_argument("--smoke", type=int, nargs="?", const=6,
                        default=None, metavar="N",
                        help="hermetic self-test (fake-Joern): seeded "
                             "N-function corpus, one edit, re-scan, "
                             "exactly the changed function re-featurized "
                             "(the scripts/test.sh gate)")
    p_scan.add_argument("--seed", type=int, default=0,
                        help="--smoke corpus seed")
    p_scan.add_argument("--run-dir", default=None,
                        help="telemetry sink (--smoke defaults to "
                             "runs/scan_smoke)")
    serve_knobs(p_scan)
    scan_knobs(p_scan, default_transport="joern")
    p_scan.set_defaults(func=cmd_scan)

    p_sc = sub.add_parser(
        "score", help="offline batch client of the serving path (cache + "
                      "micro-batcher + bucketed executables)")
    common(p_sc)
    p_sc.add_argument("--checkpoint-dir", default=None,
                      help="cli fit run dir (omit for random-init smoke)")
    p_sc.add_argument("--which", default="best")
    p_sc.add_argument("--split", default="all",
                      choices=["all", "train", "val", "test"])
    p_sc.add_argument("--out-dir", default="runs/score")
    serve_knobs(p_sc)
    p_sc.set_defaults(func=cmd_score)

    p_an = sub.add_parser("analyze")
    common(p_an)
    p_an.set_defaults(func=cmd_analyze)

    p_ac = sub.add_parser(
        "analyze-code",
        help="graftlint: static JAX/TPU-hazard analysis over this repo's "
             "own sources (reaching-defs + tracer taint); nonzero exit on "
             "non-baselined findings")
    p_ac.add_argument("paths", nargs="*",
                      help="files/dirs to analyze (default: the "
                           "deepdfa_tpu package)")
    p_ac.add_argument("--baseline", default=None,
                      help="baseline-suppressions JSON (default: "
                           "configs/lint_baseline.json)")
    p_ac.add_argument("--write-baseline", action="store_true",
                      help="regenerate the baseline from the current "
                           "findings (accepts them all)")
    p_ac.add_argument("--json", action="store_true",
                      help="machine-readable report on stdout")
    p_ac.add_argument("--verbose", action="store_true",
                      help="also list baselined findings")
    p_ac.add_argument("--incremental", action="store_true",
                      help="reuse the content-hash cache "
                           "(.graftlint_cache.json): re-analyze only "
                           "changed files + their importers; CI runs cold")
    p_ac.add_argument("--sarif", default=None, metavar="PATH",
                      help="also write the report as SARIF 2.1.0 (CI "
                           "annotation format)")
    p_ac.set_defaults(func=cmd_analyze_code)

    p_ch = sub.add_parser(
        "chaos",
        help="fault-injection soak: preemption/NaN/corruption/ETL/serving "
             "faults against a tiny run, verifying every recovery contract "
             "(resume determinism is bit-for-bit); nonzero exit on any miss")
    p_ch.add_argument("--dataset", default="synthetic:48",
                      help="synthetic:N — the soak's workload size")
    p_ch.add_argument("--epochs", type=int, default=3,
                      help="epochs per training scenario (>= 2)")
    p_ch.add_argument("--out-dir", default="runs/chaos")
    p_ch.add_argument("--slo", default="chaos",
                      help="SLO spec the soak's trace is gated on after "
                           "the scenarios (JSON file, built-in name, or "
                           "'none')")
    p_ch.set_defaults(func=cmd_chaos)

    p_val = sub.add_parser(
        "validate",
        help="schema-validate a cached corpus through the data contracts "
             "(deepdfa_tpu/contracts); violating rows move to "
             "<cache>/quarantine/ with a reason-coded manifest; nonzero "
             "exit when anything was quarantined")
    p_val.add_argument("cache_dir", nargs="?", default=None,
                       help="cache directory (every *.jsonl under it) or "
                            "one corpus file")
    p_val.add_argument("--smoke", action="store_true",
                       help="seeded corrupt-corpus self-test: every "
                            "corruption class must be repaired or "
                            "quarantined under its expected reason code")
    p_val.add_argument("--max-nodes", type=int, default=None,
                       help="oversize-graph cap (default: no cap)")
    p_val.add_argument("--feature", default=None,
                       help="legacy feature name of the export (sets the "
                            "required subkeys; default: all four)")
    p_val.add_argument("--seed", type=int, default=0,
                       help="--smoke corruption seed")
    p_val.set_defaults(func=cmd_validate)

    p_tr = sub.add_parser(
        "trace",
        help="telemetry tooling: `trace report <run>` summarizes a run's "
             "events.jsonl (step p50/p99, host/device split, post-warmup "
             "compiles, retry/fault/quarantine totals); `trace --smoke` "
             "runs a tiny instrumented fit and round-trips the report")
    p_tr.add_argument("action", nargs="?",
                      choices=["report", "recommend-buckets"],
                      help="report: summarize one run directory; "
                           "recommend-buckets: replay the run's traffic "
                           "shape sketches against percentile-fitted "
                           "bucket ladders (report-only)")
    p_tr.add_argument("run_dir", nargs="?", default=None,
                      help="run directory holding telemetry/events.jsonl")
    p_tr.add_argument("--smoke", action="store_true",
                      help="tiny instrumented fit + report round-trip "
                           "(the scripts/test.sh gate)")
    p_tr.add_argument("--out-dir", default="runs/trace_smoke",
                      help="--smoke run directory")
    p_tr.add_argument("--seed", type=int, default=0)
    p_tr.add_argument("--slo", default=None, metavar="SPEC",
                      help="evaluate the report against an SLO spec "
                           "(JSON file or built-in name smoke/chaos/"
                           "default); breaches exit nonzero")
    p_tr.add_argument("--quantiles", default=None,
                      help="recommend-buckets ladder rung quantiles, "
                           "comma-separated (default "
                           "0.5,0.75,0.9,0.95,0.99,1.0)")
    p_tr.set_defaults(func=cmd_trace)

    p_bn = sub.add_parser(
        "bench",
        help="bench-regression observatory: `bench diff --smoke` measures "
             "the seconds-sized smoke benchmarks, compares them variance-"
             "aware against benchmarks/history.jsonl (same environment "
             "fingerprint), appends the row, and exits nonzero on a "
             "regression")
    p_bn.add_argument("action", choices=["diff"],
                      help="diff: compare a measurement against the "
                           "recorded trajectory")
    p_bn.add_argument("--history", default="benchmarks/history.jsonl",
                      help="trajectory file (env-fingerprinted JSONL rows)")
    p_bn.add_argument("--smoke", action="store_true",
                      help="run the smoke-sized benchmarks as the current "
                           "measurement (the scripts/test.sh gate)")
    p_bn.add_argument("--current", default=None, metavar="FILE",
                      help="diff an existing bench artifact instead (raw "
                           "bench stdout or a driver BENCH_r*.json)")
    p_bn.add_argument("--tolerance-pct", type=float, default=10.0,
                      help="base regression band; widened to the observed "
                           "historical spread when that is larger")
    p_bn.add_argument("--no-append", action="store_true",
                      help="do not record the smoke measurement into the "
                           "history")
    p_bn.set_defaults(func=cmd_bench)

    p_tune = sub.add_parser("tune")
    common(p_tune)
    p_tune.add_argument("--trials", type=int, default=8)
    p_tune.add_argument("--space", default=None,
                        help="JSON search-space file: {'model.<field>'|"
                             "'train.<field>': [candidates...]}; default "
                             "is the published four-axis space")
    p_tune.add_argument("--epochs-per-trial", type=int, default=3)
    p_tune.add_argument("--out-dir", default="runs/tune")
    p_tune.add_argument("--assessor-warmup", type=int, default=1,
                        help="epochs before the median-stop assessor may "
                             "terminate a trial (NNI start_step; with the "
                             "3-epoch trial default, 1 leaves epochs 2-3 "
                             "cuttable)")
    p_tune.add_argument("--assessor-min-trials", type=int, default=3,
                        help="completed trials before the assessor may cut "
                             "anything — runs with --trials <= this can "
                             "never early-stop")
    p_tune.set_defaults(func=cmd_tune)

    args = parser.parse_args(argv)
    from deepdfa_tpu.resilience import lifecycle as _lifecycle

    # Multi-controller bring-up (ISSUE 18): the elastic fleet harness (and
    # any real multi-host launcher) sets DEEPDFA_DIST_COORD/COUNT/ID so
    # every process joins one jax.distributed job BEFORE any command code
    # touches jax — process_count()/process_index() then shape every
    # host-sharded surface (mesh, batches, sharded snapshots). Absent the
    # env, nothing changes: single-controller stays the default.
    dist_coord = os.environ.get("DEEPDFA_DIST_COORD")
    dist_joined = False
    if dist_coord:
        import jax as _jax

        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            # The CPU backend refuses cross-process computations without
            # a collectives implementation; gloo-over-TCP ships in jaxlib
            # and rides the coordination service joined below. Must land
            # before the first backend touch (config, not env — the flag
            # has no env hook).
            _jax.config.update("jax_cpu_collectives_implementation", "gloo")
        _jax.distributed.initialize(
            coordinator_address=dist_coord,
            num_processes=int(os.environ["DEEPDFA_DIST_COUNT"]),
            process_id=int(os.environ["DEEPDFA_DIST_ID"]),
        )
        dist_joined = True
        logger.info("joined distributed job at %s as process %d/%d",
                    dist_coord, _jax.process_index(), _jax.process_count())
    try:
        result = args.func(args)
    except _lifecycle.Preempted as p:
        # Surfaces without a bespoke handler (fit-gen via the exp driver,
        # tune, clone): the typed preemption exit must still reach the
        # orchestrator as EXIT_PREEMPTED, never as a raw traceback — the
        # loop already drained its durable snapshot before raising.
        print(json.dumps({"preempted": True, "reason": p.notice.reason,
                          "epoch": p.epoch, "step": p.step,
                          "snapshot": p.snapshot,
                          "exit_code": _lifecycle.EXIT_PREEMPTED}))
        return _lifecycle.EXIT_PREEMPTED
    finally:
        if dist_joined:
            # Leave the coordination service cleanly on EVERY path —
            # preempted drains included — so peers' barriers never hang
            # on a vanished process (the GL026 hazard class).
            import jax as _jax

            try:
                _jax.distributed.shutdown()
            except Exception:
                logger.warning("jax.distributed.shutdown failed",
                               exc_info=True)
    # analyze-code carries the CI contract in exit_code (new findings -> 1);
    # every other command reports via its JSON line and exits 0.
    if isinstance(result, dict) and result.get("exit_code"):
        return int(result["exit_code"])
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Bench-regression observatory: env-fingerprinted history + variance-aware diff.

The bench trajectory (BENCH_r01..r05) was, until now, compared by
eyeball. This module gives it machinery:

* **History** — every bench run appends one env-fingerprinted row to
  ``benchmarks/history.jsonl`` (:func:`append_history`; ``bench.py
  main()`` calls it with the final record). Rows from different
  environments never compare: the fingerprint (device kind, backend,
  device count) is the join key, because a CPU smoke number and a v5e
  headline share nothing but a name.
* **Diff** — :func:`diff` compares a fresh measurement against the
  recorded trajectory per metric, *variance-aware*: both sides are
  best-of-reps estimates (the ``_timed`` protocol bench.py measures
  under — min wall time over trials, the estimator robust to contention
  outliers), so the reference is the best historical value and the
  tolerance widens to the observed historical spread when the history
  shows more run-to-run variance than the base tolerance allows. A
  regression is a move beyond that band in the metric's worse direction
  (units decide direction: ms/s/% are lower-better).
* **Smoke** — :func:`bench_smoke` is the seconds-sized measurement the
  ``scripts/test.sh`` gate runs on every CI pass (tiny AOT-compiled GNN
  step + contract-validated ingest), so the regression gate exercises
  end to end on every change without the ~12-minute full bench.
"""

from __future__ import annotations

import json
import logging
import os
import platform
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence

logger = logging.getLogger(__name__)

HISTORY_PATH = os.path.join("benchmarks", "history.jsonl")

# Units where smaller is better; everything else (graphs/s, examples/s,
# tokens/s, rows/s) is a throughput.
_LOWER_IS_BETTER_UNITS = frozenset({"ms", "s", "%"})

# The fingerprint fields that must match for two rows to be comparable.
_MATCH_KEYS = ("device_kind", "backend", "n_devices")


def env_fingerprint() -> Dict[str, Any]:
    """The environment identity a measurement is only comparable within.

    Deliberately coarse: all CPU hosts share one fingerprint (JAX reports
    the same kind everywhere), so CPU rows from differently-sized boxes
    do compare — the wide base band plus spread-widening is the guard,
    and ``host`` rides the row for forensics without fragmenting the
    trajectory into per-container singletons that would never gate."""
    import jax

    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # pragma: no cover - no backend
        logger.warning("no device for the bench fingerprint",
                       exc_info=True)
        kind = "unknown"
    return {
        "device_kind": kind,
        "backend": jax.default_backend(),
        "n_devices": jax.device_count(),
        "jax": jax.__version__,
        "python": platform.python_version(),
        "host": platform.node(),
    }


def flatten_record(record: Mapping[str, Any]) -> Dict[str, Dict[str, Any]]:
    """One bench final-line dict -> {metric: {"value", "unit"}} covering
    the headline and every ``extra`` entry."""
    out: Dict[str, Dict[str, Any]] = {}

    def add(entry: Mapping[str, Any]) -> None:
        name = entry.get("metric")
        value = entry.get("value")
        if isinstance(name, str) and isinstance(value, (int, float)) \
                and not isinstance(value, bool):
            out[name] = {"value": float(value),
                         "unit": entry.get("unit", "")}

    add(record)
    for entry in record.get("extra", ()) or ():
        if isinstance(entry, Mapping):
            add(entry)
    return out


def parse_bench_file(path: str) -> Dict[str, Dict[str, Any]]:
    """Metrics from a bench artifact: a raw bench stdout capture, a
    driver ``BENCH_r*.json`` (whose ``tail`` holds the stdout), or a
    single JSON record. The LAST parseable record wins — bench.py's
    final complete line supersedes its provisional safety lines."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict) and "tail" in doc:
            text = doc["tail"]
        elif isinstance(doc, dict) and "metric" in doc:
            return flatten_record(doc)
    except ValueError:
        pass
    last: Optional[Dict[str, Any]] = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            last = rec
    if last is None:
        raise ValueError(f"{path}: no bench record found")
    return flatten_record(last)


def read_history(path: str = HISTORY_PATH) -> List[Dict[str, Any]]:
    """History rows, skip-and-counting unparseable lines: append_history
    is a plain append (no atomic rename), so a process killed mid-write
    can leave a torn trailing line — that must cost one datapoint, never
    the CI gate (the same posture as the contracts layer's torn-JSONL
    handling)."""
    rows: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return rows
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if isinstance(row, dict):
                rows.append(row)
            else:
                skipped += 1
    if skipped:
        logger.warning("%s: skipped %d unparseable history row(s) "
                       "(torn append?)", path, skipped)
    return rows


def append_history(metrics: Mapping[str, Mapping[str, Any]],
                   fingerprint: Optional[Mapping[str, Any]] = None,
                   source: str = "bench.py",
                   path: str = HISTORY_PATH) -> Dict[str, Any]:
    """Append one fingerprinted row; returns it."""
    row = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "source": source,
        "fingerprint": dict(fingerprint if fingerprint is not None
                            else env_fingerprint()),
        "metrics": {k: dict(v) for k, v in metrics.items()},
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")
    return row


def _comparable(row: Mapping[str, Any],
                fingerprint: Mapping[str, Any]) -> bool:
    fp = row.get("fingerprint") or {}
    return all(fp.get(k) == fingerprint.get(k) for k in _MATCH_KEYS)


def diff(current: Mapping[str, Mapping[str, Any]],
         history: Sequence[Mapping[str, Any]],
         fingerprint: Optional[Mapping[str, Any]] = None,
         base_tolerance_pct: float = 10.0) -> Dict[str, Any]:
    """Variance-aware comparison of ``current`` against the trajectory.

    Per metric: the reference is the best historical value under the
    metric's direction (both sides are best-of-reps estimates — the
    ``_timed`` protocol); the tolerance is ``base_tolerance_pct`` widened
    to the observed historical spread (max-min over median, when ≥ 3
    samples show the environment is noisier than the base band). Metrics
    with no comparable history are ``new`` — the first run in a fresh
    environment seeds the trajectory instead of failing it.
    """
    if fingerprint is None:
        fingerprint = env_fingerprint()
    rows = [r for r in history if _comparable(r, fingerprint)]
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    stable: List[str] = []
    new: List[str] = []
    for name, cur in sorted(current.items()):
        value = float(cur["value"])
        hist = [float(r["metrics"][name]["value"]) for r in rows
                if name in r.get("metrics", {})]
        if not hist:
            new.append(name)
            continue
        lower_better = cur.get("unit", "") in _LOWER_IS_BETTER_UNITS
        best = min(hist) if lower_better else max(hist)
        tol_pct = base_tolerance_pct
        if len(hist) >= 3:
            med = sorted(hist)[len(hist) // 2]
            if med:
                spread_pct = (max(hist) - min(hist)) / abs(med) * 100.0
                tol_pct = max(tol_pct, min(spread_pct, 50.0))
        band = abs(best) * tol_pct / 100.0
        worse = (value - best) if lower_better else (best - value)
        entry = {
            "metric": name, "value": value, "best": best,
            "unit": cur.get("unit", ""), "n_history": len(hist),
            "tolerance_pct": round(tol_pct, 2),
            "delta_pct": round((value - best) / abs(best) * 100.0, 2)
            if best else None,
        }
        if worse > band:
            regressions.append(entry)
        elif -worse > band:
            improvements.append(entry)
        else:
            stable.append(name)
    return {
        "ok": not regressions,
        "fingerprint": {k: fingerprint.get(k) for k in _MATCH_KEYS},
        "compared_rows": len(rows),
        "regressions": regressions,
        "improvements": improvements,
        "stable": stable,
        "new": new,
    }


# ---------------------------------------------------------------------------
# The smoke-sized measurement (the scripts/test.sh gate)
# ---------------------------------------------------------------------------


def sigterm_to_snapshot_ms(state, reps: int = 3) -> float:
    """Signal delivery → committed durable preempt snapshot (ISSUE 10):
    a real self-SIGTERM through the lifecycle coordinator's flag-only
    handler, the main-path notice poll (the step loop's check), an async
    ``save_preempt``, and the drain barrier to the atomic meta commit.
    Best-of-reps per the ``_timed`` variance protocol; one fresh
    coordinator per rep. Off the main thread the signal half degrades to
    a simulated notice (``signal.signal`` is main-thread-only) — the
    snapshot+drain cost still measures. Shared by bench.py's
    ``sigterm_to_durable_snapshot_ms`` headline and the smoke gate."""
    import os as _os
    import shutil
    import signal as _signal
    import tempfile
    import threading

    from deepdfa_tpu.resilience import lifecycle
    from deepdfa_tpu.train.checkpoint import AsyncCheckpointManager

    tmp = tempfile.mkdtemp(prefix="bench_sigterm_")
    on_main = threading.current_thread() is threading.main_thread()
    best = float("inf")
    try:
        mgr = AsyncCheckpointManager(tmp)
        for i in range(reps):
            co = lifecycle.LifecycleCoordinator(grace_s=120.0)
            lifecycle.reset(co)
            if on_main:
                co.install(signals=(_signal.SIGTERM,))
            t0 = time.perf_counter()
            if on_main:
                _os.kill(_os.getpid(), _signal.SIGTERM)
            else:
                co.notify("simulated")
            while co.poll() is None:  # the step loop's check, spun tight
                pass
            mgr.save_preempt(state, epoch=0, step=i, resume={"seen": i})
            mgr.drain()
            best = min(best, (time.perf_counter() - t0) * 1000.0)
        if mgr.errors:
            raise AssertionError(
                f"async writer failed during sigterm bench: {mgr.errors}")
    finally:
        lifecycle.reset()
        shutil.rmtree(tmp, ignore_errors=True)
    return float(best)


def _best_of(call, calls: int, reps: int) -> float:
    """Best-of-reps wall seconds for ``calls`` dispatches — the bench
    ``_timed`` protocol at smoke scale (min is the estimator robust to
    shared-CI contention outliers)."""
    import jax

    out = None
    for _ in range(2):  # warm both the executable and the dispatch path
        out = call()
    jax.device_get(out)
    dt = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(calls):
            out = call()
        jax.device_get(out)
        dt = min(dt, time.perf_counter() - t0)
    return dt


def bench_smoke(n_steps: int = 40, n_rows: int = 400,
                reps: int = 3) -> Dict[str, Dict[str, Any]]:
    """Seconds-sized measurements for the CI regression gate:

    * ``smoke_gnn_train_graphs_per_sec`` — an AOT-compiled tiny FlowGNN
      train step (segment impl, the portable path) at batch 32;
    * ``smoke_gnn_train_graphs_per_sec_persistent`` — the same
      slot-packed batch through ``message_impl="persistent"`` (ISSUE 15:
      the K-step unroll as one pallas_call per direction); on CPU the
      flag degrades to the band composition, so the gated mechanism is
      the dispatch/degrade path, like the fused row;
    * ``smoke_ingest_rows_per_sec`` — the contract-validated JSONL
      loader over a small synthetic corpus;
    * ``smoke_sigterm_to_durable_snapshot_ms`` — real self-SIGTERM →
      lifecycle notice poll → async ``save_preempt`` → drained durable
      commit, on the tiny trainer state (the preemption drain's
      critical path; bench.py carries the full-state headline).
    * ``smoke_ckpt_redistribute_ms`` — a fabricated 2-process sharded
      snapshot of the tiny state rewritten for one process by the
      consolidate path (ISSUE 18: the elastic-resume critical path —
      reassemble, plain orbax rewrite, checksum re-commit; bench.py
      carries the flagship-state headline and the 4→2 hardlink-fast
      companion).
    * ``smoke_serve_fleet_rps`` — a 2-replica serving fleet's
      saturation throughput over a tiny open-loop trace (the ISSUE-12
      fleet mechanism: routing, per-replica batchers, continuous
      batching; bench.py carries the 4-replica headline).
    * ``smoke_serve_multiproc_rps`` — TWO real engine OS processes
      (tiny-model ``cli serve`` children) behind the real router tier,
      wall-clock items/s through ``POST /score`` (ISSUE 17: the
      spawn/warm handshake, content routing, sub-batch forwarding and
      zero-post-warmup-compiles baseline are all on the measured path;
      bench.py carries the calibrated 1-vs-3 capacity headline).
    * ``smoke_gen_decode_tok_per_sec`` — an AOT-compiled batched-beam
      decode (ISSUE 13: one physical KV cache, ancestry resolved at
      attention-read time, fixed trip count) on a tiny T5 — the
      mechanism gate for the generation lane's hot loop; bench.py
      carries the codet5-base beam-10 headline and its reference-impl
      A/B row.
    * ``smoke_trace_propagation_rps`` — a warmed serve replay with the
      distributed trace plane fully on (trace-id continuation on every
      submit, an active run writing shards, a flush inside the timed
      region — ISSUE 14). The GATED value is the instrumented
      throughput: a regression means the propagation/sharding path got
      expensive. The A/B percent vs ``DEEPDFA_TELEMETRY=0`` rides the
      row (``overhead_pct``, recorded in the history for the <2%
      discipline) but is NOT the gated value — near zero, a relative
      band on it would flap on CI noise; bench.py's
      ``trace_propagation_overhead_pct`` carries the gated headline.

    Deliberately tiny shapes: the gate protects against *mechanism*
    regressions (a host sync creeping into the step loop, a validator
    going quadratic) on every CI pass; the full bench.py run remains the
    headline trajectory.
    """
    import shutil
    import tempfile

    import jax

    from deepdfa_tpu.contracts import (
        Quarantine,
        load_examples_jsonl,
        write_examples_jsonl,
    )
    from deepdfa_tpu.core.config import (
        ALL_SUBKEYS,
        DataConfig,
        FeatureSpec,
        FlowGNNConfig,
        TrainConfig,
        subkeys_for,
    )
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import (
        _batches,
        make_train_state,
        make_train_step,
    )

    feat = FeatureSpec(limit_all=20, limit_subkeys=20)
    model_cfg = FlowGNNConfig(feature=feat, hidden_dim=16, n_steps=2,
                              message_impl="segment")
    data_cfg = DataConfig(batch_size=16, max_nodes_per_graph=64,
                          max_edges_per_node=4)
    examples = synthetic_bigvul(data_cfg.batch_size, feat,
                                positive_fraction=0.5, seed=0)
    import numpy as np

    batch = next(_batches(examples, np.arange(len(examples)), data_cfg,
                          subkeys_for(feat), data_cfg.batch_size))

    def gnn_lane(message_impl: str, lane_batch) -> float:
        """graphs/s of one AOT-compiled tiny train step — the one lane
        protocol (jit + donation + _best_of) every GNN smoke row uses."""
        cfg = FlowGNNConfig(feature=feat, hidden_dim=16, n_steps=2,
                            message_impl=message_impl)
        model = FlowGNN(cfg)
        state, tx = make_train_state(model, lane_batch, TrainConfig())
        step = jax.jit(make_train_step(model, tx, TrainConfig()),
                       donate_argnums=(0,)).lower(state,
                                                  lane_batch).compile()

        def call():
            nonlocal state
            state, loss, _ = step(state, lane_batch)
            return loss

        dt = _best_of(call, n_steps, reps)
        return n_steps * data_cfg.batch_size / dt

    gps = gnn_lane("segment", batch)

    # The fused-step lane (ISSUE 9) and the persistent-unroll lane
    # (ISSUE 15): the same slot-packed band batch through
    # message_impl="fused" / "persistent". On the CPU gate both resolve
    # to the XLA band composition — still the mechanism guard the smoke
    # exists for (slot packing, band build, dispatch/eligibility gating,
    # param-tree identity, and any host sync creeping into the degrade
    # paths), while the TPU trajectory carries the kernels' real numbers.
    from deepdfa_tpu.graphs.batch import batch_graphs, slot_nodes_for
    from deepdfa_tpu.ops.tile_spmm import DEFAULT_TILE, align_to_tile

    slot = slot_nodes_for(examples, tile=DEFAULT_TILE)
    fused_batch = batch_graphs(
        examples, data_cfg.batch_size,
        align_to_tile(data_cfg.batch_size * slot), data_cfg.max_edges,
        subkeys_for(feat), build_band_adj=True, slot_nodes=slot,
    )
    fused_gps = gnn_lane("fused", fused_batch)
    pers_gps = gnn_lane("persistent", fused_batch)

    corpus = synthetic_bigvul(n_rows, FeatureSpec(), positive_fraction=0.5,
                              seed=0)
    tmp = tempfile.mkdtemp(prefix="bench_smoke_")
    try:
        path = os.path.join(tmp, "corpus.jsonl")
        write_examples_jsonl(corpus, path, checksum=False)

        def load():
            exs, _ = load_examples_jsonl(
                path, ALL_SUBKEYS,
                quarantine=Quarantine(os.path.join(tmp, "q")))
            return exs

        load()  # warm imports/allocator
        ingest_dt = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            load()
            ingest_dt = min(ingest_dt, time.perf_counter() - t0)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # The tiny trainer state the preemption-drain smoke snapshots (same
    # shapes as the lane states above; content is irrelevant to timing).
    sig_state, _ = make_train_state(FlowGNN(model_cfg), batch,
                                    TrainConfig())
    sigterm_ms = sigterm_to_snapshot_ms(sig_state, reps=reps)

    # Elastic redistribution mechanism smoke (ISSUE 18): a fabricated
    # 2-process sharded snapshot of the tiny state rewritten 2→1 by the
    # consolidate path (reassemble + plain orbax + checksum re-commit) —
    # the elastic-resume critical path; bench.py carries the
    # flagship-state headline plus the 4→2 hardlink-fast companion.
    # Best-of-reps, a fresh fabricated snapshot per rep (the rewrite
    # consumes its input).
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    redist_dt = float("inf")
    for _ in range(reps):
        rtmp = tempfile.mkdtemp(prefix="bench_redist_smoke_")
        try:
            mgrs = [CheckpointManager(rtmp) for _ in range(2)]
            for i, m in enumerate(mgrs):
                m.set_host(i, 2)
            mgrs[1].save_last(sig_state, epoch=0)
            mgrs[0].save_last(sig_state, epoch=0)
            t0 = time.perf_counter()
            mgrs[0].redistribute("last", 1, target=sig_state)
            redist_dt = min(redist_dt, time.perf_counter() - t0)
        finally:
            shutil.rmtree(rtmp, ignore_errors=True)

    # Serving-fleet mechanism smoke (ISSUE 12): a 2-replica fleet's
    # saturation throughput over a tiny open-loop trace on per-replica
    # virtual timelines — routing, per-replica batchers, continuous
    # batching, adaptive flush all on the measured path. Best-of-reps
    # per the _timed protocol (the trace is seeded; only measured flush
    # compute varies run to run).
    from deepdfa_tpu.models.flowgnn import FlowGNN as _FlowGNN
    from deepdfa_tpu.serve import ServeConfig, ServeFleet
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import (
        ReplicaTimeline,
        VirtualClock,
        open_loop_trace,
        replay_fleet,
    )

    serve_cfg = ServeConfig(batch_slots=4, deadline_ms=200.0,
                            queue_capacity=32, cache_capacity=0,
                            adaptive_flush=True)
    serve_model = _FlowGNN(model_cfg)
    serve_params = random_gnn_params(serve_model, serve_cfg)
    fleet_trace = open_loop_trace(160, feat, seed=0, rps=8000.0,
                                  duplicate_fraction=0.0)
    primer = synthetic_bigvul(sum(serve_cfg.slot_buckets), feat,
                              positive_fraction=0.5, seed=7)
    fleet_rps = 0.0
    for _ in range(reps):
        clock = VirtualClock()
        timelines = [ReplicaTimeline(clock) for _ in range(2)]
        fleet = ServeFleet.build(serve_model, serve_params,
                                 config=serve_cfg, n_replicas=2,
                                 clock_factory=lambda i: timelines[i])
        fleet.warmup()
        fleet.prime(primer)
        rep = replay_fleet(fleet, fleet_trace, clock)
        if rep["compiles_after_warmup"]:
            raise AssertionError("fleet smoke recompiled after warmup")
        fleet_rps = max(fleet_rps, rep["rps"])

    # Shared-nothing process-fleet mechanism smoke (ISSUE 17): two REAL
    # engine children behind the real router tier — spawn/warm
    # handshake, content routing, sub-batch forwarding, and the
    # per-child compile baseline all on the measured path. The value is
    # wall-clock items/s through the router (a mechanism gate — a slow
    # forward or a lost sub-batch fails here, not in a soak); the
    # calibrated 1-vs-N capacity headline lives in bench.py. One pass,
    # not best-of-reps: spawning dominates and re-spawning would buy
    # variance, not signal.
    import threading
    import urllib.request as _urllib_request

    from deepdfa_tpu.core.config import FeatureSpec as _FeatureSpec
    from deepdfa_tpu.serve.procfleet import ProcFleet
    from deepdfa_tpu.serve.router import RouterHTTPServer

    mp_cfg = ServeConfig(batch_slots=4, deadline_ms=200.0,
                         queue_capacity=32, cache_capacity=0)
    mp_fleet = ProcFleet(2, child_args=[
        "--set", "model.hidden_dim=8", "--set", "model.n_steps=2",
        "--batch-slots", "4", "--deadline-ms", "200",
        "--cache-capacity", "0",
        "--replicas", "1", "--processes", "1", "--slo", "none"])
    mp_fleet.start()
    mp_server = RouterHTTPServer(("127.0.0.1", 0), mp_fleet, mp_cfg)
    threading.Thread(target=mp_server.serve_forever, daemon=True).start()
    try:
        # Default feature spec: the children run the default config.
        mp_graphs = synthetic_bigvul(48, _FeatureSpec(),
                                     positive_fraction=0.5, seed=11)
        mp_payload = [
            {"id": int(g["id"]),
             "graph": {"num_nodes": int(g["num_nodes"]),
                       "senders": np.asarray(g["senders"]).tolist(),
                       "receivers": np.asarray(g["receivers"]).tolist(),
                       "feats": {k: np.asarray(v).tolist()
                                 for k, v in g["feats"].items()}}}
            for g in mp_graphs
        ]
        mp_base = f"http://127.0.0.1:{mp_server.server_address[1]}"

        def mp_post(chunk) -> None:
            req = _urllib_request.Request(
                f"{mp_base}/score",
                data=json.dumps({"functions": chunk}).encode(),
                headers={"Content-Type": "application/json"})
            with _urllib_request.urlopen(req, timeout=60.0) as resp:
                body = json.loads(resp.read())
            if not all("prob" in r for r in body["results"]):
                raise AssertionError(f"multiproc smoke scoring failed: "
                                     f"{body['results'][:2]}")

        mp_post(mp_payload[:8])  # warm the HTTP/forward path
        t0 = time.perf_counter()
        for start in range(0, len(mp_payload), 8):
            mp_post(mp_payload[start:start + 8])
        mp_dt = time.perf_counter() - t0
        if mp_fleet.compiles_after_warmup():
            raise AssertionError("multiproc smoke recompiled after warmup")
        multiproc_rps = len(mp_payload) / mp_dt
    finally:
        mp_server.shutdown()
        mp_fleet.shutdown()

    # Batched-beam decode mechanism smoke (ISSUE 13): tiny T5, beam 4,
    # early exit OFF so tokens/s counts exactly batch * max_len steps
    # (the comparable-trajectory rule bench_gen_decode documents).
    import dataclasses as _dc

    from deepdfa_tpu.models.t5 import T5Config, T5Model
    from deepdfa_tpu.models.t5_generate import beam_search

    gen_cfg = _dc.replace(T5Config.tiny(vocab_size=256), dropout_rate=0.0)
    gen_model = T5Model(gen_cfg)
    g_rng = np.random.RandomState(0)
    gen_b, gen_src, gen_len, gen_beam = 4, 32, 16, 4
    gen_src_ids = jax.numpy.asarray(
        g_rng.randint(3, gen_cfg.vocab_size,
                      size=(gen_b, gen_src)).astype(np.int32))
    gen_params = gen_model.init(
        jax.random.PRNGKey(0), gen_src_ids,
        jax.numpy.zeros((gen_b, 4), jax.numpy.int32))
    gen_step = jax.jit(
        lambda p, s: beam_search(gen_model, p, s, gen_len, gen_beam,
                                 early_exit=False)[0]
    ).lower(gen_params, gen_src_ids).compile()

    gen_dt = _best_of(lambda: gen_step(gen_params, gen_src_ids),
                      n_steps // 4, reps)
    gen_tps = (n_steps // 4) * gen_b * gen_len / gen_dt

    # Trace-plane mechanism smoke (ISSUE 14): a warmed engine replay with
    # trace-id continuation + shard writing on the measured path, A/B'd
    # against DEEPDFA_TELEMETRY=0 — tiny shape, best-of-reps.
    from deepdfa_tpu import telemetry
    from deepdfa_tpu.serve import ServeEngine
    from deepdfa_tpu.serve.replay import VirtualClock
    from deepdfa_tpu.telemetry import context as trace_context

    trace_cfg = ServeConfig(batch_slots=4, cache_capacity=0)
    trace_engine = ServeEngine(serve_model,
                               random_gnn_params(serve_model, trace_cfg),
                               config=trace_cfg, clock=VirtualClock())
    trace_graphs = synthetic_bigvul(64, feat, positive_fraction=0.5,
                                    seed=3)
    trace_ids = [trace_context.new_trace_id() for _ in trace_graphs]

    def trace_replay(with_trace: bool) -> float:
        t0 = time.perf_counter()
        for gi, g in enumerate(trace_graphs):
            trace_engine.submit(
                g, trace_id=trace_ids[gi] if with_trace else None,
                trace_continued=with_trace)
        trace_engine.drain()
        telemetry.flush()
        return time.perf_counter() - t0

    trace_tmp = tempfile.mkdtemp(prefix="bench_trace_smoke_")
    t_on, t_off = [], []
    try:
        with telemetry.run_scope(trace_tmp):
            trace_engine.warmup()
            trace_replay(True)  # warm both paths + the event machinery
            for _ in range(reps):
                t_on.append(trace_replay(True))
                telemetry.set_enabled(False)
                try:
                    t_off.append(trace_replay(False))
                finally:
                    telemetry.set_enabled(None)
    finally:
        shutil.rmtree(trace_tmp, ignore_errors=True)
    trace_on, trace_off = min(t_on), min(t_off)
    trace_overhead_pct = (trace_on - trace_off) / trace_off * 100.0

    # Traffic-observatory mechanism smoke (ISSUE 20): the same warmed
    # replay A/B'd on the shape-capture kill switch alone — telemetry
    # stays ON both sides, so the delta is the sketch/waste-accounting
    # cost itself, tiny shape, best-of-reps.
    from deepdfa_tpu.telemetry import sketch as traffic_sketch

    cap_tmp = tempfile.mkdtemp(prefix="bench_traffic_smoke_")
    c_on, c_off = [], []
    try:
        with telemetry.run_scope(cap_tmp):
            trace_replay(False)  # warm the capture path in this run
            for _ in range(reps):
                c_on.append(trace_replay(False))
                traffic_sketch.set_capture(False)
                try:
                    c_off.append(trace_replay(False))
                finally:
                    traffic_sketch.set_capture(True)
    finally:
        shutil.rmtree(cap_tmp, ignore_errors=True)
    cap_on, cap_off = min(c_on), min(c_off)
    cap_overhead_pct = (cap_on - cap_off) / cap_off * 100.0

    # graftlint full-repo cold pass (stdlib AST work, no jax): the
    # analyzer's own cost rides the same gate as kernel perf. One rep —
    # deterministic CPU work, and the smoke budget matters.
    from deepdfa_tpu.analysis.runner import run_analysis

    t0 = time.perf_counter()
    lint_report = run_analysis()
    lint_ms = (time.perf_counter() - t0) * 1e3
    assert lint_report["files"] > 50

    return {
        "smoke_gnn_train_graphs_per_sec": {
            "value": round(gps, 1), "unit": "graphs/s"},
        "smoke_gnn_train_graphs_per_sec_fused": {
            "value": round(fused_gps, 1), "unit": "graphs/s"},
        "smoke_gnn_train_graphs_per_sec_persistent": {
            "value": round(pers_gps, 1), "unit": "graphs/s"},
        "smoke_ingest_rows_per_sec": {
            "value": round(n_rows / ingest_dt, 1), "unit": "rows/s"},
        "smoke_sigterm_to_durable_snapshot_ms": {
            "value": round(sigterm_ms, 2), "unit": "ms"},
        "smoke_ckpt_redistribute_ms": {
            "value": round(redist_dt * 1000.0, 2), "unit": "ms"},
        "smoke_serve_fleet_rps": {
            "value": round(fleet_rps, 1), "unit": "req/s"},
        "smoke_serve_multiproc_rps": {
            "value": round(multiproc_rps, 1), "unit": "req/s",
            "processes": 2},
        "smoke_gen_decode_tok_per_sec": {
            "value": round(gen_tps, 1), "unit": "tok/s"},
        "smoke_graftlint_full_repo_ms": {
            "value": round(lint_ms, 1), "unit": "ms"},
        "smoke_trace_propagation_rps": {
            "value": round(len(trace_graphs) / trace_on, 1),
            "unit": "req/s",
            # Companion facts ride the history row un-gated: the A/B
            # percent hovers at the noise floor, where a relative band
            # would flap (docstring) — the throughput above is the gate.
            "overhead_pct": round(trace_overhead_pct, 2),
            "disabled_rps": round(len(trace_graphs) / trace_off, 1),
        },
        "smoke_traffic_capture_rps": {
            "value": round(len(trace_graphs) / cap_on, 1),
            "unit": "req/s",
            # Same un-gated-companion rule as trace propagation: the A/B
            # percent sits at the noise floor on a smoke-sized replay —
            # throughput is the gated number, the percent is the fact.
            "overhead_pct": round(cap_overhead_pct, 2),
            "uncaptured_rps": round(len(trace_graphs) / cap_off, 1),
        },
    }

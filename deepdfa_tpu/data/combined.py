"""Data assembly for the combined DeepDFA+transformer models.

The reference trains the combined model from two inputs joined by example
id: a text dataset (MSR-style CSVs with ``processed_func``/``func`` code and
``target`` labels, the row index being the example id —
LineVul/linevul/linevul_main.py:55-91) and the DDFA graph cache
(BigVulDatasetLineVDDataModule over the dbize CSVs, linevul_main.py:421-475 /
CodeT5/run_defect.py:160-205). This module loads either side from any of the
framework's sources and hands ``fit_text`` its ``(data, splits,
graphs_by_id)`` triple.

Graph sources (``load_graph_source``):
  - ``synthetic[:N]``          generated sample graphs (ids 0..N-1)
  - ``<dir with nodes.csv>``   the reference pipeline's dbize cache
                               (etl/legacy_cache.py)
  - ``<file.jsonl>``           this framework's etl export format

Text sources (``load_combined_dataset``):
  - ``synthetic[:N]``          C-like functions rendered from the graphs
                               (data/text.py synthetic_function_text)
  - ``<dir with train.csv>``   train/val/test CSVs in the MSR layout;
                               the CSV partition is the fixed split
"""

from __future__ import annotations

import os
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from deepdfa_tpu.core.config import ALL_SUBKEYS, FeatureSpec, subkeys_for


def read_examples_jsonl(path: str,
                        feature: Optional[FeatureSpec] = None) -> List[Dict]:
    """Graph examples in the etl export format (one JSON object per line
    with num_nodes/senders/receivers/vuln/feats[/label/id]), read through
    the shared ingestion contract: schema-violating rows are quarantined
    into the corpus's ``quarantine/`` sibling and skipped, never joined
    into a combined batch (deepdfa_tpu/contracts). The required subkeys
    come from ``feature`` — a single-subkey export (concat_all=False) must
    not be quarantined for lacking the other three."""
    from deepdfa_tpu.contracts import load_examples_jsonl

    subkeys = subkeys_for(feature) if feature is not None else ALL_SUBKEYS
    examples, _ = load_examples_jsonl(path, subkeys)
    return examples


def load_graph_source(
    spec: str, feature: FeatureSpec, seed: int = 0
) -> List[Dict]:
    """Graph examples from a spec string (see module docstring)."""
    if spec.startswith("synthetic"):
        from deepdfa_tpu.data.synthetic import synthetic_bigvul

        n = int(spec.split(":")[1]) if ":" in spec else 256
        examples = synthetic_bigvul(n, feature, positive_fraction=0.5,
                                    seed=seed)
        for i, ex in enumerate(examples):
            ex["label"] = int(np.asarray(ex["vuln"]).max())
            ex["id"] = i
        return examples
    if spec.endswith(".jsonl") and os.path.exists(spec):
        return read_examples_jsonl(spec, feature)
    if os.path.isdir(spec) and (
        os.path.exists(os.path.join(spec, "nodes.csv"))
        or os.path.exists(os.path.join(spec, "nodes_sample.csv"))
    ):
        from deepdfa_tpu.etl.legacy_cache import load_reference_cache

        sample = not os.path.exists(os.path.join(spec, "nodes.csv"))
        return load_reference_cache(spec, feature, sample=sample)
    raise ValueError(
        f"unknown graph source {spec!r} (want synthetic[:N], an etl export "
        ".jsonl, or a dbize cache directory holding nodes.csv)"
    )


def _read_text_csvs(data_dir: str) -> Tuple[List[Dict], Dict[str, List[int]]]:
    """MSR-layout train/val/test CSVs -> (rows, positions-per-split).

    Column handling mirrors the reference loader (linevul_main.py:64-91):
    code from ``processed_func`` falling back to ``func``, labels from
    ``target``, example ids from the frame index.
    """
    import pandas as pd

    rows: List[Dict] = []
    split_pos: Dict[str, List[int]] = {}
    for split, name in (("train", "train.csv"), ("val", "val.csv"),
                        ("test", "test.csv")):
        path = os.path.join(data_dir, name)
        if not os.path.exists(path):
            if split == "test":  # test.csv optional: fit-only directories
                split_pos[split] = []
                continue
            raise FileNotFoundError(f"{path} (MSR layout needs {name})")
        df = pd.read_csv(path, index_col=0)
        func_key = "processed_func" if "processed_func" in df.columns else "func"
        pos = []
        for code, label, idx in zip(df[func_key].tolist(),
                                    df["target"].astype(int).tolist(),
                                    df.index.astype(int).tolist()):
            pos.append(len(rows))
            rows.append({"code": code, "label": label, "id": idx})
        split_pos[split] = pos
    return rows, split_pos


def graph_join_and_budget(
    gexamples: List[Dict], batch_size: int,
    max_nodes: Optional[int] = None, max_edges: Optional[int] = None,
) -> Tuple[Dict[int, Dict], Dict[str, int]]:
    """(graphs_by_id, per-batch node/edge budget) for the combined join.

    The budget doubles the order-preserving ``pad_budget_for`` bound:
    shuffling regroups batches each epoch, so the exact bound can be
    exceeded — headroom beats dropping graphs mid-training. Explicit
    ``max_nodes``/``max_edges`` override the sizing.
    """
    from deepdfa_tpu.graphs.batch import pad_budget_for

    graphs_by_id = {int(g["id"]): g for g in gexamples}
    if max_nodes and max_edges:
        return graphs_by_id, {"max_nodes": max_nodes, "max_edges": max_edges}
    ordered = [graphs_by_id[k] for k in sorted(graphs_by_id)]
    b = pad_budget_for(ordered, batch_size)
    return graphs_by_id, {
        "max_nodes": max_nodes or 2 * b["max_nodes"],
        "max_edges": max_edges or 2 * b["max_edges"],
    }


def load_combined_dataset(
    dataset: str,
    feature: FeatureSpec,
    tokenizer,
    block_size: int,
    style: str = "roberta",
    graphs: Optional[str] = None,
    seed: int = 0,
    split_mode: str = "random",
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray],
           Optional[Mapping[int, Mapping]]]:
    """(data, splits, graphs_by_id) for ``fit_text``.

    ``dataset``: ``synthetic[:N]`` (text rendered from generated graphs) or
    a directory of MSR CSVs. ``graphs``: graph source spec; defaults to the
    same synthetic graphs for synthetic text, None (text-only) otherwise.
    """
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.data.text import attach_synthetic_text, encode_dataset

    graphs_by_id = None
    if dataset.startswith("synthetic"):
        if graphs is not None and not graphs.startswith("synthetic"):
            # Synthetic text is rendered FROM the generated graphs; a
            # foreign graph cache would join on unrelated ids.
            raise ValueError(
                "synthetic text pairs only with its own synthetic graphs "
                "(pass --graphs synthetic or drop it)"
            )
        gexamples = load_graph_source(dataset, feature, seed=seed)
        if graphs is not None:
            graphs_by_id = {int(g["id"]): g for g in gexamples}
        rows = attach_synthetic_text(
            [dict(g) for g in gexamples], seed=seed
        )
        splits_ids = make_splits(rows, mode=split_mode, seed=seed)
        data = encode_dataset(rows, tokenizer, block_size=block_size,
                              style=style)
        return data, splits_ids, graphs_by_id
    if os.path.isdir(dataset):
        if graphs is not None and graphs.startswith("synthetic"):
            # Positional synthetic ids (0..N-1) vs the CSVs' arbitrary idx
            # ids: rows would join to unrelated graphs or mask out.
            raise ValueError(
                "a CSV dataset needs its own graph cache (dbize dir or etl "
                ".jsonl); synthetic graphs join by positional id only"
            )
        rows, split_pos = _read_text_csvs(dataset)
        data = encode_dataset(rows, tokenizer, block_size=block_size,
                              style=style)
        splits = {k: np.asarray(v, np.int64) for k, v in split_pos.items()}
        if graphs is not None:
            gexamples = load_graph_source(graphs, feature, seed=seed)
            graphs_by_id = {int(g["id"]): g for g in gexamples}
        return data, splits, graphs_by_id
    raise ValueError(
        f"unknown dataset {dataset!r} (want synthetic[:N] or a directory "
        "holding train.csv/val.csv[/test.csv])"
    )

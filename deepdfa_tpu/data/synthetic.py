"""Deterministic synthetic Big-Vul-like graphs.

The reference's integration-test path is a 100+100 sample of the real dataset
(DDFA/sastvd/scripts/sample_MSR_data.py:5-16, ``--sample`` flags threaded
through every layer). The real Big-Vul archives are not redistributable here,
so the generalized sample mode is a *generator*: CFG-shaped random graphs
whose vulnerability label is a planted, learnable function of the
abstract-dataflow features — end-to-end training must drive F1 up on it,
which is the same role sample mode plays in the reference.

Shape statistics mimic post-filter Big-Vul CFGs: ~10-60 nodes, mostly-linear
control flow with branches/back-edges, ~6%-positive default imbalance
(paper §5.2) unless ``balanced``.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from deepdfa_tpu.core.config import ALL_SUBKEYS, FeatureSpec


def synthetic_bigvul(
    num_examples: int = 200,
    feature: FeatureSpec = FeatureSpec(),
    positive_fraction: float = 0.5,
    seed: int = 0,
    min_nodes: int = 8,
    max_nodes: int = 48,
) -> List[Dict]:
    """Generate a list of graph dicts compatible with ``batch_graphs``.

    The planted signal: vulnerable functions contain a small motif — a chain
    of definition nodes carrying a specific "tainted" feature index on the
    ``api`` subkey feeding a node with a "sink" index — so a dataflow-aware
    GNN can separate the classes but a bag-of-nodes cannot do so perfectly
    (the motif indices also appear, unchained, in negatives).
    """
    rng = np.random.default_rng(seed)
    vocab = feature.input_dim
    taint = 2  # feature index used as the tainted source marker
    sink = 3  # feature index used as the sink marker

    out: List[Dict] = []
    for i in range(num_examples):
        vul = int(rng.random() < positive_fraction)
        n = int(rng.integers(min_nodes, max_nodes + 1))
        # Mostly-linear CFG: i -> i+1, plus a few branch/back edges.
        senders = list(range(n - 1))
        receivers = list(range(1, n))
        for _ in range(max(1, n // 8)):
            a, b = rng.integers(0, n, size=2)
            if a != b:
                senders.append(int(a))
                receivers.append(int(b))
        feats = {
            k: rng.integers(4, vocab, size=n).astype(np.int64) for k in ALL_SUBKEYS
        }
        # ~40% of nodes are non-definitions (index 0 on EVERY subkey — the
        # zero set is a per-node property shared across subkeys, asserted
        # at export, etl/export.py); a few definitions are UNKNOWN (1),
        # per-subkey like real out-of-vocab hashes.
        nondef = rng.random(n) < 0.4
        for k in ALL_SUBKEYS:
            feats[k][nondef] = 0
            feats[k][(rng.random(n) < 0.05) & ~nondef] = 1

        node_vuln = np.zeros(n, np.int32)
        if vul:
            # Plant a connected taint->...->sink chain of length 3.
            chain = rng.choice(n - 3, size=1)[0]
            chain_nodes = [chain, chain + 1, chain + 2]
            feats["api"][chain_nodes[0]] = taint
            feats["api"][chain_nodes[1]] = taint
            feats["api"][chain_nodes[2]] = sink
            node_vuln[chain_nodes] = 1
        else:
            # Distractors: same markers but never chained along an edge.
            if n >= 6 and rng.random() < 0.7:
                feats["api"][0] = taint
                feats["api"][n - 1] = sink

        # Planting can promote a zeroed node to a definition on "api" alone;
        # restore the shared-zero-set invariant: a node nonzero on ANY
        # subkey is a definition, so its other subkeys read UNKNOWN (1).
        is_def = np.zeros(n, bool)
        for k in ALL_SUBKEYS:
            is_def |= feats[k] != 0
        for k in ALL_SUBKEYS:
            feats[k][is_def & (feats[k] == 0)] = 1

        s_arr = np.asarray(senders, np.int32)
        r_arr = np.asarray(receivers, np.int32)

        # Dataflow-solution bits: the genuine reachability fixpoint over the
        # generated CFG (df_in[v] = some definition reaches v's entry,
        # df_out[v] = df_in[v] or v defines) — kill-free reaching
        # definitions, so the dataflow_solution_in/out label styles train
        # against a real flow property of the graph, not noise.
        df_in = np.zeros(n, bool)
        df_out = is_def.copy()
        for _ in range(n):
            new_in = df_in.copy()
            np.logical_or.at(new_in, r_arr, df_out[s_arr])
            new_out = is_def | new_in
            if np.array_equal(new_in, df_in) and np.array_equal(new_out, df_out):
                break
            df_in, df_out = new_in, new_out

        out.append(
            {
                "id": i,
                "num_nodes": n,
                "senders": s_arr,
                "receivers": r_arr,
                "vuln": node_vuln,
                "feats": feats,
                "label": vul,
                "df_in": df_in.astype(np.int32),
                "df_out": df_out.astype(np.int32),
                # project id for cross-project split protocols
                "project": int(rng.integers(0, 10)),
            }
        )
    return out

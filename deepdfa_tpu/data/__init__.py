from deepdfa_tpu.data.synthetic import synthetic_bigvul
from deepdfa_tpu.data.splits import make_splits
from deepdfa_tpu.data.sampling import epoch_indices

__all__ = ["synthetic_bigvul", "make_splits", "epoch_indices"]

"""Text dataset pipeline for the transformer model families.

Reference semantics (LineVul/linevul/linevul_main.py:55-131
``TextDataset``/``convert_examples_to_features``): tokenize the processed
function, truncate to block_size-2, wrap with [CLS]/[SEP], pad with the pad
id to block_size; attention mask is ``ids != pad``.

Tokenizers: any object with ``tokenize(str) -> list[str]`` and
``convert_tokens_to_ids(list[str]) -> list[int]`` plus cls/sep/pad ids works
(a HF BPE tokenizer loaded from local files, e.g. the codebert vocab). For
sample-mode/testing — this image has no pretrained vocabularies — a
deterministic :class:`HashingCodeTokenizer` splits code into
identifier/number/operator tokens and hashes them into a fixed vocab.
"""

from __future__ import annotations

import hashlib
import re
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

# RoBERTa special-token convention (codebert/unixcoder share it).
CLS_ID = 0
PAD_ID = 1
SEP_ID = 2
UNK_ID = 3
_N_SPECIAL = 4

_TOKEN_RE = re.compile(r"[A-Za-z_]\w*|0x[0-9a-fA-F]+|\d+\.?\d*|->|<<|>>|[^\sA-Za-z0-9_]")


class HashingCodeTokenizer:
    """Deterministic, vocabulary-free code tokenizer for sample mode."""

    cls_token_id = CLS_ID
    sep_token_id = SEP_ID
    pad_token_id = PAD_ID
    _n_special = _N_SPECIAL  # ids below this are reserved for special tokens

    def __init__(self, vocab_size: int = 50265):
        self.vocab_size = vocab_size

    def tokenize(self, text: str) -> List[str]:
        return _TOKEN_RE.findall(text)

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        out = []
        for t in tokens:
            h = int.from_bytes(hashlib.blake2s(t.encode(), digest_size=4).digest(), "little")
            out.append(self._n_special + h % (self.vocab_size - self._n_special))
        return out


def encode_function(code: str, tokenizer, block_size: int = 512) -> np.ndarray:
    """[CLS] + tokens[:block_size-2] + [SEP], pad to block_size
    (linevul_main.py:126-131)."""
    tokens = tokenizer.tokenize(str(code))[: block_size - 2]
    ids = (
        [tokenizer.cls_token_id]
        + tokenizer.convert_tokens_to_ids(tokens)
        + [tokenizer.sep_token_id]
    )
    ids = ids + [tokenizer.pad_token_id] * (block_size - len(ids))
    return np.asarray(ids, np.int32)


def encode_function_t5(code: str, tokenizer, block_size: int = 512) -> np.ndarray:
    """CodeT5 convention (CodeT5/_utils.py:33 ``tokenizer.encode(...,
    truncation=True)`` with the codet5 BPE tokenizer): <s> + tokens[:block-2]
    + </s>, pad with 0 — exactly one eos per row, which the eos-pooled
    classifier requires (CodeT5/_utils.py:34 asserts
    ``source_ids.count(eos) == 1``)."""
    tokens = tokenizer.tokenize(str(code))[: block_size - 2]
    ids = (
        [tokenizer.bos_token_id]
        + tokenizer.convert_tokens_to_ids(tokens)
        + [tokenizer.eos_token_id]
    )
    ids = ids + [tokenizer.pad_token_id] * (block_size - len(ids))
    return np.asarray(ids, np.int32)


class HashingT5Tokenizer(HashingCodeTokenizer):
    """Hashing tokenizer with the codet5 special-token ids
    (<pad>=0, <s>=1, </s>=2)."""

    pad_token_id = 0
    bos_token_id = 1
    eos_token_id = 2
    _n_special = 3


class BPETokenizerAdapter:
    """A trained ``tokenizers`` tokenizer behind the hashing tokenizers'
    protocol (tokenize / convert_tokens_to_ids + special-token ids), so real
    BPE assets (etl/tokenizer_train.py output, or HF tokenizer.json) slot
    into encode_function / encode_function_t5 / seq2seq.encode_examples
    unchanged."""

    def __init__(self, tok):
        self._tok = tok
        self.vocab_size = int(tok.get_vocab_size())

        def tid(*names, default):
            for n in names:
                i = tok.token_to_id(n)
                if i is not None:
                    return int(i)
            return default

        # codet5/roberta special-token conventions (SPECIAL_TOKENS in
        # etl/tokenizer_train.py; HF codebert/codet5 assets use the same).
        self.pad_token_id = tid("<pad>", "[PAD]", default=0)
        self.bos_token_id = self.cls_token_id = tid("<s>", "[CLS]", default=1)
        self.eos_token_id = self.sep_token_id = tid("</s>", "[SEP]", default=2)
        self.unk_token_id = tid("<unk>", "[UNK]", default=None)

    def tokenize(self, text: str) -> List[str]:
        # No template specials: the encoders add <s>/</s> themselves
        # (encode_function*, seq2seq.encode_examples expect raw tokens) —
        # HF tokenizer.json assets ship post-processors that would
        # otherwise duplicate them.
        return self._tok.encode(str(text), add_special_tokens=False).tokens

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        out = []
        for t in tokens:
            i = self._tok.token_to_id(t)
            if i is None:
                # Tokens from any source other than this tokenizer's own
                # tokenize() (or assets missing an unk entry) must not die
                # with a bare int(None) TypeError.
                if self.unk_token_id is None:
                    raise ValueError(
                        f"token {t!r} is not in the vocabulary and the "
                        "assets define no unk token"
                    )
                i = self.unk_token_id
            out.append(int(i))
        return out

    def decode(self, ids: Sequence[int]) -> str:
        """Ids -> text (the reference evals decode predictions for
        BLEU/CodeBLEU, run_gen.py:115)."""
        return self._tok.decode(list(int(i) for i in ids),
                                skip_special_tokens=True)


def check_tok_vocab(tok, vocab: int, pad_id=None, eos_id=None) -> None:
    """Tokenizer/model compatibility: ids must fit the embedding table AND
    the special-token conventions must agree — rows are padded with the
    tokenizer's pad id but masked with the model config's, and the T5
    classifier pools at the config's eos id, so a convention mismatch
    (e.g. roberta assets with a codet5 model) trains silently wrong."""
    if tok is None:
        return
    if tok.vocab_size > vocab:
        raise ValueError(
            f"tokenizer vocab {tok.vocab_size} exceeds the model's "
            f"embedding table ({vocab}) — ids would index out of bounds"
        )
    if pad_id is not None and tok.pad_token_id != pad_id:
        raise ValueError(
            f"tokenizer pad id {tok.pad_token_id} != model pad id {pad_id}"
        )
    if eos_id is not None and tok.eos_token_id != eos_id:
        raise ValueError(
            f"tokenizer eos id {tok.eos_token_id} != model eos id {eos_id}"
        )


def load_bpe_tokenizer(path: str) -> BPETokenizerAdapter:
    """Load trained tokenizer assets: a ``tokenizer.json`` file, a directory
    containing one, or a directory with the ``<prefix>-vocab.json`` +
    ``<prefix>-merges.txt`` pair that etl/tokenizer_train.train_bpe writes
    (the salesforce/codet5 asset layout)."""
    import os

    from tokenizers import ByteLevelBPETokenizer, Tokenizer

    if os.path.isfile(path):
        return BPETokenizerAdapter(Tokenizer.from_file(path))
    tj = os.path.join(path, "tokenizer.json")
    if os.path.exists(tj):
        return BPETokenizerAdapter(Tokenizer.from_file(tj))
    import glob

    # Pair vocab/merges by shared prefix — a directory holding assets for
    # two tokenizers must not silently mix one's vocab with the other's
    # merges (ByteLevelBPETokenizer would load the mismatch without error).
    def prefix(p, suffix):
        return os.path.basename(p)[: -len(suffix)]

    vocabs = {prefix(p, "vocab.json"): p
              for p in glob.glob(os.path.join(path, "*vocab.json"))}
    merges = {prefix(p, "merges.txt"): p
              for p in glob.glob(os.path.join(path, "*merges.txt"))}
    pairs = sorted(set(vocabs) & set(merges))
    if len(pairs) > 1:
        raise ValueError(
            f"ambiguous tokenizer assets under {path!r}: prefixes {pairs}"
        )
    if pairs:
        return BPETokenizerAdapter(
            ByteLevelBPETokenizer(vocabs[pairs[0]], merges[pairs[0]])
        )
    raise FileNotFoundError(
        f"no tokenizer assets under {path!r} (want tokenizer.json or a "
        "matching *vocab.json + *merges.txt pair)"
    )


def encode_dataset(
    examples: Sequence[Mapping],
    tokenizer,
    block_size: int = 512,
    code_key: str = "code",
    style: str = "roberta",
) -> Dict[str, np.ndarray]:
    """Batch-encode to {input_ids [N, block], labels [N], index [N]}."""
    if style not in ("roberta", "t5"):
        raise ValueError(f"unknown encoding style: {style!r} (want 'roberta' or 't5')")
    enc = encode_function if style == "roberta" else encode_function_t5
    ids = np.stack([enc(ex[code_key], tokenizer, block_size) for ex in examples])
    labels = np.asarray([int(ex["label"]) for ex in examples], np.int32)
    index = np.asarray([int(ex["id"]) for ex in examples], np.int64)
    return {"input_ids": ids, "labels": labels, "index": index}


_VULN_CALLS = ["strcpy", "memcpy", "sprintf", "gets", "system"]
_SAFE_CALLS = ["strncpy", "snprintf", "fgets", "calloc", "strnlen"]


def synthetic_function_text(ex: Mapping, rng: Optional[np.random.Generator] = None) -> str:
    """Render a C-like function whose text correlates with the planted graph
    label, giving the text models a learnable sample-mode signal (the
    analogue of the reference's 100+100 real-data sample)."""
    rng = rng or np.random.default_rng(int(ex["id"]))
    n = int(ex["num_nodes"])
    calls = _VULN_CALLS if ex["label"] else _SAFE_CALLS
    body = []
    for i in range(min(n, 12)):
        v = f"v{i}"
        kind = rng.integers(0, 3)
        if kind == 0:
            body.append(f"  int {v} = {int(rng.integers(0, 100))};")
        elif kind == 1:
            body.append(f"  {v} = {calls[int(rng.integers(0, len(calls)))]}(buf, src);")
        else:
            body.append(f"  if ({v} > {int(rng.integers(1, 64))}) return {v};")
    name = f"func_{int(ex['id'])}"
    return "int " + name + "(char *buf, char *src) {\n" + "\n".join(body) + "\n  return 0;\n}"


def attach_synthetic_text(examples: List[Dict], seed: int = 0) -> List[Dict]:
    for ex in examples:
        ex["code"] = synthetic_function_text(ex, np.random.default_rng((seed, int(ex["id"]))))
    return examples

"""Text dataset pipeline for the transformer model families.

Reference semantics (LineVul/linevul/linevul_main.py:55-131
``TextDataset``/``convert_examples_to_features``): tokenize the processed
function, truncate to block_size-2, wrap with [CLS]/[SEP], pad with the pad
id to block_size; attention mask is ``ids != pad``.

Tokenizers: any object with ``tokenize(str) -> list[str]`` and
``convert_tokens_to_ids(list[str]) -> list[int]`` plus cls/sep/pad ids works
(a HF BPE tokenizer loaded from local files, e.g. the codebert vocab). For
sample-mode/testing — this image has no pretrained vocabularies — a
deterministic :class:`HashingCodeTokenizer` splits code into
identifier/number/operator tokens and hashes them into a fixed vocab.
"""

from __future__ import annotations

import hashlib
import re
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

# RoBERTa special-token convention (codebert/unixcoder share it).
CLS_ID = 0
PAD_ID = 1
SEP_ID = 2
UNK_ID = 3
_N_SPECIAL = 4

_TOKEN_RE = re.compile(r"[A-Za-z_]\w*|0x[0-9a-fA-F]+|\d+\.?\d*|->|<<|>>|[^\sA-Za-z0-9_]")


class HashingCodeTokenizer:
    """Deterministic, vocabulary-free code tokenizer for sample mode."""

    cls_token_id = CLS_ID
    sep_token_id = SEP_ID
    pad_token_id = PAD_ID

    def __init__(self, vocab_size: int = 50265):
        self.vocab_size = vocab_size

    def tokenize(self, text: str) -> List[str]:
        return _TOKEN_RE.findall(text)

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        out = []
        for t in tokens:
            h = int.from_bytes(hashlib.blake2s(t.encode(), digest_size=4).digest(), "little")
            out.append(_N_SPECIAL + h % (self.vocab_size - _N_SPECIAL))
        return out


def encode_function(code: str, tokenizer, block_size: int = 512) -> np.ndarray:
    """[CLS] + tokens[:block_size-2] + [SEP], pad to block_size
    (linevul_main.py:126-131)."""
    tokens = tokenizer.tokenize(str(code))[: block_size - 2]
    ids = (
        [tokenizer.cls_token_id]
        + tokenizer.convert_tokens_to_ids(tokens)
        + [tokenizer.sep_token_id]
    )
    ids = ids + [tokenizer.pad_token_id] * (block_size - len(ids))
    return np.asarray(ids, np.int32)


def encode_dataset(
    examples: Sequence[Mapping], tokenizer, block_size: int = 512, code_key: str = "code"
) -> Dict[str, np.ndarray]:
    """Batch-encode to {input_ids [N, block], labels [N], index [N]}."""
    ids = np.stack([encode_function(ex[code_key], tokenizer, block_size) for ex in examples])
    labels = np.asarray([int(ex["label"]) for ex in examples], np.int32)
    index = np.asarray([int(ex["id"]) for ex in examples], np.int64)
    return {"input_ids": ids, "labels": labels, "index": index}


_VULN_CALLS = ["strcpy", "memcpy", "sprintf", "gets", "system"]
_SAFE_CALLS = ["strncpy", "snprintf", "fgets", "calloc", "strnlen"]


def synthetic_function_text(ex: Mapping, rng: Optional[np.random.Generator] = None) -> str:
    """Render a C-like function whose text correlates with the planted graph
    label, giving the text models a learnable sample-mode signal (the
    analogue of the reference's 100+100 real-data sample)."""
    rng = rng or np.random.default_rng(int(ex["id"]))
    n = int(ex["num_nodes"])
    calls = _VULN_CALLS if ex["label"] else _SAFE_CALLS
    body = []
    for i in range(min(n, 12)):
        v = f"v{i}"
        kind = rng.integers(0, 3)
        if kind == 0:
            body.append(f"  int {v} = {int(rng.integers(0, 100))};")
        elif kind == 1:
            body.append(f"  {v} = {calls[int(rng.integers(0, len(calls)))]}(buf, src);")
        else:
            body.append(f"  if ({v} > {int(rng.integers(1, 64))}) return {v};")
    name = f"func_{int(ex['id'])}"
    return "int " + name + "(char *buf, char *src) {\n" + "\n".join(body) + "\n  return 0;\n}"


def attach_synthetic_text(examples: List[Dict], seed: int = 0) -> List[Dict]:
    for ex in examples:
        ex["code"] = synthetic_function_text(ex, np.random.default_rng((seed, int(ex["id"]))))
    return examples

"""Train/val/test partitioning.

Mirrors the reference's split modes (DDFA/sastvd/helpers/datasets.py:475-520
``ds_partition``): "fixed" (a provided id->partition table, the LineVul split
file), "random" (80/10/10, seed-deterministic), and "cross-project"
(partition by project id so no project spans splits — the Table 7 protocol,
reference scripts/run_cross_project.sh).
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence

import numpy as np


def make_splits(
    examples: Sequence[Mapping],
    mode: str = "random",
    seed: int = 0,
    fixed: Optional[Mapping[int, str]] = None,
    fractions=(0.8, 0.1, 0.1),
) -> Dict[str, np.ndarray]:
    """Return {"train": idx[], "val": idx[], "test": idx[]} into ``examples``."""
    n = len(examples)
    if mode == "fixed":
        if fixed is None:
            raise ValueError("fixed split requires an id->partition mapping")
        out = {"train": [], "val": [], "test": []}
        for i, ex in enumerate(examples):
            part = fixed.get(int(ex["id"]))
            if part in out:
                out[part].append(i)
        return {k: np.asarray(v, np.int64) for k, v in out.items()}

    if mode == "random":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(n)
        n_train = int(n * fractions[0])
        n_val = int(n * fractions[1])
        return {
            "train": perm[:n_train],
            "val": perm[n_train : n_train + n_val],
            "test": perm[n_train + n_val :],
        }

    if mode == "cross-project":
        rng = np.random.default_rng(seed)
        # Project ids may be ints or strings (the Big-Vul CSV carries
        # names); anything sortable works.
        projects = sorted({str(ex.get("project", "")) for ex in examples})
        if len(projects) < 3:
            raise ValueError(
                f"cross-project split needs >= 3 distinct projects, "
                f"got {projects!r}"
            )
        perm = rng.permutation(len(projects))
        projects = [projects[i] for i in perm]
        # Every partition keeps >= 1 project: clamp train/val so the test
        # slice can't go empty at small project counts.
        n_train = max(1, min(int(len(projects) * fractions[0]), len(projects) - 2))
        n_val = max(1, min(int(len(projects) * fractions[1]),
                           len(projects) - n_train - 1))
        train_p = set(projects[:n_train])
        val_p = set(projects[n_train : n_train + n_val])
        out = {"train": [], "val": [], "test": []}
        for i, ex in enumerate(examples):
            p = str(ex.get("project", ""))
            key = "train" if p in train_p else ("val" if p in val_p else "test")
            out[key].append(i)
        return {k: np.asarray(v, np.int64) for k, v in out.items()}

    raise ValueError(f"unknown split mode: {mode}")


def assert_no_leakage(splits: Mapping[str, np.ndarray]) -> None:
    """Reference datamodule's always-on invariant
    (DDFA/sastvd/linevd/datamodule.py:74-78)."""
    train = set(splits["train"].tolist())
    val = set(splits["val"].tolist())
    test = set(splits["test"].tolist())
    assert not (train & val), "train/val leakage"
    assert not (train & test), "train/test leakage"
    assert not (val & test), "val/test leakage"

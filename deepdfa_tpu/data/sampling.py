"""Per-epoch class rebalancing.

Reference semantics (DDFA/sastvd/helpers/dclass.py:84-105
``get_epoch_indices`` with undersample "v1.0"): every epoch keeps all
positives and draws ``factor * n_positive`` negatives without replacement,
with a fresh RNG state per epoch (dataloaders are reloaded every epoch,
config_default.yaml:42). Oversampling draws positives with replacement.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def epoch_indices(
    labels: Sequence[int],
    epoch: int,
    seed: int = 0,
    undersample_factor: Optional[float] = 1.0,
    oversample_factor: Optional[float] = None,
    shuffle: bool = True,
) -> np.ndarray:
    labels = np.asarray(labels)
    idx = np.arange(len(labels))
    rng = np.random.default_rng((seed, epoch))
    if undersample_factor is None and oversample_factor is None:
        return rng.permutation(idx) if shuffle else idx
    pos = idx[labels == 1]
    neg = idx[labels == 0]
    if undersample_factor is not None:
        k = min(len(neg), int(len(pos) * undersample_factor))
        neg = rng.choice(neg, size=k, replace=False)
    if oversample_factor is not None:
        pos = rng.choice(pos, size=int(len(pos) * oversample_factor), replace=True)
    out = np.concatenate([pos, neg])
    return rng.permutation(out) if shuffle else np.sort(out)

"""Seq2seq generation-task datasets (the CodeT5 capability surface beyond
defect classification).

Reader parity with the reference (CodeT5/_utils.py):
  - summarize: jsonl, source = joined ``code_tokens``, target = joined
    ``docstring_tokens``, whitespace-normalized (_utils.py:235-258)
  - translate / refine: "src_file,tgt_file" line-parallel pair
    (_utils.py:168-212)
  - concode: jsonl with ``nl`` -> ``code`` (_utils.py:215-232)
  - clone: "index_file + url_to_code jsonl" pair labels (_utils.py:283-305)
  - defect-as-data: jsonl ``func``/``target`` (handled by etl/datasets.py)

Tokenization/padding land in fixed [N, L] int32 arrays (static shapes for
XLA); every task becomes {"source_ids", "target_ids", "index"}.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class Example:
    idx: int
    source: str
    target: str


def _norm(tokens) -> str:
    return " ".join(" ".join(tokens).replace("\n", " ").strip().split())


def read_summarize_examples(path: str, limit: Optional[int] = None) -> List[Example]:
    out: List[Example] = []
    with open(path, encoding="utf-8") as f:
        for idx, line in enumerate(f):
            if limit is not None and idx >= limit:
                break
            js = json.loads(line)
            out.append(
                Example(
                    idx=js.get("idx", idx),
                    source=_norm(js["code_tokens"]),
                    target=_norm(js["docstring_tokens"]),
                )
            )
    return out


def read_pair_examples(path_pair: str, limit: Optional[int] = None) -> List[Example]:
    """translate / refine: comma-joined "src,tgt" line-parallel files."""
    src_path, tgt_path = path_pair.split(",")
    out: List[Example] = []
    with open(src_path) as f1, open(tgt_path) as f2:
        for idx, (l1, l2) in enumerate(zip(f1, f2)):
            if limit is not None and idx >= limit:
                break
            out.append(Example(idx=idx, source=l1.strip(), target=l2.strip()))
    return out


def read_concode_examples(path: str, limit: Optional[int] = None) -> List[Example]:
    out: List[Example] = []
    with open(path) as f:
        for idx, line in enumerate(f):
            if limit is not None and idx >= limit:
                break
            js = json.loads(line)
            out.append(
                Example(idx=idx, source=js["nl"].strip(), target=js["code"].strip())
            )
    return out


def read_clone_examples(
    index_path: str, code_path: str, limit: Optional[int] = None
) -> List[Tuple[str, str, int]]:
    """BigCloneBench-style: jsonl of {idx, func} + tab-separated
    "url1 url2 label" index (CodeT5/_utils.py:283-305)."""
    url_to_code: Dict[str, str] = {}
    with open(code_path) as f:
        for line in f:
            js = json.loads(line)
            url_to_code[str(js["idx"])] = js["func"]
    out: List[Tuple[str, str, int]] = []
    with open(index_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) != 3:
                continue
            u1, u2, label = parts
            if u1 not in url_to_code or u2 not in url_to_code:
                continue
            out.append((url_to_code[u1], url_to_code[u2], int(label)))
            if limit is not None and len(out) >= limit:
                break
    return out


def read_defect_examples(path: str, limit: Optional[int] = None):
    """Defect JSONL ``{idx, code|func, target}`` — the schema our export
    writes (etl/export.py export_codet5_defect_jsonl) and the reference
    reads (CodeT5/_utils.py read_defect_examples; ``func`` in the published
    dumps). Returns (codes, labels, indices)."""
    codes: List[str] = []
    labels: List[int] = []
    indices: List[int] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            if limit is not None and i >= limit:
                break
            js = json.loads(line)
            codes.append(" ".join(str(js.get("code", js.get("func", ""))).split()))
            labels.append(int(js["target"]))
            indices.append(int(js.get("idx", i)))
    return codes, labels, indices


READERS: Dict[str, Callable] = {
    "summarize": read_summarize_examples,
    "translate": read_pair_examples,
    "refine": read_pair_examples,
    "concode": read_concode_examples,
}

_SPLIT_NAMES = {"train": "train", "dev": "valid", "test": "test"}


def get_filenames(data_root: str, task: str, sub_task: str, split: str) -> str:
    """The reference's dataset layout (CodeT5/utils.py get_filenames),
    with the defect task rooted under ``{root}/defect`` (the reference
    hardcodes an author-machine path there)."""
    name = _SPLIT_NAMES.get(split, split)
    if task == "concode":
        return f"{data_root}/concode/{'dev' if split == 'dev' else split}.json"
    if task == "summarize":
        return f"{data_root}/summarize/{sub_task}/{name}.jsonl"
    if task == "refine":
        d = f"{data_root}/refine/{sub_task}"
        return f"{d}/{name}.buggy-fixed.buggy,{d}/{name}.buggy-fixed.fixed"
    if task == "translate":
        d = f"{data_root}/translate"
        a, b = ("cs", "java") if sub_task == "cs-java" else ("java", "cs")
        return (f"{d}/{name}.java-cs.txt.{a},{d}/{name}.java-cs.txt.{b}")
    if task == "clone":
        return f"{data_root}/clone/{name}.txt"
    if task == "defect":
        return f"{data_root}/defect/{name}.jsonl"
    raise ValueError(f"unknown task {task!r}")


def encode_examples(
    examples: Sequence[Example],
    tokenize: Callable[[str], Sequence[int]],
    max_source_length: int,
    max_target_length: int,
    pad_id: int = 0,
    eos_id: int = 2,
) -> Dict[str, np.ndarray]:
    """Tokenize + pad to fixed [N, L] arrays. ``tokenize`` maps a string to
    token ids WITHOUT eos; eos is appended then the row padded (HF
    ``padding='max_length', truncation=True`` semantics with one eos,
    CodeT5/_utils.py:33-34)."""

    def fit(ids, max_len):
        ids = list(ids)[: max_len - 1] + [eos_id]
        return ids + [pad_id] * (max_len - len(ids))

    n = len(examples)
    src = np.full((n, max_source_length), pad_id, np.int32)
    tgt = np.full((n, max_target_length), pad_id, np.int32)
    index = np.zeros(n, np.int64)
    for i, ex in enumerate(examples):
        src[i] = fit(tokenize(ex.source), max_source_length)
        tgt[i] = fit(tokenize(ex.target), max_target_length)
        index[i] = ex.idx
    return {"source_ids": src, "target_ids": tgt, "index": index}


def synthetic_seq2seq(
    n: int,
    vocab_size: int = 64,
    max_source_length: int = 24,
    max_target_length: int = 12,
    pad_id: int = 0,
    eos_id: int = 2,
    seed: int = 0,
    reverse: bool = True,
) -> Dict[str, np.ndarray]:
    """Deterministic learnable toy task (target = reversed — or copied —
    source prefix): the generation-loop integration test, like
    synthetic_bigvul for graphs."""
    rng = np.random.RandomState(seed)
    src = np.full((n, max_source_length), pad_id, np.int32)
    tgt = np.full((n, max_target_length), pad_id, np.int32)
    for i in range(n):
        ln = rng.randint(3, max_target_length - 1)
        toks = rng.randint(3, vocab_size, size=ln)
        src[i, :ln] = toks
        src[i, ln] = eos_id
        out = toks[::-1] if reverse else toks
        tgt[i, :ln] = out
        tgt[i, ln] = eos_id
    return {
        "source_ids": src,
        "target_ids": tgt,
        "index": np.arange(n, dtype=np.int64),
    }

"""Chaos soak: the ``cli chaos`` engine.

One deterministic end-to-end run that provokes every fault class the
resilience layer claims to survive (five distinct fault kinds — the
acceptance gate asks for >= 3) and verifies the recovery behavior, on a
tiny synthetic workload sized for seconds on CPU:

* ``preempt_resume`` — a training run killed at an injected epoch-start
  raise, resumed with ``--resume``, must end with history/metrics
  **bit-for-bit identical** to the uninterrupted run (the headline
  determinism property: a preemption costs wall clock, never numerics).
* ``nan_rollback`` — an injected NaN loss under
  ``anomaly_policy="rollback"`` rolls back and completes instead of
  dying with FloatingPointError.
* ``corrupt_restore`` — a snapshot corrupted right after its checksum was
  recorded must fail verification on restore and fall back to the newest
  intact snapshot.
* ``etl_retry`` — an injected per-item ETL failure self-heals under the
  pmap attempt cap.
* ``serve_flush_fault`` — an injected raise inside a serving micro-batch
  fails only that flush; later requests succeed and the compile count
  stays flat (no warmed-executable loss).
* ``poison_corpus`` — the corrupt-corpus gauntlet (deepdfa_tpu/contracts):
  a seeded fuzzer damages a synthetic corpus across every corruption
  class; training on the poisoned corpus must complete, the quarantine
  manifest must list every poisoned item under its expected reason code
  (zero false quarantines), and the final history must be **bit-for-bit
  identical** to a run on the pre-corruption clean subset — data faults
  cost the poisoned rows, never the numerics of the surviving ones.

Every scenario reports ``ok`` plus enough detail to debug a regression;
``run_soak`` aggregates them and the CLI exits nonzero unless all pass.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List

import numpy as np

from deepdfa_tpu.core.config import (
    DataConfig,
    FeatureSpec,
    FlowGNNConfig,
    TrainConfig,
)
from deepdfa_tpu.resilience import inject

logger = logging.getLogger(__name__)

TINY = FlowGNNConfig(
    feature=FeatureSpec(limit_all=20, limit_subkeys=20),
    hidden_dim=8,
    n_steps=2,
    num_output_layers=2,
)
DATA = DataConfig(
    batch_size=16,
    eval_batch_size=16,
    max_nodes_per_graph=64,
    max_edges_per_node=4,
    undersample_factor=1.0,
)


def _dataset(n: int, seed: int = 1):
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    examples = synthetic_bigvul(n, TINY.feature, positive_fraction=0.5,
                                seed=seed)
    for i, ex in enumerate(examples):
        ex["label"] = int(np.asarray(ex["vuln"]).max())
        ex["id"] = i
    return examples, make_splits(examples, "random", seed=0)


def _records_match(a: Dict, b: Dict) -> bool:
    """Bit-for-bit equality of two epoch records, wall-clock excluded."""
    return (
        a["epoch"] == b["epoch"]
        and a["train_loss"] == b["train_loss"]
        and a["val_loss"] == b["val_loss"]
        and a["train_metrics"] == b["train_metrics"]
        and a["val_metrics"] == b["val_metrics"]
    )


def scenario_preempt_resume(out_dir: str, n_examples: int,
                            epochs: int) -> Dict[str, Any]:
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit

    examples, splits = _dataset(n_examples)
    walls: Dict[str, float] = {}

    def run(sub: str, resume: bool = False):
        import time

        cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0,
                          checkpoint_dir=os.path.join(out_dir, sub))
        t0 = time.perf_counter()
        try:
            return fit(FlowGNN(TINY), examples, splits, cfg, DATA,
                       resume=resume)
        finally:
            walls[sub + ("_resume" if resume else "")] = (
                time.perf_counter() - t0
            )

    _, full_hist = run("full")

    preempt_at = max(epochs // 2, 1)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "train.epoch_start", "kind": "raise", "at": preempt_at,
         "msg": "chaos: simulated preemption"},
    ]})
    preempted = False
    with inject.armed(plan):
        try:
            run("part")
        except inject.FaultError:
            preempted = True
    _, res_hist = run("part", resume=True)

    tail = full_hist["epochs"][preempt_at:]
    match = (
        len(res_hist["epochs"]) == len(tail)
        and all(_records_match(a, b)
                for a, b in zip(res_hist["epochs"], tail))
        and res_hist["best_val_loss"] == full_hist["best_val_loss"]
        and res_hist["best_epoch"] == full_hist["best_epoch"]
    )
    return {
        "ok": preempted and match,
        "fault_kinds": ["raise"],
        "preempted": preempted,
        "bitwise_match": match,
        "resumed_epochs": [e["epoch"] for e in res_hist["epochs"]],
        # The robustness tax one preemption charges this workload:
        # (preempted run + resumed run) minus the uninterrupted run —
        # restore cost plus the resumed process's fresh jit compiles.
        "resume_overhead_s": (walls.get("part", 0.0)
                              + walls.get("part_resume", 0.0)
                              - walls.get("full", 0.0)),
    }


def scenario_nan_rollback(n_examples: int, epochs: int) -> Dict[str, Any]:
    import math

    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit

    examples, splits = _dataset(n_examples)
    cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0,
                      anomaly_policy="rollback", anomaly_retry_budget=3)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "train.loss", "kind": "nan", "at": 1},
    ]})
    with inject.armed(plan):
        _, hist = fit(FlowGNN(TINY), examples, splits, cfg, DATA)
    rollbacks = hist.get("anomaly_rollbacks", 0)
    final_loss = hist["epochs"][-1]["train_loss"] if hist["epochs"] else None
    ok = (rollbacks >= 1 and len(hist["epochs"]) == epochs
          and final_loss is not None and math.isfinite(final_loss))
    return {"ok": ok, "fault_kinds": ["nan"], "rollbacks": rollbacks,
            "final_train_loss": final_loss}


def scenario_corrupt_restore(out_dir: str, n_examples: int,
                             epochs: int) -> Dict[str, Any]:
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.checkpoint import CheckpointManager
    from deepdfa_tpu.train.loop import fit

    examples, splits = _dataset(n_examples)
    ckpt_dir = os.path.join(out_dir, "corrupt")
    cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0,
                      checkpoint_dir=ckpt_dir, checkpoint_every_epochs=1)
    # Damage the FINAL 'last' snapshot right after its checksum lands —
    # the preemption-mid-write shape verification exists for.
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "checkpoint.saved", "kind": "corrupt", "name": "last",
         "at": epochs - 1},
    ]})
    with inject.armed(plan):
        fit(FlowGNN(TINY), examples, splits, cfg, DATA)

    mgr = CheckpointManager(ckpt_dir)
    detected = not mgr.verify("last")
    mgr.restore_params("last")
    used = mgr.last_restored or {}
    ok = bool(detected and used.get("fallback")
              and used.get("name") != "last")
    return {"ok": ok, "fault_kinds": ["corrupt"],
            "corruption_detected": detected,
            "fallback_snapshot": used.get("name"),
            "fallback_epoch": used.get("epoch")}


def scenario_etl_retry() -> Dict[str, Any]:
    from deepdfa_tpu.etl.parallel import pmap

    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "etl.item", "kind": "raise", "at": 2,
         "msg": "chaos: transient ETL fault"},
    ]})
    with inject.armed(plan):
        # Serial path: the retry shares this process, so the one-shot
        # fault is spent on attempt 1 and attempt 2 heals the item.
        healed = pmap(lambda x: x * 10, list(range(6)), workers=1,
                      attempts=2)
    with inject.armed(inject.FaultPlan.from_doc({"faults": [
        {"site": "etl.item", "kind": "raise", "at": 2, "times": 5},
    ]})):
        capped = pmap(lambda x: x * 10, list(range(6)), workers=1,
                      attempts=2)
    ok = (healed == [0, 10, 20, 30, 40, 50]
          and capped == [0, 10, None, 30, 40, 50])
    return {"ok": ok, "fault_kinds": ["raise"], "healed": healed,
            "capped_item_failed": capped[2] is None}


def scenario_serve_flush_fault(n_examples: int = 6) -> Dict[str, Any]:
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock

    config = ServeConfig(batch_slots=4)
    model = FlowGNN(TINY)
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config, clock=VirtualClock())
    engine.warmup()
    compiles_after_warmup = engine.stats.compiles

    graphs = synthetic_bigvul(n_examples, TINY.feature,
                              positive_fraction=0.5, seed=2)
    half = n_examples // 2
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "serve.batch", "kind": "raise", "at": 0,
         "msg": "chaos: flush fault"},
    ]})
    with inject.armed(plan):
        first = engine.score_sync(graphs[:half])
        second = engine.score_sync(graphs[half:])
    ok = (
        all(r.get("error") == "internal" for r in first)
        and all("prob" in r for r in second)
        and engine.stats.failures == half
        and engine.stats.compiles == compiles_after_warmup
    )
    return {"ok": ok, "fault_kinds": ["raise"],
            "failed_flush_requests": len(first),
            "later_requests_ok": all("prob" in r for r in second),
            "compiles_flat":
                engine.stats.compiles == compiles_after_warmup}


def scenario_poison_corpus(out_dir: str, n_examples: int,
                           epochs: int) -> Dict[str, Any]:
    """The data-contract gauntlet as a chaos scenario (ISSUE 4 headline):
    train on a seeded poisoned corpus, then on its pre-corruption clean
    subset, and demand (a) a complete, correctly reason-coded quarantine
    manifest with zero false quarantines and (b) bit-for-bit identical
    training histories — quarantine+repair must be exactly equivalent to
    never having seen the corruption."""
    from deepdfa_tpu.contracts import Quarantine, load_examples_jsonl, read_manifest
    from deepdfa_tpu.contracts import gauntlet, quarantine as cq
    from deepdfa_tpu.core.config import ALL_SUBKEYS
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit

    examples, _ = _dataset(n_examples, seed=3)
    root = os.path.join(out_dir, "poison")
    plan = gauntlet.poison_corpus(examples, root, seed=0)

    sink = Quarantine(os.path.join(root, cq.DIRNAME))
    cq.clear(sink.root)  # fresh manifest per soak: the grade below is exact
    poisoned, report = load_examples_jsonl(
        os.path.join(root, "corpus.jsonl"), ALL_SUBKEYS,
        max_nodes=gauntlet.GAUNTLET_MAX_NODES, quarantine=sink)
    clean_sink = Quarantine(os.path.join(root, "quarantine_clean"))
    cq.clear(clean_sink.root)
    clean, _ = load_examples_jsonl(
        os.path.join(root, "clean_subset.jsonl"), ALL_SUBKEYS,
        max_nodes=gauntlet.GAUNTLET_MAX_NODES, quarantine=clean_sink)

    grade = gauntlet.check_manifest(plan, read_manifest(sink.root),
                                    [ex["id"] for ex in poisoned])

    def run(exs):
        cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0)
        splits = make_splits(exs, "random", seed=0)
        return fit(FlowGNN(TINY), exs, splits, cfg, DATA)

    _, hist_poisoned = run(poisoned)
    _, hist_clean = run(clean)
    match = (
        len(hist_poisoned["epochs"]) == len(hist_clean["epochs"]) == epochs
        and all(_records_match(a, b)
                for a, b in zip(hist_poisoned["epochs"],
                                hist_clean["epochs"]))
        and hist_poisoned["best_val_loss"] == hist_clean["best_val_loss"]
        and hist_poisoned["best_epoch"] == hist_clean["best_epoch"]
    )
    ok = bool(
        grade["ok"]
        and clean_sink.total == 0          # the clean subset is truly clean
        and len(poisoned) == len(clean)    # survivors == clean subset
        and report["repaired"] >= grade["repairable_victims"]
        and match
    )
    return {
        "ok": ok,
        "fault_kinds": ["data-corrupt"],
        "classes": len(plan["classes"]),
        "quarantined": report["quarantined"],
        "by_reason": report["by_reason"],
        "repaired": report["repaired"],
        "manifest_grade": grade,
        "survivors": len(poisoned),
        "bitwise_match": match,
    }


def run_soak(out_dir: str = "runs/chaos", n_examples: int = 48,
             epochs: int = 3) -> Dict[str, Any]:
    """All scenarios, one report. ``ok`` only when every scenario passed;
    ``fault_kinds`` lists the distinct injected fault kinds exercised."""
    os.makedirs(out_dir, exist_ok=True)
    scenarios: Dict[str, Dict[str, Any]] = {}
    scenarios["preempt_resume"] = scenario_preempt_resume(
        out_dir, n_examples, epochs)
    scenarios["nan_rollback"] = scenario_nan_rollback(n_examples, epochs)
    scenarios["corrupt_restore"] = scenario_corrupt_restore(
        out_dir, n_examples, epochs)
    scenarios["etl_retry"] = scenario_etl_retry()
    scenarios["serve_flush_fault"] = scenario_serve_flush_fault()
    scenarios["poison_corpus"] = scenario_poison_corpus(
        out_dir, n_examples, epochs)

    kind_of = {"preempt_resume": "preempt-raise",
               "nan_rollback": "nan-loss",
               "corrupt_restore": "checkpoint-corrupt",
               "etl_retry": "etl-item-raise",
               "serve_flush_fault": "serve-batch-raise",
               "poison_corpus": "data-corrupt"}
    kinds: List[str] = sorted(kind_of[name] for name in scenarios)
    ok = all(res["ok"] for res in scenarios.values())
    return {
        "ok": ok,
        "distinct_fault_kinds": kinds,
        "n_fault_kinds": len(kinds),
        "scenarios": scenarios,
        "exit_code": 0 if ok else 1,
    }

"""Chaos soak: the ``cli chaos`` engine.

One deterministic end-to-end run that provokes every fault class the
resilience layer claims to survive (twelve distinct fault kinds — the
acceptance gate asks for >= 3) and verifies the recovery behavior, on a
tiny synthetic workload sized for seconds on CPU:

* ``preempt_resume`` — a training run killed at an injected epoch-start
  raise, resumed with ``--resume``, must end with history/metrics
  **bit-for-bit identical** to the uninterrupted run (the headline
  determinism property: a preemption costs wall clock, never numerics).
* ``nan_rollback`` — an injected NaN loss under
  ``anomaly_policy="rollback"`` rolls back and completes instead of
  dying with FloatingPointError.
* ``corrupt_restore`` — a snapshot corrupted right after its checksum was
  recorded must fail verification on restore and fall back to the newest
  intact snapshot.
* ``etl_retry`` — an injected per-item ETL failure self-heals under the
  pmap attempt cap.
* ``serve_flush_fault`` — an injected raise inside a serving micro-batch
  fails only that flush; later requests succeed and the compile count
  stays flat (no warmed-executable loss).
* ``poison_corpus`` — the corrupt-corpus gauntlet (deepdfa_tpu/contracts):
  a seeded fuzzer damages a synthetic corpus across every corruption
  class; training on the poisoned corpus must complete, the quarantine
  manifest must list every poisoned item under its expected reason code
  (zero false quarantines), and the final history must be **bit-for-bit
  identical** to a run on the pre-corruption clean subset — data faults
  cost the poisoned rows, never the numerics of the surviving ones.
* ``elastic_resume`` — a fit killed MID-epoch under async checkpointing
  (with the writer thread itself crashed mid-serialize on one snapshot)
  must resume on a *different* DP device count: verified restore from
  the last committed snapshot, the torn write never winning the
  fallback order, the recorded layout driving a reshard, and the loss
  curve continuing bit-for-bit (same shard count) or within the
  documented tolerance (across a reshape).
* ``scan_joern_deaths`` — pooled Joern workers killed and hung mid-scan
  (on the hermetic fake transport) while one function is a deterministic
  quarantine poison: the sweep completes with every healthy function
  scored, the poison is reason-coded in an exact manifest, restarts and
  retries are asserted from the run's trace, and the warmed serving
  executables survive untouched.
* ``preempt_drain`` — a **real SIGTERM** to a mid-epoch ``cli fit``
  subprocess: the child drains to a committed step-granular
  ``preempt_<epoch>_<step>`` snapshot, exits ``EXIT_PREEMPTED``, and a
  ``--resume`` restarts mid-epoch with history bit-continuous against
  the uninterrupted reference — the partial epoch is not lost. A second
  phase SIGTERMs into a wedged step (injected long delay): the hung-step
  watchdog fires ``lifecycle.hang`` with thread stacks and the process
  still exits behind a durable snapshot inside the grace budget.
* ``serve_lame_duck`` — SIGTERM to a live ``cli serve`` subprocess under
  replay load: zero dropped admitted requests (responses == admissions,
  asserted from the trace), new admissions 503 + Retry-After with
  ``/healthz`` reporting ``draining``, partial buckets flushed
  immediately, drain inside the grace budget, compiles flat.
* ``fleet_roll`` — one of THREE serving-fleet replicas takes a
  per-replica preemption mid-load (the in-process SIGTERM analog): its
  admitted requests are all answered, the router shuns it while the
  other two keep serving every POST, fleet ``/healthz`` degrades then
  recovers, and compiles stay flat across the roll (re-entry reuses the
  warmed executables).
* ``proc_crash`` — a **real SIGKILL** to one of THREE engine OS
  processes behind the router tier (serve/procfleet.py) under
  three-thread live HTTP load: every admitted POST is still answered
  with scores (the forward that died with the victim re-routes to a
  sibling), the router sheds to the survivors while ``/healthz``
  degrades, a warmed replacement rejoins at a bumped generation with
  zero post-warmup compiles measured THROUGH the router, and ONE merged
  trace shows kill/shed/rejoin across >= 4 real (process, pid)
  identities.

Every scenario reports ``ok`` plus enough detail to debug a regression;
``run_soak`` aggregates them and the CLI exits nonzero unless all pass.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List

import numpy as np

from deepdfa_tpu.core.config import (
    DataConfig,
    FeatureSpec,
    FlowGNNConfig,
    TrainConfig,
)
from deepdfa_tpu.resilience import inject

logger = logging.getLogger(__name__)

TINY = FlowGNNConfig(
    feature=FeatureSpec(limit_all=20, limit_subkeys=20),
    hidden_dim=8,
    n_steps=2,
    num_output_layers=2,
)
DATA = DataConfig(
    batch_size=16,
    eval_batch_size=16,
    max_nodes_per_graph=64,
    max_edges_per_node=4,
    undersample_factor=1.0,
)


def _dataset(n: int, seed: int = 1):
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    examples = synthetic_bigvul(n, TINY.feature, positive_fraction=0.5,
                                seed=seed)
    for i, ex in enumerate(examples):
        ex["label"] = int(np.asarray(ex["vuln"]).max())
        ex["id"] = i
    return examples, make_splits(examples, "random", seed=0)


def _records_match(a: Dict, b: Dict) -> bool:
    """Bit-for-bit equality of two epoch records, wall-clock excluded."""
    return (
        a["epoch"] == b["epoch"]
        and a["train_loss"] == b["train_loss"]
        and a["val_loss"] == b["val_loss"]
        and a["train_metrics"] == b["train_metrics"]
        and a["val_metrics"] == b["val_metrics"]
    )


def scenario_preempt_resume(out_dir: str, n_examples: int,
                            epochs: int) -> Dict[str, Any]:
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit

    examples, splits = _dataset(n_examples)
    walls: Dict[str, float] = {}

    def run(sub: str, resume: bool = False):
        import time

        cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0,
                          checkpoint_dir=os.path.join(out_dir, sub))
        t0 = time.perf_counter()
        try:
            return fit(FlowGNN(TINY), examples, splits, cfg, DATA,
                       resume=resume)
        finally:
            walls[sub + ("_resume" if resume else "")] = (
                time.perf_counter() - t0
            )

    _, full_hist = run("full")

    preempt_at = max(epochs // 2, 1)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "train.epoch_start", "kind": "raise", "at": preempt_at,
         "msg": "chaos: simulated preemption"},
    ]})
    preempted = False
    with inject.armed(plan):
        try:
            run("part")
        except inject.FaultError:
            preempted = True
    _, res_hist = run("part", resume=True)

    tail = full_hist["epochs"][preempt_at:]
    match = (
        len(res_hist["epochs"]) == len(tail)
        and all(_records_match(a, b)
                for a, b in zip(res_hist["epochs"], tail))
        and res_hist["best_val_loss"] == full_hist["best_val_loss"]
        and res_hist["best_epoch"] == full_hist["best_epoch"]
    )
    return {
        "ok": preempted and match,
        "fault_kinds": ["raise"],
        "preempted": preempted,
        "bitwise_match": match,
        "resumed_epochs": [e["epoch"] for e in res_hist["epochs"]],
        # The robustness tax one preemption charges this workload:
        # (preempted run + resumed run) minus the uninterrupted run —
        # restore cost plus the resumed process's fresh jit compiles.
        "resume_overhead_s": (walls.get("part", 0.0)
                              + walls.get("part_resume", 0.0)
                              - walls.get("full", 0.0)),
    }


def scenario_nan_rollback(n_examples: int, epochs: int) -> Dict[str, Any]:
    import math

    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit

    examples, splits = _dataset(n_examples)
    cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0,
                      anomaly_policy="rollback", anomaly_retry_budget=3)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "train.loss", "kind": "nan", "at": 1},
    ]})
    with inject.armed(plan):
        _, hist = fit(FlowGNN(TINY), examples, splits, cfg, DATA)
    rollbacks = hist.get("anomaly_rollbacks", 0)
    final_loss = hist["epochs"][-1]["train_loss"] if hist["epochs"] else None
    ok = (rollbacks >= 1 and len(hist["epochs"]) == epochs
          and final_loss is not None and math.isfinite(final_loss))
    return {"ok": ok, "fault_kinds": ["nan"], "rollbacks": rollbacks,
            "final_train_loss": final_loss}


def scenario_corrupt_restore(out_dir: str, n_examples: int,
                             epochs: int) -> Dict[str, Any]:
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.checkpoint import CheckpointManager
    from deepdfa_tpu.train.loop import fit

    examples, splits = _dataset(n_examples)
    ckpt_dir = os.path.join(out_dir, "corrupt")
    cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0,
                      checkpoint_dir=ckpt_dir, checkpoint_every_epochs=1)
    # Damage EVERY 'last' write right after its checksum lands — the
    # preemption-mid-write shape verification exists for. (Every write,
    # not an index-targeted one: the async writer may supersede a queued
    # 'last' with a newer one, so physical-write ordinals are not stable
    # across manager flavors.)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "checkpoint.saved", "kind": "corrupt", "name": "last",
         "every": 1, "times": 0},
    ]})
    with inject.armed(plan):
        fit(FlowGNN(TINY), examples, splits, cfg, DATA)

    mgr = CheckpointManager(ckpt_dir)
    detected = not mgr.verify("last")
    mgr.restore_params("last")
    used = mgr.last_restored or {}
    ok = bool(detected and used.get("fallback")
              and used.get("name") != "last")
    return {"ok": ok, "fault_kinds": ["corrupt"],
            "corruption_detected": detected,
            "fallback_snapshot": used.get("name"),
            "fallback_epoch": used.get("epoch")}


def scenario_etl_retry() -> Dict[str, Any]:
    from deepdfa_tpu.etl.parallel import pmap

    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "etl.item", "kind": "raise", "at": 2,
         "msg": "chaos: transient ETL fault"},
    ]})
    with inject.armed(plan):
        # Serial path: the retry shares this process, so the one-shot
        # fault is spent on attempt 1 and attempt 2 heals the item.
        healed = pmap(lambda x: x * 10, list(range(6)), workers=1,
                      attempts=2)
    with inject.armed(inject.FaultPlan.from_doc({"faults": [
        {"site": "etl.item", "kind": "raise", "at": 2, "times": 5},
    ]})):
        capped = pmap(lambda x: x * 10, list(range(6)), workers=1,
                      attempts=2)
    ok = (healed == [0, 10, 20, 30, 40, 50]
          and capped == [0, 10, None, 30, 40, 50])
    return {"ok": ok, "fault_kinds": ["raise"], "healed": healed,
            "capped_item_failed": capped[2] is None}


def scenario_serve_flush_fault(n_examples: int = 6) -> Dict[str, Any]:
    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.replay import VirtualClock

    config = ServeConfig(batch_slots=4)
    model = FlowGNN(TINY)
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config, clock=VirtualClock())
    engine.warmup()
    compiles_after_warmup = engine.stats.compiles

    graphs = synthetic_bigvul(n_examples, TINY.feature,
                              positive_fraction=0.5, seed=2)
    half = n_examples // 2
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "serve.batch", "kind": "raise", "at": 0,
         "msg": "chaos: flush fault"},
    ]})
    with inject.armed(plan):
        first = engine.score_sync(graphs[:half])
        second = engine.score_sync(graphs[half:])
    ok = (
        all(r.get("error") == "internal" for r in first)
        and all("prob" in r for r in second)
        and engine.stats.failures == half
        and engine.stats.compiles == compiles_after_warmup
    )
    return {"ok": ok, "fault_kinds": ["raise"],
            "failed_flush_requests": len(first),
            "later_requests_ok": all("prob" in r for r in second),
            "compiles_flat":
                engine.stats.compiles == compiles_after_warmup}


def scenario_poison_corpus(out_dir: str, n_examples: int,
                           epochs: int) -> Dict[str, Any]:
    """The data-contract gauntlet as a chaos scenario (ISSUE 4 headline):
    train on a seeded poisoned corpus, then on its pre-corruption clean
    subset, and demand (a) a complete, correctly reason-coded quarantine
    manifest with zero false quarantines and (b) bit-for-bit identical
    training histories — quarantine+repair must be exactly equivalent to
    never having seen the corruption."""
    from deepdfa_tpu.contracts import Quarantine, load_examples_jsonl, read_manifest
    from deepdfa_tpu.contracts import gauntlet, quarantine as cq
    from deepdfa_tpu.core.config import ALL_SUBKEYS
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.train.loop import fit

    examples, _ = _dataset(n_examples, seed=3)
    root = os.path.join(out_dir, "poison")
    plan = gauntlet.poison_corpus(examples, root, seed=0)

    sink = Quarantine(os.path.join(root, cq.DIRNAME))
    cq.clear(sink.root)  # fresh manifest per soak: the grade below is exact
    poisoned, report = load_examples_jsonl(
        os.path.join(root, "corpus.jsonl"), ALL_SUBKEYS,
        max_nodes=gauntlet.GAUNTLET_MAX_NODES, quarantine=sink)
    clean_sink = Quarantine(os.path.join(root, "quarantine_clean"))
    cq.clear(clean_sink.root)
    clean, _ = load_examples_jsonl(
        os.path.join(root, "clean_subset.jsonl"), ALL_SUBKEYS,
        max_nodes=gauntlet.GAUNTLET_MAX_NODES, quarantine=clean_sink)

    grade = gauntlet.check_manifest(plan, read_manifest(sink.root),
                                    [ex["id"] for ex in poisoned])

    def run(exs):
        cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0)
        splits = make_splits(exs, "random", seed=0)
        return fit(FlowGNN(TINY), exs, splits, cfg, DATA)

    _, hist_poisoned = run(poisoned)
    _, hist_clean = run(clean)
    match = (
        len(hist_poisoned["epochs"]) == len(hist_clean["epochs"]) == epochs
        and all(_records_match(a, b)
                for a, b in zip(hist_poisoned["epochs"],
                                hist_clean["epochs"]))
        and hist_poisoned["best_val_loss"] == hist_clean["best_val_loss"]
        and hist_poisoned["best_epoch"] == hist_clean["best_epoch"]
    )
    ok = bool(
        grade["ok"]
        and clean_sink.total == 0          # the clean subset is truly clean
        and len(poisoned) == len(clean)    # survivors == clean subset
        and report["repaired"] >= grade["repairable_victims"]
        and match
    )
    return {
        "ok": ok,
        "fault_kinds": ["data-corrupt"],
        "classes": len(plan["classes"]),
        "quarantined": report["quarantined"],
        "by_reason": report["by_reason"],
        "repaired": report["repaired"],
        "manifest_grade": grade,
        "survivors": len(poisoned),
        "bitwise_match": match,
    }


def scenario_elastic_resume(out_dir: str, n_examples: int,
                            epochs: int) -> Dict[str, Any]:
    """THE elastic/async acceptance scenario (ISSUE 6): a fit is killed
    *mid-epoch* while checkpointing asynchronously — with the writer
    thread itself crashed mid-serialize on the first snapshot — and
    resumed on a *different* data-parallel device count. Demands:

    * the mid-epoch kill and the torn writer never leave a corrupt
      snapshot winning ``_fallback_order`` — the resumed run restores a
      **verified** ``last`` from the final completed epoch;
    * the snapshot records the DP layout it was written under, and the
      resumed run reshards onto the new topology instead of refusing;
    * loss-curve continuity: the resumed epochs match the uninterrupted
      run bit-for-bit when the shard count is unchanged, and within a
      documented tolerance (FP reduction order moves with the per-shard
      packing) across a reshape.
    """
    import math
    import shutil
    import time

    import jax

    from deepdfa_tpu.core.config import subkeys_for
    from deepdfa_tpu.data.sampling import epoch_indices
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.parallel.mesh import make_mesh
    from deepdfa_tpu.train.checkpoint import AsyncCheckpointManager, CheckpointManager
    from deepdfa_tpu.train.loop import _batches, fit

    d = jax.device_count()
    from_n = 4 if d >= 4 else (2 if d >= 2 else 1)
    to_n = max(from_n // 2, 1)
    mesh_from = make_mesh(n_data=from_n) if from_n > 1 else None
    mesh_to = make_mesh(n_data=to_n) if to_n > 1 else None

    examples, splits = _dataset(n_examples)
    labels = [int(ex["label"]) for ex in examples]
    ckpt_dir = os.path.join(out_dir, "elastic")
    # The scenario asserts the torn `best` never survives; a snapshot dir
    # left by a previous soak in the same out_dir would hand it an intact
    # prior `best` and fail that check, so start from a clean slate.
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    cfg = TrainConfig(max_epochs=epochs, learning_rate=2e-3, seed=0)
    walls: Dict[str, float] = {}

    def run(mesh, checkpointer=None, resume=False, key=""):
        t0 = time.perf_counter()
        try:
            return fit(FlowGNN(TINY), examples, splits, cfg, DATA,
                       mesh=mesh, checkpointer=checkpointer, resume=resume)
        finally:
            walls[key] = time.perf_counter() - t0

    # Uninterrupted reference on the original topology.
    _, ref_hist = run(mesh_from, key="full")

    # The kill must land MID-epoch 1 (after epoch 0's snapshots, before
    # epoch 1 completes): count epoch 0's actual step dispatches with the
    # loop's own packer, then aim the train.loss raise one step past it.
    train_idx = splits["train"]
    idx0 = epoch_indices(
        [labels[i] for i in train_idx], 0, seed=DATA.seed,
        undersample_factor=DATA.undersample_factor,
        oversample_factor=DATA.oversample_factor,
    )
    steps_ep0 = sum(1 for _ in _batches(
        examples, train_idx[idx0], DATA, subkeys_for(TINY.feature),
        DATA.batch_size, n_shards=from_n))
    kill_at = steps_ep0 + 1  # the second step of epoch 1

    mgr = AsyncCheckpointManager(ckpt_dir)
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "checkpoint.async_write", "kind": "truncate", "at": 0,
         "msg": "chaos: writer killed mid-serialize"},
        {"site": "train.loss", "kind": "raise", "at": kill_at,
         "msg": "chaos: simulated mid-epoch preemption"},
    ]})
    preempted = False
    with inject.armed(plan):
        try:
            run(mesh_from, checkpointer=mgr, key="part")
        except inject.FaultError:
            preempted = True
    writer_crashes = len(mgr.errors)

    # Post-mortem before resume: the completed epoch's 'last' must be on
    # disk, verified, and tagged with the original DP layout; the torn
    # write must never be the resume candidate. The torn 'best' (write
    # seq 0 — fit saves best before last) was a FIRST write of its name,
    # so the crashed writer must have removed the partial bytes outright:
    # with no meta record, verification would have nothing to fail them
    # against, and an unrecorded partial dir must never be restorable.
    probe = CheckpointManager(ckpt_dir)
    last_verified = probe.verify("last")
    torn_best_removed = not probe.has("best")
    layout_before = probe.snapshot_layout("last") or {}
    resume_candidate = probe.resume_candidate()

    # Resume on the RESHAPED topology.
    mgr2 = AsyncCheckpointManager(ckpt_dir)
    _, res_hist = run(mesh_to, checkpointer=mgr2, resume=True,
                      key="part_resume")
    layout_after = (CheckpointManager(ckpt_dir).snapshot_layout("last")
                    or {})

    # Loss-curve continuity against the uninterrupted run's tail.
    tail = ref_hist["epochs"][1:]
    resumed = res_hist["epochs"]
    deltas = [
        abs(a[k] - b[k]) / max(abs(b[k]), 1e-12)
        for a, b in zip(resumed, tail) for k in ("train_loss", "val_loss")
        if math.isfinite(a[k]) and math.isfinite(b[k])
    ]
    max_rel_delta = max(deltas) if deltas else float("inf")
    if from_n == to_n:
        continuity = (len(resumed) == len(tail)
                      and all(_records_match(a, b)
                              for a, b in zip(resumed, tail)))
        tolerance = 0.0
    else:
        # The reshape moves per-shard packing, hence FP reduction order:
        # bit-equality is not on offer, a bounded drift is (README
        # "Elastic training & async checkpoints").
        tolerance = 2e-3
        continuity = (len(resumed) == len(tail)
                      and max_rel_delta <= tolerance)

    ok = bool(
        preempted
        and writer_crashes >= 1           # the torn write really happened
        and last_verified                 # ...and never reached 'last'
        and torn_best_removed             # ...and its partial bytes are gone
        and resume_candidate == "last"
        and layout_before.get("n_shards") == from_n
        and layout_after.get("n_shards") == to_n
        and [e["epoch"] for e in resumed] == [e["epoch"] for e in tail]
        and continuity
    )
    return {
        "ok": ok,
        "fault_kinds": ["raise", "truncate"],
        "preempted": preempted,
        "kill_step": kill_at,
        "writer_crashes": writer_crashes,
        "last_verified": last_verified,
        "torn_best_removed": torn_best_removed,
        "resume_candidate": resume_candidate,
        "from_shards": from_n,
        "to_shards": to_n,
        "layout_recorded": layout_before,
        "layout_after_resume": layout_after,
        "resumed_epochs": [e["epoch"] for e in resumed],
        "continuity": continuity,
        "continuity_tolerance": tolerance,
        "max_rel_loss_delta": max_rel_delta,
        "resume_overhead_s": (walls.get("part", 0.0)
                              + walls.get("part_resume", 0.0)
                              - walls.get("full", 0.0)),
    }


def scenario_scan_joern_deaths(out_dir: str) -> Dict[str, Any]:
    """The streaming-scan availability scenario (ISSUE 8): pooled Joern
    workers are killed AND hung mid-sweep (faults injected at the REPL
    protocol site, on the hermetic fake transport — no JVM), while one
    function is a deterministic poison whose export has no METHOD node.
    Demands:

    * the sweep **completes**: every healthy function scores (a dead or
      hung Joern costs one session restart and a re-run of its item,
      never the pool, never the sweep);
    * the poisoned function lands in the scan quarantine under its exact
      reason code, with the manifest exact (one entry, zero false
      quarantines) and an inline error verdict — not an aborted POST;
    * restart/retry/quarantine totals are asserted from the run's
      **trace** (events.jsonl via the report summarizer), not from
      in-process state alone — the observability substrate must tell the
      same story the pool counters do;
    * the warmed serve engine's compile count stays flat: worker deaths
      in L0 never invalidate the scoring executables.
    """
    import json as _json
    import shutil
    import tempfile

    from deepdfa_tpu import telemetry
    from deepdfa_tpu.contracts import read_manifest
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.scan import ScanConfig, ScanService, fake_joern_command
    from deepdfa_tpu.scan.fake_joern import POISON_TOKEN, seeded_sources
    from deepdfa_tpu.serve import ServeConfig, ServeEngine
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.telemetry.report import events_path_of, summarize

    def trace_totals():
        """Retry/fault/quarantine totals from the active run's events so
        far (None when untraced — DEEPDFA_TELEMETRY=0 runs the scenario
        on its functional checks alone)."""
        run = telemetry.current_run()
        if run is None or not telemetry.enabled():
            return None
        telemetry.flush()
        path = events_path_of(run.run_dir)
        events = []
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                events = [_json.loads(line) for line in f if line.strip()]
        rep = summarize(events)
        return {"retries": rep["retries"],
                "fault_total": rep["faults"]["total"],
                "joern_faults":
                    rep["faults"]["by_site"].get("joern.send", 0),
                "quarantined": rep["quarantined"]}

    config = ServeConfig(batch_slots=4)
    model = FlowGNN(TINY)
    engine = ServeEngine(model, random_gnn_params(model, config),
                         config=config)
    engine.warmup()
    compiles0 = engine.stats.compiles

    sources = seeded_sources(8, seed=5)
    items = [{"id": i, "source": s} for i, s in enumerate(sources)]
    items.insert(3, {"id": "poison",
                     "source": f"int bad(void) {{ {POISON_TOKEN}; }}\n"})

    # One killed JVM and one hung REPL, mid-protocol (each item is two
    # REPL commands, so ordinals 3 and 9 land inside the sweep).
    plan = inject.FaultPlan.from_doc({"faults": [
        {"site": "joern.send", "kind": "kill", "at": 3},
        {"site": "joern.send", "kind": "hang", "at": 9},
    ]})

    before = trace_totals()
    tmp = tempfile.mkdtemp(prefix="chaos_scan_")
    try:
        with ScanService(
            engine, TINY.feature, workdir=tmp,
            config=ScanConfig(pool_size=2, timeout_s=60.0, attempts=3),
            command=fake_joern_command(),
        ) as svc:
            with inject.armed(plan):
                verdicts = svc.scan_sources(items)
            restarts = svc.pool.restarts
            alive = svc.pool.alive_workers
            manifest = read_manifest(svc.quarantine.root)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    after = trace_totals()

    by_id = {r["id"]: r for r in verdicts}
    healthy_scored = all("prob" in by_id[i] for i in range(len(sources)))
    poison = by_id.get("poison", {})
    poison_quarantined = (
        poison.get("error") == "no_method_node"
        and len(manifest) == 1
        and manifest[0].get("reason") == "no_method_node"
    )
    fired = {(s["site"], s["kind"]): s["fired"] for s in plan.report()}
    both_fired = (fired.get(("joern.send", "kill")) == 1
                  and fired.get(("joern.send", "hang")) == 1)

    if before is not None and after is not None:
        trace_ok = (
            after["joern_faults"] - before["joern_faults"] == 2
            # Each session-fatal fault is one retry of its item.
            and after["retries"] - before["retries"] == 2
            and after["quarantined"] - before["quarantined"] == 1
        )
    else:
        trace_ok = None  # untraced run: functional checks only

    ok = bool(
        healthy_scored
        and poison_quarantined
        and both_fired
        and restarts == 2            # one restart per injected death
        and alive == 2               # the pool is whole again
        and engine.stats.compiles == compiles0
        and trace_ok is not False
    )
    return {
        "ok": ok,
        "fault_kinds": ["kill", "hang"],
        "n_functions": len(items),
        "healthy_scored": healthy_scored,
        "poison_quarantined": poison_quarantined,
        "manifest_entries": len(manifest),
        "pool_restarts": restarts,
        "pool_alive": alive,
        "compiles_flat": engine.stats.compiles == compiles0,
        "trace_totals_ok": trace_ok,
        "trace_delta": (None if before is None or after is None else
                        {k: after[k] - before[k] for k in after}),
    }


def _fit_argv(run_dir: str, n_examples: int, epochs: int,
              resume: bool = False) -> List[str]:
    """The ``cli fit`` argv the preempt-drain scenario's subprocesses run:
    the chaos TINY/DATA shapes expressed as --set overrides (the REAL
    training CLI, not a test harness — the SIGTERM lands on exactly what
    production runs)."""
    import sys

    argv = [sys.executable, "-m", "deepdfa_tpu.cli", "fit",
            "--dataset", f"synthetic:{n_examples}",
            "--checkpoint-dir", run_dir,
            "--set", "model.hidden_dim=8", "--set", "model.n_steps=2",
            "--set", "model.num_output_layers=2",
            "--set", f"train.max_epochs={epochs}",
            "--set", "train.learning_rate=0.002", "--set", "train.seed=0",
            "--set", "data.batch_size=16", "--set", "data.eval_batch_size=16",
            "--set", "data.max_nodes_per_graph=64",
            "--set", "data.max_edges_per_node=4",
            "--set", "data.undersample_factor=1.0"]
    if resume:
        argv.append("--resume")
    return argv


def _child_env(process: "str | None" = None, **extra: str) -> Dict[str, str]:
    """Subprocess env for the SIGTERM scenarios' children.

    ``process`` opts the child into the parent's trace plane (ISSUE 14):
    ``DEEPDFA_TRACE_CONTEXT`` rides the env (via the blessed
    ``context.child_env`` helper GL020 polices) so the child's telemetry
    lands as an ``events-<process>-<pid>.jsonl`` shard of the soak's own
    run — its drain spans appear in the parent's merged trace. Children
    whose scenarios audit their OWN run dir (serve_lame_duck) pass no
    process and keep the historic isolated-run behavior; a stale
    inherited payload is scrubbed either way.
    """
    from deepdfa_tpu.telemetry import context as trace_context

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop(inject.ENV_VAR, None)  # each child arms only its own plan
    env.pop(trace_context.ENV_VAR, None)
    if process is not None:
        env = trace_context.child_env(process, base=env)
    env.update(extra)
    return env


def _wait_for_meta_epoch(ckpt_dir: str, epoch: int, timeout_s: float,
                         proc=None) -> bool:
    """Poll the run's checkpoint ``meta.json`` until ``last_epoch >=
    epoch`` — the durable marker that the epoch's snapshots committed.
    THE sync point the SIGTERM scenarios key on: a log line races the
    async writer, but once meta commits, the next epoch's delayed step
    is already holding the loop open."""
    import json as _json
    import time

    path = os.path.join(ckpt_dir, "meta.json")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            return False
        try:
            with open(path, encoding="utf-8") as f:
                if int(_json.load(f).get("last_epoch", -1)) >= epoch:
                    return True
        except (OSError, ValueError):
            pass  # not written yet / mid-replace
        time.sleep(0.05)
    return False


def _read_events(run_dir: str) -> List[Dict[str, Any]]:
    # THE events reader (telemetry/export.py), not a private re-parse:
    # merged over every shard and rotation segment, so a run that
    # rotated (or grew child shards) still audits whole.
    from deepdfa_tpu.telemetry.export import read_run_dir

    events, _shards = read_run_dir(run_dir)
    return events


def _steps_in_epoch0(n_examples: int, n_shards: int = 1) -> int:
    """Step count of the subprocess fit's epoch 0, computed with the SAME
    config/packer the child runs (the fault-plan ordinal anchor).
    ``n_shards`` matches the child's global mesh (the fleet scenario runs
    an 8-shard mesh; every process iterates the same global step count)."""
    from deepdfa_tpu import cli
    from deepdfa_tpu.core.config import (
        DataConfig as DC,
        FlowGNNConfig as MC,
        TrainConfig as TC,
        subkeys_for,
    )
    from deepdfa_tpu.data.sampling import epoch_indices
    from deepdfa_tpu.train.loop import _batches

    model_cfg = MC(hidden_dim=8, n_steps=2, num_output_layers=2)
    data_cfg = DC(batch_size=16, eval_batch_size=16, max_nodes_per_graph=64,
                  max_edges_per_node=4, undersample_factor=1.0)
    train_cfg = TC(seed=0)
    examples, splits = cli.load_dataset(f"synthetic:{n_examples}",
                                        model_cfg.feature,
                                        seed=train_cfg.seed)
    labels = [int(ex["label"]) for ex in examples]
    train_idx = splits["train"]
    idx0 = epoch_indices([labels[i] for i in train_idx], 0,
                         seed=data_cfg.seed,
                         undersample_factor=data_cfg.undersample_factor,
                         oversample_factor=data_cfg.oversample_factor)
    return sum(1 for _ in _batches(examples, train_idx[idx0], data_cfg,
                                   subkeys_for(model_cfg.feature),
                                   data_cfg.batch_size, n_shards))


def scenario_preempt_drain(out_dir: str, n_examples: int,
                           epochs: int) -> Dict[str, Any]:
    """THE preemption acceptance scenario (ISSUE 10): a **real SIGTERM**
    to a mid-epoch ``cli fit`` subprocess. Demands:

    * the child exits with the distinct ``EXIT_PREEMPTED`` code behind a
      committed, verified, step-granular ``preempt_<epoch>_<step>``
      snapshot (an injected ``delay`` at a known step pins where the
      signal lands, so the preemption point is deterministic);
    * the drain is auditable from the child's trace — ``lifecycle.notice``
      (reason SIGTERM), ``lifecycle.preempted``, and a ``lifecycle.drain``
      inside the grace budget;
    * a ``--resume`` run restarts **mid-epoch** from the preempt snapshot
      and its loss history is bit-continuous with the uninterrupted
      reference from the preemption step — the partial epoch is not lost
      (CPU determinism gives exact equality; the tolerance story across
      topology changes is the elastic scenario's);
    * **watchdog phase**: the same SIGTERM landing while a step is wedged
      (injected long delay > the hang deadline) trips ``lifecycle.hang``
      — thread stacks captured into the trace — and the process still
      exits (``EXIT_HANG``) behind a durable emergency snapshot inside
      the grace budget, never a SIGKILLed wedge.
    """
    import json as _json
    import shutil
    import signal as _signal
    import subprocess
    import time

    from deepdfa_tpu import telemetry
    from deepdfa_tpu.resilience import lifecycle

    root = os.path.join(out_dir, "preempt_drain")
    shutil.rmtree(root, ignore_errors=True)
    os.makedirs(root, exist_ok=True)
    steps_ep0 = _steps_in_epoch0(n_examples)

    # Trace plane (ISSUE 14): with the soak's run active, the fit
    # children join it via DEEPDFA_TRACE_CONTEXT — each writes its own
    # shard of THIS run dir, and the drain audit reads the child's spans
    # from the parent's merged trace. Untraced (DEEPDFA_TELEMETRY=0)
    # runs keep the historic child-owned-run-dir behavior.
    active = telemetry.current_run() if telemetry.enabled() else None

    def _child_trace(proc_name: str, own_dir: str) -> List[Dict[str, Any]]:
        if active is not None:
            telemetry.flush()
            return [e for e in _read_events(active.run_dir)
                    if e.get("_process") == proc_name]
        return _read_events(own_dir)

    def history_of(run_dir):
        with open(os.path.join(run_dir, "history.json")) as f:
            return _json.load(f)

    # --- uninterrupted reference ---------------------------------------
    ref_dir = os.path.join(root, "ref")
    ref = subprocess.run(_fit_argv(ref_dir, n_examples, epochs),
                         env=_child_env(), capture_output=True, text=True,
                         timeout=600)
    ref_ok = ref.returncode == 0
    ref_hist = history_of(ref_dir) if ref_ok else {"epochs": []}

    # --- SIGTERM mid-epoch 1 -------------------------------------------
    # The delay pins the landing zone: epoch 1's SECOND step sleeps 10 s
    # (train.loss ordinal steps_ep0 + 1, counted across the run), the
    # parent signals inside that window, the loop finishes the step,
    # polls, and drains at exactly (epoch 1, step 2).
    part_dir = os.path.join(root, "part")
    plan = _json.dumps({"faults": [
        {"site": "train.loss", "kind": "delay", "at": steps_ep0 + 1,
         "seconds": 10.0}]})
    child = subprocess.Popen(
        _fit_argv(part_dir, n_examples, epochs),
        env=_child_env(process="fit-part", DEEPDFA_FAULT_PLAN=plan,
                       DEEPDFA_DRAIN_GRACE_S="60"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    # Sync on epoch 0's committed meta.json: by then the loop is already
    # inside epoch-1 step 2's 10 s injected delay (the boundary poll and
    # fast step 1 ran while the writer was still committing), so the
    # signal lands mid-step deterministically — no fixed-sleep race.
    saw_epoch0 = _wait_for_meta_epoch(part_dir, 0, 300.0, proc=child)
    time.sleep(0.5)
    child.send_signal(_signal.SIGTERM)
    try:
        child_out, child_err = child.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        child.kill()
        child_out, child_err = child.communicate()
    preempt_rc = child.returncode

    from deepdfa_tpu.train.checkpoint import CheckpointManager

    probe = CheckpointManager(part_dir)
    candidate = probe.resume_candidate()
    pinfo = probe.preempt_info(candidate) if candidate else None
    snapshot_verified = bool(candidate and probe.verify(candidate))
    events = _child_trace("fit-part", part_dir)

    def named_events(events, name):
        return [e for e in events if e.get("name") == name]

    notices = named_events(events, "lifecycle.notice")
    drains = named_events(events, "lifecycle.drain")
    grace_s = 60.0
    drain_ms = [float((e.get("attrs") or {}).get("drain_ms", 1e12))
                for e in drains]
    trace_ok = (
        any((e.get("attrs") or {}).get("reason") == "SIGTERM"
            for e in notices)
        and bool(named_events(events, "lifecycle.preempted"))
        and bool(drain_ms) and max(drain_ms) < grace_s * 1e3
    )

    # --- resume: the partial epoch is NOT lost --------------------------
    res = subprocess.run(_fit_argv(part_dir, n_examples, epochs,
                                   resume=True),
                         env=_child_env(), capture_output=True, text=True,
                         timeout=600)
    res_ok = res.returncode == 0
    res_hist = history_of(part_dir) if res_ok else {"epochs": []}
    preempt_epoch = int(pinfo["epoch"]) if pinfo else -1
    tail = ref_hist["epochs"][preempt_epoch:] if preempt_epoch >= 0 else []
    continuity = (
        res_ok and len(res_hist["epochs"]) == len(tail) and bool(tail)
        and all(_records_match(a, b)
                for a, b in zip(res_hist["epochs"], tail))
        and res_hist["best_val_loss"] == ref_hist["best_val_loss"]
    )

    # --- watchdog phase: SIGTERM into a wedged step ---------------------
    hang_dir = os.path.join(root, "hang")
    hang_plan = _json.dumps({"faults": [
        {"site": "train.loss", "kind": "delay", "at": steps_ep0,
         "seconds": 60.0}]})
    hang_child = subprocess.Popen(
        _fit_argv(hang_dir, n_examples, epochs),
        env=_child_env(process="fit-hang", DEEPDFA_FAULT_PLAN=hang_plan,
                       DEEPDFA_DRAIN_GRACE_S="8",
                       DEEPDFA_HANG_DEADLINE_S="2"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    # Same meta-commit sync: epoch-1 step 1's 60 s wedge is already
    # holding the loop when epoch 0's meta lands.
    _wait_for_meta_epoch(hang_dir, 0, 300.0, proc=hang_child)
    time.sleep(0.5)
    t_kill = time.monotonic()
    hang_child.send_signal(_signal.SIGTERM)
    try:
        hang_child.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        hang_child.kill()
        hang_child.communicate()
    hang_rc = hang_child.returncode
    hang_exit_s = time.monotonic() - t_kill
    hang_events = _child_trace("fit-hang", hang_dir)
    hangs = named_events(hang_events, "lifecycle.hang")
    stacks_captured = bool(hangs) and bool(
        (hangs[0].get("attrs") or {}).get("stacks"))
    hang_probe = CheckpointManager(hang_dir)
    hang_candidate = hang_probe.resume_candidate()
    hang_snapshot_ok = bool(hang_candidate
                            and hang_probe.verify(hang_candidate))

    # ONE merged trace.json (the ISSUE 14 acceptance): regenerate the
    # parent run's Perfetto view now that both children's shards are on
    # disk, and assert parent and children render under distinct named
    # processes (M-phase process_name metadata, per-emitter pids).
    merged: Dict[str, Any] = {"checked": False}
    if active is not None:
        from deepdfa_tpu.telemetry.export import write_merged_trace

        telemetry.flush()
        write_merged_trace(active.run_dir)
        with open(os.path.join(active.run_dir, "telemetry",
                               "trace.json")) as f:
            doc = _json.load(f)
        metas = [e for e in doc.get("traceEvents", [])
                 if e.get("ph") == "M" and e.get("name") == "process_name"]
        named = {(m.get("args") or {}).get("name") for m in metas}
        merged = {
            "checked": True,
            "processes": sorted(n for n in named if n),
            "distinct_pids": len({m.get("pid") for m in metas}),
            "parent_and_children":
                {"main", "fit-part", "fit-hang"} <= named,
        }

    ok = bool(
        ref_ok and saw_epoch0
        and preempt_rc == lifecycle.EXIT_PREEMPTED
        and pinfo is not None and int(pinfo["epoch"]) == 1
        and int(pinfo["step"]) >= 1
        and snapshot_verified
        and trace_ok
        and continuity
        and hang_rc == lifecycle.EXIT_HANG
        and stacks_captured
        and hang_snapshot_ok
        and hang_exit_s < 12.0   # well inside grace + teardown margin
        and (not merged["checked"] or merged["parent_and_children"])
    )
    return {
        "ok": ok,
        "fault_kinds": ["sigterm", "delay"],
        "merged_trace": merged,
        "preempt_exit_code": preempt_rc,
        "preempt_snapshot": candidate,
        "preempt_info": pinfo,
        "snapshot_verified": snapshot_verified,
        "trace_ok": trace_ok,
        "drain_ms": max(drain_ms) if drain_ms else None,
        "resume_exit_code": res.returncode,
        "resumed_epochs": [e["epoch"] for e in res_hist["epochs"]],
        "bit_continuous": continuity,
        "continuity_tolerance": 0.0,
        "watchdog": {
            "exit_code": hang_rc,
            "expected": lifecycle.EXIT_HANG,
            "hang_events": len(hangs),
            "stacks_captured": stacks_captured,
            "durable_snapshot": hang_candidate,
            "snapshot_verified": hang_snapshot_ok,
            "exit_after_sigterm_s": round(hang_exit_s, 2),
        },
        "child_stderr_tail": (child_err or "")[-800:],
    }


def scenario_serve_lame_duck(out_dir: str) -> Dict[str, Any]:
    """The serving drain acceptance scenario (ISSUE 10): SIGTERM to a
    live ``cli serve`` subprocess under replay load. Demands:

    * **zero dropped admitted requests**, asserted from the run trace:
      every ``serve.enqueue`` rid has a completed ``serve.request`` span,
      and every in-flight POST returns 200 with scores;
    * lame-duck admission: POSTs after the notice answer **503 +
      Retry-After** while the drain runs, and ``/healthz`` reports
      ``draining``;
    * partially-filled buckets flush **immediately** (the load is sized
      below ``batch_slots`` with a 10 s deadline — answers arriving in
      well under the deadline-flush horizon prove the drain didn't wait
      for it);
    * drain duration under the grace budget and compiles flat after
      warmup, both from the trace; the child exits ``EXIT_PREEMPTED``.

    An injected ``serve.batch`` delay (0.4 s per flush) widens the drain
    window so the 503/healthz probes are deterministic, not a race.
    """
    import json as _json
    import shutil
    import signal as _signal
    import subprocess
    import sys
    import threading
    import time
    import urllib.error
    import urllib.request

    import numpy as np

    from deepdfa_tpu.data.synthetic import synthetic_bigvul
    from deepdfa_tpu.core.config import FlowGNNConfig
    from deepdfa_tpu.resilience import lifecycle
    from deepdfa_tpu.telemetry.report import summarize

    root = os.path.join(out_dir, "serve_lame_duck")
    shutil.rmtree(root, ignore_errors=True)
    os.makedirs(root, exist_ok=True)
    run_dir = os.path.join(root, "run")
    port_file = os.path.join(root, "port")
    grace_s = 30.0
    plan = _json.dumps({"faults": [
        {"site": "serve.batch", "kind": "delay", "every": 1, "times": 0,
         "seconds": 0.4}]})
    argv = [sys.executable, "-m", "deepdfa_tpu.cli", "serve",
            "--port", "0", "--port-file", port_file, "--run-dir", run_dir,
            "--slo", "none", "--batch-slots", "4",
            "--deadline-ms", "10000",
            "--set", "model.hidden_dim=8", "--set", "model.n_steps=2"]
    child = subprocess.Popen(
        argv, env=_child_env(DEEPDFA_FAULT_PLAN=plan,
                             DEEPDFA_DRAIN_GRACE_S=str(grace_s)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

    deadline = time.monotonic() + 300.0
    while not os.path.exists(port_file) and time.monotonic() < deadline \
            and child.poll() is None:
        time.sleep(0.05)
    if not os.path.exists(port_file):
        # A wedged child (warmup hang) must cost this scenario, not the
        # soak: kill it and report, never raise or orphan the subprocess.
        child.kill()
        try:
            out, err = child.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            err = ""
        return {"ok": False, "fault_kinds": ["sigterm"],
                "error": "serve child never bound",
                "child_stderr_tail": (err or "")[-800:]}
    with open(port_file) as f:
        base = f"http://127.0.0.1:{int(f.read().strip())}"

    feature = FlowGNNConfig(hidden_dim=8, n_steps=2).feature
    graphs = synthetic_bigvul(12, feature, positive_fraction=0.5, seed=11)
    payload = [
        {"id": int(g["id"]),
         "graph": {"num_nodes": int(g["num_nodes"]),
                   "senders": np.asarray(g["senders"]).tolist(),
                   "receivers": np.asarray(g["receivers"]).tolist(),
                   "feats": {k: np.asarray(v).tolist()
                             for k, v in g["feats"].items()}}}
        for g in graphs
    ]

    def post(doc, timeout=60.0):
        req = urllib.request.Request(
            f"{base}/score", data=_json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, dict(resp.headers), \
                    _json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), _json.loads(e.read() or b"{}")
        except (urllib.error.URLError, OSError) as e:
            return None, {}, {"error": str(e)}

    # Warm round (also exercises the injected flush delay once); a short
    # per-request deadline so its partial bucket doesn't sit out the
    # load phase's long one.
    warm_status, _, warm_body = post({"functions": payload[:2],
                                      "deadline_ms": 500})

    # Replay load: three 2-function POSTs — partial buckets that would
    # sit until the 5 s deadline-flush without the drain's immediate
    # flush. They block server-side; SIGTERM lands while all are
    # admitted and unanswered.
    results: Dict[int, Any] = {}
    answered_at: Dict[int, float] = {}

    def load_thread(i, chunk):
        results[i] = post({"functions": chunk})
        # The honest answer clock: when THIS admitted request's response
        # landed — not when the parent's probe loop happened to finish.
        answered_at[i] = time.monotonic()

    threads = [threading.Thread(target=load_thread,
                                args=(i, payload[2 + 2 * i: 4 + 2 * i]))
               for i in range(3)]
    t_load = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(0.5)  # admissions land (POST submit is ms; flush is not)
    child.send_signal(_signal.SIGTERM)

    # Deterministic lame-duck probes: the injected flush delay holds the
    # drain open ≥ 0.8 s after the notice materializes.
    saw_503 = saw_retry_after = saw_draining = False
    probe_deadline = time.monotonic() + 10.0
    while time.monotonic() < probe_deadline and not (saw_503
                                                     and saw_draining):
        status, headers, _body = post({"functions": payload[:1]},
                                      timeout=5.0)
        if status == 503:
            saw_503 = True
            saw_retry_after = saw_retry_after or "Retry-After" in headers
        try:
            with urllib.request.urlopen(f"{base}/healthz",
                                        timeout=5.0) as resp:
                hdoc = _json.loads(resp.read())
        except urllib.error.HTTPError as e:
            hdoc = _json.loads(e.read() or b"{}")
        except (urllib.error.URLError, OSError):
            break  # server already gone: drain finished
        if hdoc.get("status") == "draining":
            saw_draining = True
        time.sleep(0.05)

    for t in threads:
        t.join(timeout=grace_s + 30.0)
    answered_s = (max(answered_at.values()) - t_load if answered_at
                  else float("inf"))
    try:
        out, err = child.communicate(timeout=grace_s + 30.0)
    except subprocess.TimeoutExpired:
        child.kill()
        out, err = child.communicate()

    admitted_answered = all(
        results.get(i) and results[i][0] == 200
        and all("prob" in r for r in results[i][2].get("results", []))
        for i in range(3)
    )
    events = _read_events(run_dir)
    rep = summarize(events)
    enq_rids = {(e.get("attrs") or {}).get("rid")
                for e in events if e.get("name") == "serve.enqueue"}
    req_rids = {(e.get("attrs") or {}).get("rid")
                for e in events
                if e.get("kind") == "span"
                and e.get("name") == "serve.request"}
    dropped = sorted(r for r in enq_rids if r not in req_rids)
    drains = [e for e in events if e.get("name") == "lifecycle.drain"
              and (e.get("attrs") or {}).get("participant") == "serve"]
    drain_ms = [float((e.get("attrs") or {}).get("drain_ms", 1e12))
                for e in drains]
    ok = bool(
        warm_status == 200
        and admitted_answered
        and not dropped and enq_rids
        and saw_503 and saw_retry_after and saw_draining
        and child.returncode == lifecycle.EXIT_PREEMPTED
        and drains and all((e.get("attrs") or {}).get("ok")
                           for e in drains)
        and max(drain_ms) < grace_s * 1e3
        and answered_s < 5.0   # never waited out the 10 s deadline flush
        and rep["compiles"]["after_warmup"] == 0
    )
    return {
        "ok": ok,
        "fault_kinds": ["sigterm", "delay"],
        "admitted_answered": admitted_answered,
        "admissions": len(enq_rids),
        "responses": len(req_rids & enq_rids),
        "dropped_rids": dropped[:8],
        "rejected_503": saw_503,
        "retry_after_header": saw_retry_after,
        "healthz_draining": saw_draining,
        "exit_code": child.returncode,
        "drain_ms": max(drain_ms) if drain_ms else None,
        "answered_under_s": round(answered_s, 2),
        "compiles_after_warmup": rep["compiles"]["after_warmup"],
        "child_stderr_tail": (err or "")[-800:],
    }


def scenario_fleet_roll(out_dir: str) -> Dict[str, Any]:
    """The replicated-serving roll scenario (ISSUE 12): one of THREE
    engine replicas takes its per-replica preemption (the in-process
    SIGTERM analog — ``fleet.begin_replica_drain``, the same lame-duck
    machinery a real per-replica signal would drive) in the middle of
    live HTTP load. Demands:

    * **its admitted requests are all answered** — fleet-wide zero
      dropped rids from the trace (every ``serve.enqueue`` has a
      completed ``serve.request`` span), which covers the draining
      replica's bucket;
    * **the other two keep serving** — every load POST during the drain
      returns 200 with scores, and the router never selects the
      draining replica;
    * **fleet /healthz degrades then recovers** — 503 "degraded" with
      the replica marked draining mid-roll, 200 "ok" after restore;
    * **compiles stay flat** — re-entering rotation reuses the warmed
      executables: zero ``jax.compile`` events after the scenario's last
      warmup marker (and engine counters unchanged across the roll).
    """
    import json as _json
    import threading
    import time
    import urllib.error
    import urllib.request

    from deepdfa_tpu import telemetry
    from deepdfa_tpu.models.flowgnn import FlowGNN
    from deepdfa_tpu.serve import ServeConfig, ServeFleet
    from deepdfa_tpu.serve.engine import random_gnn_params
    from deepdfa_tpu.serve.http import ServeHTTPServer

    # Event timestamps are run-relative; the window start must be too
    # (this scenario shares the soak's one run with its siblings).
    active = telemetry.current_run()
    t_window = active.now() if active is not None else 0.0
    config = ServeConfig(batch_slots=4, deadline_ms=500.0, replicas=3,
                         adaptive_flush=True)
    model = FlowGNN(TINY)
    fleet = ServeFleet.build(model, random_gnn_params(model, config),
                             config=config, n_replicas=3)
    fleet.warmup()
    compiles0 = sum(r.engine.stats.compiles for r in fleet.replicas)
    server = ServeHTTPServer(("127.0.0.1", 0), fleet)
    server.start_pump()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    graphs = synthetic_bigvul(32, TINY.feature, positive_fraction=0.5,
                              seed=17)
    payload = [
        {"id": int(g["id"]),
         "graph": {"num_nodes": int(g["num_nodes"]),
                   "senders": np.asarray(g["senders"]).tolist(),
                   "receivers": np.asarray(g["receivers"]).tolist(),
                   "feats": {k: np.asarray(v).tolist()
                             for k, v in g["feats"].items()}}}
        for g in graphs
    ]

    def post(chunk, timeout=30.0):
        req = urllib.request.Request(
            f"{base}/score", data=_json.dumps({"functions": chunk}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, _json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, _json.loads(e.read() or b"{}")

    def healthz():
        try:
            with urllib.request.urlopen(f"{base}/healthz",
                                        timeout=10.0) as resp:
                return resp.status, _json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, _json.loads(e.read() or b"{}")

    # Sustained load: three client threads, two functions per POST —
    # partial buckets in flight across the roll.
    load_results: List[Any] = []
    load_lock = threading.Lock()
    stop_load = threading.Event()

    def load_thread(tid: int):
        i = 0
        while not stop_load.is_set():
            start = (8 * tid + 2 * (i % 4)) % (len(payload) - 2)
            status, body = post(payload[start:start + 2])
            with load_lock:
                load_results.append((status, body))
            i += 1

    threads = [threading.Thread(target=load_thread, args=(tid,))
               for tid in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.6)  # load established, buckets cycling

    victim = "r1"
    fleet.begin_replica_drain(victim, reason="sigterm")
    # Mid-roll: health degrades, the router shuns the victim, and a
    # fresh POST is still answered by the survivors.
    saw_degraded = False
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and not saw_degraded:
        status, doc = healthz()
        if status == 503 and doc.get("status") == "degraded" \
                and doc.get("fleet", {}).get("replicas", {}) \
                        .get(victim, {}).get("status") == "draining":
            saw_degraded = True
        time.sleep(0.02)
    routed_clean = all(fleet.route(f"probe-{i}").rid != victim
                       for i in range(16))
    mid_status, mid_body = post(payload[-2:])
    mid_ok = (mid_status == 200
              and all("prob" in r for r in mid_body.get("results", [])))
    drained = fleet.await_replica_drained(victim, deadline_s=15.0)
    fleet.restore_replica(victim)
    saw_recovered = False
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and not saw_recovered:
        status, doc = healthz()
        if status == 200 and doc.get("status") == "ok" \
                and doc.get("fleet", {}).get("live") == 3:
            saw_recovered = True
        time.sleep(0.02)
    time.sleep(0.3)  # a post-recovery load slice lands on the victim too
    stop_load.set()
    for t in threads:
        t.join(timeout=30.0)
    server.shutdown()

    with load_lock:
        results = list(load_results)
    all_answered = bool(results) and all(
        status == 200 and all("prob" in r for r in body.get("results", []))
        for status, body in results
    )
    compiles1 = sum(r.engine.stats.compiles for r in fleet.replicas)

    # Trace audit (skipped untraced — DEEPDFA_TELEMETRY=0 runs the
    # functional checks alone): zero dropped rids in the scenario
    # window, the drain/restore events present, and zero compiles after
    # the window's last warmup marker.
    trace: Dict[str, Any] = {"checked": False}
    run = telemetry.current_run()
    if run is not None and telemetry.enabled():
        telemetry.flush()
        events = [e for e in _read_events(run.run_dir)
                  if float(e.get("ts", 0.0)) >= t_window]

        # Join admissions to responses on (replica, rid), never bare rid:
        # rids are per-engine counters, so r0's rid 5 completing must not
        # mask r1's rid 5 being dropped.
        def _ids(e):
            attrs = e.get("attrs") or {}
            return (attrs.get("replica"), attrs.get("rid"))

        enq = {_ids(e) for e in events if e.get("name") == "serve.enqueue"}
        done = {_ids(e) for e in events
                if e.get("kind") == "span"
                and e.get("name") == "serve.request"}
        warmups = [float(e["ts"]) for e in events
                   if e.get("name") == "serve.warmup_done"]
        boundary = max(warmups) if warmups else t_window
        late_compiles = [e for e in events
                         if e.get("name") == "jax.compile"
                         and float(e["ts"]) > boundary]
        trace = {
            "checked": True,
            "admissions": len(enq),
            "dropped_rids": sorted(r for r in enq if r not in done)[:8],
            "drain_events": len([e for e in events
                                 if e.get("name") == "fleet.replica_drain"]),
            "restore_events": len([
                e for e in events
                if e.get("name") == "fleet.replica_restore"]),
            "compiles_after_warmup": len(late_compiles),
            "flush_policy_decisions": len([
                e for e in events
                if e.get("name") == "serve.flush_policy"]),
        }

    ok = bool(
        all_answered
        and saw_degraded and saw_recovered
        and routed_clean and mid_ok
        and drained
        and compiles1 == compiles0
        and (not trace["checked"]
             or (not trace["dropped_rids"] and trace["admissions"]
                 and trace["drain_events"] >= 1
                 and trace["restore_events"] >= 1
                 and trace["compiles_after_warmup"] == 0))
    )
    return {
        "ok": ok,
        "fault_kinds": ["replica-sigterm"],
        "replicas": 3,
        "victim": victim,
        "load_posts": len(results),
        "all_answered": all_answered,
        "healthz_degraded": saw_degraded,
        "healthz_recovered": saw_recovered,
        "router_shunned_victim": routed_clean,
        "served_during_drain": mid_ok,
        "victim_drained": drained,
        "compiles_flat": compiles1 == compiles0,
        "trace": trace,
    }


def scenario_proc_crash(out_dir: str) -> Dict[str, Any]:
    """The shared-nothing crash-isolation scenario (ISSUE 17): a real
    **SIGKILL** to one of THREE engine OS processes (each a spawned
    ``cli serve`` child with its own warmed engine) in the middle of
    three-thread live HTTP load through the router tier. Demands:

    * **zero dropped admitted requests** — every load POST the router
      admits is answered 200 with scores: a forward that dies with the
      victim is re-routed to a live sibling, never surfaced to the
      client (scoring is pure, so re-execution is safe);
    * **the router sheds to siblings** — ``/healthz`` degrades (503,
      live < 3) after the kill and routing excludes the dead slot while
      the replacement warms, yet a mid-outage POST still scores;
    * **a warmed replacement rejoins** — ``/healthz`` recovers to 200
      "ok" with 3 live and the victim slot at generation >= 1, with
      zero post-warmup compiles fleet-wide measured THROUGH the router
      (the per-child baseline recorded at spawn);
    * **one merged trace shows the whole story** — ``proc.spawn`` /
      ``proc.dead`` / ``proc.live`` instants across >= 4 distinct
      (process, pid) shard identities, zero ``jax.compile`` after each
      engine shard's last warmup marker, and every *surviving* engine's
      admitted rids completed (the victim's mid-flight admissions are
      exactly the re-routed ones).
    """
    import json as _json
    import signal as _signal
    import threading
    import time
    import urllib.error
    import urllib.request

    from deepdfa_tpu import telemetry
    from deepdfa_tpu.cli import build_configs
    from deepdfa_tpu.serve import ServeConfig
    from deepdfa_tpu.serve.procfleet import ProcFleet
    from deepdfa_tpu.serve.router import RouterHTTPServer

    active = telemetry.current_run()
    t_window = active.now() if active is not None else 0.0
    sets = ["model.hidden_dim=8", "model.n_steps=2"]
    child_args: List[str] = []
    for s in sets:
        child_args += ["--set", s]
    child_args += ["--batch-slots", "4", "--deadline-ms", "500",
                   "--queue-capacity", "64", "--cache-capacity", "512",
                   "--replicas", "1", "--processes", "1", "--slo", "none",
                   # Joined to this run via DEEPDFA_TRACE_CONTEXT (env
                   # wins); the flag covers the untraced-soak case so
                   # children never scatter default run dirs.
                   "--run-dir", os.path.join(out_dir, "proc_crash_children")]
    config = ServeConfig(batch_slots=4, deadline_ms=500.0,
                         queue_capacity=64, cache_capacity=512)
    fleet = ProcFleet(3, child_args=child_args,
                      probe_interval_s=0.25, probe_timeout_s=1.0,
                      probe_failures=2, spawn_deadline_s=240.0,
                      drain_grace_s=5.0)
    fleet.start()
    server = RouterHTTPServer(("127.0.0.1", 0), fleet, config)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    model_cfg = build_configs([], sets)["model"]
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    graphs = synthetic_bigvul(32, model_cfg.feature, positive_fraction=0.5,
                              seed=23)
    payload = [
        {"id": int(g["id"]),
         "graph": {"num_nodes": int(g["num_nodes"]),
                   "senders": np.asarray(g["senders"]).tolist(),
                   "receivers": np.asarray(g["receivers"]).tolist(),
                   "feats": {k: np.asarray(v).tolist()
                             for k, v in g["feats"].items()}}}
        for g in graphs
    ]

    def post(chunk, timeout=90.0):
        req = urllib.request.Request(
            f"{base}/score", data=_json.dumps({"functions": chunk}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, _json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, _json.loads(e.read() or b"{}")
        except (urllib.error.URLError, OSError) as e:
            return None, {"error": str(e)}

    def healthz():
        try:
            with urllib.request.urlopen(f"{base}/healthz",
                                        timeout=10.0) as resp:
                return resp.status, _json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, _json.loads(e.read() or b"{}")

    # Sustained load: three client threads, two functions per POST —
    # partial sub-batches in flight across the kill.
    load_results: List[Any] = []
    load_lock = threading.Lock()
    stop_load = threading.Event()

    def load_thread(tid: int):
        i = 0
        while not stop_load.is_set():
            start = (8 * tid + 2 * (i % 4)) % (len(payload) - 2)
            status, body = post(payload[start:start + 2])
            with load_lock:
                load_results.append((status, body))
            i += 1

    threads = [threading.Thread(target=load_thread, args=(tid,))
               for tid in range(3)]
    for t in threads:
        t.start()
    time.sleep(1.0)  # load established, forwards cycling

    # The SIGKILL: victim pid read from the fleet's own routing table
    # (what /metrics exposes under "processes").
    victim = "p1"
    victim_pid = int(fleet.processes()[victim]["pid"])
    os.kill(victim_pid, _signal.SIGKILL)

    # Shed: /healthz degrades and routing excludes the dead slot while
    # the auto-respawned replacement warms; a fresh POST still scores.
    saw_degraded = False
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and not saw_degraded:
        status, doc = healthz()
        if status == 503 and doc.get("status") == "degraded" \
                and doc.get("live") == 2:
            saw_degraded = True
        time.sleep(0.02)
    routed_clean = all(fleet.route(f"probe-{i}").rid != victim
                       for i in range(16))
    mid_status, mid_body = post(payload[-2:])
    mid_ok = (mid_status == 200
              and all("prob" in r for r in mid_body.get("results", [])))

    # Rejoin: the replacement (generation >= 1) warms and goes live —
    # minutes-scale on a shared CPU, so the deadline is generous.
    saw_recovered = False
    deadline = time.monotonic() + 240.0
    while time.monotonic() < deadline and not saw_recovered:
        status, doc = healthz()
        slot = doc.get("processes", {}).get(victim, {})
        if status == 200 and doc.get("status") == "ok" \
                and doc.get("live") == 3 \
                and int(slot.get("generation", 0)) >= 1:
            saw_recovered = True
        time.sleep(0.1)
    time.sleep(1.0)  # a post-rejoin load slice lands on the replacement too
    stop_load.set()
    for t in threads:
        t.join(timeout=120.0)
    compiles_after = fleet.compiles_after_warmup()
    server.shutdown()
    fleet.shutdown()  # SIGTERM drain: children flush their trace shards

    with load_lock:
        results = list(load_results)
    all_answered = bool(results) and all(
        status == 200 and all("prob" in r for r in body.get("results", []))
        for status, body in results
    )

    # Merged-trace audit (skipped untraced): the kill/shed/rejoin story
    # across real pids, from ONE run's shards.
    trace: Dict[str, Any] = {"checked": False}
    run = telemetry.current_run()
    if run is not None and telemetry.enabled():
        telemetry.flush()
        events = [e for e in _read_events(run.run_dir)
                  if float(e.get("ts", 0.0)) >= t_window]

        def _attr(e, key, default=None):
            return (e.get("attrs") or {}).get(key, default)

        spawns = [e for e in events if e.get("name") == "proc.spawn"]
        deaths = [e for e in events if e.get("name") == "proc.dead"]
        replacement_live = [
            e for e in events if e.get("name") == "proc.live"
            and _attr(e, "proc") == victim
            and int(_attr(e, "generation", 0)) >= 1]
        idents = {(e.get("_process"), e.get("_pid")) for e in events
                  if str(e.get("_process") or "").startswith("engine-")}

        # Per engine shard: compiles only before that shard's own last
        # warmup marker, and (survivors only) every admitted rid has a
        # completed serve.request span. The victim's shard is exempt
        # from the rid join — its mid-flight admissions are exactly the
        # ones the router re-routed.
        by_shard: Dict[Any, List[Dict[str, Any]]] = {}
        for e in events:
            p = e.get("_process")
            if isinstance(p, str) and p.startswith("engine-"):
                by_shard.setdefault((p, e.get("_pid")), []).append(e)
        late_compiles = 0
        admissions = 0
        dropped: List[str] = []
        for (pname, pid), shard in sorted(by_shard.items(),
                                          key=lambda kv: str(kv[0])):
            warmups = [float(e["ts"]) for e in shard
                       if e.get("name") == "serve.warmup_done"]
            boundary = max(warmups) if warmups else t_window
            late_compiles += len([e for e in shard
                                  if e.get("name") == "jax.compile"
                                  and float(e["ts"]) > boundary])
            if pid == victim_pid:
                continue
            enq = {_attr(e, "rid") for e in shard
                   if e.get("name") == "serve.enqueue"}
            done = {_attr(e, "rid") for e in shard
                    if e.get("kind") == "span"
                    and e.get("name") == "serve.request"}
            admissions += len(enq)
            dropped += [f"{pname}:{r}" for r in sorted(
                (r for r in enq - done), key=str)]
        trace = {
            "checked": True,
            "spawns": len(spawns),
            "deaths": len(deaths),
            "death_reasons": sorted({str(_attr(e, "reason"))
                                     for e in deaths}),
            "replacement_live": len(replacement_live),
            "process_identities": len(idents),
            "admissions": admissions,
            "dropped_rids": dropped[:8],
            "compiles_after_warmup_trace": late_compiles,
        }

    ok = bool(
        all_answered
        and saw_degraded and saw_recovered
        and routed_clean and mid_ok
        and compiles_after == 0
        and (not trace["checked"]
             or (trace["spawns"] >= 4
                 and trace["deaths"] >= 1
                 and trace["replacement_live"] >= 1
                 and trace["process_identities"] >= 4
                 and trace["admissions"]
                 and not trace["dropped_rids"]
                 and trace["compiles_after_warmup_trace"] == 0))
    )
    return {
        "ok": ok,
        "fault_kinds": ["sigkill-process"],
        "processes": 3,
        "victim": victim,
        "victim_pid": victim_pid,
        "load_posts": len(results),
        "all_answered": all_answered,
        "healthz_degraded": saw_degraded,
        "healthz_recovered": saw_recovered,
        "router_shunned_victim": routed_clean,
        "served_during_outage": mid_ok,
        "compiles_after_warmup": compiles_after,
        "trace": trace,
    }


def scenario_elastic_shrink(out_dir: str, n_examples: int,
                            epochs: int) -> Dict[str, Any]:
    """THE elastic-fleet acceptance scenario (ISSUE 18): a **real
    SIGTERM** to one of two ``jax.distributed`` training processes
    mid-epoch, then a shrunk 2→1 resume. Demands:

    * the signalled process announces the drain barrier and the
      SURVIVOR follows it — both exit ``EXIT_PREEMPTED`` behind one
      committed 2-process sharded ``preempt_<E>_<S>`` snapshot (the
      coordinated drain, not one orphan and one wedged peer);
    * the choreography is auditable from ONE merged trace: named
      per-host tracks carrying ``lifecycle.drain_barrier`` events —
      ``announce`` from the signalled host, ``observe``/``drain`` from
      both;
    * a single-process ``--resume`` on the same run dir redistributes
      the sharded snapshots 2→1 via the new checkpoint path (audited by
      its ``ckpt.redistribute`` event), restarts MID-epoch, and its
      loss history is continuous with the uninterrupted 2-process
      reference — pre-kill epochs bitwise (identical topology), resumed
      epochs tolerance-bounded (the process-topology change moves the
      cross-shard reduction order; same bound as the reshape story).

    Topology: 2 processes × 4 virtual CPU devices → one 8-shard global
    mesh; resume is 1 process × 8 devices — n_shards stays 8, so the
    step-granular resume cursor and the per-shard packing survive the
    shrink and only the process count changes.
    """
    import json as _json
    import math
    import shutil
    import signal as _signal
    import subprocess
    import time

    from deepdfa_tpu import telemetry
    from deepdfa_tpu.core.hostmesh import cpu_mesh_env
    from deepdfa_tpu.resilience import elastic, lifecycle
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    root = os.path.join(out_dir, "elastic_shrink")
    shutil.rmtree(root, ignore_errors=True)
    os.makedirs(root, exist_ok=True)
    n_shards = 8
    steps_ep0 = _steps_in_epoch0(n_examples, n_shards=n_shards)

    active = telemetry.current_run() if telemetry.enabled() else None

    def history_of(run_dir):
        with open(os.path.join(run_dir, "history.json")) as f:
            return _json.load(f)

    # --- uninterrupted 2-process reference ------------------------------
    # (fleet_member_env scrubs inherited fault plans / trace payloads and
    # re-adds the trace join per member, by name.)
    ref_dir = os.path.join(root, "ref")
    ref_procs = elastic.launch_fleet(
        elastic.fit_argv(ref_dir, n_examples, epochs, n_devices=n_shards),
        process_count=2, n_devices_per_proc=n_shards // 2,
        process_prefix="ref", member_env={
            pi: {"DEEPDFA_DRAIN_GRACE_S": "60"} for pi in range(2)},
    )
    ref_results = elastic.wait_fleet(ref_procs, timeout_s=600)
    ref_ok = [r.get("returncode") for r in ref_results] == [0, 0]
    ref_hist = history_of(ref_dir) if ref_ok else {"epochs": []}

    # --- SIGTERM one of two, mid-epoch 1 --------------------------------
    part_dir = os.path.join(root, "part")
    plan = _json.dumps({"faults": [
        {"site": "train.loss", "kind": "delay", "at": steps_ep0,
         "seconds": 10.0}]})
    member_env = {
        0: {"DEEPDFA_DRAIN_GRACE_S": "60"},
        # The delay pins where the signal lands: epoch 1's FIRST step
        # sleeps 10 s on the to-be-killed member (its peer blocks on the
        # same step's collective), the parent signals into that window.
        # First step, not a later one: the drain target is completed+1,
        # so a signal in the epoch's last step would slip the barrier to
        # the next epoch boundary — legal, but this scenario must prove
        # the MID-epoch drain (preempt_1_<s> with 0 < s < steps).
        1: {"DEEPDFA_DRAIN_GRACE_S": "60", "DEEPDFA_FAULT_PLAN": plan},
    }
    procs = elastic.launch_fleet(
        elastic.fit_argv(part_dir, n_examples, epochs, n_devices=n_shards),
        process_count=2, n_devices_per_proc=n_shards // 2,
        process_prefix="fleet", member_env=member_env,
    )
    saw_epoch0 = _wait_for_meta_epoch(part_dir, 0, 300.0, proc=procs[1])
    time.sleep(0.5)
    t_kill = time.monotonic()
    procs[1].send_signal(_signal.SIGTERM)
    results = elastic.wait_fleet(procs, timeout_s=180)
    drain_wall_s = time.monotonic() - t_kill
    exit_codes = [r.get("returncode") for r in results]
    both_preempted = exit_codes == [lifecycle.EXIT_PREEMPTED,
                                    lifecycle.EXIT_PREEMPTED]

    # --- post-mortem: ONE coordinated sharded preempt snapshot ----------
    probe = CheckpointManager(part_dir)
    candidate = probe.resume_candidate()
    pinfo = probe.preempt_info(candidate) if candidate else None
    snapshot_verified = bool(candidate and probe.verify(candidate))
    rec = (probe.best_meta.get("snapshots", {}) or {}).get(candidate or "",
                                                           {})
    snapshot_sharded_2 = int(rec.get("shards", 1)) == 2
    # The fleet's preempt-time history (pre-kill epochs) — read NOW: the
    # resume below rewrites history.json with the resumed epochs only.
    part_hist = history_of(part_dir) if both_preempted else {"epochs": []}

    # --- choreography audit from the parent's merged trace --------------
    barrier: Dict[str, Any] = {"checked": False}
    if active is not None:
        telemetry.flush()
        events = _read_events(active.run_dir)
        db = [e for e in events if e.get("name") == "lifecycle.drain_barrier"]
        by_phase: Dict[str, set] = {}
        for e in db:
            phase = (e.get("attrs") or {}).get("phase")
            by_phase.setdefault(phase, set()).add(e.get("_process"))
        barrier = {
            "checked": True,
            "events": len(db),
            "announce_from": sorted(by_phase.get("announce", ())),
            "observe_from": sorted(by_phase.get("observe", ())),
            "drain_from": sorted(by_phase.get("drain", ())),
            # The signalled host announces; the survivor observes; BOTH
            # reach the drain phase on their own named tracks.
            "choreography_ok": (
                "fleet1" in by_phase.get("announce", set())
                and "fleet0" in by_phase.get("observe", set())
                and {"fleet0", "fleet1"} <= by_phase.get("drain", set())
            ),
        }

    # --- shrunk resume: 1 process × 8 devices ---------------------------
    env = cpu_mesh_env(_child_env(process="fit-shrunk"), n_shards,
                       force_count=True)
    res = subprocess.run(
        elastic.fit_argv(part_dir, n_examples, epochs, n_devices=n_shards,
                         resume=True),
        env=env, capture_output=True, text=True, timeout=600)
    res_ok = res.returncode == 0
    res_hist = history_of(part_dir) if res_ok else {"epochs": []}
    meta_after = CheckpointManager(part_dir).best_meta
    snaps_after = meta_after.get("snapshots", {})
    all_plain_after = all("shards" not in r for r in snaps_after.values())

    redistributed = False
    if active is not None:
        telemetry.flush()
        redist = [
            (e.get("attrs") or {})
            for e in _read_events(active.run_dir)
            if e.get("name") == "ckpt.redistribute"
            and e.get("_process") == "fit-shrunk"
            and "strategy" in (e.get("attrs") or {})
        ]
        redistributed = bool(redist) and redist[0]["from_processes"] == 2 \
            and redist[0]["to_processes"] == 1
    else:
        # Untraced runs: the on-disk rewrite is the evidence.
        redistributed = all_plain_after

    # --- loss continuity -------------------------------------------------
    # Pre-kill epochs ran on the identical 2-process topology: bitwise.
    preempt_epoch = int(pinfo["epoch"]) if pinfo else -1
    pre_kill = part_hist["epochs"][:preempt_epoch] if preempt_epoch >= 0 \
        else []
    pre_kill_bitwise = (
        bool(pre_kill)
        and all(_records_match(a, b)
                for a, b in zip(pre_kill, ref_hist["epochs"]))
    )
    # Resumed epochs re-run the preempted epoch onward on the shrunk
    # process topology: same 8-shard packing, but the cross-process
    # reduction became a single-process one — bounded drift, not
    # bit-equality (the documented elastic tolerance).
    tail = ref_hist["epochs"][preempt_epoch:] if preempt_epoch >= 0 else []
    resumed = res_hist["epochs"]
    deltas = [
        abs(a[k] - b[k]) / max(abs(b[k]), 1e-12)
        for a, b in zip(resumed, tail) for k in ("train_loss", "val_loss")
        if math.isfinite(a[k]) and math.isfinite(b[k])
    ]
    max_rel_delta = max(deltas) if deltas else float("inf")
    tolerance = 2e-3
    continuity = (
        bool(tail) and len(resumed) == len(tail)
        and [e["epoch"] for e in resumed] == [e["epoch"] for e in tail]
        and max_rel_delta <= tolerance
    )

    ok = bool(
        ref_ok and saw_epoch0
        and both_preempted
        and drain_wall_s < 75.0          # grace + one fenced step + margin
        and pinfo is not None and int(pinfo["epoch"]) == 1
        and int(pinfo.get("seen", 0)) > 0   # genuinely MID-epoch
        and snapshot_verified
        and snapshot_sharded_2
        and (not barrier["checked"] or barrier["choreography_ok"])
        and res_ok
        and redistributed
        and all_plain_after
        and pre_kill_bitwise
        and continuity
    )
    return {
        "ok": ok,
        "fault_kinds": ["sigterm", "delay"],
        "fleet_exit_codes": exit_codes,
        "drain_wall_s": round(drain_wall_s, 2),
        "preempt_snapshot": candidate,
        "preempt_info": pinfo,
        "snapshot_verified": snapshot_verified,
        "snapshot_sharded_2": snapshot_sharded_2,
        "drain_barrier": barrier,
        "resume_exit_code": res.returncode,
        "redistributed": redistributed,
        "snapshots_plain_after_resume": all_plain_after,
        "pre_kill_bitwise": pre_kill_bitwise,
        "resumed_epochs": [e["epoch"] for e in resumed],
        "continuity": continuity,
        "continuity_tolerance": tolerance,
        "max_rel_loss_delta": max_rel_delta,
        "fleet_stderr_tail": {
            i: (r.get("stderr") or "")[-800:]
            for i, r in enumerate(results)
            if r.get("returncode") != lifecycle.EXIT_PREEMPTED
        },
    }


def run_soak(out_dir: str = "runs/chaos", n_examples: int = 48,
             epochs: int = 3) -> Dict[str, Any]:
    """All scenarios, one report. ``ok`` only when every scenario passed;
    ``fault_kinds`` lists the distinct injected fault kinds exercised."""
    os.makedirs(out_dir, exist_ok=True)
    scenarios: Dict[str, Dict[str, Any]] = {}
    scenarios["preempt_resume"] = scenario_preempt_resume(
        out_dir, n_examples, epochs)
    scenarios["nan_rollback"] = scenario_nan_rollback(n_examples, epochs)
    scenarios["corrupt_restore"] = scenario_corrupt_restore(
        out_dir, n_examples, epochs)
    scenarios["etl_retry"] = scenario_etl_retry()
    scenarios["serve_flush_fault"] = scenario_serve_flush_fault()
    scenarios["poison_corpus"] = scenario_poison_corpus(
        out_dir, n_examples, epochs)
    scenarios["elastic_resume"] = scenario_elastic_resume(
        out_dir, n_examples, epochs)
    scenarios["scan_joern_deaths"] = scenario_scan_joern_deaths(out_dir)
    scenarios["preempt_drain"] = scenario_preempt_drain(
        out_dir, n_examples, epochs)
    scenarios["serve_lame_duck"] = scenario_serve_lame_duck(out_dir)
    scenarios["fleet_roll"] = scenario_fleet_roll(out_dir)
    scenarios["proc_crash"] = scenario_proc_crash(out_dir)
    scenarios["elastic_shrink"] = scenario_elastic_shrink(
        out_dir, n_examples, epochs)

    kind_of = {"preempt_resume": "preempt-raise",
               "nan_rollback": "nan-loss",
               "corrupt_restore": "checkpoint-corrupt",
               "etl_retry": "etl-item-raise",
               "serve_flush_fault": "serve-batch-raise",
               "poison_corpus": "data-corrupt",
               "elastic_resume": "elastic-reshape",
               "scan_joern_deaths": "joern-worker-kill",
               "preempt_drain": "sigterm-drain",
               "serve_lame_duck": "sigterm-lame-duck",
               "fleet_roll": "replica-roll",
               "proc_crash": "sigkill-process",
               "elastic_shrink": "sigterm-fleet-drain"}
    kinds: List[str] = sorted(kind_of[name] for name in scenarios)
    ok = all(res["ok"] for res in scenarios.values())
    return {
        "ok": ok,
        "distinct_fault_kinds": kinds,
        "n_fault_kinds": len(kinds),
        "scenarios": scenarios,
        "exit_code": 0 if ok else 1,
    }

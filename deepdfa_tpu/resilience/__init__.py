"""Resilience layer: deterministic fault injection, chaos soak, and the
shared recovery utilities the training/ETL/serving stacks build on.

The north-star hardware (preemptible TPU v5e) makes failure the common
case, not the exception: preemptions land mid-checkpoint, numerics blow
up ten hours into a run, Joern JVMs hang, ETL workers die. This package
supplies the two halves of surviving that:

* ``inject`` — a seeded, declarative fault-injection framework. Tests,
  the ``cli chaos`` soak, and ad-hoc debugging arm a *fault plan* (JSON,
  via env var or programmatically) and the instrumented sites across the
  codebase fire those faults deterministically.
* ``chaos`` — the end-to-end soak scenarios behind ``cli chaos``:
  preempt-and-resume determinism, NaN-loss rollback, corrupt-checkpoint
  fallback, ETL retry, serving flush isolation, and the real-SIGTERM
  drains (``preempt_drain``, ``serve_lame_duck``).
* ``lifecycle`` — the preemption-notice lifecycle (ISSUE 10): SIGTERM/
  SIGINT (or a fault-injected simulation) becomes a typed
  ``PreemptionNotice`` broadcast to registered drain participants under
  a global grace budget, policed by the hung-step watchdog; training
  exits ``EXIT_PREEMPTED`` behind a step-granular ``preempt_*``
  snapshot, serving lame-ducks, the scan pool drains.

Recovery itself lives where the work lives (``train/checkpoint.py``,
``train/loop.py``, ``core/retry.py``, ``etl/*``, ``serve/engine.py``);
this package only *provokes* and *verifies* it.
"""

from deepdfa_tpu.resilience.inject import (  # noqa: F401
    ENV_VAR,
    FaultError,
    FaultPlan,
    FaultSpec,
    armed,
    clear,
    corrupt_loss,
    corrupt_path,
    fire,
    install,
)

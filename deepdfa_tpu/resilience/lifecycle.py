"""Process-wide preemption lifecycle: SIGTERM → typed notice → graceful drain.

The target deployment is preemptible TPU capacity: the scheduler delivers
SIGTERM and a grace window, then SIGKILL. This module turns that signal
(or SIGINT, or a *simulated* preemption injected through the fault
framework — the hermetic-test path) into a typed
:class:`PreemptionNotice` that every long-running surface consumes on its
own main path:

* **train** — the three loops poll :func:`poll` at step granularity,
  snapshot ``preempt_<epoch>_<step>`` (step-level resume state), drain
  the async checkpoint writer, and exit with :data:`EXIT_PREEMPTED`.
* **serve** — lame-duck mode: admission answers 503 + ``Retry-After``,
  the batcher flushes partially-filled buckets immediately, every
  already-admitted request is answered before exit.
* **scan** — the Joern pool stops dispatch, finishes in-flight items,
  shuts workers down via the session protocol, flushes the verdict cache.

Design constraints this module owns:

* **The signal handler only sets a flag.** Handlers run between
  bytecodes on the main thread; blocking work there (I/O, locks, jit
  dispatch) deadlocks or re-enters — the hazard class graftlint GL017
  ``unsafe-signal-handler`` flags. The handler body is a single
  attribute assignment; the notice object is materialized on the main
  path (:func:`poll`) or by the monitor thread, whichever runs first.
* **A wedged step can't eat the grace window.** On notice, a thread-based
  hung-step watchdog arms: participants heartbeat via
  :meth:`LifecycleCoordinator.beat` as they make drain progress; a
  wedged device/JVM (no beat inside the hang deadline) or a global
  grace overrun triggers ``lifecycle.hang`` — thread stacks captured
  into the trace — then the registered emergency ``on_hang`` hooks
  (the train loop's saves a preempt snapshot of the last completed
  step) and a forced exit with :data:`EXIT_HANG`. Never a wedged
  process.
* **Everything is auditable.** ``lifecycle.notice`` / ``lifecycle.drain``
  / ``lifecycle.hang`` / ``lifecycle.lame_duck`` events ride the shared
  telemetry run, summarized by ``cli trace report`` (the ``lifecycle``
  section).

Knobs: ``DEEPDFA_DRAIN_GRACE_S`` (global grace budget, default 30 s —
the v5e preemption notice is 30+ s), ``DEEPDFA_HANG_DEADLINE_S``
(watchdog no-progress deadline inside the grace budget, default
``grace/2``). Per-participant deadlines are clamped inside the global
budget at registration.

Fault site: ``lifecycle.preempt`` — any matching (non-raising) spec at
the site simulates a TPU preemption notice, so chaos/tier-1 tests drive
the full drain machinery without a real signal:

.. code-block:: json

    {"faults": [{"site": "lifecycle.preempt", "kind": "kill", "at": 7}]}
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepdfa_tpu import telemetry
from deepdfa_tpu.resilience import inject

logger = logging.getLogger(__name__)

GRACE_ENV_VAR = "DEEPDFA_DRAIN_GRACE_S"
HANG_ENV_VAR = "DEEPDFA_HANG_DEADLINE_S"
DEFAULT_GRACE_S = 30.0

# Distinct exit codes, so orchestrators (and the chaos scenarios) can tell
# a graceful preemption drain from a crash — and a watchdog-forced exit
# from a clean one. 75 is EX_TEMPFAIL ("try again later"), the
# conventional preemption posture.
EXIT_PREEMPTED = 75
EXIT_HANG = 76

# Monitor cadence: how often the daemon thread converts a pending signal
# flag into a notice when the main path isn't polling (a serve process
# blocked in its selector), and the watchdog check tick.
_MONITOR_TICK_S = 0.02


class Preempted(BaseException):
    """Raised by a training loop after it drained for a preemption notice.

    Derives from BaseException on purpose: a preemption drain must unwind
    past ``except Exception`` recovery layers (retry wrappers, anomaly
    policies) that would otherwise swallow the exit. Carries what the
    caller needs to report and resume."""

    def __init__(self, notice: "PreemptionNotice", snapshot: Optional[str],
                 epoch: int, step: int, history: Optional[dict] = None):
        super().__init__(
            f"preempted ({notice.reason}) at epoch {epoch} step {step}; "
            f"snapshot {snapshot!r}"
        )
        self.notice = notice
        self.snapshot = snapshot
        self.epoch = epoch
        self.step = step
        self.history = history


@dataclasses.dataclass(frozen=True)
class PreemptionNotice:
    """One preemption notice: why, when, and how long the process has."""

    reason: str          # "SIGTERM" | "SIGINT" | "simulated"
    received_at: float   # time.monotonic() seconds
    grace_s: float       # global drain budget from receipt

    @property
    def deadline(self) -> float:
        return self.received_at + self.grace_s

    def remaining(self) -> float:
        return max(self.deadline - time.monotonic(), 0.0)


def grace_budget_s() -> float:
    """The global drain budget (``DEEPDFA_DRAIN_GRACE_S``)."""
    try:
        return max(float(os.environ.get(GRACE_ENV_VAR, DEFAULT_GRACE_S)),
                   0.1)
    except ValueError:
        return DEFAULT_GRACE_S


def hang_deadline_s(grace: float) -> float:
    """Watchdog no-progress deadline (``DEEPDFA_HANG_DEADLINE_S``,
    default half the grace budget): a drain that makes no heartbeat for
    this long is wedged, and waiting out the rest of the grace window
    would only convert a recoverable snapshot into a SIGKILL."""
    raw = os.environ.get(HANG_ENV_VAR)
    if raw:
        try:
            return max(float(raw), 0.05)
        except ValueError:
            pass
    return max(grace / 2.0, 0.05)


class Participant:
    """One registered drain participant.

    ``deadline_s`` is the per-component share of the global grace budget
    (clamped to it). ``on_notice`` runs on the monitor thread when the
    notice fires — use it for surfaces that block outside a step loop
    (the HTTP server); polling surfaces (train loops) ignore it.
    ``on_hang`` runs on the watchdog thread right before a forced exit —
    the emergency-snapshot hook."""

    def __init__(self, coordinator: "LifecycleCoordinator", name: str,
                 deadline_s: float,
                 on_notice: Optional[Callable[[PreemptionNotice], None]],
                 on_hang: Optional[Callable[[PreemptionNotice], None]]):
        self._coordinator = coordinator
        self.name = name
        self.deadline_s = deadline_s
        self.on_notice = on_notice
        self.on_hang = on_hang
        self.drain_started: Optional[float] = None
        self.drain_ms: Optional[float] = None
        self.drain_ok: Optional[bool] = None

    def beat(self) -> None:
        """Heartbeat: this participant is making drain progress."""
        self._coordinator.beat()

    def drained(self, ok: bool = True) -> None:
        """Mark this participant's drain complete (audited as a
        ``lifecycle.drain`` event carrying the measured duration)."""
        self._coordinator._mark_drained(self, ok)


class LifecycleCoordinator:
    """Converts SIGTERM/SIGINT (or a simulated notice) into one
    process-wide :class:`PreemptionNotice` broadcast to registered drain
    participants, and polices the drain with the hung-step watchdog.

    One instance per process (module-level :func:`coordinator`); tests
    build private instances with short budgets and a captured ``_exit``.
    """

    def __init__(self, grace_s: Optional[float] = None,
                 hang_s: Optional[float] = None,
                 _exit: Callable[[int], None] = os._exit):
        self._grace_s = grace_s
        self._hang_s = hang_s
        self._exit = _exit
        self._lock = threading.Lock()
        self._participants: List[Participant] = []
        # Written ONLY by the signal handler (a single attribute
        # assignment — the GL017-clean handler body); consumed by poll()
        # on the main path or the monitor thread, whichever runs first.
        self._pending_signal: Optional[int] = None
        self._notice: Optional[PreemptionNotice] = None
        self._notice_event = threading.Event()
        self._last_beat = 0.0
        self._complete = threading.Event()
        self._installed: Dict[int, Any] = {}
        self._monitor: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self.hang_fired = False

    # -- signal plumbing ---------------------------------------------------

    def _handler(self, signum, frame) -> None:
        # Flag only. Anything heavier (locks, I/O, telemetry, jit) in a
        # signal handler is the GL017 hazard this module documents.
        self._pending_signal = signum

    def install(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                  signal.SIGINT)) -> bool:
        """Install the flag-setting handlers + the monitor thread.
        Idempotent; returns False (and stays uninstalled) when not on the
        main thread — ``signal.signal`` is main-thread-only."""
        if threading.current_thread() is not threading.main_thread():
            logger.warning("lifecycle: install skipped (not main thread)")
            return False
        with self._lock:
            for sig in signals:
                if sig not in self._installed:
                    self._installed[sig] = signal.signal(sig, self._handler)
        self._ensure_monitor()
        return True

    def uninstall(self) -> None:
        """Restore the previous handlers (bench/test hygiene)."""
        if threading.current_thread() is not threading.main_thread():
            return
        with self._lock:
            installed, self._installed = self._installed, {}
        for sig, prev in installed.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # interpreter teardown
                pass

    def _ensure_monitor(self) -> None:
        with self._lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="lifecycle-monitor",
                daemon=True)
            self._monitor.start()

    def _monitor_loop(self) -> None:
        # Converts a pending signal flag into the notice for processes
        # whose main thread is blocked (serve's selector loop, a wedged
        # step). The main path's poll() usually wins the race; both
        # funnel through _materialize, which is idempotent.
        while not self._complete.is_set():
            pending = self._pending_signal
            if pending is not None and self._notice is None:
                self._materialize(_signal_name(pending))
            self._complete.wait(_MONITOR_TICK_S)

    # -- notice creation ---------------------------------------------------

    def _materialize(self, reason: str) -> PreemptionNotice:
        run_callbacks: List[Participant] = []
        created = False
        with self._lock:
            if self._notice is None:
                created = True
                grace = (self._grace_s if self._grace_s is not None
                         else grace_budget_s())
                self._notice = PreemptionNotice(
                    reason=reason, received_at=time.monotonic(),
                    grace_s=grace)
                self._last_beat = self._notice.received_at
                for p in self._participants:
                    p.drain_started = self._notice.received_at
                run_callbacks = list(self._participants)
        notice = self._notice
        if created:
            self._notice_event.set()
            logger.warning(
                "lifecycle: preemption notice (%s); draining %d "
                "participant(s) inside a %.1fs grace budget",
                notice.reason, len(run_callbacks), notice.grace_s)
            telemetry.event("lifecycle.notice", reason=notice.reason,
                            grace_s=notice.grace_s,
                            participants=[p.name for p in run_callbacks])
            # Armed even with no participants (library use without
            # registration): a wedged process must never outlive the
            # grace window silently.
            self._start_watchdog()
            for p in run_callbacks:
                if p.on_notice is not None:
                    try:
                        p.on_notice(notice)
                    except Exception:
                        logger.exception(
                            "lifecycle: %s on_notice failed", p.name)
        return notice

    def notify(self, reason: str = "simulated") -> PreemptionNotice:
        """Programmatic preemption notice — the simulated-TPU path and
        the fault framework's entry."""
        return self._materialize(reason)

    # -- the main-path hooks ----------------------------------------------

    def poll(self, index: Optional[int] = None) -> Optional[PreemptionNotice]:
        """The step-granularity check: cheap when nothing is pending (one
        flag read + the fault-site no-op). Fires the ``lifecycle.preempt``
        fault site — a matching spec simulates a preemption notice."""
        for _spec in inject.fire("lifecycle.preempt", index=index):
            # Any non-raising matching kind at this site IS the simulated
            # notice; which kind was used doesn't matter.
            return self.notify("simulated")
        pending = self._pending_signal
        if pending is not None and self._notice is None:
            return self._materialize(_signal_name(pending))
        return self._notice

    @property
    def notice(self) -> Optional[PreemptionNotice]:
        return self._notice

    def wait(self, timeout: Optional[float] = None) -> Optional[PreemptionNotice]:
        """Block until a notice exists (monitor-thread delivery)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._notice is None:
            if deadline is not None and time.monotonic() >= deadline:
                return None
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            self._notice_event.wait(min(_MONITOR_TICK_S,
                                        remaining or _MONITOR_TICK_S))
        return self._notice

    def beat(self) -> None:
        """Watchdog heartbeat: drain progress happened."""
        self._last_beat = time.monotonic()

    # -- participants ------------------------------------------------------

    def register(self, name: str,
                 on_notice: Optional[Callable[[PreemptionNotice], None]] = None,
                 on_hang: Optional[Callable[[PreemptionNotice], None]] = None,
                 deadline_s: Optional[float] = None) -> Participant:
        """Register a drain participant. ``deadline_s`` is clamped inside
        the global grace budget — a component can narrow its share, never
        widen the window."""
        grace = self._grace_s if self._grace_s is not None else grace_budget_s()
        share = grace if deadline_s is None else min(float(deadline_s), grace)
        p = Participant(self, name, share, on_notice, on_hang)
        with self._lock:
            self._participants.append(p)
            pending = self._notice
        if pending is not None:
            # Late registration during an active notice: deliver — on a
            # separate thread, never synchronously. A registrant whose
            # on_notice ultimately blocks on work the registering thread
            # hasn't started yet (serve_forever registers, THEN serves;
            # its callback calls server.shutdown(), which waits for
            # serve_forever to run) would otherwise deadlock the exact
            # drain this module exists to guarantee.
            p.drain_started = time.monotonic()
            if p.on_notice is not None:
                def _deliver():
                    try:
                        p.on_notice(pending)
                    except Exception:
                        logger.exception("lifecycle: %s on_notice failed",
                                         name)

                threading.Thread(target=_deliver,
                                 name=f"lifecycle-notify:{name}",
                                 daemon=True).start()
        return p

    def unregister(self, participant: Participant) -> None:
        with self._lock:
            if participant in self._participants:
                self._participants.remove(participant)

    def _mark_drained(self, participant: Participant, ok: bool) -> None:
        self.beat()
        now = time.monotonic()
        start = participant.drain_started
        if start is None and self._notice is not None:
            start = self._notice.received_at
        participant.drain_ms = ((now - start) * 1e3
                                if start is not None else 0.0)
        participant.drain_ok = ok
        telemetry.event("lifecycle.drain", participant=participant.name,
                        ok=ok, drain_ms=participant.drain_ms,
                        deadline_s=participant.deadline_s)
        telemetry.REGISTRY.histogram("lifecycle_drain_ms").observe(
            participant.drain_ms)
        with self._lock:
            pending = [p for p in self._participants
                       if p.drain_ok is None]
        if not pending and self._notice is not None:
            self.complete()

    def complete(self) -> None:
        """Declare the drain finished: the watchdog stands down."""
        if not self._complete.is_set():
            self._complete.set()
            if self._notice is not None:
                telemetry.event(
                    "lifecycle.exit", reason=self._notice.reason,
                    drain_s=time.monotonic() - self._notice.received_at)

    # -- the hung-step watchdog --------------------------------------------

    def _start_watchdog(self) -> None:
        with self._lock:
            if self._watchdog is not None and self._watchdog.is_alive():
                return
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="lifecycle-watchdog",
                daemon=True)
            self._watchdog.start()

    def _watchdog_loop(self) -> None:
        notice = self._notice
        if notice is None:
            return
        grace = notice.grace_s
        hang = (self._hang_s if self._hang_s is not None
                else hang_deadline_s(grace))
        while not self._complete.wait(_MONITOR_TICK_S):
            now = time.monotonic()
            silent = now - self._last_beat
            overrun = now - notice.received_at - grace
            if silent >= hang or overrun >= 0:
                why = "no_progress" if silent >= hang else "grace_exceeded"
                self._on_hang(notice, why, silent)
                return

    def _on_hang(self, notice: PreemptionNotice, why: str,
                 silent_s: float) -> None:
        """The forced-exit path: a wedged step/flush (or a drain that
        overran the grace budget) must still leave a durable snapshot and
        a diagnosable trace, then exit — never a wedged process that eats
        the whole window and gets SIGKILLed mid-write."""
        self.hang_fired = True
        stacks = _thread_stacks()
        logger.error(
            "lifecycle: hung drain (%s; %.2fs without progress) — forcing "
            "the snapshot/exit path.\n%s", why, silent_s,
            "\n".join(stacks.values()))
        telemetry.event("lifecycle.hang", reason=notice.reason, why=why,
                        silent_s=silent_s, stacks=list(stacks.values()))
        with self._lock:
            hooks = [p for p in self._participants if p.on_hang is not None]
        for p in hooks:
            try:
                p.on_hang(notice)
            except Exception:
                logger.exception("lifecycle: %s on_hang failed", p.name)
        telemetry.event("lifecycle.exit", reason=notice.reason,
                        forced=True,
                        drain_s=time.monotonic() - notice.received_at)
        try:
            telemetry.flush()
        except Exception:
            pass
        self._complete.set()
        self._exit(EXIT_HANG)


def _signal_name(signum: int) -> str:
    try:
        return signal.Signals(signum).name
    except ValueError:
        return f"signal_{signum}"


def _thread_stacks() -> Dict[str, str]:
    """One formatted stack per live thread — the lifecycle.hang payload
    that makes a wedged device/JVM diagnosable post-mortem."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, str] = {}
    for tid, frame in sys._current_frames().items():
        name = names.get(tid, f"tid-{tid}")
        out[name] = (f"--- thread {name} ---\n"
                     + "".join(traceback.format_stack(frame)))
    return out


# ---------------------------------------------------------------------------
# The process-wide instance
# ---------------------------------------------------------------------------

_COORDINATOR: Optional[LifecycleCoordinator] = None
_COORD_LOCK = threading.Lock()


def coordinator() -> LifecycleCoordinator:
    """THE process-wide coordinator (created lazily; signal handlers are
    installed only by explicit :meth:`LifecycleCoordinator.install` —
    importing this module never touches signal state)."""
    global _COORDINATOR
    with _COORD_LOCK:
        if _COORDINATOR is None:
            _COORDINATOR = LifecycleCoordinator()
        return _COORDINATOR


def reset(instance: Optional[LifecycleCoordinator] = None) -> None:
    """Swap the process-wide coordinator (tests/bench). Uninstalls the
    old one's handlers."""
    global _COORDINATOR
    with _COORD_LOCK:
        old, _COORDINATOR = _COORDINATOR, instance
    if old is not None:
        old.complete()
        old.uninstall()


def fresh(install_signals: bool = True) -> LifecycleCoordinator:
    """A fresh process-wide coordinator for a new CLI command: clears any
    consumed notice from a previous in-process invocation (tests drive
    ``cli.main`` repeatedly in one process) and installs the SIGTERM/
    SIGINT handlers when on the main thread."""
    reset(LifecycleCoordinator())
    co = coordinator()
    if install_signals:
        co.install()
    return co


def poll(index: Optional[int] = None) -> Optional[PreemptionNotice]:
    """Module-level step check (the loops' one-liner): cheap no-op when
    no coordinator exists, no plan is armed, and no signal landed."""
    co = _COORDINATOR
    if co is None:
        # Without a live coordinator the fault site must still work — a
        # tier-1 test arming lifecycle.preempt expects the simulated
        # notice machinery end to end.
        if inject.active() is None:
            return None
        co = coordinator()
    return co.poll(index)


def drain_with_beats(checkpointer, notice: PreemptionNotice,
                     co: LifecycleCoordinator, slice_s: float = 1.0) -> None:
    """Drain the checkpoint writer in heartbeat-sized slices: a slow but
    live snapshot write must read as drain *progress*, not a wedge — the
    hang deadline exists for silent device/JVM wedges, while the global
    grace overrun (which the watchdog enforces independently) stays the
    honest ceiling on a genuinely stuck write. Raises TimeoutError when
    the grace budget runs out with writes still pending."""
    while True:
        remaining = notice.remaining()
        try:
            checkpointer.drain(timeout=min(slice_s, max(remaining, 0.1)))
            return
        except TimeoutError:
            co.beat()
            if notice.remaining() <= 0:
                raise


def preempt_snapshot_exit(notice: PreemptionNotice, checkpointer, state,
                          epoch: int, step: int,
                          history: Optional[dict] = None,
                          resume: Optional[dict] = None,
                          participant: Optional[Participant] = None,
                          **attrs) -> None:
    """The shared train-loop drain: one immediate ``preempt_<epoch>_<step>``
    snapshot, the checkpoint writer drained inside the remaining grace,
    the audit events flushed, then the typed :class:`Preempted` exit.
    ``checkpointer=None`` (an un-checkpointed fit) still exits typed —
    there is just nothing durable to leave behind. Never returns."""
    co = coordinator()
    co.beat()
    snapshot = None
    if checkpointer is not None:
        with telemetry.span("lifecycle.snapshot", epoch=int(epoch),
                            step=int(step)):
            snapshot = checkpointer.save_preempt(state, epoch, step,
                                                 resume=resume or {})
            co.beat()
            try:
                drain_with_beats(checkpointer, notice, co)
            except TimeoutError:
                # A drain overrun must not turn the typed preemption exit
                # into a crash: the bytes may still commit behind us, and
                # the orchestrator contract is EXIT_PREEMPTED either way.
                logger.error(
                    "lifecycle: preempt snapshot drain overran the grace "
                    "budget; exiting preempted with the write in flight")
                telemetry.event("lifecycle.drain_timeout",
                                snapshot=snapshot)
        co.beat()
    telemetry.event("lifecycle.preempted", epoch=int(epoch),
                    step=int(step), snapshot=snapshot,
                    reason=notice.reason, **attrs)
    telemetry.flush()
    if participant is not None:
        participant.drained(ok=True)
    raise Preempted(notice, snapshot, int(epoch), int(step), history)


# ---------------------------------------------------------------------------
# Coordinated fleet preemption drain (ISSUE 18)
# ---------------------------------------------------------------------------

FLEET_DRAIN_FILE = "FLEET_DRAIN.json"
FLEET_CLEAR_WAIT_S = 30.0


class FleetDrain:
    """Filesystem drain barrier for a multi-controller training fleet.

    One host's SIGTERM must not strand the others inside a collective:
    the notified process *announces* a drain target — the next step
    boundary, ``(epoch, step+1)`` — by atomically creating
    ``FLEET_DRAIN.json`` under the shared run dir (the same rendezvous
    discipline as the serve router's port files), then keeps
    participating until the target. Every process checks the file
    before dispatching each step and drains at exactly the target, so
    all ``preempt_<E>_<S>`` shards describe the same state and every
    process exits :data:`EXIT_PREEMPTED`.

    Why "one more step": with per-step dispatch fencing (the train loop
    blocks on each step's loss when a fleet is live) a peer can have
    dispatched at most one step beyond the announcer's completed step,
    and the announcer writes the file BEFORE dispatching that step
    itself — so by the time any peer completes the target step the file
    is already visible, and nobody ever dispatches a collective the
    rest of the fleet will not join. First writer wins when two hosts
    are signalled at once (atomic ``os.link`` create-if-absent); the
    loser follows the existing target, which is within one step of its
    own by the same fencing argument.

    Every phase is auditable from the merged trace:
    ``lifecycle.drain_barrier`` events with ``phase="announce"`` /
    ``"observe"`` / ``"drain"`` carry the process index, so ``cli trace
    report`` reconstructs the choreography per host.
    """

    def __init__(self, directory: str, process_index: int,
                 process_count: int):
        self.directory = os.path.abspath(directory)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.path = os.path.join(self.directory, FLEET_DRAIN_FILE)
        self._announced = False
        self._target: Optional[Dict[str, Any]] = None
        self._observed = False

    def clear(self, timeout_s: float = FLEET_CLEAR_WAIT_S) -> None:
        """Start-of-fit hygiene: the primary removes a drain file left by
        the run being resumed (it would otherwise read as an instantly
        reached target); peers wait for the removal so no process can
        observe the stale target first."""
        if self.process_index == 0:
            try:
                os.remove(self.path)
            except OSError:
                pass
            return
        deadline = time.monotonic() + timeout_s
        while os.path.exists(self.path):
            if time.monotonic() > deadline:
                logger.warning(
                    "fleet drain: stale %s not cleared by the primary "
                    "within %.0fs; proceeding", self.path, timeout_s)
                return
            time.sleep(0.05)

    def announce(self, epoch: int, step: int, reason: str) -> Dict[str, Any]:
        """Publish the drain target (first writer wins; idempotent per
        process). Returns the authoritative target."""
        if not self._announced:
            self._announced = True
            payload = {
                "epoch": int(epoch), "step": int(step),
                "reason": str(reason), "initiator": self.process_index,
            }
            tmp = f"{self.path}.{self.process_index}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            try:
                # Atomic create-if-absent: os.link fails with EEXIST when
                # a peer announced first — its target is authoritative.
                os.link(tmp, self.path)
                self._target = payload
                telemetry.event("lifecycle.drain_barrier", phase="announce",
                                epoch=int(epoch), step=int(step),
                                reason=str(reason),
                                process_index=self.process_index,
                                process_count=self.process_count)
                logger.warning(
                    "fleet drain: process %d announced drain target "
                    "(epoch %d, step %d) after %s", self.process_index,
                    int(epoch), int(step), reason)
            except FileExistsError:
                pass
            finally:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        target = self.poll()
        return target if target is not None else {
            "epoch": int(epoch), "step": int(step), "reason": str(reason),
            "initiator": self.process_index,
        }

    def poll(self) -> Optional[Dict[str, Any]]:
        """The authoritative drain target, or None. Cached after the
        first read — the file is immutable once created."""
        if self._target is not None:
            return self._target
        try:
            with open(self.path) as f:
                target = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            return None
        self._target = target
        if not self._observed and int(target.get("initiator", -1)) \
                != self.process_index:
            self._observed = True
            telemetry.event("lifecycle.drain_barrier", phase="observe",
                            epoch=int(target.get("epoch", -1)),
                            step=int(target.get("step", -1)),
                            initiator=int(target.get("initiator", -1)),
                            process_index=self.process_index)
            logger.warning(
                "fleet drain: process %d observed drain target "
                "(epoch %d, step %d) from process %d", self.process_index,
                int(target.get("epoch", -1)), int(target.get("step", -1)),
                int(target.get("initiator", -1)))
        return target

    def reached(self, epoch: int, seen: int) -> Optional[Dict[str, Any]]:
        """The target, when ``(epoch, seen)`` is at or past it — the
        step-boundary check every process runs before dispatching."""
        target = self.poll()
        if target is None:
            return None
        if (int(epoch), int(seen)) >= (int(target.get("epoch", -1)),
                                       int(target.get("step", 0))):
            return target
        return None

    def mark_draining(self, epoch: int, seen: int) -> None:
        telemetry.event("lifecycle.drain_barrier", phase="drain",
                        epoch=int(epoch), step=int(seen),
                        process_index=self.process_index)


def fleet_drain(directory: Optional[str],
                host: Optional[Tuple[int, int]]) -> Optional[FleetDrain]:
    """A :class:`FleetDrain` for a multi-process fit with a shared run
    dir; None otherwise (single-process fits keep the immediate-drain
    path and pay nothing)."""
    if directory is None or host is None or int(host[1]) <= 1:
        return None
    return FleetDrain(directory, host[0], host[1])

"""Two-process CI harness for elastic multi-controller training (ISSUE 18).

Real multi-host TPU fleets are unavailable in CI; multi-CONTROLLER
correctness (two ``jax.distributed``-joined processes running the real
``cli fit``, host-sharded batches, sharded snapshots, the fleet drain
barrier) is validated on CPU instead: each spawned process gets its own
virtual device set via the ``cpu_mesh_env`` recipe
(``--xla_force_host_platform_device_count=N``) and joins one
coordination service via ``DEEPDFA_DIST_COORD/COUNT/ID`` (consumed by
``cli.main`` before any command touches jax). Same program, same
collectives, same snapshot rendezvous as a real fleet — CPU execution.

Three consumers:

* ``tests/test_elastic_fleet.py`` — the tier-1 gate (fleet fit, sharded
  snapshots on disk, 2→1 elastic resume).
* ``chaos.scenario_elastic_shrink`` — SIGTERM one of two processes
  mid-epoch, audit the coordinated drain + redistributed resume.
* ``scripts/test.sh`` — ``python -m deepdfa_tpu.resilience.elastic
  --smoke``, the fast end-to-end bring-up check.

Every process can join the caller's trace plane (``process=`` →
``DEEPDFA_TRACE_CONTEXT`` via the blessed ``context.child_env`` helper),
so one merged trace carries named per-host tracks — the choreography is
audited from ONE ``cli trace report``.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence

from deepdfa_tpu.core.hostmesh import cpu_mesh_env

COORD_HOST = "127.0.0.1"
#: Generous by design: two cold CPU jax processes compile serially on a
#: loaded CI box.
DEFAULT_TIMEOUT_S = 600.0


def free_port() -> int:
    """An OS-assigned free TCP port for the coordination service."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((COORD_HOST, 0))
        return int(s.getsockname()[1])


def fleet_member_env(
    process_index: int,
    process_count: int,
    coord_port: int,
    n_devices_per_proc: int,
    process: Optional[str] = None,
    base: Optional[Dict[str, str]] = None,
    **extra: str,
) -> Dict[str, str]:
    """Env for one member of a ``process_count``-process fleet.

    ``cpu_mesh_env`` pins the platform + per-process virtual device
    count; ``DEEPDFA_DIST_*`` makes ``cli.main`` join the shared
    ``jax.distributed`` job; ``process`` opts the child into the
    caller's trace plane (named track in the merged trace). Stale fault
    plans and trace payloads from the caller are scrubbed — each member
    carries only what it is given.
    """
    from deepdfa_tpu.resilience import inject
    from deepdfa_tpu.telemetry import context as trace_context

    env = cpu_mesh_env(base or os.environ, n_devices_per_proc,
                       force_count=True)
    env.pop(inject.ENV_VAR, None)
    env.pop(trace_context.ENV_VAR, None)
    if process is not None:
        env = trace_context.child_env(process, base=env)
    env.update(
        DEEPDFA_DIST_COORD=f"{COORD_HOST}:{coord_port}",
        DEEPDFA_DIST_COUNT=str(int(process_count)),
        DEEPDFA_DIST_ID=str(int(process_index)),
        # The CPU backend refuses cross-process computations without a
        # collectives implementation; gloo-over-TCP ships in jaxlib and
        # rides the same coordination service the processes already join.
        JAX_CPU_COLLECTIVES_IMPLEMENTATION="gloo",
    )
    env.update(extra)
    return env


def launch_fleet(
    argv: Sequence[str],
    process_count: int,
    n_devices_per_proc: int,
    process_prefix: Optional[str] = None,
    coord_port: Optional[int] = None,
    member_env: Optional[Dict[int, Dict[str, str]]] = None,
    **popen_kw: Any,
) -> List[subprocess.Popen]:
    """Spawn ``process_count`` copies of ``argv`` joined as one fleet.

    Every member runs the SAME argv (the multi-controller contract: one
    program, per-process data slices). ``member_env`` adds per-index env
    on top (fault plans target one host). Members capture their own
    stdout/stderr by default (``text=True``) so a failed fleet is
    diagnosable per process.
    """
    port = coord_port if coord_port is not None else free_port()
    procs: List[subprocess.Popen] = []
    popen_kw.setdefault("stdout", subprocess.PIPE)
    popen_kw.setdefault("stderr", subprocess.PIPE)
    popen_kw.setdefault("text", True)
    try:
        for pi in range(process_count):
            extra = dict((member_env or {}).get(pi, {}))
            env = fleet_member_env(
                pi, process_count, port, n_devices_per_proc,
                process=(f"{process_prefix}{pi}"
                         if process_prefix is not None else None),
                **extra,
            )
            procs.append(subprocess.Popen(list(argv), env=env, **popen_kw))
    except Exception:
        for p in procs:
            p.kill()
        raise
    return procs


def wait_fleet(procs: Sequence[subprocess.Popen],
               timeout_s: float = DEFAULT_TIMEOUT_S) -> List[Dict[str, Any]]:
    """Wait for every member; returns per-member ``{returncode, stdout,
    stderr}``. On timeout the WHOLE fleet is killed first (one wedged
    member wedges every collective) and the timeout is reported as
    returncode ``None`` in that member's record."""
    deadline = time.monotonic() + timeout_s
    results: List[Dict[str, Any]] = [{} for _ in procs]
    timed_out = False
    for i, p in enumerate(procs):
        remaining = deadline - time.monotonic()
        try:
            out, err = p.communicate(timeout=max(remaining, 0.1))
            results[i] = {"returncode": p.returncode, "stdout": out or "",
                          "stderr": err or ""}
        except subprocess.TimeoutExpired:
            timed_out = True
            break
    if timed_out:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for i, p in enumerate(procs):
            if not results[i]:
                out, err = p.communicate()
                # None marks "killed by the harness timeout", distinct
                # from any real exit code the member chose.
                results[i] = {"returncode": None, "stdout": out or "",
                              "stderr": err or ""}
    return results


def fit_argv(run_dir: str, n_examples: int, epochs: int, n_devices: int,
             resume: bool = False) -> List[str]:
    """A tiny-but-real ``cli fit`` argv for the fleet (the chaos TINY
    shape with an explicit global mesh — every member runs this same
    command)."""
    argv = [sys.executable, "-m", "deepdfa_tpu.cli", "fit",
            "--dataset", f"synthetic:{n_examples}",
            "--checkpoint-dir", run_dir,
            "--n-devices", str(int(n_devices)),
            "--set", "model.hidden_dim=8", "--set", "model.n_steps=2",
            "--set", "model.num_output_layers=2",
            "--set", f"train.max_epochs={epochs}",
            "--set", "train.learning_rate=0.002", "--set", "train.seed=0",
            "--set", "data.batch_size=16", "--set", "data.eval_batch_size=16",
            "--set", "data.max_nodes_per_graph=64",
            "--set", "data.max_edges_per_node=4",
            "--set", "data.undersample_factor=1.0"]
    if resume:
        argv.append("--resume")
    return argv


def smoke(out_dir: Optional[str] = None,
          timeout_s: float = DEFAULT_TIMEOUT_S) -> Dict[str, Any]:
    """End-to-end bring-up check: a 2-process × 2-virtual-device fleet
    trains 2 tiny epochs through the real CLI; both members must exit 0
    and the shared run dir must hold a committed 2-process sharded
    snapshot. Returns an ``{"ok": bool, ...}`` report (the scripts/
    test.sh contract)."""
    own_tmp = out_dir is None
    if own_tmp:
        out_dir = tempfile.mkdtemp(prefix="elastic_smoke_")
    run_dir = os.path.join(out_dir, "fleet")
    procs = launch_fleet(fit_argv(run_dir, 32, 2, n_devices=4),
                         process_count=2, n_devices_per_proc=2)
    results = wait_fleet(procs, timeout_s=timeout_s)
    codes = [r.get("returncode") for r in results]
    report: Dict[str, Any] = {"ok": codes == [0, 0], "returncodes": codes,
                              "run_dir": run_dir}
    if not report["ok"]:
        for i, r in enumerate(results):
            report[f"stderr_{i}"] = (r.get("stderr") or "")[-2000:]
        return report
    meta_path = os.path.join(run_dir, "meta.json")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        report["ok"] = False
        report["error"] = f"no readable {meta_path}"
        return report
    report["last_epoch"] = int(meta.get("last_epoch", -1))
    snaps = meta.get("snapshots", {})
    report["sharded_snapshots"] = sorted(
        n for n, rec in snaps.items() if int(rec.get("shards", 1)) == 2)
    if report["last_epoch"] != 1 or not report["sharded_snapshots"]:
        report["ok"] = False
        report["error"] = ("fleet finished but left no committed 2-process "
                           "sharded snapshot")
    return report


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="deepdfa_tpu.resilience.elastic")
    parser.add_argument("--smoke", action="store_true",
                        help="run the 2-process fleet bring-up check")
    parser.add_argument("--out-dir", default=None)
    parser.add_argument("--timeout-s", type=float, default=DEFAULT_TIMEOUT_S)
    args = parser.parse_args(argv)
    if not args.smoke:
        parser.error("nothing to do (pass --smoke)")
    report = smoke(args.out_dir, timeout_s=args.timeout_s)
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

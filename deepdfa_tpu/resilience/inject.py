"""Seeded, declarative fault injection.

A *fault plan* is a JSON document describing faults to fire at named
*sites* instrumented across the codebase:

.. code-block:: json

    {"seed": 0, "faults": [
      {"site": "train.epoch_start", "kind": "raise", "at": 1},
      {"site": "train.loss",        "kind": "nan",   "at": 5},
      {"site": "checkpoint.saved",  "kind": "corrupt", "name": "last"},
      {"site": "joern.send",        "kind": "kill"},
      {"site": "joern.send",        "kind": "hang"},
      {"site": "etl.item",          "kind": "raise", "at": 2},
      {"site": "serve.batch",       "kind": "raise", "at": 0}
    ]}

Spec fields:

``site``
    Which hook fires it. Instrumented sites and their index semantics:

    ========================  =================================================
    ``train.epoch_start``     start of each training epoch; index = epoch
                              number (simulated preemption when ``kind=raise``)
    ``train.loss``            after each optimizer step; index = step ordinal
                              within the run (``kind=nan`` poisons the loss)
    ``checkpoint.saved``      after each snapshot write; ``name`` filters on
                              the snapshot name; ``kind=corrupt|truncate``
                              damages the on-disk snapshot
    ``checkpoint.async_write``  inside the async writer thread, between the
                              snapshot byte write and the checksum/meta
                              commit; index = write ordinal; ``kind=raise``
                              is the writer dying mid-serialize (bytes on
                              disk, meta.json still pointing at the
                              previous intact snapshot)
    ``checkpoint.supersede``  after an async save is submitted; ``name``
                              filters, index = epoch; ``kind=raise``
                              simulates the submitting thread dying right
                              after the handoff
    ``joern.send``            before each Joern REPL command; ``kind=kill``
                              kills the child JVM, ``kind=hang`` simulates an
                              unresponsive REPL (raises ``TimeoutError``)
    ``etl.item``              before each parallel-map work item; index = item
                              position in the input sequence
    ``serve.batch``           before each serving micro-batch executes; index
                              = flush ordinal
    ``lifecycle.preempt``     the training loops' per-step preemption check
                              (``resilience/lifecycle.poll``); index = poll
                              ordinal. ANY matching non-raising kind is
                              treated as a simulated TPU preemption notice —
                              the hermetic stand-in for a real SIGTERM
    ``scan.item``             before each pooled scan item dispatches; index
                              = submission ordinal
    ========================  =================================================

``kind``
    ``raise`` (throw ``exc``), ``nan`` (poison a loss), ``corrupt`` /
    ``truncate`` (damage a snapshot file), ``kill`` / ``hang`` (child
    process faults), ``delay`` (sleep ``seconds`` at the site — a pure
    latency fault: the work completes, late; the SLO monitor's p99
    injection). Sites ignore kinds they don't understand.
``at`` / ``every`` / ``p``
    Match conditions on the spec's occurrence index: exact index, a
    period, or a probability drawn from the plan's seeded RNG. With none
    given the spec matches every occurrence.
``times``
    Maximum number of fires (default 1 for exact/unconditional specs,
    unlimited for ``every``/``p`` specs). Exhausted specs go inert.
``exc`` / ``msg`` / ``name``
    Exception type name for ``raise`` (resolved from builtins, default
    :class:`FaultError`), message, and the snapshot-name filter for
    checkpoint faults.

Arming: programmatically (``install(plan)`` / ``clear()`` / the ``armed``
context manager) or via the environment — ``DEEPDFA_FAULT_PLAN`` holding
either inline JSON or a path to a JSON file. The env plan is loaded once
per process; its per-spec counters then evolve with the process, which
is what makes a plan deterministic: same plan + same code path = same
faults. Forked workers inherit a *copy* of the armed plan, so counters
diverge per process — plans targeting forked sites should match on the
caller-provided index (``at``), which is position-derived, not
count-derived.

With no plan armed every hook is a cheap no-op (one None check), so the
instrumentation stays in production code paths.
"""

from __future__ import annotations

import builtins
import contextlib
import dataclasses
import json
import logging
import os
import random
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

ENV_VAR = "DEEPDFA_FAULT_PLAN"

KINDS = ("raise", "nan", "corrupt", "truncate", "kill", "hang", "delay")


class FaultError(RuntimeError):
    """Default exception for injected ``raise`` faults."""


@dataclasses.dataclass
class FaultSpec:
    site: str
    kind: str
    at: Optional[int] = None
    every: Optional[int] = None
    p: Optional[float] = None
    times: Optional[int] = None
    exc: str = "FaultError"
    msg: str = ""
    name: Optional[str] = None
    seconds: float = 0.05  # delay-kind sleep
    # runtime state
    seen: int = 0   # filter-passing occurrences of this spec's site
    fired: int = 0  # times this spec actually fired

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {KINDS})")
        if self.times is None:
            # Exact-index and unconditional specs are one-shot by default;
            # periodic/probabilistic specs keep firing.
            self.times = 1 if (self.every is None and self.p is None) else 0

    def exhausted(self) -> bool:
        return bool(self.times) and self.fired >= self.times

    def matches(self, idx: int, rng: random.Random) -> bool:
        if self.at is not None and idx != self.at:
            return False
        if self.every is not None and idx % self.every != 0:
            return False
        if self.p is not None and rng.random() >= self.p:
            return False
        return True

    def exception(self) -> BaseException:
        cls: Any = FaultError
        if self.exc and self.exc != "FaultError":
            cand = getattr(builtins, self.exc, None)
            if isinstance(cand, type) and issubclass(cand, BaseException):
                cls = cand
            else:
                logger.warning("fault plan names unknown exception %r; "
                               "raising FaultError", self.exc)
        return cls(self.msg or
                   f"injected fault at {self.site} (occurrence {self.seen})")


class FaultPlan:
    """A parsed plan plus its per-spec runtime counters."""

    def __init__(self, faults: Sequence[FaultSpec], seed: int = 0):
        import threading

        self.faults = list(faults)
        self.seed = seed
        self.rng = random.Random(seed)
        # Sites may fire from concurrent threads (the scan layer's pooled
        # Joern workers all pass through ``joern.send``); the counter
        # read-modify-write must be exact or an ``at`` spec can double-
        # fire or skip. Actions (sleep/raise) stay outside the lock.
        self._lock = threading.Lock()

    @classmethod
    def from_doc(cls, doc: Dict) -> "FaultPlan":
        fields = {f.name for f in dataclasses.fields(FaultSpec)
                  if f.name not in ("seen", "fired")}
        specs = []
        for raw in doc.get("faults", []):
            unknown = set(raw) - fields
            if unknown:
                raise ValueError(
                    f"fault spec {raw!r}: unknown field(s) {sorted(unknown)}"
                )
            specs.append(FaultSpec(**raw))
        return cls(specs, seed=int(doc.get("seed", 0)))

    @classmethod
    def from_source(cls, source: str) -> "FaultPlan":
        """Inline JSON (starts with ``{``) or a path to a JSON file — the
        ``DEEPDFA_FAULT_PLAN`` formats."""
        text = source.strip()
        if not text.startswith("{"):
            with open(text, encoding="utf-8") as f:
                text = f.read()
        return cls.from_doc(json.loads(text))

    def fire(self, site: str, index: Optional[int] = None,
             **ctx: Any) -> Tuple[FaultSpec, ...]:
        """Advance counters; raise any matching ``raise``/``hang`` fault,
        return the other matching specs for the caller to act on."""
        hits: List[FaultSpec] = []
        with self._lock:
            for spec in self.faults:
                if spec.site != site or spec.exhausted():
                    continue
                want_name = spec.name
                if want_name is not None and ctx.get("name") != want_name:
                    continue
                idx = index if index is not None else spec.seen
                spec.seen += 1
                if spec.matches(idx, self.rng):
                    spec.fired += 1
                    hits.append(spec)
                    # Every fired fault is a first-class trace event BEFORE
                    # it acts (a `raise` fault must still appear in
                    # events.jsonl) — the chaos-coverage gate matches these
                    # on site + seed.
                    from deepdfa_tpu import telemetry

                    telemetry.event("fault.fired", site=site,
                                    kind=spec.kind, index=idx,
                                    seed=self.seed)
        for spec in hits:
            if spec.kind == "delay":
                # Pure latency: the site's work still runs — afterwards,
                # and late enough to blow a p99 SLO.
                import time

                time.sleep(spec.seconds)
            if spec.kind == "raise":
                raise spec.exception()
            if spec.kind == "hang":
                # A real hang would stall the caller until its own read
                # deadline; surfacing the deadline's TimeoutError directly
                # keeps soaks fast while exercising the same recovery path.
                raise TimeoutError(
                    spec.msg or f"injected hang at {site} "
                                f"(occurrence {spec.seen - 1})")
        return tuple(hits)

    def report(self) -> List[Dict]:
        return [
            {"site": s.site, "kind": s.kind, "seen": s.seen, "fired": s.fired}
            for s in self.faults
        ]


# ---------------------------------------------------------------------------
# Process-global arming
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_ENV_CHECKED = False
# Guards writes to the arming pair (GL022): active() runs inside every
# fire() call, including from the checkpoint-writer and Joern-pool thread
# closures, and its lazy env arming raced install()/clear() on the main
# path. Reads stay lock-free — only writers serialize.
_ARM_LOCK = threading.Lock()


def install(plan: FaultPlan) -> FaultPlan:
    global _PLAN, _ENV_CHECKED
    with _ARM_LOCK:
        _PLAN = plan
        _ENV_CHECKED = True
    from deepdfa_tpu import telemetry

    telemetry.event("fault.armed", specs=len(plan.faults), seed=plan.seed)
    return plan


def clear() -> None:
    global _PLAN, _ENV_CHECKED
    with _ARM_LOCK:
        _PLAN = None
        _ENV_CHECKED = True


def active() -> Optional[FaultPlan]:
    global _PLAN, _ENV_CHECKED
    if _PLAN is None and not _ENV_CHECKED:
        with _ARM_LOCK:
            if _PLAN is None and not _ENV_CHECKED:
                _ENV_CHECKED = True
                raw = os.environ.get(ENV_VAR)
                if raw:
                    _PLAN = FaultPlan.from_source(raw)
                    logger.warning("fault plan armed from %s (%d specs)",
                                   ENV_VAR, len(_PLAN.faults))
    return _PLAN


@contextlib.contextmanager
def armed(plan: FaultPlan):
    """Arm ``plan`` for the duration of the block, restoring the previous
    arming state after — the test/soak entry point."""
    global _PLAN, _ENV_CHECKED
    with _ARM_LOCK:
        prev, prev_checked = _PLAN, _ENV_CHECKED
    install(plan)
    try:
        yield plan
    finally:
        with _ARM_LOCK:
            _PLAN, _ENV_CHECKED = prev, prev_checked


# ---------------------------------------------------------------------------
# Site hooks (the instrumented-code API)
# ---------------------------------------------------------------------------


def fire(site: str, index: Optional[int] = None,
         **ctx: Any) -> Tuple[FaultSpec, ...]:
    """The hook call: no-op unless a plan is armed. May raise (``raise``/
    ``hang`` faults); returns matching non-raising specs otherwise."""
    plan = active()
    if plan is None:
        return ()
    return plan.fire(site, index, **ctx)


def corrupt_loss(loss, site: str = "train.loss", index: Optional[int] = None):
    """NaN-poison a loss value when a matching ``nan`` fault fires.

    Works on jnp and numpy scalars alike (multiplication by NaN keeps the
    value on device — no host sync added by the hook)."""
    for spec in fire(site, index):
        if spec.kind == "nan":
            return loss * float("nan")
    return loss


def corrupt_path(path: str, mode: str = "corrupt") -> str:
    """Damage a snapshot: flip bytes in (``corrupt``) or halve
    (``truncate``) the largest file under ``path``. Returns the damaged
    file's path. Deterministic target selection so plans replay."""
    target = path
    if os.path.isdir(path):
        files = []
        for dirpath, _, filenames in os.walk(path):
            for fn in sorted(filenames):
                p = os.path.join(dirpath, fn)
                files.append((os.path.getsize(p), p))
        files = [f for f in sorted(files, reverse=True) if f[0] > 0]
        if not files:
            raise FileNotFoundError(f"no non-empty file under {path}")
        target = files[0][1]
    if mode == "truncate":
        size = os.path.getsize(target)
        with open(target, "r+b") as f:
            f.truncate(size // 2)
    else:
        with open(target, "r+b") as f:
            data = bytearray(f.read())
            for pos in (0, len(data) // 2, len(data) - 1):
                data[pos] ^= 0xFF
            f.seek(0)
            f.write(data)
    return target


def tear_snapshot(path: str, frac: float) -> int:
    """Simulate a writer killed after ``frac`` of a snapshot's byte stream
    landed: walking files in the deterministic checksum order
    (sorted relative paths), keep every byte before the cut, truncate the
    file straddling it, and remove everything after — the torn-write shape
    the byte-boundary-quantile tests replay. Returns the cut offset."""
    if not 0.0 <= frac <= 1.0:
        raise ValueError(f"frac must be in [0, 1], got {frac}")
    files: List[str] = []
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames.sort()
        for fn in sorted(filenames):
            files.append(os.path.join(dirpath, fn))
    total = sum(os.path.getsize(f) for f in files)
    cut = int(total * frac)
    written = 0
    for f in files:
        size = os.path.getsize(f)
        if written + size <= cut:
            written += size  # fully landed before the kill
        elif written >= cut:
            os.remove(f)     # never reached
        else:
            with open(f, "r+b") as fh:
                fh.truncate(cut - written)
            written = cut
    return cut

"""Hyperparameter-search service protocol: reporting + assessor.

The reference integrates NNI three ways (all reproduced here):
- parameter injection into the parsed config
  (DDFA/code_gnn/main_cli.py:110-121 — here ``DEEPDFA_TUNE_PARAMS`` env
  injection in cli.build_configs, plus ``nni.get_next_parameter`` when the
  real service is attached),
- per-epoch intermediate val-F1 reports (base_module.py:346),
- a final-result report after fit (main_cli.py:184),
with NNI's assessor terminating hopeless trials from the intermediate
stream. The service is not in this image, so :class:`MedianStopAssessor`
implements the same early-termination rule in-process for the built-in
random-search tuner, and :class:`TrialReporter` bridges to the real ``nni``
package when it is importable.
"""

from __future__ import annotations

import logging
import statistics
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


class MedianStopAssessor:
    """NNI medianstop semantics: terminate a trial at step E when its best
    intermediate result so far is below the median of the *running averages*
    (over steps 0..E) of all completed trials.

    ``warmup_steps``: never stop before this many reports (NNI
    ``start_step``). ``min_trials``: the median is meaningless over too few
    completed curves. Values are higher-is-better (val F1).
    """

    def __init__(self, warmup_steps: int = 2, min_trials: int = 3):
        self.warmup_steps = warmup_steps
        self.min_trials = min_trials
        self._running: Dict[object, List[float]] = {}
        self._completed: List[List[float]] = []

    def report(self, trial_id, value: float) -> None:
        self._running.setdefault(trial_id, []).append(float(value))

    def complete(self, trial_id) -> None:
        curve = self._running.pop(trial_id, None)
        if curve:
            self._completed.append(curve)

    def should_stop(self, trial_id) -> bool:
        curve = self._running.get(trial_id, [])
        step = len(curve)  # reports so far (1-based step count)
        if step <= self.warmup_steps or len(self._completed) < self.min_trials:
            return False
        avgs = [
            statistics.mean(c[: min(step, len(c))]) for c in self._completed
        ]
        return max(curve) < statistics.median(avgs)


class TrialReporter:
    """Intermediate/final result reporting, bridged to the real ``nni``
    package when the process runs under an NNI trial, else a no-op sink
    (the in-process tuner reads the assessor directly)."""

    def __init__(self):
        try:
            import nni  # not in this image; present under a real service

            self._nni = nni
        except ImportError:
            self._nni = None

    @property
    def attached(self) -> bool:
        return self._nni is not None

    def intermediate(self, value: float) -> None:
        if self._nni is not None:
            self._nni.report_intermediate_result(float(value))

    def final(self, value: float) -> None:
        if self._nni is not None:
            self._nni.report_final_result(float(value))


def nni_next_parameters() -> Optional[Dict]:
    """``nni.get_next_parameter()`` when attached (main_cli.py:110-121);
    None otherwise — callers fall back to DEEPDFA_TUNE_PARAMS/env."""
    try:
        import nni

        params = nni.get_next_parameter()
        return dict(params) if params else None
    except ImportError:
        return None

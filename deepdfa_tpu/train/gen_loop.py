"""Seq2seq fine-tuning loop for the T5 generation tasks
(summarize/translate/refine/concode — reference CodeT5/run_gen.py).

Reference semantics: teacher-forced CE over target tokens with pads ignored
(HF ``labels=-100`` masking), AdamW + linear warmup, per-epoch eval with
best-loss/best-metric checkpointing, beam-search generation for the final
metric (run_gen.py:104-112 with num_beams=args.beam_size). Here the loss
masks pads explicitly, the train step is one jitted function over a pjit
mesh, and generation uses models/t5_generate.py.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepdfa_tpu.core.prng import fold_in_dropout
from flax import struct

from deepdfa_tpu.core.config import TransformerTrainConfig
from deepdfa_tpu.models.t5 import T5Config, T5Model, shift_right
from deepdfa_tpu.models.t5_generate import generate
from deepdfa_tpu.resilience import inject, lifecycle
from deepdfa_tpu.train.text_loop import make_schedule, make_text_optimizer
from deepdfa_tpu import telemetry

logger = logging.getLogger(__name__)


@struct.dataclass
class GenTrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any
    dropout_rng: jnp.ndarray


def seq2seq_loss(
    model: T5Model, params, source_ids, target_ids, dropout_rng=None,
    deterministic: bool = True,
):
    """Masked teacher-forced CE (mean over non-pad target tokens)."""
    c = model.cfg
    dec_in = shift_right(target_ids, c.decoder_start_token_id)
    dec_mask = dec_in != c.pad_token_id
    # position 0 is the start token: always attended
    dec_mask = dec_mask.at[:, 0].set(True)
    rngs = {"dropout": dropout_rng} if dropout_rng is not None else None
    hidden = model.apply(
        params, source_ids, dec_in, decoder_mask=dec_mask,
        deterministic=deterministic, rngs=rngs,
    )
    logits = model.apply(params, hidden, method=type(model).logits)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    tok_lp = jnp.take_along_axis(logp, target_ids[..., None], axis=-1)[..., 0]
    mask = (target_ids != c.pad_token_id).astype(jnp.float32)
    return -(tok_lp * mask).sum() / jnp.maximum(mask.sum(), 1.0)


# Same optimizer recipe as the classifier fine-tunes (one source of truth).
make_gen_optimizer = make_text_optimizer


def make_gen_train_state(
    model: T5Model, example_src, example_tgt, cfg: TransformerTrainConfig,
    max_steps: int, init_params: Optional[Any] = None,
) -> Tuple[GenTrainState, optax.GradientTransformation]:
    rng = jax.random.PRNGKey(cfg.seed)
    params_rng, dropout_rng = jax.random.split(rng)
    params = model.init(
        {"params": params_rng, "dropout": dropout_rng},
        jnp.asarray(example_src),
        shift_right(jnp.asarray(example_tgt), model.cfg.decoder_start_token_id),
    )
    if init_params is not None:
        # Graft (don't replace): pretrained trees may cover only a subtree
        # (e.g. the RoBERTa encoder under a fresh decoder) — text_loop's
        # merge validates every override key/shape against the fresh init.
        from deepdfa_tpu.train.text_loop import _merge_params

        params = _merge_params(params, init_params)
    tx = make_gen_optimizer(cfg, max_steps)
    return (
        GenTrainState(jnp.zeros((), jnp.int32), params, tx.init(params), dropout_rng),
        tx,
    )


def make_gen_train_step(model: T5Model, tx, cfg: TransformerTrainConfig) -> Callable:
    def step(state: GenTrainState, source_ids, target_ids):
        dropout_rng = fold_in_dropout(state.dropout_rng, state.step)

        def loss_fn(params):
            return seq2seq_loss(
                model, params, source_ids, target_ids,
                dropout_rng=dropout_rng, deterministic=False,
            )

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            GenTrainState(state.step + 1, params, opt_state, state.dropout_rng),
            loss,
        )

    return step


def _batches(data: Dict[str, np.ndarray], batch_size: int, rng=None,
             pad_tail: bool = False, pad_id: int = 0):
    """Yield (source, target, n_valid). With ``pad_tail`` the final short
    batch is padded with all-``pad_id`` rows — such targets contribute
    nothing to the masked loss, so metrics cover every row."""
    n = len(data["source_ids"])
    order = np.arange(n)
    if rng is not None:
        order = rng.permutation(order)
    stop = n if pad_tail else n - batch_size + 1
    for start in range(0, stop, batch_size):
        sel = order[start : start + batch_size]
        src, tgt = data["source_ids"][sel], data["target_ids"][sel]
        n_valid = len(sel)
        if n_valid < batch_size:
            pad = batch_size - n_valid
            src = np.concatenate(
                [src, np.full((pad, src.shape[1]), pad_id, src.dtype)]
            )
            tgt = np.concatenate(
                [tgt, np.full((pad, tgt.shape[1]), pad_id, tgt.dtype)]
            )
        yield src, tgt, n_valid


def _host_of() -> Optional[Tuple[int, int]]:
    """(process_index, process_count) in multi-controller runs, else None —
    the _batches/host contract of train/loop.py extended to the gen/clone
    trainers (reference DDP covered its generation trainer,
    CodeT5/run_defect.py:274-277)."""
    return (
        (jax.process_index(), jax.process_count())
        if jax.process_count() > 1 else None
    )


def _lift_rows(arr: np.ndarray, mesh, host):
    """Slice this host's rows of a deterministic global batch and lift them
    onto the mesh (identity on a single host)."""
    if host is None:
        return jnp.asarray(arr)
    from deepdfa_tpu.parallel.mesh import assemble_global_batch

    pi, pc = host
    if arr.shape[0] % pc:
        # Truncating would silently drop examples from every batch; the
        # trainers validate batch sizes up front, this is the backstop.
        raise ValueError(f"batch rows {arr.shape[0]} % hosts {pc} != 0")
    rows = arr.shape[0] // pc
    return assemble_global_batch(arr[pi * rows : (pi + 1) * rows], mesh)


def _check_host_batch_sizes(cfg: TransformerTrainConfig, host) -> None:
    """Fail before training, not at the first lifted batch (the fit_text
    guard, text_loop.py): every global batch splits evenly across hosts."""
    if host is None:
        return
    pc = host[1]
    if cfg.batch_size % pc or cfg.eval_batch_size % pc:
        raise ValueError(
            f"batch_size {cfg.batch_size} and eval_batch_size "
            f"{cfg.eval_batch_size} must divide by the process count {pc}"
        )


def strip_ids(row, pad_id: int, eos_id: int) -> list:
    """Token ids up to the first eos, pads removed (the ``skip_special_
    tokens`` slice of the reference's decode, run_gen.py:115)."""
    out = []
    for t in row:
        if t == eos_id:
            break
        if t != pad_id:
            out.append(int(t))
    return out


def exact_match(pred: np.ndarray, target: np.ndarray, pad_id: int, eos_id: int) -> float:
    """Fraction of rows whose generated tokens (up to eos) equal the
    reference target tokens (up to eos)."""
    hits = sum(
        strip_ids(p, pad_id, eos_id) == strip_ids(t, pad_id, eos_id)
        for p, t in zip(pred, target)
    )
    return hits / max(len(pred), 1)


def _ids_to_text(rows, pad_id: int, eos_id: int, decode_fn=None) -> list:
    """Decode id rows for the BLEU pipeline. Without a real (invertible)
    tokenizer the ids themselves become the tokens — n-gram overlap in id
    space is the same quantity the reference computes over subword text."""
    stripped = [strip_ids(r, pad_id, eos_id) for r in rows]
    if decode_fn is not None:
        return [decode_fn(ids) for ids in stripped]
    return [" ".join(str(t) for t in ids) for ids in stripped]


def bleu_for_task(task: str, gold_texts, pred_texts) -> float:
    """The dev BLEU the reference selects on (run_gen.py:148-154):
    summarize scores per-example smoothed BLEU via the CodeXGLUE maps,
    every other generation task the corpus nmt ``_bleu``."""
    from deepdfa_tpu.eval.codebleu.smooth_bleu import (
        nmt_bleu,
        smooth_bleu_score,
    )

    if task == "summarize":
        return smooth_bleu_score(gold_texts, pred_texts)
    return nmt_bleu([[g.split()] for g in gold_texts],
                    [p.split() for p in pred_texts])


def combine_bleu_em(task: str, bleu: float, em_fraction: float) -> float:
    """``dev_bleu_em`` (run_gen.py:316-322): summarize selects on BLEU
    alone, defect on EM alone, everything else on their sum (EM in
    percent)."""
    if task == "summarize":
        return bleu
    if task == "defect":
        return em_fraction * 100.0
    return bleu + em_fraction * 100.0


def _make_eval_fns(model: T5Model, max_target_length: int, beam_size: int,
                   mesh=None) -> Tuple[Callable, Callable]:
    """Jitted (eval loss, generate) pair — created once per fit so the
    per-epoch BLEU evals reuse one compilation."""
    loss_fn = lambda params, s, t: seq2seq_loss(model, params, s, t)
    gen_fn = lambda params, src: generate(
        model, params, src, max_len=max_target_length, beam_size=beam_size
    )
    if mesh is not None:
        from deepdfa_tpu.parallel.mesh import batch_sharding, replicated

        rep, dsh = replicated(mesh), batch_sharding(mesh)
        return (
            jax.jit(loss_fn, in_shardings=(rep, dsh, dsh), out_shardings=rep),
            jax.jit(gen_fn, in_shardings=(rep, dsh), out_shardings=rep),
        )
    return jax.jit(loss_fn), jax.jit(gen_fn)


def evaluate_gen(
    model: T5Model,
    state: GenTrainState,
    eval_data: Dict[str, np.ndarray],
    cfg: TransformerTrainConfig,
    max_target_length: int = 32,
    beam_size: int = 1,
    mesh=None,
    host=None,
    return_preds: bool = False,
    fns: Optional[Tuple[Callable, Callable]] = None,
) -> Dict[str, Any]:
    """Eval loss over padded batches + generation exact-match (shared by
    fit_gen and fit_gen_multitask). ``return_preds`` adds the raw generated
    id rows (``pred_ids``) for BLEU scoring / prediction dumps. ``fns``:
    pre-jitted (loss, generate) from ``_make_eval_fns`` — pass them when
    calling per epoch, or every call re-traces fresh lambdas.

    ``mesh``/``host``: dp sharding / multi-controller feeding. Outputs
    replicate, so predictions and metrics are identical on every host."""
    pad_id = model.cfg.pad_token_id
    eval_loss_fn, gen = fns or _make_eval_fns(
        model, max_target_length, beam_size, mesh
    )
    losses, preds, valids = [], [], []
    for s, t, n_valid in _batches(
        eval_data, cfg.eval_batch_size, pad_tail=True, pad_id=pad_id
    ):
        s_dev = _lift_rows(s, mesh, host)
        t_dev = _lift_rows(t, mesh, host)
        # BOTH accumulators stay on device until the single device_get
        # below. The old float() on losses blocked the host BEFORE the
        # gen dispatch each batch (graftlint GL004, fixed in PR 1); the
        # np.asarray on preds left behind by that pass did the same on
        # the gen side — every eval batch's loss dispatch waited out the
        # previous decode instead of queueing behind it (ISSUE 13).
        losses.append(eval_loss_fn(state.params, s_dev, t_dev))
        preds.append(gen(state.params, s_dev))
        valids.append(n_valid)
    losses, preds = jax.device_get((losses, preds))
    pred = (
        np.concatenate([p[:n] for p, n in zip(preds, valids)])
        if preds
        else np.zeros((0, max_target_length), np.int32)
    )
    out: Dict[str, Any] = {
        "eval_loss": (float(np.mean(losses))
                      if losses else float("nan")),
        "exact_match": exact_match(
            pred, eval_data["target_ids"][: len(pred)],
            model.cfg.pad_token_id, model.cfg.eos_token_id,
        ),
    }
    if return_preds:
        out["pred_ids"] = pred
    return out


def _dump_gen_predictions(output_dir: str, tag: str, pred_texts, gold_texts,
                          src_texts) -> None:
    """``.output``/``.gold``/``.src`` prediction files per eval
    (run_gen.py:106-123 eval_bleu_epoch)."""
    import os

    os.makedirs(output_dir, exist_ok=True)
    for suffix, rows in (("output", pred_texts), ("gold", gold_texts),
                         ("src", src_texts)):
        with open(os.path.join(output_dir, f"{tag}.{suffix}"), "w") as f:
            for row in rows:
                f.write(row.strip() + "\n")


def fit_gen(
    model: T5Model,
    train_data: Dict[str, np.ndarray],
    eval_data: Dict[str, np.ndarray],
    cfg: TransformerTrainConfig,
    max_target_length: int = 32,
    beam_size: int = 1,
    init_params: Optional[Any] = None,
    log: Optional[Callable[[str], None]] = None,
    mesh=None,
    task: str = "",
    decode_fn: Optional[Callable] = None,
    output_dir: Optional[str] = None,
    codebleu_lang: Optional[str] = None,
    eval_bleu: bool = True,
    checkpointer=None,
) -> Dict[str, Any]:
    """run_gen's training protocol: per-epoch dev eval computing loss (the
    ppl track) AND generation BLEU+EM, checkpoint selection on the
    task-dependent ``dev_bleu_em``, early stop only when BOTH tracks have
    stalled past the patience (run_gen.py:283-356). Returns the BEST state
    with its epoch's metrics plus the full per-epoch ``history``.

    ``eval_bleu=False`` is the reference's ``--do_eval_bleu`` off mode:
    only the loss track runs per epoch, the best state is best-ppl
    (checkpoint-best-ppl), early stop on the loss patience alone, and the
    generation metrics are computed once on the selected state.

    ``task`` picks the BLEU flavor and the selection combination
    (bleu_for_task / combine_bleu_em); ``decode_fn`` maps stripped id lists
    to text for BLEU/dumps (ids score as tokens without it);
    ``output_dir`` writes per-epoch ``dev_e{N}.output/.gold/.src`` files;
    ``codebleu_lang`` additionally reports CodeBLEU on the dev predictions
    (the concode metric, run_gen.py:152-154) — requires ``decode_fn``.

    ``mesh``: optional data-parallel mesh — batches shard over the data
    axis, params replicate, GSPMD all-reduces the grads (the jit analog of
    the reference's DataParallel over the gen tasks). Multi-controller
    (jax.process_count() > 1): every host runs the same deterministic batch
    sequence and feeds its local row slice — the _batches/host contract of
    train/loop.py, replacing DistributedSampler
    (CodeT5/run_defect.py:274-277)."""
    host = _host_of()
    if host is not None and mesh is None:
        raise ValueError("multi-process fit_gen needs an explicit global mesh")
    _check_host_batch_sizes(cfg, host)
    if codebleu_lang and decode_fn is None:
        raise ValueError("codebleu_lang needs a decode_fn: CodeBLEU parses "
                         "source text, not token ids")
    n = len(train_data["source_ids"])
    steps_per_epoch = -(-n // cfg.batch_size)  # ceil: small sets still train
    max_steps = steps_per_epoch * cfg.max_epochs
    state, tx = make_gen_train_state(
        model,
        train_data["source_ids"][: cfg.batch_size],
        train_data["target_ids"][: cfg.batch_size],
        cfg,
        max_steps,
        init_params=init_params,
    )
    step = _jit_gen_step(make_gen_train_step(model, tx, cfg), mesh, cfg,
                         donate=False)
    pad_id = model.cfg.pad_token_id
    eos_id = model.cfg.eos_token_id
    gold_texts = _ids_to_text(eval_data["target_ids"], pad_id, eos_id,
                              decode_fn)
    src_texts = _ids_to_text(eval_data["source_ids"], pad_id, eos_id,
                             decode_fn)
    rng = np.random.RandomState(cfg.seed)
    eval_fns = _make_eval_fns(model, max_target_length, beam_size, mesh)
    history: list = []
    best = {"state": state, "bleu_em": -1.0, "epoch": -1, "record": None}
    best_loss = float("inf")
    not_loss_dec = not_bleu_em_inc = 0
    eval_loss_fn = eval_fns[0]

    def loss_only_eval() -> float:
        # Device-accumulated like evaluate_gen: one host transfer at the
        # end, not one per eval batch (graftlint GL004).
        losses = []
        for s, t, _ in _batches(eval_data, cfg.eval_batch_size,
                                pad_tail=True, pad_id=pad_id):
            losses.append(eval_loss_fn(
                state.params, _lift_rows(s, mesh, host),
                _lift_rows(t, mesh, host)))
        return (float(np.mean(jax.device_get(losses)))
                if losses else float("nan"))

    def bleu_eval(cur_state):
        ev = evaluate_gen(model, cur_state, eval_data, cfg,
                          max_target_length, beam_size, mesh=mesh, host=host,
                          return_preds=True, fns=eval_fns)
        pred_texts = _ids_to_text(ev["pred_ids"], pad_id, eos_id, decode_fn)
        bleu = bleu_for_task(task, gold_texts[: len(pred_texts)], pred_texts)
        metrics = {
            "eval_loss": ev["eval_loss"],
            "exact_match": ev["exact_match"],
            "bleu": bleu,
            "bleu_em": combine_bleu_em(task, bleu, ev["exact_match"]),
        }
        if codebleu_lang:
            from deepdfa_tpu.eval.codebleu import get_codebleu

            metrics["codebleu"] = get_codebleu(
                gold_texts[: len(pred_texts)], pred_texts, codebleu_lang
            )["codebleu"]
        return metrics, pred_texts

    if cfg.anomaly_policy not in ("raise", "rollback"):
        raise ValueError(
            f"anomaly_policy must be 'raise' or 'rollback', "
            f"got {cfg.anomaly_policy!r}"
        )
    detect_anomaly = cfg.detect_anomaly or cfg.anomaly_policy == "rollback"
    anomaly_budget = cfg.anomaly_retry_budget
    anomaly_rollbacks = 0
    if checkpointer is not None:
        # Same preemption-survival posture as train/loop.py: ``last``
        # every epoch, ``best`` on selection improvement, layout recorded
        # for topology-independent restore, drained before returning.
        from deepdfa_tpu.parallel.mesh import snapshot_layout

        checkpointer.set_layout(snapshot_layout(mesh))
    # Coordinated fleet drain (ISSUE 18): one host's notice becomes a
    # shared step-boundary target — same barrier as train/loop.py.
    fleet = lifecycle.fleet_drain(
        checkpointer.directory if checkpointer is not None else None, host)
    if fleet is not None:
        fleet.clear()
    try:
        for epoch in range(cfg.max_epochs):
            inject.fire("train.epoch_start", index=epoch)
            epoch_start_state = state
            losses = []
            # Same fenced-epoch / dispatch-step span pairing as loop.py —
            # the report's host/device split works for the gen loop too.
            with telemetry.span("train.epoch", epoch=epoch, loop="gen") as ep:
                for src, tgt, _ in _batches(
                    train_data, cfg.batch_size, rng, pad_tail=True,
                    pad_id=pad_id
                ):
                    # Fleet drain target check BEFORE dispatch: every
                    # process stops at the same (epoch, step).
                    if fleet is not None:
                        tgt_drain = fleet.reached(epoch, len(losses))
                        if tgt_drain is not None:
                            notice = lifecycle.poll()
                            if notice is None:
                                notice = lifecycle.coordinator().notify(
                                    "fleet_drain")
                            fleet.mark_draining(epoch, len(losses))
                            lifecycle.preempt_snapshot_exit(
                                notice,
                                checkpointer
                                if (host is None or host[0] == 0) else None,
                                state, epoch, len(losses),
                                history={"epochs": history},
                                resume={"seen": len(losses), "loop": "gen"},
                                loop="gen")
                    with telemetry.span("train.step", epoch=epoch,
                                        step=len(losses)):
                        state, loss = step(
                            state, _lift_rows(src, mesh, host),
                            _lift_rows(tgt, mesh, host)
                        )
                    if fleet is not None:
                        # Dispatch fence: the barrier's one-step-ahead
                        # bound.
                        jax.block_until_ready(loss)
                    losses.append(inject.corrupt_loss(loss))
                    # Step-granular preemption check (ISSUE 10): drain to
                    # a durable preempt snapshot and exit typed instead
                    # of losing the partial epoch to SIGKILL. Process 0
                    # owns the run dir (the save_last gating).
                    notice = lifecycle.poll()
                    if notice is not None:
                        if fleet is None:
                            lifecycle.preempt_snapshot_exit(
                                notice,
                                checkpointer
                                if (host is None or host[0] == 0) else None,
                                state, epoch, len(losses),
                                history={"epochs": history},
                                resume={"seen": len(losses), "loop": "gen"},
                                loop="gen")
                        # Fleet: announce the next boundary (a peer may be
                        # inside the next step's collective already) and
                        # keep participating until reached() drains.
                        fleet.announce(epoch, len(losses) + 1, notice.reason)
                ep.fence(losses)
                ep.set(steps=len(losses))
            record = {"epoch": epoch,
                      "train_loss": float(np.mean(jax.device_get(losses)))}
            # Epoch-granular anomaly handling: the mean above is the one
            # host transfer that already exists; NaN/inf propagates
            # through it.
            if detect_anomaly and not math.isfinite(record["train_loss"]):
                if cfg.anomaly_policy != "rollback":
                    raise FloatingPointError(
                        f"non-finite loss in epoch {epoch}")
                if anomaly_budget <= 0:
                    raise FloatingPointError(
                        f"non-finite loss in epoch {epoch} "
                        "(anomaly retry budget exhausted)"
                    )
                anomaly_budget -= 1
                anomaly_rollbacks += 1
                logger.warning(
                    "non-finite loss in epoch %d: rolling back to the "
                    "epoch-start state and continuing (%d retries left)",
                    epoch, anomaly_budget,
                )
                state = epoch_start_state
                record["rolled_back"] = True
                telemetry.event("train.rollback", epoch=epoch, loop="gen")
            if eval_bleu:
                metrics, pred_texts = bleu_eval(state)
                record.update(metrics)
                if output_dir and (host is None or host[0] == 0):
                    _dump_gen_predictions(output_dir, f"dev_e{epoch}",
                                          pred_texts,
                                          gold_texts[: len(pred_texts)],
                                          src_texts[: len(pred_texts)])
            else:
                record["eval_loss"] = loss_only_eval()
            if epoch == 0:
                telemetry.event("train.warmup_done", epoch=epoch, loop="gen")
            telemetry.event("train.epoch_end", epoch=epoch, loop="gen",
                            train_loss=record["train_loss"])
            telemetry.flush()  # epoch cadence: don't ride the ring to close
            history.append(record)
            if log:
                log(f"epoch {epoch}: " + " ".join(
                    f"{k}={v:.4f}" for k, v in record.items()
                    if k != "epoch" and isinstance(v, float)))
            if checkpointer is not None and (host is None or host[0] == 0):
                checkpointer.save_last(state, epoch)
                checkpointer.maybe_save_periodic(state, epoch)
            # Two independent stall counters; a trailing epoch must beat
            # BOTH to keep training past the patience (run_gen.py:283-356).
            # Without the bleu track, best-ppl selects and the loss
            # patience alone stops.
            if record["eval_loss"] < best_loss:
                best_loss, not_loss_dec = record["eval_loss"], 0
                if not eval_bleu:
                    best = {"state": state, "bleu_em": -1.0, "epoch": epoch,
                            "record": record}
                    if checkpointer is not None and (host is None
                                                     or host[0] == 0):
                        checkpointer.save_best(
                            state, epoch,
                            metrics={"eval_loss": record["eval_loss"]})
            else:
                not_loss_dec += 1
            if eval_bleu:
                if record["bleu_em"] > best["bleu_em"]:
                    best = {"state": state, "bleu_em": record["bleu_em"],
                            "epoch": epoch, "record": record}
                    not_bleu_em_inc = 0
                    if checkpointer is not None and (host is None
                                                     or host[0] == 0):
                        checkpointer.save_best(
                            state, epoch,
                            metrics={"bleu_em": record["bleu_em"]})
                else:
                    not_bleu_em_inc += 1
            if (cfg.early_stop_patience is not None
                    and not_loss_dec > cfg.early_stop_patience
                    and (not eval_bleu
                         or not_bleu_em_inc > cfg.early_stop_patience)):
                if log:
                    log(f"early stop at epoch {epoch} "
                        f"(best {best['epoch']})")
                break
    finally:
        if checkpointer is not None:
            # Fit-exit drain barrier: every submitted snapshot commits (or
            # records its failure) before the caller can act on the run.
            checkpointer.drain()

    r = dict(best["record"] or {"eval_loss": float("nan")})
    if "bleu" not in r:
        # Loss-only selection: generation metrics computed once on the
        # selected state (the reference's final eval_bleu_epoch on the
        # loaded best checkpoint).
        metrics, pred_texts = bleu_eval(best["state"])
        r.update(metrics, eval_loss=r.get("eval_loss", metrics["eval_loss"]))
        if output_dir and (host is None or host[0] == 0):
            _dump_gen_predictions(output_dir, "dev_best", pred_texts,
                                  gold_texts[: len(pred_texts)],
                                  src_texts[: len(pred_texts)])
    out = {"state": best["state"], "best_epoch": best["epoch"],
           "history": history, "eval_loss": r["eval_loss"],
           "exact_match": r["exact_match"], "bleu": r["bleu"],
           "bleu_em": r["bleu_em"]}
    if anomaly_rollbacks:
        out["anomaly_rollbacks"] = anomaly_rollbacks
    if "codebleu" in r:
        out["codebleu"] = r["codebleu"]
    return out


def _jit_gen_step(step_fn, mesh, cfg, donate: bool = False):
    """Donation is opt-in: whenever a past state is retained across steps
    (best-epoch selection, the fit_gen default), donating the state
    argument deletes the retained copy's buffers and the final eval
    crashes with 'Array has been deleted' — the fit_text pattern. Pass
    donate=True only for loops that keep no old state."""
    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0,) if donate else ())
    from deepdfa_tpu.parallel.mesh import jit_dp_step

    return jit_dp_step(step_fn, mesh, n_batch_args=2, n_out=2,
                       batch_sizes=(cfg.batch_size,),
                       donate=(0,) if donate else ())


def task_sampling_probs(sizes: Dict[str, int], alpha: float = 0.7) -> Dict[str, float]:
    """Size-proportional task mixing with temperature smoothing: normalize,
    raise to ``alpha``, renormalize (run_multi_gen.py:269-272)."""
    total = sum(sizes.values())
    p = {k: (v / total) ** alpha for k, v in sizes.items()}
    z = sum(p.values())
    return {k: v / z for k, v in p.items()}


# Per-task early-stop patience table (run_multi_gen.py:254-267: summarize 2,
# translate 5, refine 5, concode 3, defect 2).
MULTITASK_PATIENCE = {"summarize": 2, "translate": 5, "refine": 5,
                      "concode": 3, "defect": 2}


def multitask_patience(name: str, fallback: Optional[int] = None) -> int:
    """Patience for a task name like ``summarize_python`` (the reference
    keys its table by ``cur_task.split('_')[0]``)."""
    base = name.split("_")[0]
    if base in MULTITASK_PATIENCE:
        return MULTITASK_PATIENCE[base]
    return fallback if fallback is not None else 3


def fit_gen_multitask(
    model: T5Model,
    task_data: Dict[str, Dict[str, np.ndarray]],
    eval_data: Dict[str, Dict[str, np.ndarray]],
    cfg: TransformerTrainConfig,
    max_steps: int,
    alpha: float = 0.7,
    max_target_length: int = 32,
    beam_size: int = 1,
    eval_interval: Optional[int] = None,
    init_params: Optional[Any] = None,
    log: Optional[Callable[[str], None]] = None,
    decode_fn: Optional[Callable] = None,
    patience: Optional[Dict[str, int]] = None,
    mesh=None,
) -> Dict[str, Any]:
    """Multi-task fine-tuning (run_multi_gen.py parity): each step samples a
    task by smoothed size-proportional probability and trains on a random
    batch from it. Task prefixes ("Summarize python: ...") belong in the
    data prep, as in the reference.

    Selection protocol (run_multi_gen.py:248-357): every ``eval_interval``
    steps (the reference's ``save_steps``) each not-yet-stopped task runs a
    dev eval — loss (the ppl track, best value recorded) AND generation
    BLEU+EM — and its ``dev_bleu_em`` (combine_bleu_em per task family)
    drives PER-TASK best-state selection with PER-TASK patience
    (``multitask_patience`` table; ``cfg.early_stop_patience`` overrides
    every task when set; ``patience`` overrides per task, a value of None
    disabling that task's early stop). A task whose
    bleu_em stalls past its patience early-stops: its sampled training
    batches are skipped from then on (:278-287), and 50 consecutive skipped
    draws end training (:281-285, "all tasks have early stopped"). Best
    params are snapshotted to HOST memory per task (the analog of the
    reference's per-task ``checkpoint-best-bleu`` dirs), so retaining them
    does not multiply device memory by the task count.

    Returns the final ``state`` (the reference's checkpoint-last), per-task
    ``tasks[name]`` = best-eval record (step/eval_loss/exact_match/bleu/
    bleu_em + ``early_stopped``/``best_loss``), per-task ``history``, and
    ``best_params[name]`` = host param tree of each task's selected state.

    ``mesh``: optional dp mesh — batches shard over the data axis, params
    replicate (fit_gen's contract). Multi-controller: every host samples
    the identical task/batch sequence (same seeded RandomState) and feeds
    its local row slice — the _batches/host contract — replacing the
    reference's DDP over run_multi_gen (its local_rank plumbing).
    """
    host = _host_of()
    if host is not None and mesh is None:
        raise ValueError(
            "multi-process fit_gen_multitask needs an explicit global mesh"
        )
    _check_host_batch_sizes(cfg, host)
    names = sorted(task_data)
    eval_names = sorted(eval_data)
    probs = task_sampling_probs({k: len(task_data[k]["source_ids"]) for k in names},
                                alpha)
    pat: Dict[str, Optional[int]] = dict(patience or {})
    for k in eval_names:
        pat.setdefault(k, cfg.early_stop_patience
                       if cfg.early_stop_patience is not None
                       else multitask_patience(k))
    if eval_interval is None:
        eval_interval = max(max_steps // 5, 1)
    first = task_data[names[0]]
    state, tx = make_gen_train_state(
        model, first["source_ids"][: cfg.batch_size],
        first["target_ids"][: cfg.batch_size], cfg, max_steps,
        init_params=init_params,
    )
    step = _jit_gen_step(make_gen_train_step(model, tx, cfg), mesh, cfg,
                         donate=True)
    eval_fns = _make_eval_fns(model, max_target_length, beam_size, mesh)
    pad_id, eos_id = model.cfg.pad_token_id, model.cfg.eos_token_id
    gold = {k: _ids_to_text(eval_data[k]["target_ids"], pad_id, eos_id,
                            decode_fn) for k in eval_names}

    best: Dict[str, Dict[str, Any]] = {
        k: {"bleu_em": -1.0, "params": None, "record": None}
        for k in eval_names
    }
    best_loss = {k: float("inf") for k in eval_names}
    stall = {k: 0 for k in eval_names}
    stopped = {k: False for k in eval_names}
    history: Dict[str, list] = {k: [] for k in eval_names}

    def eval_round(at_step: int) -> None:
        # One host snapshot per round, shared by every improving task —
        # the trees are identical and immutable within a round, so N tasks
        # must not mean N device-to-host fetches of the same params.
        snap: list = [None]
        for name in eval_names:
            if stopped[name]:
                continue
            ev = evaluate_gen(model, state, eval_data[name], cfg,
                              max_target_length, beam_size, mesh=mesh,
                              host=host, return_preds=True, fns=eval_fns)
            base = name.split("_")[0]
            preds = _ids_to_text(ev["pred_ids"], pad_id, eos_id, decode_fn)
            bleu = bleu_for_task(base, gold[name][: len(preds)], preds)
            record = {"step": at_step, "eval_loss": ev["eval_loss"],
                      "exact_match": ev["exact_match"], "bleu": bleu,
                      "bleu_em": combine_bleu_em(base, bleu,
                                                 ev["exact_match"])}
            history[name].append(record)
            # ppl track: best value recorded (the reference additionally
            # keeps a checkpoint-best-ppl dir per task, :412-427; only the
            # bleu-selected state is retained here).
            best_loss[name] = min(best_loss[name], record["eval_loss"])
            if record["bleu_em"] > best[name]["bleu_em"]:
                stall[name] = 0
                if snap[0] is None:
                    snap[0] = jax.device_get(state.params)
                best[name] = {"bleu_em": record["bleu_em"],
                              "params": snap[0], "record": record}
            else:
                stall[name] += 1
                if pat[name] is not None and stall[name] > pat[name]:
                    stopped[name] = True
            if log:
                log(f"eval@{at_step} [{name}] " + " ".join(
                    f"{k}={v:.4f}" for k, v in record.items()
                    if isinstance(v, float))
                    + (" EARLY-STOPPED" if stopped[name] else ""))

    rng = np.random.RandomState(cfg.seed)
    p_vec = np.asarray([probs[k] for k in names])
    g = last_eval = skip = 0
    while g < max_steps:
        task = names[rng.choice(len(names), p=p_vec)]
        if stopped.get(task, False):
            skip += 1
            if skip > 50:
                if log:
                    log(f"all tasks early stopped at step {g}")
                break
            continue
        skip = 0
        data = task_data[task]
        sel = rng.choice(len(data["source_ids"]),
                         min(cfg.batch_size, len(data["source_ids"])),
                         replace=False)
        src = data["source_ids"][sel]
        tgt = data["target_ids"][sel]
        if len(sel) < cfg.batch_size:  # pad short task batches
            pad = cfg.batch_size - len(sel)
            src = np.concatenate([src, np.full((pad, src.shape[1]),
                                               model.cfg.pad_token_id, src.dtype)])
            tgt = np.concatenate([tgt, np.full((pad, tgt.shape[1]),
                                               model.cfg.pad_token_id, tgt.dtype)])
        state, loss = step(state, _lift_rows(src, mesh, host),
                           _lift_rows(tgt, mesh, host))
        g += 1
        if log and g % max(max_steps // 10, 1) == 0:
            log(f"step {g}/{max_steps} [{task}] loss={float(loss):.4f}")
        if g % eval_interval == 0:
            last_eval = g
            eval_round(g)

    if last_eval != g:
        # Trailing steps since the last eval boundary (or no eval at all:
        # eval_interval > max_steps) still get a selection round, so every
        # task leaves with a best record/state.
        eval_round(g)

    out: Dict[str, Any] = {"state": state, "tasks": {}, "history": history,
                           "best_params": {}}
    for name in eval_names:
        rec = dict(best[name]["record"] or {"eval_loss": float("nan")})
        rec["early_stopped"] = stopped[name]
        rec["best_loss"] = best_loss[name]
        out["tasks"][name] = rec
        out["best_params"][name] = best[name]["params"]
    return out

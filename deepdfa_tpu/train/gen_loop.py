"""Seq2seq fine-tuning loop for the T5 generation tasks
(summarize/translate/refine/concode — reference CodeT5/run_gen.py).

Reference semantics: teacher-forced CE over target tokens with pads ignored
(HF ``labels=-100`` masking), AdamW + linear warmup, per-epoch eval with
best-loss/best-metric checkpointing, beam-search generation for the final
metric (run_gen.py:104-112 with num_beams=args.beam_size). Here the loss
masks pads explicitly, the train step is one jitted function over a pjit
mesh, and generation uses models/t5_generate.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from deepdfa_tpu.core.config import TransformerTrainConfig
from deepdfa_tpu.models.t5 import T5Config, T5Model, shift_right
from deepdfa_tpu.models.t5_generate import generate
from deepdfa_tpu.train.text_loop import make_schedule, make_text_optimizer


@struct.dataclass
class GenTrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any
    dropout_rng: jnp.ndarray


def seq2seq_loss(
    model: T5Model, params, source_ids, target_ids, dropout_rng=None,
    deterministic: bool = True,
):
    """Masked teacher-forced CE (mean over non-pad target tokens)."""
    c = model.cfg
    dec_in = shift_right(target_ids, c.decoder_start_token_id)
    dec_mask = dec_in != c.pad_token_id
    # position 0 is the start token: always attended
    dec_mask = dec_mask.at[:, 0].set(True)
    rngs = {"dropout": dropout_rng} if dropout_rng is not None else None
    hidden = model.apply(
        params, source_ids, dec_in, decoder_mask=dec_mask,
        deterministic=deterministic, rngs=rngs,
    )
    logits = model.apply(params, hidden, method=type(model).logits)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    tok_lp = jnp.take_along_axis(logp, target_ids[..., None], axis=-1)[..., 0]
    mask = (target_ids != c.pad_token_id).astype(jnp.float32)
    return -(tok_lp * mask).sum() / jnp.maximum(mask.sum(), 1.0)


# Same optimizer recipe as the classifier fine-tunes (one source of truth).
make_gen_optimizer = make_text_optimizer


def make_gen_train_state(
    model: T5Model, example_src, example_tgt, cfg: TransformerTrainConfig,
    max_steps: int, init_params: Optional[Any] = None,
) -> Tuple[GenTrainState, optax.GradientTransformation]:
    rng = jax.random.PRNGKey(cfg.seed)
    params_rng, dropout_rng = jax.random.split(rng)
    params = model.init(
        {"params": params_rng, "dropout": dropout_rng},
        jnp.asarray(example_src),
        shift_right(jnp.asarray(example_tgt), model.cfg.decoder_start_token_id),
    )
    if init_params is not None:
        # Graft (don't replace): pretrained trees may cover only a subtree
        # (e.g. the RoBERTa encoder under a fresh decoder) — text_loop's
        # merge validates every override key/shape against the fresh init.
        from deepdfa_tpu.train.text_loop import _merge_params

        params = _merge_params(params, init_params)
    tx = make_gen_optimizer(cfg, max_steps)
    return (
        GenTrainState(jnp.zeros((), jnp.int32), params, tx.init(params), dropout_rng),
        tx,
    )


def make_gen_train_step(model: T5Model, tx, cfg: TransformerTrainConfig) -> Callable:
    def step(state: GenTrainState, source_ids, target_ids):
        dropout_rng = jax.random.fold_in(state.dropout_rng, state.step)

        def loss_fn(params):
            return seq2seq_loss(
                model, params, source_ids, target_ids,
                dropout_rng=dropout_rng, deterministic=False,
            )

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            GenTrainState(state.step + 1, params, opt_state, state.dropout_rng),
            loss,
        )

    return step


def _batches(data: Dict[str, np.ndarray], batch_size: int, rng=None,
             pad_tail: bool = False, pad_id: int = 0):
    """Yield (source, target, n_valid). With ``pad_tail`` the final short
    batch is padded with all-``pad_id`` rows — such targets contribute
    nothing to the masked loss, so metrics cover every row."""
    n = len(data["source_ids"])
    order = np.arange(n)
    if rng is not None:
        order = rng.permutation(order)
    stop = n if pad_tail else n - batch_size + 1
    for start in range(0, stop, batch_size):
        sel = order[start : start + batch_size]
        src, tgt = data["source_ids"][sel], data["target_ids"][sel]
        n_valid = len(sel)
        if n_valid < batch_size:
            pad = batch_size - n_valid
            src = np.concatenate(
                [src, np.full((pad, src.shape[1]), pad_id, src.dtype)]
            )
            tgt = np.concatenate(
                [tgt, np.full((pad, tgt.shape[1]), pad_id, tgt.dtype)]
            )
        yield src, tgt, n_valid


def _host_of() -> Optional[Tuple[int, int]]:
    """(process_index, process_count) in multi-controller runs, else None —
    the _batches/host contract of train/loop.py extended to the gen/clone
    trainers (reference DDP covered its generation trainer,
    CodeT5/run_defect.py:274-277)."""
    return (
        (jax.process_index(), jax.process_count())
        if jax.process_count() > 1 else None
    )


def _lift_rows(arr: np.ndarray, mesh, host):
    """Slice this host's rows of a deterministic global batch and lift them
    onto the mesh (identity on a single host)."""
    if host is None:
        return jnp.asarray(arr)
    from deepdfa_tpu.parallel.mesh import assemble_global_batch

    pi, pc = host
    if arr.shape[0] % pc:
        # Truncating would silently drop examples from every batch; the
        # trainers validate batch sizes up front, this is the backstop.
        raise ValueError(f"batch rows {arr.shape[0]} % hosts {pc} != 0")
    rows = arr.shape[0] // pc
    return assemble_global_batch(arr[pi * rows : (pi + 1) * rows], mesh)


def _check_host_batch_sizes(cfg: TransformerTrainConfig, host) -> None:
    """Fail before training, not at the first lifted batch (the fit_text
    guard, text_loop.py): every global batch splits evenly across hosts."""
    if host is None:
        return
    pc = host[1]
    if cfg.batch_size % pc or cfg.eval_batch_size % pc:
        raise ValueError(
            f"batch_size {cfg.batch_size} and eval_batch_size "
            f"{cfg.eval_batch_size} must divide by the process count {pc}"
        )


def exact_match(pred: np.ndarray, target: np.ndarray, pad_id: int, eos_id: int) -> float:
    """Fraction of rows whose generated tokens (up to eos) equal the
    reference target tokens (up to eos)."""

    def strip(row):
        out = []
        for t in row:
            if t == eos_id:
                break
            if t != pad_id:
                out.append(int(t))
        return out

    hits = sum(
        strip(p) == strip(t) for p, t in zip(pred, target)
    )
    return hits / max(len(pred), 1)


def evaluate_gen(
    model: T5Model,
    state: GenTrainState,
    eval_data: Dict[str, np.ndarray],
    cfg: TransformerTrainConfig,
    max_target_length: int = 32,
    beam_size: int = 1,
    mesh=None,
    host=None,
) -> Dict[str, float]:
    """Eval loss over padded batches + generation exact-match (shared by
    fit_gen and fit_gen_multitask).

    ``mesh``/``host``: dp sharding / multi-controller feeding. Outputs
    replicate, so predictions and metrics are identical on every host."""
    pad_id = model.cfg.pad_token_id
    loss_fn = lambda params, s, t: seq2seq_loss(model, params, s, t)
    gen_fn = lambda params, src: generate(
        model, params, src, max_len=max_target_length, beam_size=beam_size
    )
    if mesh is not None:
        from deepdfa_tpu.parallel.mesh import batch_sharding, replicated

        rep, dsh = replicated(mesh), batch_sharding(mesh)
        eval_loss_fn = jax.jit(loss_fn, in_shardings=(rep, dsh, dsh),
                               out_shardings=rep)
        gen = jax.jit(gen_fn, in_shardings=(rep, dsh), out_shardings=rep)
    else:
        eval_loss_fn = jax.jit(loss_fn)
        gen = jax.jit(gen_fn)
    losses, preds = [], []
    for s, t, n_valid in _batches(
        eval_data, cfg.eval_batch_size, pad_tail=True, pad_id=pad_id
    ):
        s_dev = _lift_rows(s, mesh, host)
        t_dev = _lift_rows(t, mesh, host)
        losses.append(float(eval_loss_fn(state.params, s_dev, t_dev)))
        preds.append(np.asarray(gen(state.params, s_dev))[:n_valid])
    pred = (
        np.concatenate(preds)
        if preds
        else np.zeros((0, max_target_length), np.int32)
    )
    return {
        "eval_loss": float(np.mean(losses)) if losses else float("nan"),
        "exact_match": exact_match(
            pred, eval_data["target_ids"][: len(pred)],
            model.cfg.pad_token_id, model.cfg.eos_token_id,
        ),
    }


def fit_gen(
    model: T5Model,
    train_data: Dict[str, np.ndarray],
    eval_data: Dict[str, np.ndarray],
    cfg: TransformerTrainConfig,
    max_target_length: int = 32,
    beam_size: int = 1,
    init_params: Optional[Any] = None,
    log: Optional[Callable[[str], None]] = None,
    mesh=None,
) -> Dict[str, Any]:
    """Mini run_gen: train, per-epoch eval loss, final generation metric.
    Returns {"state", "eval_loss", "exact_match"}.

    ``mesh``: optional data-parallel mesh — batches shard over the data
    axis, params replicate, GSPMD all-reduces the grads (the jit analog of
    the reference's DataParallel over the gen tasks). Multi-controller
    (jax.process_count() > 1): every host runs the same deterministic batch
    sequence and feeds its local row slice — the _batches/host contract of
    train/loop.py, replacing DistributedSampler
    (CodeT5/run_defect.py:274-277)."""
    host = _host_of()
    if host is not None and mesh is None:
        raise ValueError("multi-process fit_gen needs an explicit global mesh")
    _check_host_batch_sizes(cfg, host)
    n = len(train_data["source_ids"])
    steps_per_epoch = -(-n // cfg.batch_size)  # ceil: small sets still train
    max_steps = steps_per_epoch * cfg.max_epochs
    state, tx = make_gen_train_state(
        model,
        train_data["source_ids"][: cfg.batch_size],
        train_data["target_ids"][: cfg.batch_size],
        cfg,
        max_steps,
        init_params=init_params,
    )
    step = _jit_gen_step(make_gen_train_step(model, tx, cfg), mesh, cfg)
    pad_id = model.cfg.pad_token_id
    rng = np.random.RandomState(cfg.seed)
    for epoch in range(cfg.max_epochs):
        losses = []
        for src, tgt, _ in _batches(
            train_data, cfg.batch_size, rng, pad_tail=True, pad_id=pad_id
        ):
            state, loss = step(
                state, _lift_rows(src, mesh, host), _lift_rows(tgt, mesh, host)
            )
            losses.append(loss)
        if log:
            log(f"epoch {epoch}: train_loss={float(np.mean(jax.device_get(losses))):.4f}")

    ev = evaluate_gen(model, state, eval_data, cfg, max_target_length, beam_size,
                      mesh=mesh, host=host)
    return {"state": state, **ev}


def _jit_gen_step(step_fn, mesh, cfg):
    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0,))
    from deepdfa_tpu.parallel.mesh import jit_dp_step

    return jit_dp_step(step_fn, mesh, n_batch_args=2, n_out=2,
                       batch_sizes=(cfg.batch_size,))


def task_sampling_probs(sizes: Dict[str, int], alpha: float = 0.7) -> Dict[str, float]:
    """Size-proportional task mixing with temperature smoothing: normalize,
    raise to ``alpha``, renormalize (run_multi_gen.py:269-272)."""
    total = sum(sizes.values())
    p = {k: (v / total) ** alpha for k, v in sizes.items()}
    z = sum(p.values())
    return {k: v / z for k, v in p.items()}


def fit_gen_multitask(
    model: T5Model,
    task_data: Dict[str, Dict[str, np.ndarray]],
    eval_data: Dict[str, Dict[str, np.ndarray]],
    cfg: TransformerTrainConfig,
    max_steps: int,
    alpha: float = 0.7,
    max_target_length: int = 32,
    init_params: Optional[Any] = None,
    log: Optional[Callable[[str], None]] = None,
) -> Dict[str, Any]:
    """Multi-task fine-tuning (run_multi_gen.py parity): each step samples a
    task by smoothed size-proportional probability and trains on a random
    batch from it; eval reports per-task loss + exact match. Task prefixes
    ("Summarize python: ...") belong in the data prep, as in the reference.
    """
    names = sorted(task_data)
    probs = task_sampling_probs({k: len(task_data[k]["source_ids"]) for k in names},
                                alpha)
    first = task_data[names[0]]
    state, tx = make_gen_train_state(
        model, first["source_ids"][: cfg.batch_size],
        first["target_ids"][: cfg.batch_size], cfg, max_steps,
        init_params=init_params,
    )
    step = jax.jit(make_gen_train_step(model, tx, cfg), donate_argnums=(0,))

    rng = np.random.RandomState(cfg.seed)
    p_vec = np.asarray([probs[k] for k in names])
    for i in range(max_steps):
        task = names[rng.choice(len(names), p=p_vec)]
        data = task_data[task]
        sel = rng.choice(len(data["source_ids"]),
                         min(cfg.batch_size, len(data["source_ids"])),
                         replace=False)
        src = data["source_ids"][sel]
        tgt = data["target_ids"][sel]
        if len(sel) < cfg.batch_size:  # pad short task batches
            pad = cfg.batch_size - len(sel)
            src = np.concatenate([src, np.full((pad, src.shape[1]),
                                               model.cfg.pad_token_id, src.dtype)])
            tgt = np.concatenate([tgt, np.full((pad, tgt.shape[1]),
                                               model.cfg.pad_token_id, tgt.dtype)])
        state, loss = step(state, jnp.asarray(src), jnp.asarray(tgt))
        if log and (i + 1) % max(max_steps // 10, 1) == 0:
            log(f"step {i+1}/{max_steps} [{task}] loss={float(loss):.4f}")

    out: Dict[str, Any] = {"state": state, "tasks": {}}
    for task in sorted(eval_data):
        out["tasks"][task] = evaluate_gen(
            model, state, eval_data[task], cfg, max_target_length
        )
    return out

"""Fine-tuning loop for the transformer families (LineVul, UniXcoder, and
the DeepDFA-combined variants).

Reference semantics (LineVul/linevul/linevul_main.py:141-251): AdamW
(lr 2e-5, eps 1e-8) with linear warmup over ``max_steps/5`` then linear
decay, grad-clip 1.0, per-epoch eval keeping the best-F1 state; combined
batches join graphs to text rows by example id, dropping rows whose graph is
missing (here: masking them, counting ``num_missing`` identically).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepdfa_tpu.core.prng import fold_in_dropout
from flax import struct

from deepdfa_tpu.core.config import DataConfig, TransformerTrainConfig, subkeys_for
from deepdfa_tpu.core.metrics import BinaryStats, binary_stats, compute_metrics
from deepdfa_tpu.graphs.batch import GraphBatch, batch_graphs, pad_budget_for
from deepdfa_tpu.models.linevul import LineVul, cross_entropy_loss
from deepdfa_tpu.parallel.mesh import batch_sharding, replicated
from deepdfa_tpu.resilience import inject, lifecycle
from deepdfa_tpu import telemetry

logger = logging.getLogger(__name__)


@struct.dataclass
class TextTrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any
    dropout_rng: jnp.ndarray


@dataclasses.dataclass
class TextBatch:
    input_ids: np.ndarray
    labels: np.ndarray
    example_mask: np.ndarray
    index: np.ndarray
    graphs: Optional[GraphBatch]
    # Rows with an example but no usable graph, counted over the GLOBAL
    # batch before any host slicing (keep_idx accounting, num_missing).
    n_missing: int = 0
    # Multi-controller: host-side numpy copies of the FULL batch's
    # labels/mask/index (taken before row slicing). Eval outputs replicate
    # across hosts, so these are all that's needed for per-example dumps.
    global_meta: Optional[Dict[str, np.ndarray]] = None


def make_schedule(cfg: TransformerTrainConfig, max_steps: int) -> optax.Schedule:
    warmup = max(int(max_steps * cfg.warmup_fraction), 1)
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, cfg.learning_rate, warmup),
            optax.linear_schedule(cfg.learning_rate, 0.0, max(max_steps - warmup, 1)),
        ],
        [warmup],
    )


def make_text_optimizer(
    cfg: TransformerTrainConfig, max_steps: int,
    freeze_submodules: Tuple[str, ...] = (),
) -> optax.GradientTransformation:
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        optax.adamw(
            make_schedule(cfg, max_steps),
            eps=cfg.adam_epsilon,
            weight_decay=cfg.weight_decay,
        ),
    )
    if freeze_submodules:
        # --freeze_graph semantics (reference main_cli.py:136-144 /
        # linevul_main.py:595-602 set requires_grad=False on the loaded
        # encoder): masked zero-updates keep the frozen subtree at its
        # loaded values while the trainable side keeps full clip+adamw —
        # the global-norm clip then sees only trainable grads, matching
        # torch's clip over parameters-with-grad.
        import flax

        frozen = set(freeze_submodules)

        def labels(params):
            flat = flax.traverse_util.flatten_dict(params)
            lab = {
                k: "frozen" if any(p in frozen for p in k[:2]) else "train"
                for k in flat
            }
            return flax.traverse_util.unflatten_dict(lab)

        tx = optax.multi_transform(
            {"train": tx, "frozen": optax.set_to_zero()}, labels
        )
    return tx


def text_graph_batches(
    data: Dict[str, np.ndarray],
    indices: np.ndarray,
    batch_size: int,
    graphs_by_id: Optional[Mapping[int, Mapping]] = None,
    subkeys=None,
    graph_budget: Optional[Dict[str, int]] = None,
    shuffle_rng: Optional[np.random.Generator] = None,
    pad_id: int = 1,
    build_tile_adj: bool = False,
    n_shards: int = 1,
    host: Optional[Tuple[int, int]] = None,
    build_band_adj: bool = False,
) -> Iterable[TextBatch]:
    """Fixed-size text batches, each pre-joined with its graphs.

    Graph slot i belongs to text row i (replacing the reference's per-batch
    ``get_indices`` dict lookup + ``dgl.batch``, linevul/dataset.py:63-76).
    Rows with no parsed graph stay in the batch but are masked out
    (``keep_idx`` semantics). The final short batch is padded with masked
    rows to keep shapes static.

    ``n_shards > 1``: the graph batch is assembled from per-device
    sub-batches via ``shard_concat`` so text row i's graph lives on the same
    data-axis shard as row i — graphs shard with the text instead of riding
    replicated, and the GNN stays collective-free (the mesh alignment
    contract in parallel/mesh.py). Each shard has its own node/edge budget
    (global budget / n_shards); a graph that overflows its shard masks its
    row like a missing graph.

    ``host=(process_index, process_count)`` (multi-controller): every host
    runs the same deterministic packing but yields only its local slice of
    each batch (rows AND the matching graph shards, with node references at
    their global offsets); the caller lifts the slices to global arrays
    with ``assemble_global_batch`` — the _batches/host contract of
    train/loop.py, the DistributedSampler replacement.
    """
    if batch_size % n_shards:
        raise ValueError(f"batch_size {batch_size} % n_shards {n_shards} != 0")
    order = np.array(indices)
    if shuffle_rng is not None:
        order = shuffle_rng.permutation(order)
    for start in range(0, len(order), batch_size):
        sel = order[start : start + batch_size]
        pad = batch_size - len(sel)
        ids = np.concatenate([data["input_ids"][sel],
                              np.full((pad,) + data["input_ids"].shape[1:], pad_id, np.int32)])
        labels = np.concatenate([data["labels"][sel], np.zeros(pad, np.int32)])
        index = np.concatenate([data["index"][sel], np.full(pad, -1, np.int64)])
        mask = np.concatenate([np.ones(len(sel), bool), np.zeros(pad, bool)])

        gbatch = None
        if graphs_by_id is not None:
            budget = graph_budget or {}
            max_nodes = budget.get("max_nodes", batch_size * 64)
            max_edges = budget.get("max_edges", batch_size * 64 * 4)
            rows_per_shard = batch_size // n_shards
            shard_nodes = max_nodes // n_shards
            shard_edges = max_edges // n_shards
            shard_slots = [[] for _ in range(n_shards)]
            used = [[0, 0] for _ in range(n_shards)]
            for row, ex_id in enumerate(index):
                g = graphs_by_id.get(int(ex_id))
                if g is None:
                    mask[row] = False  # keep_idx semantics: no graph, no loss
                    continue
                d = row // rows_per_shard
                n = int(g["num_nodes"])
                e = len(g["senders"]) + n  # + self loops
                if used[d][0] + n > shard_nodes or used[d][1] + e > shard_edges:
                    # Shuffling regroups batches each epoch, so a budget that
                    # held before can overflow now; degrade like a missing
                    # graph instead of aborting training.
                    logger.warning(
                        "graph for example %d dropped: batch over budget "
                        "(%d+%d/%d nodes)", int(ex_id), used[d][0], n, shard_nodes
                    )
                    mask[row] = False
                    continue
                used[d][0] += n
                used[d][1] += e
                shard_slots[d].append((row - d * rows_per_shard, g))
            if n_shards == 1:
                gbatch = _slotted_graph_batch(
                    shard_slots[0], rows_per_shard, shard_nodes, shard_edges,
                    subkeys, build_tile_adj, build_band_adj,
                )
            else:
                from deepdfa_tpu.parallel.mesh import (
                    local_shard_slice,
                    shard_concat,
                )

                sel_sh = (
                    local_shard_slice(n_shards, host[0], host[1])
                    if host is not None else slice(None, n_shards)
                )
                # The slot/budget bookkeeping above already fixed the
                # packing globally; each host materializes only its own
                # shards.
                subs = [
                    _slotted_graph_batch(
                        shard_slots[d], rows_per_shard, shard_nodes,
                        shard_edges, subkeys, build_tile_adj, build_band_adj,
                    )
                    for d in range(*sel_sh.indices(n_shards))
                ]
                tile_nz = tile_dt = None
                band_bw = band_dt = None
                if host is not None and build_tile_adj:
                    # The pow2 tile budget and vals dtype depend on every
                    # shard's edge layout; compute them from edge lists
                    # alone (no dense tiles for remote shards) so all hosts
                    # stack their local slices to one agreed shape+dtype.
                    from deepdfa_tpu.ops.tile_spmm import combine_tile_stats

                    tile_nz, tile_dt = combine_tile_stats([
                        _shard_tile_stats(shard_slots[d], shard_nodes)
                        for d in range(n_shards)
                    ])
                if host is not None and build_band_adj:
                    # Same contract for the banded path: bandwidth + dtype
                    # from edge lists alone.
                    from deepdfa_tpu.ops.band_spmm import combine_band_stats

                    band_bw, band_dt = combine_band_stats([
                        _shard_band_stats(shard_slots[d])
                        for d in range(n_shards)
                    ])
                gbatch = shard_concat(
                    subs, base_shard=sel_sh.start or 0, tile_nz=tile_nz,
                    tile_dtype=tile_dt, band_bandwidth=band_bw,
                    band_dtype=band_dt,
                )
        n_missing = int((index >= 0).sum() - mask.sum())
        gmeta = None
        if host is not None:
            gmeta = {"labels": labels, "mask": mask, "index": index}
            pi, pc = host
            rows_local = batch_size // pc
            row_sel = slice(pi * rows_local, (pi + 1) * rows_local)
            ids, labels = ids[row_sel], labels[row_sel]
            mask, index = mask[row_sel], index[row_sel]
        yield TextBatch(ids, labels, mask, index, gbatch, n_missing, gmeta)


def _shard_tile_stats(slot_graphs, max_nodes: int):
    """(pow2 tile budget, vals dtype) a shard's adjacency will carry, from
    edge lists alone.

    Replicates just enough of ``batch_graphs``' layout (contiguous packing
    in slot order + per-graph self loops, graphs/batch.py:189-214) to know
    which adjacency tiles are nonzero and whether multiplicities stay
    bf16-exact — parity with the materialized batch is pinned by
    ``test_shard_tile_stats_match_built_batch``.
    """
    from deepdfa_tpu.ops.tile_spmm import (
        align_to_tile,
        tile_nz_budget,
        tile_vals_dtype,
    )

    senders, receivers, off = [], [], 0
    for _, g in slot_graphs:
        n = int(g["num_nodes"])
        loops = np.arange(off, off + n, dtype=np.int64)
        senders += [np.asarray(g["senders"], np.int64) + off, loops]
        receivers += [np.asarray(g["receivers"], np.int64) + off, loops]
        off += n
    z = np.zeros(0, np.int64)
    s = np.concatenate(senders) if senders else z
    r = np.concatenate(receivers) if receivers else z
    return (
        tile_nz_budget(s, r, align_to_tile(max_nodes)),
        tile_vals_dtype(s, r),
    )


def _shard_band_stats(slot_graphs):
    """(bucketed bandwidth, vals dtype) a shard's banded adjacency will
    carry, from edge lists alone — the band sibling of _shard_tile_stats,
    sharing its packed-layout replication. Unlike the tile budget, the
    bandwidth depends only on node spans within the packed layout, not on
    the shard's node budget."""
    from deepdfa_tpu.ops.band_spmm import band_width_for
    from deepdfa_tpu.ops.tile_spmm import tile_vals_dtype

    senders, receivers, off = [], [], 0
    for _, g in slot_graphs:
        n = int(g["num_nodes"])
        loops = np.arange(off, off + n, dtype=np.int64)
        senders += [np.asarray(g["senders"], np.int64) + off, loops]
        receivers += [np.asarray(g["receivers"], np.int64) + off, loops]
        off += n
    z = np.zeros(0, np.int64)
    s = np.concatenate(senders) if senders else z
    r = np.concatenate(receivers) if receivers else z
    return band_width_for(s, r), tile_vals_dtype(s, r)


def _slotted_graph_batch(slot_graphs, n_slots, max_nodes, max_edges, subkeys,
                         build_tile_adj: bool = False,
                         build_band_adj: bool = False):
    """batch_graphs, but graphs land in given slots (empty slots masked)."""
    ordered = []
    slot_of = {}
    for row, g in slot_graphs:
        slot_of[len(ordered)] = row
        ordered.append(g)
    # n_slots graph slots regardless of how many graphs exist, so batch
    # shapes stay static across batches with missing graphs.
    if build_tile_adj or build_band_adj:
        from deepdfa_tpu.ops.tile_spmm import align_to_tile

        max_nodes = align_to_tile(max_nodes)
    b = batch_graphs(ordered, n_slots, max_nodes, max_edges, subkeys,
                     build_tile_adj=build_tile_adj,
                     build_band_adj=build_band_adj)
    # Remap graph slot ids to text-row slots.
    remap = np.zeros(max(len(ordered), 1), np.int32)
    graph_mask = np.zeros(n_slots, bool)
    graph_ids = np.full(n_slots, -1, np.int64)
    for k, row in slot_of.items():
        remap[k] = row
        graph_mask[row] = True
        graph_ids[row] = int(np.asarray(b.graph_ids)[k])
    node_graph = remap[np.asarray(b.node_graph)]
    return GraphBatch(
        node_feats=b.node_feats,
        node_vuln=b.node_vuln,
        senders=b.senders,
        receivers=b.receivers,
        node_graph=jnp.asarray(node_graph),
        node_mask=b.node_mask,
        edge_mask=b.edge_mask,
        graph_mask=jnp.asarray(graph_mask),
        graph_ids=jnp.asarray(graph_ids),
        # The tile/band adjacencies depend only on senders/receivers, which
        # the slot remap leaves untouched.
        tile_adj=b.tile_adj,
        band_adj=b.band_adj,
    )


def make_text_train_state(
    model: LineVul,
    example: TextBatch,
    cfg: TransformerTrainConfig,
    max_steps: int,
    init_params: Optional[Any] = None,
    freeze_submodules: Tuple[str, ...] = (),
) -> Tuple[TextTrainState, optax.GradientTransformation]:
    rng = jax.random.PRNGKey(cfg.seed)
    params_rng, dropout_rng = jax.random.split(rng)
    params = model.init(
        {"params": params_rng, "dropout": dropout_rng},
        jnp.asarray(example.input_ids),
        example.graphs,
        deterministic=True,
    )
    if init_params is not None:
        params = _merge_params(params, init_params)
    tx = make_text_optimizer(cfg, max_steps, freeze_submodules)
    return TextTrainState(jnp.zeros((), jnp.int32), params, tx.init(params), dropout_rng), tx


def _merge_params(params: Any, overrides: Any) -> Any:
    """Graft pretrained subtrees (e.g. converted HF weights under
    params['params']['roberta'], or a trained flowgnn encoder) onto a fresh
    init."""
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    over = flax.traverse_util.flatten_dict(overrides)
    unknown = [k for k in over if k not in flat]
    if unknown:
        # An override that matches nothing would silently leave the model at
        # its random init (e.g. converter output not nested under the
        # submodule name the model uses).
        raise KeyError(
            f"{len(unknown)} override params not present in the model tree, "
            f"e.g. {'/'.join(unknown[0])!r}; nest the pretrained tree under "
            "the submodule name (e.g. params['params']['roberta'])"
        )
    for k, v in over.items():
        assert flat[k].shape == v.shape, (k, flat[k].shape, v.shape)
        flat[k] = v
    return flax.traverse_util.unflatten_dict(flat)


def make_text_train_step(model: LineVul, tx, cfg: TransformerTrainConfig) -> Callable:
    def step(state: TextTrainState, input_ids, labels, example_mask, graphs):
        dropout_rng = fold_in_dropout(state.dropout_rng, state.step)

        def loss_fn(params):
            logits = model.apply(
                params, input_ids, graphs, deterministic=False,
                rngs={"dropout": dropout_rng},
            )
            return cross_entropy_loss(logits, labels, example_mask), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        probs = jax.nn.softmax(logits, axis=-1)[:, 1]
        stats = binary_stats(probs, labels.astype(jnp.float32), example_mask)
        return (
            TextTrainState(state.step + 1, params, opt_state, state.dropout_rng),
            loss,
            stats,
        )

    return step


def make_text_eval_step(model: LineVul) -> Callable:
    def step(state: TextTrainState, input_ids, labels, example_mask, graphs):
        logits = model.apply(state.params, input_ids, graphs, deterministic=True)
        loss = cross_entropy_loss(logits, labels, example_mask)
        probs = jax.nn.softmax(logits, axis=-1)[:, 1]
        return loss, probs

    return step


def _run_step(step_fn, state, batch: TextBatch):
    return step_fn(
        state,
        jnp.asarray(batch.input_ids),
        jnp.asarray(batch.labels),
        jnp.asarray(batch.example_mask),
        batch.graphs,
    )


def _assemble_text(batch: TextBatch, mesh) -> TextBatch:
    """Multi-controller: lift each host's local batch slice onto the global
    mesh (jax.make_array_from_process_local_data — parallel/mesh.py)."""
    from deepdfa_tpu.parallel.mesh import assemble_global_batch, batch_sharding

    sh = batch_sharding(mesh)
    lift = lambda x: assemble_global_batch(jnp.asarray(x), mesh, sharding=sh)
    return TextBatch(
        input_ids=lift(np.asarray(batch.input_ids)),
        labels=lift(np.asarray(batch.labels)),
        example_mask=lift(np.asarray(batch.example_mask)),
        index=batch.index,  # host bookkeeping only
        graphs=(
            assemble_global_batch(batch.graphs, mesh) if batch.graphs is not None
            else None
        ),
        n_missing=batch.n_missing,
        global_meta=batch.global_meta,
    )


def evaluate_text(
    eval_step, state, data, indices, cfg: TransformerTrainConfig,
    graphs_by_id=None, subkeys=None, graph_budget=None, pad_id: int = 1,
    build_tile_adj: bool = False, n_shards: int = 1, host=None, mesh=None,
    build_band_adj: bool = False,
):
    """``host``/``mesh``: multi-controller mode — the jitted eval outputs
    replicate across hosts, and the batch carries host-side global
    labels/mask/index, so every host returns the same full per-example
    dump (PR CSVs, export_predictions, DbgBench all work on a pod)."""
    stats = BinaryStats.zeros()
    # Losses stay on device until one jax.device_get after the loop — the
    # blocking scalar read the old per-batch float(loss) did is gone
    # (graftlint GL004). The per-batch probs read below remains: those ARE
    # host outputs. device_get over the retained list (vs eager adds into
    # an accumulator) also stays legal on multi-controller pods, where
    # eager math on non-fully-addressable replicated outputs is not.
    losses = []
    probs_all, labels_all, index_all = [], [], []
    num_missing = 0
    for batch in text_graph_batches(
        data, indices, cfg.eval_batch_size, graphs_by_id, subkeys, graph_budget,
        pad_id=pad_id, build_tile_adj=build_tile_adj, n_shards=n_shards,
        host=host, build_band_adj=build_band_adj,
    ):
        num_missing += batch.n_missing
        if host is not None:
            gm = batch.global_meta
            labels_np, m, index_np = gm["labels"], gm["mask"], gm["index"]
            batch = _assemble_text(batch, mesh)
        else:
            labels_np, m, index_np = batch.labels, batch.example_mask, batch.index
        loss, probs = _run_step(eval_step, state, batch)
        # probs is replicated output in host mode: addressable everywhere.
        p = np.asarray(probs)
        stats = stats + binary_stats(
            jnp.asarray(p), jnp.asarray(labels_np, jnp.float32), jnp.asarray(m)
        )
        probs_all.append(p[m])
        labels_all.append(labels_np[m])
        index_all.append(index_np[m])
        losses.append(loss)
    metrics = {k: float(v) for k, v in compute_metrics(stats).items()}
    if num_missing:
        logger.info("eval: %d examples missing graphs (masked)", num_missing)
    return {
        "loss": float(np.mean(jax.device_get(losses))) if losses else 0.0,
        "metrics": metrics,
        "probs": np.concatenate(probs_all) if probs_all else np.zeros(0),
        "labels": np.concatenate(labels_all) if labels_all else np.zeros(0),
        "index": np.concatenate(index_all) if index_all else np.zeros(0, np.int64),
        "num_missing": num_missing,
    }


def fit_text(
    model: LineVul,
    data: Dict[str, np.ndarray],
    splits: Dict[str, np.ndarray],
    cfg: TransformerTrainConfig = TransformerTrainConfig(),
    graphs_by_id: Optional[Mapping[int, Mapping]] = None,
    subkeys=None,
    graph_budget: Optional[Dict[str, int]] = None,
    init_params: Optional[Any] = None,
    mesh=None,
    pad_id: int = 1,
    freeze_submodules: Tuple[str, ...] = (),
    checkpointer=None,
) -> Tuple[TextTrainState, Dict[str, Any]]:
    """Fine-tune, keeping the best state by val F1 (linevul_main.py:217-242).

    ``freeze_submodules``: top-level param subtrees (e.g. ``("flowgnn",)``)
    held at their init/loaded values via masked zero-updates — the
    ``--freeze_graph`` flow where a pretrained DDFA encoder is loaded with
    ``load_encoder_params`` and only the text side trains
    (main_cli.py:136-144).

    ``checkpointer``: optional ``CheckpointManager``-shaped manager; when
    given the loop snapshots ``last`` each epoch and ``best`` on val-F1
    improvement (the preemption-survival posture of train/loop.py — a
    10-hour combined fine-tune must resume, not restart), draining any
    async writes before returning."""
    # ceil: the padded partial batch is a real optimizer step, and the LR
    # schedule must cover it (the reference sizes by len(train_dataloader)).
    steps_per_epoch = max(-(-len(splits["train"]) // cfg.batch_size), 1)
    max_steps = steps_per_epoch * cfg.max_epochs

    build_tile_adj = (
        model.graph_config is not None
        and model.graph_config.uses_tile_adj
    )
    build_band_adj = (
        model.graph_config is not None
        and model.graph_config.uses_band_adj
    )
    from deepdfa_tpu.parallel.mesh import DATA_AXIS

    n_shards = int(mesh.shape[DATA_AXIS]) if mesh is not None else 1
    host = (jax.process_index(), jax.process_count()) if jax.process_count() > 1 else None
    if host is not None and mesh is None:
        raise ValueError("multi-process fit_text needs an explicit global mesh")
    if cfg.batch_size % n_shards or cfg.eval_batch_size % n_shards:
        # Fail before training, not at the first eval after a full epoch.
        raise ValueError(
            f"batch_size {cfg.batch_size} and eval_batch_size "
            f"{cfg.eval_batch_size} must divide by the data-axis size {n_shards}"
        )
    if mesh is not None and model.mesh is not mesh:
        # Sharded graph batches run the tile kernel under shard_map and the
        # ring-attention path also needs the mesh on the model.
        model = model.clone(mesh=mesh)
    example = next(
        text_graph_batches(
            data, splits["train"][: cfg.batch_size], cfg.batch_size,
            graphs_by_id, subkeys, graph_budget, pad_id=pad_id,
            build_tile_adj=build_tile_adj, build_band_adj=build_band_adj,
            n_shards=n_shards, host=host,
        )
    )
    if host is not None:
        example = _assemble_text(example, mesh)
    state, tx = make_text_train_state(model, example, cfg, max_steps, init_params,
                                      freeze_submodules=freeze_submodules)
    train_step = make_text_train_step(model, tx, cfg)
    eval_step = make_text_eval_step(model)
    if mesh is not None:
        from deepdfa_tpu.parallel.mesh import jit_dp_step

        train_step = jit_dp_step(train_step, mesh, n_batch_args=4, n_out=3,
                                 donate=())
        eval_step = jit_dp_step(eval_step, mesh, n_batch_args=4, n_out=2,
                                donate=())
    else:
        train_step = jax.jit(train_step)
        eval_step = jax.jit(eval_step)

    if cfg.anomaly_policy not in ("raise", "rollback"):
        raise ValueError(
            f"anomaly_policy must be 'raise' or 'rollback', "
            f"got {cfg.anomaly_policy!r}"
        )
    detect_anomaly = cfg.detect_anomaly or cfg.anomaly_policy == "rollback"
    anomaly_budget = cfg.anomaly_retry_budget
    history: Dict[str, Any] = {"epochs": [], "best_epoch": -1, "best_val_f1": -1.0}
    best_state = state
    rng = np.random.default_rng(cfg.seed)
    if checkpointer is not None:
        from deepdfa_tpu.parallel.mesh import snapshot_layout

        checkpointer.set_layout(snapshot_layout(mesh))
    try:
        best_state, history = _fit_text_epochs(
            model, data, splits, cfg, graphs_by_id, subkeys, graph_budget,
            mesh, pad_id, checkpointer, build_tile_adj, build_band_adj,
            n_shards, host, train_step, eval_step, state, best_state,
            history, rng, detect_anomaly, anomaly_budget,
        )
    finally:
        if checkpointer is not None:
            # Fit-exit drain barrier (the async-manager contract): every
            # submitted snapshot commits before the caller sees the run.
            checkpointer.drain()
    return best_state, history


def _fit_text_epochs(
    model, data, splits, cfg, graphs_by_id, subkeys, graph_budget, mesh,
    pad_id, checkpointer, build_tile_adj, build_band_adj, n_shards, host,
    train_step, eval_step, state, best_state, history, rng, detect_anomaly,
    anomaly_budget,
):
    # Coordinated fleet drain (ISSUE 18): same barrier as train/loop.py —
    # in a multi-process fine-tune one host's notice becomes a shared
    # step-boundary target instead of an immediate exit that would
    # strand peers inside a collective.
    fleet = lifecycle.fleet_drain(
        checkpointer.directory if checkpointer is not None else None, host)
    if fleet is not None:
        fleet.clear()
    for epoch in range(cfg.max_epochs):
        inject.fire("train.epoch_start", index=epoch)
        t0 = time.time()
        stats = BinaryStats.zeros()
        # Epoch-start reference for anomaly rollback (holding the
        # functional state value costs nothing).
        epoch_start_state = state
        # Loss accumulates on-device; one transfer per epoch keeps dispatch
        # running ahead of execution.
        loss_sum = jnp.zeros(())
        n_batches, num_missing = 0, 0
        # Fenced epoch span (device-inclusive wall, host/device split);
        # per-step spans inside measure host dispatch only — same
        # pairing as train/loop.py, same report semantics.
        with telemetry.span("train.epoch", epoch=epoch, loop="text") as ep:
            for batch in text_graph_batches(
                data, splits["train"], cfg.batch_size, graphs_by_id, subkeys,
                graph_budget, shuffle_rng=rng, pad_id=pad_id,
                build_tile_adj=build_tile_adj, build_band_adj=build_band_adj,
                n_shards=n_shards, host=host,
            ):
                num_missing += batch.n_missing
                # Fleet drain target check BEFORE dispatch (ISSUE 18):
                # every process stops at the same (epoch, step).
                if fleet is not None:
                    tgt = fleet.reached(epoch, n_batches)
                    if tgt is not None:
                        notice = lifecycle.poll()
                        if notice is None:
                            notice = lifecycle.coordinator().notify(
                                "fleet_drain")
                        fleet.mark_draining(epoch, n_batches)
                        lifecycle.preempt_snapshot_exit(
                            notice,
                            checkpointer if (host is None or host[0] == 0)
                            else None,
                            state, epoch, n_batches, history=history,
                            resume={"seen": int(n_batches), "loop": "text"},
                            loop="text")
                if host is not None:
                    batch = _assemble_text(batch, mesh)
                with telemetry.span("train.step", epoch=epoch,
                                    step=n_batches):
                    state, loss, bstats = _run_step(train_step, state, batch)
                if fleet is not None:
                    # Dispatch fence: the barrier's one-step-ahead bound.
                    jax.block_until_ready(loss)
                loss = inject.corrupt_loss(loss)
                loss_sum = loss_sum + loss
                stats = stats + bstats
                n_batches += 1
                # Step-granular preemption check (ISSUE 10): SIGTERM (or
                # a simulated notice) drains to a durable
                # preempt_<epoch>_<step> snapshot and exits typed — a
                # 10-hour combined fine-tune loses at most one step, not
                # the partial epoch. (Resume restarts this epoch from
                # the preempt state; the step-granular batch skip is the
                # graph fit's — train/loop.py.) Multi-controller: only
                # process 0 owns the run dir, same gating as save_last.
                notice = lifecycle.poll()
                if notice is not None:
                    if fleet is None:
                        lifecycle.preempt_snapshot_exit(
                            notice,
                            checkpointer if (host is None or host[0] == 0)
                            else None,
                            state, epoch, n_batches, history=history,
                            resume={"seen": int(n_batches), "loop": "text"},
                            loop="text")
                    # Fleet: announce the next step boundary as the drain
                    # target (a peer may already be inside step
                    # n_batches + 1's collective) and keep participating
                    # until it — the reached() check above drains.
                    fleet.announce(epoch, n_batches + 1, notice.reason)
            ep.fence(loss_sum)
            ep.set(steps=n_batches)
        epoch_loss = float(loss_sum)
        # Anomaly handling at epoch granularity: the per-epoch host
        # transfer above is the one sync that already exists, so detection
        # adds none. NaN/inf propagates through the sum, so a single
        # poisoned step marks the whole epoch.
        rolled_back = False
        if detect_anomaly and not math.isfinite(epoch_loss):
            if cfg.anomaly_policy != "rollback":
                raise FloatingPointError(
                    f"non-finite loss in epoch {epoch}"
                )
            if anomaly_budget <= 0:
                raise FloatingPointError(
                    f"non-finite loss in epoch {epoch} "
                    "(anomaly retry budget exhausted)"
                )
            anomaly_budget -= 1
            rolled_back = True
            history["anomaly_rollbacks"] = (
                history.get("anomaly_rollbacks", 0) + 1
            )
            logger.warning(
                "non-finite loss in epoch %d: rolling back to the "
                "epoch-start state and continuing (%d retries left)",
                epoch, anomaly_budget,
            )
            state = epoch_start_state
            telemetry.event("train.rollback", epoch=epoch, loop="text")
        with telemetry.span("train.eval", epoch=epoch, loop="text"):
            val = evaluate_text(
                eval_step, state, data, splits["val"], cfg, graphs_by_id,
                subkeys, graph_budget, pad_id=pad_id,
                build_tile_adj=build_tile_adj,
                build_band_adj=build_band_adj, n_shards=n_shards, host=host,
                mesh=mesh,
            )
        if epoch == 0:
            # Cost-model capture for the combined step (roofline input):
            # one re-lower of the warm program, instrumented runs only,
            # before the warmup marker — same contract as train/loop.py.
            # NOTE: XLA's cost analysis reports ~0 FLOPs for Pallas
            # custom calls, so a flash-attention step under-counts here;
            # bench.py's analytic correction remains the MFU headline
            # for that path (its module docstring).
            if host is None and telemetry.current_run() is not None \
                    and n_batches:
                from deepdfa_tpu.telemetry import costmodel

                costmodel.capture_jitted(
                    "train.step", train_step, state,
                    jnp.asarray(batch.input_ids),
                    jnp.asarray(batch.labels),
                    jnp.asarray(batch.example_mask),
                    batch.graphs, use_fenced_window=True)
            telemetry.event("train.warmup_done", epoch=epoch, loop="text")
        record = {
            "epoch": epoch,
            "train_loss": epoch_loss / max(n_batches, 1),
            "train_metrics": {k: float(v) for k, v in compute_metrics(stats).items()},
            "val_loss": val["loss"],
            "val_metrics": val["metrics"],
            "num_missing": num_missing,
            "seconds": time.time() - t0,
        }
        if rolled_back:
            record["rolled_back"] = True
        history["epochs"].append(record)
        telemetry.event("train.epoch_end", epoch=epoch, loop="text",
                        train_loss=record["train_loss"],
                        val_f1=val["metrics"]["f1"],
                        seconds=record["seconds"],
                        rolled_back=rolled_back)
        telemetry.flush()  # epoch cadence: don't ride the ring until close
        logger.info(
            "epoch %d train_loss %.4f val_f1 %.4f (%.1fs)",
            epoch, record["train_loss"], val["metrics"]["f1"], record["seconds"],
        )
        # Multi-controller: only process 0 writes — every host shares the
        # run dir, and racing orbax saves + meta commits would tear it
        # (same gating as gen_loop's checkpoint wiring).
        if checkpointer is not None and (host is None or host[0] == 0):
            checkpointer.save_last(state, epoch)
            checkpointer.maybe_save_periodic(state, epoch)
        if val["metrics"]["f1"] > history["best_val_f1"]:
            history["best_val_f1"] = val["metrics"]["f1"]
            history["best_epoch"] = epoch
            best_state = state
            if checkpointer is not None and (host is None or host[0] == 0):
                checkpointer.save_best(state, epoch,
                                       metrics={"val_f1": val["metrics"]["f1"]})
        elif (
            cfg.early_stop_patience is not None
            and epoch - history["best_epoch"] >= cfg.early_stop_patience
        ):
            # CodeT5 stops after `patience` epochs without an eval-F1
            # improvement (run_defect.py:383-405).
            logger.info("early stop at epoch %d (best %d)", epoch, history["best_epoch"])
            history["early_stopped"] = True
            break
    return best_state, history

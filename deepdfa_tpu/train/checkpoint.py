"""Checkpointing via orbax, hardened for preemptible hardware.

Reproduces the reference's checkpoint semantics (SURVEY §5): best-by-val-loss
with ``save_last`` (Lightning ModelCheckpoint, config_default.yaml:23-29),
periodic every-N-epochs snapshots (periodic_checkpoint.py:8-22), and
partial-load-and-freeze of the graph encoder for the combined models
(main_cli.py:136-144 ``--freeze_graph`` strips head/pooling keys). Best
checkpoint metadata is stored explicitly instead of being re-parsed out of
filenames (main_cli.py:175-184).

Robustness contract (the preemptible-TPU posture, tests/test_resilience.py):

* ``meta.json`` writes are atomic (tmp file + ``os.replace`` + fsync of
  file and directory) — a preemption mid-write can never brick resume;
  a corrupt existing meta.json degrades to defaults with a warning
  instead of crashing at construction.
* Every snapshot records a content checksum in ``meta.json``; restores
  verify it and, on mismatch (or an unreadable snapshot), fall back to
  the newest intact snapshot. Fallback order: the requested name first,
  then every other recorded snapshot by descending epoch, ties broken
  ``last`` > ``preempt_E_S`` > ``epoch_N`` > ``best``. ``last_restored``
  reports what was actually loaded so resume can restart from the
  surviving epoch.

Preemption snapshots (ISSUE 10):

* :meth:`save_preempt` writes ``preempt_<epoch>_<step>`` — the SIGTERM
  drain's step-granular snapshot. The state tree is byte-identical in
  structure to ``last`` (so the verified-restore fallback works across
  names); the step-level resume payload (step index, data-order cursor,
  host-side accumulator values) rides the snapshot's ``meta.json``
  record and comes back through :meth:`preempt_info`. A preempt
  snapshot taken mid-epoch ``E`` records epoch ``E`` and therefore
  outranks the previous epoch's ``last`` in the fallback order — the
  partial epoch wins the resume — while a torn one never beats an
  intact older snapshot (checksum verification is name-blind), and a
  completed epoch's ``last`` retakes the tie.

Elastic/async extensions (ISSUE 6):

* :class:`AsyncCheckpointManager` moves everything but the device→host
  copy *start* off the step loop: ``save_*`` begins a non-blocking
  host copy and enqueues the write; a dedicated writer thread
  serializes, checksums, and commits ``meta.json`` with the same
  atomicity/verified-restore/fallback guarantees as the sync path. At
  most one write per snapshot name is pending: a newer save of the same
  name supersedes a still-queued older one (``ckpt.superseded``).
  ``drain()`` is the barrier callers take at fit-exit and before any
  ``best``-dependent decision. A writer-thread crash costs at most the
  in-flight snapshot — the committed ``meta.json`` still references the
  previous intact bytes, so a torn write can never *win* a restore.
* Snapshots record the logical DP layout (``set_layout``), so restore
  can detect a device-count change and the training loop reshards
  (``parallel/mesh.py:reshard_state``) instead of refusing to resume.
* ``verify`` caches content digests keyed by the snapshot's stat
  signature (per-file size + mtime), so fallback resolution does not
  re-read gigabyte-class snapshots on every call.
* ``DEEPDFA_ASYNC_CKPT=0`` is the escape hatch:
  :func:`make_checkpoint_manager` then returns the synchronous manager
  and training behaves bit-identically to the pre-async layer.

Elastic multi-process snapshots (ISSUE 18):

* Under a live multi-controller topology (``set_host``), every process
  writes its own leaf-partitioned shard ``shard_<i>_of_<n>/`` (leaf
  ``k`` of the path-sorted flatten belongs to process ``k % n``); the
  primary waits for every shard's fsync'd ``.complete`` marker (a
  filesystem rendezvous), writes ``shards.json``, and alone commits the
  checksum + ``meta.json`` record. Restores of a sharded snapshot
  consolidate — the primary reads every shard, reassembles the
  replicated tree, and broadcasts it to the fleet
  (``multihost_utils.broadcast_one_to_all``, the orbax discipline) —
  so ``fit --resume`` works across a ``process_count`` change instead
  of refusing. :meth:`redistribute` rewrites a snapshot for a new
  process count up front (the benched ``ckpt_redistribute_ms`` path):
  a hardlink re-grouping fast path when the old and new shard sets
  nest (``old % new == 0``), a consolidate-and-reshard slow path
  otherwise, and a plain orbax snapshot when the new count is 1. A
  snapshot whose shard set is genuinely unrecoverable (missing shard
  dir/manifest/leaf file) raises the typed
  ``ProcessCountMismatchError`` — never a bare ``KeyError``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from deepdfa_tpu.parallel.mesh import ProcessCountMismatchError
from deepdfa_tpu.resilience import inject
from deepdfa_tpu import telemetry

logger = logging.getLogger(__name__)

_EPOCH_NAME_RE = re.compile(r"^epoch_(\d+)$")
_PREEMPT_NAME_RE = re.compile(r"^preempt_(\d+)_(\d+)$")

ASYNC_ENV_VAR = "DEEPDFA_ASYNC_CKPT"


def async_enabled() -> bool:
    """``DEEPDFA_ASYNC_CKPT=0`` forces the synchronous manager everywhere
    (the bit-identical escape hatch); anything else keeps async on."""
    return os.environ.get(ASYNC_ENV_VAR, "1") != "0"


def make_checkpoint_manager(directory: str, periodic_every: int = 25):
    """THE manager factory the training loops use: async by default,
    synchronous under ``DEEPDFA_ASYNC_CKPT=0``."""
    cls = AsyncCheckpointManager if async_enabled() else CheckpointManager
    return cls(directory, periodic_every=periodic_every)


class CheckpointError(RuntimeError):
    """No intact snapshot exists for a requested restore."""


def snapshot_checksum(path: str) -> str:
    """Content digest of one snapshot directory: sha256 over the sorted
    relative paths and file bytes."""
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames.sort()
        for fn in sorted(filenames):
            p = os.path.join(dirpath, fn)
            h.update(os.path.relpath(p, path).encode())
            h.update(b"\0")
            with open(p, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            h.update(b"\0")
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Sharded snapshot format (elastic multi-process, ISSUE 18)
# ---------------------------------------------------------------------------

_SHARD_DIR_RE = re.compile(r"^shard_(\d+)_of_(\d+)$")

# Rendezvous deadline (seconds) the primary waits for every process's
# shard marker before declaring the fleet write failed.
SHARD_WAIT_ENV = "DEEPDFA_SHARD_WAIT_S"


def _shard_wait_s() -> float:
    try:
        return float(os.environ.get(SHARD_WAIT_ENV, "120"))
    except ValueError:
        return 120.0


class _ShardSuperseded(CheckpointError):
    """A peer's shard marker reports a newer epoch than the write being
    committed: the async queue superseded this name on another process.
    The stale commit is abandoned; the newer fleet write wins."""


def _shard_dir_name(process_index: int, process_count: int) -> str:
    return f"shard_{int(process_index)}_of_{int(process_count)}"


def is_sharded_snapshot(path: str) -> bool:
    """True when the snapshot directory holds the per-process shard
    layout (written under a multi-controller topology) rather than a
    plain orbax tree."""
    if os.path.exists(os.path.join(path, "shards.json")):
        return True
    try:
        return any(_SHARD_DIR_RE.match(d) for d in os.listdir(path))
    except OSError:
        return False


def _shard_count(path: str) -> int:
    """Process count a snapshot's bytes were written under (1 = plain)."""
    sj = os.path.join(path, "shards.json")
    if os.path.exists(sj):
        try:
            with open(sj) as f:
                return int(json.load(f)["process_count"])
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            pass
    try:
        for d in os.listdir(path):
            m = _SHARD_DIR_RE.match(d)
            if m:
                return int(m.group(2))
    except OSError:
        pass
    return 1


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16 et al. by name

        return np.dtype(getattr(ml_dtypes, name))


def _fsync_write_json(path: str, payload: Dict[str, Any]) -> None:
    with open(path, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())


def write_state_shard(path: str, host_state: Any, process_index: int,
                      process_count: int, epoch: int) -> None:
    """Write THIS process's leaf partition of ``host_state`` under
    ``path/shard_<i>_of_<n>/``: raw little-endian leaf files plus a
    MANIFEST.json (dtype/shape per leaf; non-numeric leaves inline),
    finished by an fsync'd ``.complete`` marker carrying the epoch —
    the rendezvous token the primary waits for."""
    leaves, _ = jax.tree_util.tree_flatten(host_state)
    os.makedirs(path, exist_ok=True)
    sd = os.path.join(path, _shard_dir_name(process_index, process_count))
    shutil.rmtree(sd, ignore_errors=True)
    os.makedirs(sd)
    manifest: Dict[str, Any] = {
        "format": 1,
        "process_index": int(process_index),
        "process_count": int(process_count),
        "epoch": int(epoch),
        "n_leaves": len(leaves),
        "leaves": {},
    }
    for i, leaf in enumerate(leaves):
        if i % int(process_count) != int(process_index):
            continue
        arr = np.asarray(leaf)
        if arr.dtype == object:
            manifest["leaves"][str(i)] = {"value": leaf}
            continue
        fn = f"leaf_{i}.bin"
        np.ascontiguousarray(arr).tofile(os.path.join(sd, fn))
        manifest["leaves"][str(i)] = {
            "file": fn, "dtype": str(arr.dtype), "shape": list(arr.shape),
        }
    _fsync_write_json(os.path.join(sd, "MANIFEST.json"), manifest)
    # The marker is written LAST: its presence means every byte above is
    # already on disk, so the primary's rendezvous wait doubles as the
    # write barrier.
    _fsync_write_json(os.path.join(sd, ".complete"), {"epoch": int(epoch)})


def _write_shards_json(path: str, process_count: int) -> None:
    _fsync_write_json(os.path.join(path, "shards.json"),
                      {"process_count": int(process_count)})


def _read_shard_manifest(path: str, process_index: int,
                         process_count: int) -> Dict[str, Any]:
    sd = os.path.join(path, _shard_dir_name(process_index, process_count))
    mf = os.path.join(sd, "MANIFEST.json")
    if not os.path.isdir(sd) or not os.path.exists(mf):
        raise ProcessCountMismatchError(
            f"snapshot {path} was written by {process_count} processes but "
            f"shard {process_index} is missing ({sd}); the shard set is "
            "unrecoverable — restore from another snapshot or re-run the "
            "original fleet"
        )
    with open(mf) as f:
        return json.load(f)


def consolidate_sharded(path: str, host_target: Any) -> Any:
    """Reassemble the full replicated tree from every per-process shard
    of a sharded snapshot (the primary's half of the broadcast-from-
    primary restore). ``host_target`` supplies the tree structure.
    Raises the typed :class:`ProcessCountMismatchError` when a shard
    dir, manifest, or leaf file is missing — never a bare ``KeyError``.
    """
    leaves_t, treedef = jax.tree_util.tree_flatten(host_target)
    n = len(leaves_t)
    pc = _shard_count(path)
    values: Dict[int, Any] = {}
    for p in range(pc):
        manifest = _read_shard_manifest(path, p, pc)
        if int(manifest.get("n_leaves", n)) != n:
            raise ProcessCountMismatchError(
                f"snapshot {path} shard {p} records "
                f"{manifest.get('n_leaves')} leaves but the resume target "
                f"has {n}: the tree structures do not match"
            )
        sd = os.path.join(path, _shard_dir_name(p, pc))
        for key, spec in manifest["leaves"].items():
            i = int(key)
            if "value" in spec:
                values[i] = spec["value"]
                continue
            fp = os.path.join(sd, spec["file"])
            if not os.path.exists(fp):
                raise ProcessCountMismatchError(
                    f"snapshot {path} shard {p} is missing leaf file "
                    f"{spec['file']}; the shard set is unrecoverable"
                )
            arr = np.fromfile(fp, dtype=_np_dtype(spec["dtype"]))
            values[i] = arr.reshape([int(s) for s in spec["shape"]])
    missing = sorted(set(range(n)) - set(values))
    if missing:
        raise ProcessCountMismatchError(
            f"snapshot {path} shards cover only {len(values)} of {n} "
            f"leaves (missing indices {missing[:8]}...); the shard set is "
            "unrecoverable"
        )
    return jax.tree_util.tree_unflatten(treedef, [values[i] for i in range(n)])


def _regroup_shards(path: str, tmp: str, old_pc: int, new_pc: int) -> None:
    """The redistribution fast path (``old_pc % new_pc == 0``): every old
    shard's leaf set maps wholly into one new shard (leaf ``k`` lives at
    ``k % pc``, and ``k % new_pc`` is constant across an old shard), so
    leaves re-home by hardlink without deserializing a single array."""
    manifests = [_read_shard_manifest(path, p, old_pc) for p in range(old_pc)]
    os.makedirs(tmp, exist_ok=True)
    epoch = int(manifests[0].get("epoch", -1))
    for q in range(new_pc):
        sd = os.path.join(tmp, _shard_dir_name(q, new_pc))
        os.makedirs(sd)
        merged: Dict[str, Any] = {
            "format": 1,
            "process_index": q,
            "process_count": new_pc,
            "epoch": epoch,
            "n_leaves": int(manifests[0]["n_leaves"]),
            "leaves": {},
        }
        for p in range(old_pc):
            if p % new_pc != q:
                continue
            src = os.path.join(path, _shard_dir_name(p, old_pc))
            for key, spec in manifests[p]["leaves"].items():
                merged["leaves"][key] = spec
                if "file" in spec:
                    sf = os.path.join(src, spec["file"])
                    if not os.path.exists(sf):
                        raise ProcessCountMismatchError(
                            f"snapshot {path} shard {p} is missing leaf "
                            f"file {spec['file']}; the shard set is "
                            "unrecoverable"
                        )
                    df = os.path.join(sd, spec["file"])
                    try:
                        os.link(sf, df)
                    except OSError:
                        shutil.copy2(sf, df)
        _fsync_write_json(os.path.join(sd, "MANIFEST.json"), merged)
        _fsync_write_json(os.path.join(sd, ".complete"), {"epoch": epoch})
    _write_shards_json(tmp, new_pc)


class CheckpointManager:
    def __init__(self, directory: str, periodic_every: int = 25):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.periodic_every = periodic_every
        self._ckpt = ocp.StandardCheckpointer()
        self._meta_path = os.path.join(self.directory, "meta.json")
        self._meta: Dict[str, Any] = {
            "best_epoch": -1, "best_val_loss": float("inf"),
            "last_epoch": -1,
        }
        # Logical DP layout recorded with every snapshot (set_layout):
        # restore compares it against the resuming topology and reshards.
        self._layout: Optional[Dict[str, Any]] = None
        # Live multi-controller topology (set_host): (process_index,
        # process_count), or None for single-process runs. When set with
        # process_count > 1 snapshot writes are sharded per process and
        # only the primary owns meta.json.
        self._host: Optional[Tuple[int, int]] = None
        # verify() digest cache: name -> (stat signature, sha256). Fallback
        # resolution calls verify per candidate, sometimes repeatedly — a
        # gigabyte-class snapshot must not be re-read when its bytes
        # haven't changed (signature = sorted per-file size+mtime_ns).
        self._digest_cache: Dict[str, Tuple[Tuple, str]] = {}
        # What the latest restore() actually loaded ({"name", "epoch",
        # "fallback"}) — resume reads this to restart from the snapshot
        # that survived, not the one that was asked for.
        self.last_restored: Optional[Dict[str, Any]] = None
        if os.path.exists(self._meta_path):
            try:
                with open(self._meta_path) as f:
                    self._meta.update(json.load(f))
            except (json.JSONDecodeError, OSError, ValueError) as e:
                # A preemption that outran the (pre-hardening) plain write,
                # or disk corruption: the snapshots themselves may still be
                # fine, so degrade to defaults instead of bricking the run
                # directory. Checksums for existing snapshots are lost;
                # restores of them proceed unverified with a warning.
                logger.warning(
                    "corrupt meta.json in %s (%s); continuing with default "
                    "metadata — snapshot checksums are lost, restores of "
                    "pre-existing snapshots run unverified",
                    self.directory, e,
                )

    # -- multi-controller topology -----------------------------------------

    @property
    def _sharded(self) -> bool:
        return self._host is not None and self._host[1] > 1

    @property
    def _owns_meta(self) -> bool:
        """Only the primary (or a single-process run) commits checksums
        and meta.json — peers write their shard bytes and nothing else."""
        return self._host is None or self._host[0] == 0

    def set_host(self, process_index: int, process_count: int) -> None:
        """Declare the live multi-controller topology. With
        ``process_count > 1`` every subsequent snapshot write is sharded
        per process (leaf ``k`` to process ``k % n``) and only the
        primary owns ``meta.json``; restores consolidate + broadcast."""
        pi, pc = int(process_index), int(process_count)
        self._host = None if pc <= 1 else (pi, pc)

    # -- writes ------------------------------------------------------------

    def _write_bytes(self, path: str, host_state: Any, epoch: int) -> None:
        """Land the snapshot bytes: plain orbax single-process, or this
        process's shard + (primary only) the all-shards rendezvous."""
        if not self._sharded:
            self._ckpt.save(path, host_state, force=True)
            self._ckpt.wait_until_finished()
            return
        pi, pc = self._host
        write_state_shard(path, host_state, pi, pc, epoch)
        if pi != 0:
            return
        # Primary: clear stale non-shard content (a plain snapshot this
        # name held before a topology change; old-count shard dirs),
        # then wait for every peer's marker before owning the commit.
        for entry in os.listdir(path):
            m = _SHARD_DIR_RE.match(entry)
            if m and int(m.group(2)) == pc:
                continue
            if entry == "shards.json":
                continue
            full = os.path.join(path, entry)
            if os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
            else:
                try:
                    os.remove(full)
                except OSError:
                    pass
        self._wait_for_shards(path, pc, epoch)
        _write_shards_json(path, pc)

    @staticmethod
    def _wait_for_shards(path: str, process_count: int, epoch: int) -> None:
        """Primary-side rendezvous: poll every shard's ``.complete``
        marker until all report this epoch. A marker from a NEWER epoch
        means a peer's async queue superseded this name — the stale
        commit is abandoned rather than checksummed as a mixed-epoch
        snapshot."""
        deadline = time.monotonic() + _shard_wait_s()
        while True:
            done = 0
            for p in range(process_count):
                mk = os.path.join(path, _shard_dir_name(p, process_count),
                                  ".complete")
                try:
                    with open(mk) as f:
                        info = json.load(f)
                except (OSError, json.JSONDecodeError, ValueError):
                    continue
                peer_epoch = int(info.get("epoch", -1))
                if peer_epoch == int(epoch):
                    done += 1
                elif peer_epoch > int(epoch):
                    raise _ShardSuperseded(
                        f"shard {p} of {path} already holds epoch "
                        f"{peer_epoch} > {epoch}; abandoning the stale "
                        "commit"
                    )
            if done == process_count:
                return
            if time.monotonic() > deadline:
                raise CheckpointError(
                    f"shard rendezvous for {path} timed out after "
                    f"{_shard_wait_s()}s ({done}/{process_count} markers "
                    f"at epoch {epoch})"
                )
            time.sleep(0.02)

    def _save(self, name: str, state: Any, epoch: int) -> None:
        """Write the snapshot and record its checksum in the in-memory
        meta; the caller performs the single atomic meta write (this is
        the per-epoch hot path — bench_checkpoint_resilience's
        ckpt_save_ms — so one fsync'd write per save, not two)."""
        path = os.path.join(self.directory, name)
        self._write_bytes(path, jax.device_get(state), int(epoch))
        self._record_snapshot(name, path, epoch)

    def _record_snapshot(self, name: str, path: str, epoch: int) -> None:
        """Checksum the written snapshot into the in-memory meta (caller
        commits), prime the digest cache, and run the damage fault hook.
        No-op on non-primary processes — the checksum must describe the
        COMPLETE shard set, which only the primary's rendezvous sees."""
        if not self._owns_meta:
            self._digest_cache.pop(name, None)
            return
        digest = snapshot_checksum(path)
        record: Dict[str, Any] = {"epoch": int(epoch), "sha256": digest}
        if self._layout is not None:
            record["layout"] = dict(self._layout)
        if self._sharded:
            record["shards"] = self._host[1]
        self._meta.setdefault("snapshots", {})[name] = record
        self._digest_cache[name] = (self._snapshot_sig(path), digest)
        # Fault hook AFTER the checksum is recorded: injected damage is
        # exactly what verification must catch on restore.
        for spec in inject.fire("checkpoint.saved", name=name):
            if spec.kind in ("corrupt", "truncate"):
                damaged = inject.corrupt_path(path, mode=spec.kind)
                # The cached digest describes the pre-damage bytes; drop it
                # so the next verify re-reads the damaged content (the stat
                # signature would usually catch this, but injected damage
                # must be caught deterministically, not modulo mtime
                # granularity).
                self._digest_cache.pop(name, None)
                logger.warning("injected %s of snapshot %s (%s)",
                               spec.kind, name, damaged)

    def _write_meta(self) -> None:
        """Atomic: a reader (or a resumed run) sees either the old meta or
        the new one, never a torn write — and the rename is durable before
        we report success. Non-primary processes never write meta.json."""
        if not self._owns_meta:
            return
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path)
        dir_fd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    def save_best(self, state: Any, epoch: int,
                  val_loss: Optional[float] = None,
                  metrics: Optional[dict] = None) -> None:
        """``val_loss`` is the GNN trainer's selection quantity (lower is
        better); runs that select on something else (val F1, bleu+em, ...)
        record it under its own name via ``metrics`` so meta.json never
        shows a negated stand-in in the val-loss field."""
        self._save("best", state, epoch)
        self._meta["best_epoch"] = epoch
        if val_loss is not None:
            self._meta["best_val_loss"] = val_loss
        if metrics:
            self._meta["best_metrics"] = {
                k: float(v) for k, v in metrics.items()
            }
        self._write_meta()

    def save_last(self, state: Any, epoch: int) -> None:
        self._save("last", state, epoch)
        self._meta["last_epoch"] = epoch
        self._write_meta()

    def maybe_save_periodic(self, state: Any, epoch: int) -> None:
        if self.periodic_every and (epoch + 1) % self.periodic_every == 0:
            self._save(f"epoch_{epoch}", state, epoch)
            self._write_meta()

    def save_preempt(self, state: Any, epoch: int, step: int,
                     resume: Optional[Dict[str, Any]] = None) -> str:
        """The preemption drain's snapshot: ``preempt_<epoch>_<step>``,
        carrying the in-progress epoch plus a JSON-safe step-level resume
        payload (data-order cursor, host-read accumulator values) in its
        meta record. Returns the snapshot name."""
        name = f"preempt_{int(epoch)}_{int(step)}"
        self._save(name, state, epoch)
        # Non-primary processes have no meta record (the primary owns the
        # commit); keep the in-memory bookkeeping harmless for them.
        record = self._meta.setdefault("snapshots", {}).setdefault(
            name, {"epoch": int(epoch)})
        record["step"] = int(step)
        record["preempt"] = dict(resume or {})
        self._write_meta()
        return name

    def preempt_info(self, name: str) -> Optional[Dict[str, Any]]:
        """The step-level resume payload a ``preempt_*`` snapshot
        recorded, or None for every other snapshot name."""
        record = self._meta.get("snapshots", {}).get(name)
        if record is None or "preempt" not in record:
            return None
        return {"epoch": int(record["epoch"]), "step": int(record["step"]),
                **record["preempt"]}

    def remove(self, name: str) -> None:
        """Delete a snapshot and its meta record (the consumed ``preempt``
        cleanup once its epoch completes and ``last`` retakes the tie)."""
        import shutil

        shutil.rmtree(os.path.join(self.directory, name),
                      ignore_errors=True)
        self._digest_cache.pop(name, None)
        if self._meta.get("snapshots", {}).pop(name, None) is not None:
            self._write_meta()

    # -- integrity ---------------------------------------------------------

    def has(self, name: str) -> bool:
        return os.path.isdir(os.path.join(self.directory, name))

    @staticmethod
    def _snapshot_sig(path: str) -> Tuple:
        """Cheap stat signature of a snapshot directory: sorted relative
        paths with sizes and mtimes, no file reads. Any byte-level change
        that goes through the filesystem bumps it."""
        sig = []
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames.sort()
            for fn in sorted(filenames):
                p = os.path.join(dirpath, fn)
                st = os.stat(p)
                sig.append((os.path.relpath(p, path), st.st_size,
                            st.st_mtime_ns))
        return tuple(sig)

    def verify(self, name: str) -> bool:
        """True when the snapshot's content matches its recorded checksum.
        Unrecorded (pre-hardening) snapshots pass with a warning — there is
        nothing to verify against, and refusing to load them would turn the
        upgrade into a data loss.

        The content digest is cached per (name, stat signature): fallback
        resolution may verify the same snapshot several times per restore,
        and re-hashing gigabytes for each call would make the verified
        path cost O(candidates × size) instead of O(size)."""
        path = os.path.join(self.directory, name)
        if not os.path.isdir(path):
            return False
        record = self._meta.get("snapshots", {}).get(name)
        if record is None:
            logger.warning("snapshot %s has no recorded checksum "
                           "(pre-hardening?); restoring unverified", name)
            return True
        sig = self._snapshot_sig(path)
        cached = self._digest_cache.get(name)
        if cached is not None and cached[0] == sig:
            digest = cached[1]
        else:
            digest = snapshot_checksum(path)
            self._digest_cache[name] = (sig, digest)
        return digest == record["sha256"]

    # -- layout / elastic resume -------------------------------------------

    def set_layout(self, layout: Optional[Dict[str, Any]]) -> None:
        """Record the logical DP layout (``parallel.mesh.snapshot_layout``)
        with every subsequent snapshot — what topology-independent restore
        compares against."""
        self._layout = dict(layout) if layout else None

    def snapshot_layout(self, name: str) -> Optional[Dict[str, Any]]:
        record = self._meta.get("snapshots", {}).get(name)
        if record is None:
            return None
        return record.get("layout")

    def resume_candidate(self, include_preempt: bool = True) -> Optional[str]:
        """The snapshot a resume should start from: the newest on-disk
        snapshot by epoch, ties broken ``last`` > ``preempt`` >
        ``epoch_N`` > ``best``. A mid-epoch ``preempt_E_S`` records the
        in-progress epoch ``E`` and therefore outranks epoch ``E-1``'s
        ``last`` — the partial epoch resumes instead of being lost —
        while a completed epoch's ``last`` retakes the tie. A writer that
        died between deleting the old ``last`` and committing the new one
        costs one epoch, not the whole run. None when nothing restorable
        exists.

        ``include_preempt=False`` skips ``preempt_*`` candidates — the
        reshape path, where a step-granular skip count written under a
        different DP packing would shear the data order."""
        order = self._fallback_order("")
        if not include_preempt:
            order = [n for n in order if not _PREEMPT_NAME_RE.match(n)]
        return order[0] if order else None

    def drain(self, timeout: Optional[float] = None) -> float:
        """Barrier for pending asynchronous writes. The synchronous
        manager has none — a no-op so call sites never branch on the
        manager flavor."""
        return 0.0

    def _snapshot_epoch(self, name: str) -> int:
        record = self._meta.get("snapshots", {}).get(name)
        if record is not None:
            return int(record["epoch"])
        m = _EPOCH_NAME_RE.match(name)
        if m:
            return int(m.group(1))
        m = _PREEMPT_NAME_RE.match(name)
        if m:
            return int(m.group(1))
        if name == "last":
            return int(self._meta.get("last_epoch", -1))
        if name == "best":
            return int(self._meta.get("best_epoch", -1))
        return -1

    def _fallback_order(self, requested: str) -> List[str]:
        """Requested name first, then every other on-disk snapshot by
        descending epoch (ties: last > preempt > epoch_N > best) — THE
        documented checksum-fallback order (README "Fault tolerance" /
        "Graceful shutdown & preemption")."""
        on_disk = [
            d for d in sorted(os.listdir(self.directory))
            if os.path.isdir(os.path.join(self.directory, d))
            and (d in ("best", "last") or _EPOCH_NAME_RE.match(d)
                 or _PREEMPT_NAME_RE.match(d))
        ]
        pref = {"last": 0, "best": 3}

        def rank(name: str) -> Tuple:
            tie = pref.get(name, 1 if _PREEMPT_NAME_RE.match(name) else 2)
            # Among same-epoch preempt snapshots, the later step wins.
            m = _PREEMPT_NAME_RE.match(name)
            step = -int(m.group(2)) if m else 0
            return (-self._snapshot_epoch(name), tie, step, name)

        rest = sorted((d for d in on_disk if d != requested), key=rank)
        head = [requested] if requested in on_disk else []
        return head + rest

    def _resolve_intact(self, name: str) -> str:
        candidates = self._fallback_order(name)
        for cand in candidates:
            if self.verify(cand):
                if cand != name:
                    logger.error(
                        "snapshot %s failed integrity verification; falling "
                        "back to %s (epoch %d)", name, cand,
                        self._snapshot_epoch(cand),
                    )
                return cand
        raise CheckpointError(
            f"no intact snapshot under {self.directory} "
            f"(requested {name!r}, tried {candidates})"
        )

    # -- reads -------------------------------------------------------------

    def restore(self, name: str, target: Any) -> Any:
        """Verified restore: checksum-checked, with automatic fallback to
        the newest intact snapshot when the requested one is damaged.
        ``last_restored`` records what was loaded.

        A snapshot that was never written is a caller error, not damage —
        that still raises ``FileNotFoundError`` rather than silently
        loading something else."""
        if not self.has(name):
            raise FileNotFoundError(
                f"no checkpoint {name!r} under {self.directory}"
            )
        candidates = self._fallback_order(name)
        last_err: Optional[Exception] = None
        for cand in candidates:
            if not self.verify(cand):
                logger.error("snapshot %s failed integrity verification; "
                             "trying the next fallback", cand)
                continue
            path = os.path.join(self.directory, cand)
            try:
                if is_sharded_snapshot(path):
                    restored = self._restore_sharded(path, target)
                else:
                    restored = self._ckpt.restore(
                        path, target=jax.device_get(target),
                    )
            except ProcessCountMismatchError as e:
                # A verified-but-unrecoverable shard set (a doctored
                # shards.json whose checksum was re-recorded, say): keep
                # the typed error if nothing else is intact.
                logger.warning("restore of sharded snapshot %s failed "
                               "(%s); trying the next fallback", cand, e)
                last_err = e
                continue
            except Exception as e:
                # Checksums catch bit damage; this catches structural rot
                # (legacy snapshot with no checksum, half-written tree).
                logger.warning("restore of snapshot %s failed (%s); trying "
                               "the next fallback", cand, e)
                last_err = e
                continue
            self.last_restored = {
                "name": cand,
                "epoch": self._snapshot_epoch(cand),
                "fallback": cand != name,
            }
            if cand != name:
                logger.error("restored fallback snapshot %s (epoch %d) in "
                             "place of %s", cand,
                             self.last_restored["epoch"], name)
            return restored
        if isinstance(last_err, ProcessCountMismatchError):
            raise last_err
        raise CheckpointError(
            f"no intact snapshot under {self.directory} "
            f"(requested {name!r}, tried {candidates})"
        ) from last_err

    def _restore_sharded(self, path: str, target: Any) -> Any:
        """Restore a sharded snapshot: consolidate every shard into the
        replicated host tree. Under a live multi-process topology the
        PRIMARY alone reads the bytes and the tree is broadcast to the
        fleet (``multihost_utils.broadcast_one_to_all`` — the orbax
        broadcast-from-primary discipline), so N processes cost one read,
        not N."""
        host_target = jax.device_get(target)
        if not self._sharded:
            return consolidate_sharded(path, host_target)
        from jax.experimental import multihost_utils

        pi, _ = self._host
        if pi == 0:
            tree = consolidate_sharded(path, host_target)
        else:
            tree = jax.tree_util.tree_map(
                lambda x: np.zeros_like(np.asarray(x)), host_target)
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        out = multihost_utils.broadcast_one_to_all(
            tuple(leaves), is_source=(pi == 0))
        return jax.tree_util.tree_unflatten(
            treedef, [np.asarray(x) for x in out])

    def consolidate(self, name: str, target: Any) -> Any:
        """Host-side reassembly of one snapshot regardless of its on-disk
        layout: a plain snapshot restores via orbax, a sharded one
        through :func:`consolidate_sharded` (typed
        ``ProcessCountMismatchError`` on a broken shard set). No
        fallback — this is the surgical read ``redistribute`` and the
        edge-case tests build on."""
        self.drain()
        if not self.has(name):
            raise FileNotFoundError(
                f"no checkpoint {name!r} under {self.directory}"
            )
        path = os.path.join(self.directory, name)
        if is_sharded_snapshot(path):
            return consolidate_sharded(path, jax.device_get(target))
        return self._ckpt.restore(path, target=jax.device_get(target))

    def redistribute(self, name: str, new_process_count: int,
                     target: Any = None) -> Dict[str, Any]:
        """Rewrite one snapshot for a different process count — the
        cross-process-count resume path (and the benched
        ``ckpt_redistribute_ms`` operation). Primary-only.

        Strategies: ``fast`` re-homes leaf files by hardlink when the
        old and new shard sets nest (``old % new == 0``, both > 1);
        ``consolidate`` reassembles the replicated tree (needs
        ``target`` for structure) and re-shards it — or writes a plain
        orbax snapshot when ``new_process_count == 1``, so a shrunk-to-
        one resume leaves a snapshot every single-process tool reads
        natively. The swap is atomic-ish (write aside, two renames): a
        crash mid-redistribute leaves either the old intact bytes or a
        checksum-mismatched dir that the verified-restore fallback
        skips. The snapshot's recorded step/preempt payload is
        untouched — a redistributed ``preempt_<E>_<S>`` still resumes
        mid-epoch."""
        self.drain()
        if not self.has(name):
            raise FileNotFoundError(
                f"no checkpoint {name!r} under {self.directory}"
            )
        if self._host is not None and self._host[0] != 0:
            raise RuntimeError(
                "redistribute is primary-only (non-primary processes wait "
                "for the rewritten snapshot)"
            )
        path = os.path.join(self.directory, name)
        old_pc = _shard_count(path)
        new_pc = int(new_process_count)
        if new_pc < 1:
            raise ValueError(f"new_process_count must be >= 1, got {new_pc}")
        if old_pc == new_pc:
            return {"strategy": "noop", "from_processes": old_pc,
                    "to_processes": new_pc, "ms": 0.0}
        t0 = time.perf_counter()
        with telemetry.span("ckpt.redistribute", snapshot=name,
                            from_processes=old_pc, to_processes=new_pc):
            tmp = path + ".redist"
            shutil.rmtree(tmp, ignore_errors=True)
            if old_pc > 1 and new_pc > 1 and old_pc % new_pc == 0:
                strategy = "fast"
                _regroup_shards(path, tmp, old_pc, new_pc)
            else:
                strategy = "consolidate"
                if old_pc == 1:
                    if target is None:
                        tree = self._ckpt.restore(path)
                    else:
                        tree = self._ckpt.restore(
                            path, target=jax.device_get(target))
                else:
                    if target is None:
                        raise ValueError(
                            "redistribute of a sharded snapshot needs a "
                            "target state for the tree structure"
                        )
                    tree = consolidate_sharded(path, jax.device_get(target))
                if new_pc == 1:
                    self._ckpt.save(tmp, tree, force=True)
                    self._ckpt.wait_until_finished()
                else:
                    epoch = self._snapshot_epoch(name)
                    os.makedirs(tmp, exist_ok=True)
                    for p in range(new_pc):
                        write_state_shard(tmp, tree, p, new_pc, epoch)
                    _write_shards_json(tmp, new_pc)
            backup = path + ".old"
            shutil.rmtree(backup, ignore_errors=True)
            os.replace(path, backup)
            os.replace(tmp, path)
            shutil.rmtree(backup, ignore_errors=True)
            record = self._meta.get("snapshots", {}).get(name)
            if record is not None:
                digest = snapshot_checksum(path)
                record["sha256"] = digest
                record.setdefault("layout", {})["process_count"] = new_pc
                if new_pc > 1:
                    record["shards"] = new_pc
                else:
                    record.pop("shards", None)
                self._digest_cache[name] = (self._snapshot_sig(path), digest)
                self._write_meta()
            else:
                self._digest_cache.pop(name, None)
        ms = (time.perf_counter() - t0) * 1e3
        telemetry.event("ckpt.redistribute", snapshot=name,
                        from_processes=old_pc, to_processes=new_pc,
                        strategy=strategy, ms=ms)
        logger.info("redistributed snapshot %s %d->%d processes (%s, "
                    "%.1f ms)", name, old_pc, new_pc, strategy, ms)
        return {"strategy": strategy, "from_processes": old_pc,
                "to_processes": new_pc, "ms": ms}

    def _reload_meta(self) -> None:
        """Re-read ``meta.json`` from disk — the non-primary half of a
        redistribution rendezvous (the primary rewrote the record under
        our feet) — and drop digest cache entries so the next verified
        read re-hashes the rewritten bytes."""
        if os.path.exists(self._meta_path):
            try:
                with open(self._meta_path) as f:
                    self._meta = json.load(f)
            except (json.JSONDecodeError, OSError):
                pass
        self._digest_cache.clear()

    def wait_redistributed(self, name: str, process_count: int,
                           timeout_s: Optional[float] = None) -> None:
        """Non-primary rendezvous: block until the primary's
        :meth:`redistribute` of ``name`` has landed at ``process_count``
        shards, then reload meta. Tolerates the brief window where the
        snapshot dir is absent (the two-rename swap). Raises
        :class:`CheckpointError` on timeout."""
        if timeout_s is None:
            timeout_s = _shard_wait_s()
        path = os.path.join(self.directory, name)
        want = int(process_count)
        deadline = time.monotonic() + timeout_s
        while True:
            if os.path.exists(path) and not os.path.exists(path + ".redist"):
                if _shard_count(path) == want:
                    break
            if time.monotonic() > deadline:
                raise CheckpointError(
                    f"timed out after {timeout_s:.0f}s waiting for "
                    f"snapshot {name!r} to be redistributed to "
                    f"{want} process(es) (set {SHARD_WAIT_ENV} to adjust)"
                )
            time.sleep(0.05)
        self._reload_meta()

    def restore_params(self, name: str = "best") -> Any:
        """Restore just the model variables of a saved state — the
        inference path (serve engine, offline scoring).

        Target-free restore, so no optimizer tree has to be reconstructed
        (its structure varies with freeze flags and schedules and does not
        exist at serve time). Works on both checkpoint layouts: trainer
        states (``TrainState``/``TextTrainState`` — params under the
        ``params`` key) and the params-only dicts ``cmd_fit_text`` writes.
        Returns the apply-ready variables dict (``{"params": ...}``).
        Damaged snapshots fall back like :meth:`restore`.
        """
        if not self.has(name):
            raise FileNotFoundError(
                f"no checkpoint {name!r} under {self.directory}"
            )
        used = self._resolve_intact(name)
        self.last_restored = {
            "name": used,
            "epoch": self._snapshot_epoch(used),
            "fallback": used != name,
        }
        used_path = os.path.join(self.directory, used)
        if is_sharded_snapshot(used_path):
            # Target-free reads need the orbax layout; a fleet-written
            # snapshot must be consolidated first (resume does this
            # automatically; operators run redistribute(name, 1)).
            raise CheckpointError(
                f"snapshot {used!r} under {self.directory} is sharded over "
                f"{_shard_count(used_path)} processes; redistribute it to "
                "a single process (CheckpointManager.redistribute(name, 1, "
                "target)) before a params-only restore"
            )
        restored = self._ckpt.restore(used_path)
        if isinstance(restored, dict):
            inner = restored.get("params")
            if isinstance(inner, dict) and "params" in inner:
                # Trainer state (step/params/opt_state) or the
                # {"params": state.params} wrapper: unwrap one level.
                return inner
            if inner is not None:
                # Already the apply-ready variables dict.
                return restored
        raise ValueError(
            f"checkpoint {os.path.join(self.directory, used)} holds no "
            "recognizable variables dict "
            "(expected a trainer state or a {{'params': ...}} tree)"
        )

    @property
    def best_meta(self) -> dict:
        return dict(self._meta)


class _PendingWrite:
    """One queued snapshot write: the state (host copy already started),
    plus the meta fields to commit once the bytes are durable.
    ``record_extra`` merges into the snapshot's own meta record (the
    preempt resume payload)."""

    __slots__ = ("name", "state", "epoch", "meta_update", "submitted_s",
                 "record_extra")

    def __init__(self, name: str, state: Any, epoch: int,
                 meta_update: Dict[str, Any],
                 record_extra: Optional[Dict[str, Any]] = None):
        self.name = name
        self.state = state
        self.epoch = epoch
        self.meta_update = meta_update
        self.record_extra = record_extra
        self.submitted_s = time.perf_counter()


class AsyncCheckpointManager(CheckpointManager):
    """Checkpointing that charges the step loop only the device→host copy
    *start*.

    ``save_*`` begins a non-blocking host copy of every array leaf
    (``ckpt.copy`` span — the step-blocking portion, what
    ``bench.ckpt_async_blocking_ms`` measures) and enqueues the write. A
    dedicated writer thread serializes + fsyncs (``ckpt.write`` span, the
    ``checkpoint.async_write`` fault site), checksums, and commits
    ``meta.json`` atomically (``ckpt.commit`` span) — so training overlaps
    the expensive part instead of stalling on it.

    Queue discipline: at most one pending write per snapshot name. A newer
    save of a name supersedes a still-queued older one (the
    ``checkpoint.supersede`` fault site; counted in
    ``ckpt_superseded_total``) — a stalled disk can delay snapshots but
    never queue unbounded work behind the step loop.

    Failure posture: a writer-thread crash is logged, counted
    (``ckpt_async_errors_total``), recorded in :attr:`errors`, and costs at
    most that snapshot — ``meta.json`` is only committed after the bytes
    are durable, so the previous intact snapshot keeps winning
    ``_fallback_order`` and a torn write can never become ``last``.

    Reads (``verify``/``restore``/``restore_params``/``best_meta``/
    ``resume_candidate``) and fit-exit take the :meth:`drain` barrier
    first, so every ``best``-dependent decision sees committed state.
    """

    def __init__(self, directory: str, periodic_every: int = 25):
        super().__init__(directory, periodic_every=periodic_every)
        self._cv = threading.Condition()
        self._queue: List[_PendingWrite] = []
        self._active: Optional[str] = None
        self._write_seq = 0  # ordinal fed to the async_write fault site
        self.errors: List[Tuple[str, BaseException]] = []
        # Test hook: when set, the writer blocks before each write until
        # the event is set — the supersede tests need a stalled writer.
        self.write_gate: Optional[threading.Event] = None
        self._writer = threading.Thread(
            target=self._writer_loop, name=f"ckpt-writer:{directory}",
            daemon=True,
        )
        self._writer.start()

    # -- submission (the step-loop side) -----------------------------------

    @staticmethod
    def _start_host_copy(state: Any) -> Any:
        """Kick off the device→host transfer without blocking on it: the
        writer's ``jax.device_get`` then mostly finds the bytes already
        landed. Non-array leaves pass through untouched."""

        def start(x):
            if hasattr(x, "copy_to_host_async"):
                try:
                    x.copy_to_host_async()
                except Exception:  # committed arrays on exotic backends
                    pass  # device_get in the writer still works, just colder
            return x

        return jax.tree_util.tree_map(start, state)

    def _submit(self, name: str, state: Any, epoch: int,
                meta_update: Dict[str, Any],
                record_extra: Optional[Dict[str, Any]] = None) -> None:
        with telemetry.span("ckpt.copy", snapshot=name, epoch=int(epoch)):
            state = self._start_host_copy(state)
        pending = _PendingWrite(name, state, int(epoch), meta_update,
                                record_extra)
        with self._cv:
            for i, queued in enumerate(self._queue):
                if queued.name == name:
                    # Supersede the stalled same-name write: the newer
                    # state is strictly more recent, and the queue stays
                    # bounded at one pending write per name.
                    self._queue[i] = pending
                    telemetry.REGISTRY.counter(
                        "ckpt_superseded_total").inc()
                    telemetry.event("ckpt.superseded", snapshot=name,
                                    epoch=int(epoch),
                                    superseded_epoch=queued.epoch)
                    self._cv.notify_all()
                    break
            else:
                self._queue.append(pending)
                self._cv.notify_all()
        # Fault hook outside the lock: a `raise` spec here simulates the
        # submitting thread dying right after handing off the snapshot.
        inject.fire("checkpoint.supersede", name=name, index=int(epoch))

    def save_best(self, state: Any, epoch: int,
                  val_loss: Optional[float] = None,
                  metrics: Optional[dict] = None) -> None:
        update: Dict[str, Any] = {"best_epoch": int(epoch)}
        if val_loss is not None:
            update["best_val_loss"] = val_loss
        if metrics:
            update["best_metrics"] = {k: float(v) for k, v in metrics.items()}
        self._submit("best", state, epoch, update)

    def save_last(self, state: Any, epoch: int) -> None:
        self._submit("last", state, epoch, {"last_epoch": int(epoch)})

    def maybe_save_periodic(self, state: Any, epoch: int) -> None:
        if self.periodic_every and (epoch + 1) % self.periodic_every == 0:
            self._submit(f"epoch_{epoch}", state, epoch, {})

    def save_preempt(self, state: Any, epoch: int, step: int,
                     resume: Optional[Dict[str, Any]] = None) -> str:
        """Async preempt snapshot: submitted like any write (the drain
        barrier the preemption path takes right after makes it durable);
        the resume payload rides the write and lands in the snapshot's
        meta record at commit."""
        name = f"preempt_{int(epoch)}_{int(step)}"
        self._submit(name, state, epoch, {},
                     record_extra={"step": int(step),
                                   "preempt": dict(resume or {})})
        return name

    # -- the writer thread -------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue:
                    self._cv.wait()
                gate = self.write_gate
            if gate is not None:
                gate.wait()
            with self._cv:
                if not self._queue:
                    continue
                item = self._queue.pop(0)
                self._active = item.name
                seq = self._write_seq
                self._write_seq += 1
            try:
                self._write_one(item, seq)
                telemetry.REGISTRY.counter("ckpt_async_writes_total").inc()
            except BaseException as e:  # the writer must survive any write
                self.errors.append((item.name, e))
                telemetry.REGISTRY.counter("ckpt_async_errors_total").inc()
                telemetry.event("ckpt.write_error", snapshot=item.name,
                                epoch=item.epoch, error=type(e).__name__)
                logger.exception(
                    "async checkpoint write of %s (epoch %d) failed; the "
                    "previous intact snapshot remains authoritative",
                    item.name, item.epoch,
                )
                if (self._owns_meta
                        and item.name not in self._meta.get("snapshots", {})):
                    # A failed FIRST write of this name has no recorded
                    # checksum for verification to fail it against, so the
                    # pre-hardening grace path would bless the partial
                    # bytes on restore. Remove them: an absent snapshot
                    # can never win the fallback order. (With a committed
                    # record, the stale-checksum mismatch already damns
                    # the bytes — leave them for forensics. Non-primary
                    # processes never remove: the dir holds peers' shards
                    # and the primary's own failure path cleans up.)
                    shutil.rmtree(
                        os.path.join(self.directory, item.name),
                        ignore_errors=True,
                    )
            finally:
                with self._cv:
                    self._active = None
                    self._cv.notify_all()

    def _write_one(self, item: _PendingWrite, seq: int) -> None:
        path = os.path.join(self.directory, item.name)
        with telemetry.span("ckpt.write", snapshot=item.name, epoch=item.epoch):
            host_state = jax.device_get(item.state)
            self._write_bytes(path, host_state, item.epoch)
            # Fault site between the byte write and the checksum/meta
            # commit: a `raise` here is the writer dying mid-save — bytes
            # possibly on disk, meta.json still pointing at the previous
            # intact snapshot (which therefore keeps winning restores).
            # `corrupt`/`truncate` additionally damage the written bytes
            # first (the torn-write shape), then crash the same way.
            for spec in inject.fire("checkpoint.async_write", index=seq,
                                    name=item.name):
                if spec.kind in ("corrupt", "truncate"):
                    damaged = inject.corrupt_path(path, mode=spec.kind)
                    logger.warning(
                        "injected async-write %s of snapshot %s (%s)",
                        spec.kind, item.name, damaged)
                    raise inject.FaultError(
                        f"injected writer crash mid-serialize of "
                        f"{item.name}")
        with telemetry.span("ckpt.commit", snapshot=item.name, epoch=item.epoch):
            self._record_snapshot(item.name, path, item.epoch)
            if item.record_extra:
                # setdefault: non-primary processes have no record (the
                # primary owns the commit) but must not KeyError.
                self._meta.setdefault("snapshots", {}).setdefault(
                    item.name, {"epoch": item.epoch},
                ).update(item.record_extra)
            self._meta.update(item.meta_update)
            self._write_meta()

    # -- the drain barrier -------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> float:
        """Block until every submitted write has committed (or failed).
        Returns the wait in ms; observed into ``ckpt_drain_wait_ms``.
        Raises ``TimeoutError`` when ``timeout`` (seconds) elapses first —
        leaving writes pending is exactly what the caller asked to rule
        out."""
        t0 = time.perf_counter()
        deadline = None if timeout is None else t0 + timeout
        with self._cv:
            if not self._queue and self._active is None:
                return 0.0  # nothing pending: don't pollute the wait stats
            while self._queue or self._active is not None:
                if not self._writer.is_alive():
                    break  # interpreter teardown: nothing will ever finish
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"checkpoint drain timed out after {timeout}s "
                            f"(pending: {[p.name for p in self._queue]}, "
                            f"active: {self._active})"
                        )
                self._cv.wait(timeout=remaining)
        wait_ms = (time.perf_counter() - t0) * 1e3
        telemetry.REGISTRY.histogram("ckpt_drain_wait_ms").observe(wait_ms)
        telemetry.event("ckpt.drain", wait_ms=wait_ms)
        return wait_ms

    # -- reads: always behind the barrier ----------------------------------

    def has(self, name: str) -> bool:
        self.drain()
        return super().has(name)

    def verify(self, name: str) -> bool:
        self.drain()
        return super().verify(name)

    def restore(self, name: str, target: Any) -> Any:
        self.drain()
        return super().restore(name, target)

    def restore_params(self, name: str = "best") -> Any:
        self.drain()
        return super().restore_params(name)

    def resume_candidate(self, include_preempt: bool = True) -> Optional[str]:
        self.drain()
        return super().resume_candidate(include_preempt=include_preempt)

    def preempt_info(self, name: str) -> Optional[Dict[str, Any]]:
        self.drain()
        return super().preempt_info(name)

    def remove(self, name: str) -> None:
        # A queued same-name write racing the removal would resurrect the
        # snapshot; the barrier first makes removal final.
        self.drain()
        super().remove(name)

    @property
    def best_meta(self) -> dict:
        self.drain()
        return dict(self._meta)


def load_encoder_params(full_params: Any, drop_keys=("_head", "pooling")) -> Any:
    """Partial checkpoint load for encoder freezing.

    Drops the classification head (top-level key ``_head``) and ``pooling``
    parameters, keeping embeddings + GGNN — the combined models load these
    into an ``encoder_mode`` FlowGNN (reference main_cli.py:136-144,
    linevul_main.py:589-602).
    """
    params = full_params["params"]
    kept = {k: v for k, v in params.items() if k not in set(drop_keys)}
    return {"params": kept}

"""Checkpointing via orbax, hardened for preemptible hardware.

Reproduces the reference's checkpoint semantics (SURVEY §5): best-by-val-loss
with ``save_last`` (Lightning ModelCheckpoint, config_default.yaml:23-29),
periodic every-N-epochs snapshots (periodic_checkpoint.py:8-22), and
partial-load-and-freeze of the graph encoder for the combined models
(main_cli.py:136-144 ``--freeze_graph`` strips head/pooling keys). Best
checkpoint metadata is stored explicitly instead of being re-parsed out of
filenames (main_cli.py:175-184).

Robustness contract (the preemptible-TPU posture, tests/test_resilience.py):

* ``meta.json`` writes are atomic (tmp file + ``os.replace`` + fsync of
  file and directory) — a preemption mid-write can never brick resume;
  a corrupt existing meta.json degrades to defaults with a warning
  instead of crashing at construction.
* Every snapshot records a content checksum in ``meta.json``; restores
  verify it and, on mismatch (or an unreadable snapshot), fall back to
  the newest intact snapshot. Fallback order: the requested name first,
  then every other recorded snapshot by descending epoch, ties broken
  ``last`` > ``epoch_N`` > ``best``. ``last_restored`` reports what was
  actually loaded so resume can restart from the surviving epoch.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from deepdfa_tpu.resilience import inject

logger = logging.getLogger(__name__)

_EPOCH_NAME_RE = re.compile(r"^epoch_(\d+)$")


class CheckpointError(RuntimeError):
    """No intact snapshot exists for a requested restore."""


def snapshot_checksum(path: str) -> str:
    """Content digest of one snapshot directory: sha256 over the sorted
    relative paths and file bytes."""
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames.sort()
        for fn in sorted(filenames):
            p = os.path.join(dirpath, fn)
            h.update(os.path.relpath(p, path).encode())
            h.update(b"\0")
            with open(p, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            h.update(b"\0")
    return h.hexdigest()


class CheckpointManager:
    def __init__(self, directory: str, periodic_every: int = 25):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.periodic_every = periodic_every
        self._ckpt = ocp.StandardCheckpointer()
        self._meta_path = os.path.join(self.directory, "meta.json")
        self._meta: Dict[str, Any] = {
            "best_epoch": -1, "best_val_loss": float("inf"),
            "last_epoch": -1,
        }
        # What the latest restore() actually loaded ({"name", "epoch",
        # "fallback"}) — resume reads this to restart from the snapshot
        # that survived, not the one that was asked for.
        self.last_restored: Optional[Dict[str, Any]] = None
        if os.path.exists(self._meta_path):
            try:
                with open(self._meta_path) as f:
                    self._meta.update(json.load(f))
            except (json.JSONDecodeError, OSError, ValueError) as e:
                # A preemption that outran the (pre-hardening) plain write,
                # or disk corruption: the snapshots themselves may still be
                # fine, so degrade to defaults instead of bricking the run
                # directory. Checksums for existing snapshots are lost;
                # restores of them proceed unverified with a warning.
                logger.warning(
                    "corrupt meta.json in %s (%s); continuing with default "
                    "metadata — snapshot checksums are lost, restores of "
                    "pre-existing snapshots run unverified",
                    self.directory, e,
                )

    # -- writes ------------------------------------------------------------

    def _save(self, name: str, state: Any, epoch: int) -> None:
        """Write the snapshot and record its checksum in the in-memory
        meta; the caller performs the single atomic meta write (this is
        the per-epoch hot path — bench_checkpoint_resilience's
        ckpt_save_ms — so one fsync'd write per save, not two)."""
        path = os.path.join(self.directory, name)
        self._ckpt.save(path, jax.device_get(state), force=True)
        self._ckpt.wait_until_finished()
        self._meta.setdefault("snapshots", {})[name] = {
            "epoch": int(epoch),
            "sha256": snapshot_checksum(path),
        }
        # Fault hook AFTER the checksum is recorded: injected damage is
        # exactly what verification must catch on restore.
        for spec in inject.fire("checkpoint.saved", name=name):
            if spec.kind in ("corrupt", "truncate"):
                damaged = inject.corrupt_path(path, mode=spec.kind)
                logger.warning("injected %s of snapshot %s (%s)",
                               spec.kind, name, damaged)

    def _write_meta(self) -> None:
        """Atomic: a reader (or a resumed run) sees either the old meta or
        the new one, never a torn write — and the rename is durable before
        we report success."""
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path)
        dir_fd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    def save_best(self, state: Any, epoch: int,
                  val_loss: Optional[float] = None,
                  metrics: Optional[dict] = None) -> None:
        """``val_loss`` is the GNN trainer's selection quantity (lower is
        better); runs that select on something else (val F1, bleu+em, ...)
        record it under its own name via ``metrics`` so meta.json never
        shows a negated stand-in in the val-loss field."""
        self._save("best", state, epoch)
        self._meta["best_epoch"] = epoch
        if val_loss is not None:
            self._meta["best_val_loss"] = val_loss
        if metrics:
            self._meta["best_metrics"] = {
                k: float(v) for k, v in metrics.items()
            }
        self._write_meta()

    def save_last(self, state: Any, epoch: int) -> None:
        self._save("last", state, epoch)
        self._meta["last_epoch"] = epoch
        self._write_meta()

    def maybe_save_periodic(self, state: Any, epoch: int) -> None:
        if self.periodic_every and (epoch + 1) % self.periodic_every == 0:
            self._save(f"epoch_{epoch}", state, epoch)
            self._write_meta()

    # -- integrity ---------------------------------------------------------

    def has(self, name: str) -> bool:
        return os.path.isdir(os.path.join(self.directory, name))

    def verify(self, name: str) -> bool:
        """True when the snapshot's content matches its recorded checksum.
        Unrecorded (pre-hardening) snapshots pass with a warning — there is
        nothing to verify against, and refusing to load them would turn the
        upgrade into a data loss."""
        path = os.path.join(self.directory, name)
        if not os.path.isdir(path):
            return False
        record = self._meta.get("snapshots", {}).get(name)
        if record is None:
            logger.warning("snapshot %s has no recorded checksum "
                           "(pre-hardening?); restoring unverified", name)
            return True
        return snapshot_checksum(path) == record["sha256"]

    def _snapshot_epoch(self, name: str) -> int:
        record = self._meta.get("snapshots", {}).get(name)
        if record is not None:
            return int(record["epoch"])
        m = _EPOCH_NAME_RE.match(name)
        if m:
            return int(m.group(1))
        if name == "last":
            return int(self._meta.get("last_epoch", -1))
        if name == "best":
            return int(self._meta.get("best_epoch", -1))
        return -1

    def _fallback_order(self, requested: str) -> List[str]:
        """Requested name first, then every other on-disk snapshot by
        descending epoch (ties: last > epoch_N > best) — THE documented
        checksum-fallback order (README "Fault tolerance")."""
        on_disk = [
            d for d in sorted(os.listdir(self.directory))
            if os.path.isdir(os.path.join(self.directory, d))
            and (d in ("best", "last") or _EPOCH_NAME_RE.match(d))
        ]
        pref = {"last": 0, "best": 2}

        def rank(name: str) -> Tuple:
            return (-self._snapshot_epoch(name), pref.get(name, 1), name)

        rest = sorted((d for d in on_disk if d != requested), key=rank)
        head = [requested] if requested in on_disk else []
        return head + rest

    def _resolve_intact(self, name: str) -> str:
        candidates = self._fallback_order(name)
        for cand in candidates:
            if self.verify(cand):
                if cand != name:
                    logger.error(
                        "snapshot %s failed integrity verification; falling "
                        "back to %s (epoch %d)", name, cand,
                        self._snapshot_epoch(cand),
                    )
                return cand
        raise CheckpointError(
            f"no intact snapshot under {self.directory} "
            f"(requested {name!r}, tried {candidates})"
        )

    # -- reads -------------------------------------------------------------

    def restore(self, name: str, target: Any) -> Any:
        """Verified restore: checksum-checked, with automatic fallback to
        the newest intact snapshot when the requested one is damaged.
        ``last_restored`` records what was loaded.

        A snapshot that was never written is a caller error, not damage —
        that still raises ``FileNotFoundError`` rather than silently
        loading something else."""
        if not self.has(name):
            raise FileNotFoundError(
                f"no checkpoint {name!r} under {self.directory}"
            )
        candidates = self._fallback_order(name)
        last_err: Optional[Exception] = None
        for cand in candidates:
            if not self.verify(cand):
                logger.error("snapshot %s failed integrity verification; "
                             "trying the next fallback", cand)
                continue
            try:
                restored = self._ckpt.restore(
                    os.path.join(self.directory, cand),
                    target=jax.device_get(target),
                )
            except Exception as e:
                # Checksums catch bit damage; this catches structural rot
                # (legacy snapshot with no checksum, half-written tree).
                logger.warning("restore of snapshot %s failed (%s); trying "
                               "the next fallback", cand, e)
                last_err = e
                continue
            self.last_restored = {
                "name": cand,
                "epoch": self._snapshot_epoch(cand),
                "fallback": cand != name,
            }
            if cand != name:
                logger.error("restored fallback snapshot %s (epoch %d) in "
                             "place of %s", cand,
                             self.last_restored["epoch"], name)
            return restored
        raise CheckpointError(
            f"no intact snapshot under {self.directory} "
            f"(requested {name!r}, tried {candidates})"
        ) from last_err

    def restore_params(self, name: str = "best") -> Any:
        """Restore just the model variables of a saved state — the
        inference path (serve engine, offline scoring).

        Target-free restore, so no optimizer tree has to be reconstructed
        (its structure varies with freeze flags and schedules and does not
        exist at serve time). Works on both checkpoint layouts: trainer
        states (``TrainState``/``TextTrainState`` — params under the
        ``params`` key) and the params-only dicts ``cmd_fit_text`` writes.
        Returns the apply-ready variables dict (``{"params": ...}``).
        Damaged snapshots fall back like :meth:`restore`.
        """
        if not self.has(name):
            raise FileNotFoundError(
                f"no checkpoint {name!r} under {self.directory}"
            )
        used = self._resolve_intact(name)
        self.last_restored = {
            "name": used,
            "epoch": self._snapshot_epoch(used),
            "fallback": used != name,
        }
        restored = self._ckpt.restore(os.path.join(self.directory, used))
        if isinstance(restored, dict):
            inner = restored.get("params")
            if isinstance(inner, dict) and "params" in inner:
                # Trainer state (step/params/opt_state) or the
                # {"params": state.params} wrapper: unwrap one level.
                return inner
            if inner is not None:
                # Already the apply-ready variables dict.
                return restored
        raise ValueError(
            f"checkpoint {os.path.join(self.directory, used)} holds no "
            "recognizable variables dict "
            "(expected a trainer state or a {{'params': ...}} tree)"
        )

    @property
    def best_meta(self) -> dict:
        return dict(self._meta)


def load_encoder_params(full_params: Any, drop_keys=("_head", "pooling")) -> Any:
    """Partial checkpoint load for encoder freezing.

    Drops the classification head (top-level key ``_head``) and ``pooling``
    parameters, keeping embeddings + GGNN — the combined models load these
    into an ``encoder_mode`` FlowGNN (reference main_cli.py:136-144,
    linevul_main.py:589-602).
    """
    params = full_params["params"]
    kept = {k: v for k, v in params.items() if k not in set(drop_keys)}
    return {"params": kept}

"""Checkpointing via orbax.

Reproduces the reference's checkpoint semantics (SURVEY §5): best-by-val-loss
with ``save_last`` (Lightning ModelCheckpoint, config_default.yaml:23-29),
periodic every-N-epochs snapshots (periodic_checkpoint.py:8-22), and
partial-load-and-freeze of the graph encoder for the combined models
(main_cli.py:136-144 ``--freeze_graph`` strips head/pooling keys). Best
checkpoint metadata is stored explicitly instead of being re-parsed out of
filenames (main_cli.py:175-184).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, periodic_every: int = 25):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.periodic_every = periodic_every
        self._ckpt = ocp.StandardCheckpointer()
        self._meta_path = os.path.join(self.directory, "meta.json")
        self._meta = {"best_epoch": -1, "best_val_loss": float("inf"),
                      "last_epoch": -1}
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                self._meta.update(json.load(f))

    def _save(self, name: str, state: Any) -> None:
        path = os.path.join(self.directory, name)
        self._ckpt.save(path, jax.device_get(state), force=True)
        self._ckpt.wait_until_finished()

    def _write_meta(self) -> None:
        with open(self._meta_path, "w") as f:
            json.dump(self._meta, f)

    def save_best(self, state: Any, epoch: int,
                  val_loss: Optional[float] = None,
                  metrics: Optional[dict] = None) -> None:
        """``val_loss`` is the GNN trainer's selection quantity (lower is
        better); runs that select on something else (val F1, bleu+em, ...)
        record it under its own name via ``metrics`` so meta.json never
        shows a negated stand-in in the val-loss field."""
        self._save("best", state)
        self._meta["best_epoch"] = epoch
        if val_loss is not None:
            self._meta["best_val_loss"] = val_loss
        if metrics:
            self._meta["best_metrics"] = {
                k: float(v) for k, v in metrics.items()
            }
        self._write_meta()

    def save_last(self, state: Any, epoch: int) -> None:
        self._save("last", state)
        self._meta["last_epoch"] = epoch
        self._write_meta()

    def has(self, name: str) -> bool:
        return os.path.isdir(os.path.join(self.directory, name))

    def maybe_save_periodic(self, state: Any, epoch: int) -> None:
        if self.periodic_every and (epoch + 1) % self.periodic_every == 0:
            self._save(f"epoch_{epoch}", state)

    def restore(self, name: str, target: Any) -> Any:
        path = os.path.join(self.directory, name)
        return self._ckpt.restore(path, target=jax.device_get(target))

    def restore_params(self, name: str = "best") -> Any:
        """Restore just the model variables of a saved state — the
        inference path (serve engine, offline scoring).

        Target-free restore, so no optimizer tree has to be reconstructed
        (its structure varies with freeze flags and schedules and does not
        exist at serve time). Works on both checkpoint layouts: trainer
        states (``TrainState``/``TextTrainState`` — params under the
        ``params`` key) and the params-only dicts ``cmd_fit_text`` writes.
        Returns the apply-ready variables dict (``{"params": ...}``).
        """
        path = os.path.join(self.directory, name)
        if not self.has(name):
            raise FileNotFoundError(
                f"no checkpoint {name!r} under {self.directory}"
            )
        restored = self._ckpt.restore(path)
        if isinstance(restored, dict):
            inner = restored.get("params")
            if isinstance(inner, dict) and "params" in inner:
                # Trainer state (step/params/opt_state) or the
                # {"params": state.params} wrapper: unwrap one level.
                return inner
            if inner is not None:
                # Already the apply-ready variables dict.
                return restored
        raise ValueError(
            f"checkpoint {path} holds no recognizable variables dict "
            "(expected a trainer state or a {{'params': ...}} tree)"
        )

    @property
    def best_meta(self) -> dict:
        return dict(self._meta)


def load_encoder_params(full_params: Any, drop_keys=("_head", "pooling")) -> Any:
    """Partial checkpoint load for encoder freezing.

    Drops the classification head (top-level key ``_head``) and ``pooling``
    parameters, keeping embeddings + GGNN — the combined models load these
    into an ``encoder_mode`` FlowGNN (reference main_cli.py:136-144,
    linevul_main.py:589-602).
    """
    params = full_params["params"]
    kept = {k: v for k, v in params.items() if k not in set(drop_keys)}
    return {"params": kept}

"""Sharded training loop for the FlowGNN model family.

Replaces the reference's LightningModule/Trainer stack
(DDFA/code_gnn/models/base_module.py + main_cli.py): optax AdamW (Adam lr
1e-3 + weight decay 1e-2, config_default.yaml:43-47), BCE-with-logits with
optional ``pos_weight`` (base_module.py:74), per-epoch undersampling with
dataloader reload semantics (dclass.py:84-105 + config_default.yaml:42),
best-val-loss model selection (main_cli.py:167-184), all under one
``jax.jit`` whose inputs are sharded over the mesh's data axis — the
gradient all-reduce that Lightning-DDP/NCCL performed explicitly is inserted
by GSPMD.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from deepdfa_tpu.core.config import DataConfig, FlowGNNConfig, TrainConfig, subkeys_for
from deepdfa_tpu.core.metrics import BinaryStats, binary_stats, compute_metrics
from deepdfa_tpu.data.sampling import epoch_indices
from deepdfa_tpu.graphs.batch import (
    GraphBatch,
    batch_graphs,
    batch_iterator,
    graph_label_from_nodes,
)
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.parallel.mesh import DATA_AXIS, batch_sharding, make_mesh, replicated
from deepdfa_tpu.resilience import inject, lifecycle
from deepdfa_tpu import telemetry

logger = logging.getLogger(__name__)


@struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any


@dataclasses.dataclass
class EvalResult:
    loss: float
    metrics: Dict[str, float]
    probs: np.ndarray
    labels: np.ndarray
    graph_ids: np.ndarray


def bce_with_logits(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    mask: jnp.ndarray,
    positive_weight: Optional[float] = None,
) -> jnp.ndarray:
    """Masked mean BCE-with-logits; pos_weight scales the positive term like
    torch's BCEWithLogitsLoss(pos_weight=...)."""
    log_p = jax.nn.log_sigmoid(logits)
    log_not_p = jax.nn.log_sigmoid(-logits)
    w_pos = 1.0 if positive_weight is None else positive_weight
    per = -(w_pos * labels * log_p + (1.0 - labels) * log_not_p)
    m = mask.astype(jnp.float32)
    return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    tx = optax.adamw(cfg.learning_rate, weight_decay=cfg.weight_decay)
    if cfg.grad_clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), tx)
    return tx


def make_train_state(
    model: FlowGNN, example: GraphBatch, cfg: TrainConfig
) -> Tuple[TrainState, optax.GradientTransformation]:
    params = model.init(jax.random.PRNGKey(cfg.seed), example)
    tx = make_optimizer(cfg)
    return TrainState(jnp.zeros((), jnp.int32), params, tx.init(params)), tx


def _labels_for(model: FlowGNN, batch: GraphBatch) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(labels, mask) per the configured label style (base_module.py:83-95).

    ``dataflow_solution_in/out`` train against per-node reaching-definitions
    solution bits (_DF_IN/_DF_OUT, attached by the ETL export from Joern's
    ``.dataflow.json`` or the native solver — etl/pipeline.py). The "in"
    style additionally cuts loss/metrics to definition nodes (nonzero
    abstract-dataflow feature), the ``cut_nodef`` semantics of
    base_module.py:148-155,175-176.
    """
    style = model.config.label_style
    if style == "graph":
        return graph_label_from_nodes(batch), batch.graph_mask
    if style == "node":
        return batch.node_vuln.astype(jnp.float32), batch.node_mask
    if style in ("dataflow_solution_in", "dataflow_solution_out"):
        sol = batch.node_df_in if style.endswith("_in") else batch.node_df_out
        if sol is None:
            raise ValueError(
                f"label_style {style!r} needs batches built with "
                "with_dataflow=True (examples carrying df_in/df_out bits)"
            )
        mask = batch.node_mask
        if style.endswith("_in"):
            # cut_nodef (base_module.py:148-155): loss/metrics only on
            # definition nodes, i.e. nonzero abstract-dataflow index. Our
            # export asserts all subkeys share the zero set (etl/export.py);
            # OR-ing over subkeys keeps the cut correct even for external
            # caches that never ran that assert.
            is_def = jnp.zeros_like(mask)
            for f in batch.node_feats.values():
                is_def = is_def | (f != 0)
            mask = mask & is_def
        return sol.astype(jnp.float32), mask
    raise NotImplementedError(f"label_style {style!r}")


def make_train_step(
    model: FlowGNN, tx: optax.GradientTransformation, cfg: TrainConfig
) -> Callable:
    def step(state: TrainState, batch: GraphBatch):
        labels, mask = _labels_for(model, batch)

        def loss_fn(params):
            logits = model.apply(params, batch)
            loss = bce_with_logits(logits, labels, mask, cfg.positive_weight)
            return loss, logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        stats = binary_stats(jax.nn.sigmoid(logits), labels, mask)
        new_state = TrainState(state.step + 1, params, opt_state)
        return new_state, loss, stats

    return step


def make_eval_step(model: FlowGNN, cfg: TrainConfig) -> Callable:
    def step(state: TrainState, batch: GraphBatch):
        labels, mask = _labels_for(model, batch)
        logits = model.apply(state.params, batch)
        loss = bce_with_logits(logits, labels, mask, cfg.positive_weight)
        probs = jax.nn.sigmoid(logits)
        return loss, probs, labels, mask

    return step


def _batches(
    examples: List[dict],
    indices: np.ndarray,
    data_cfg: DataConfig,
    subkeys,
    batch_size: int,
    n_shards: int = 1,
    build_tile_adj: bool = False,
    build_band_adj: bool = False,
    with_dataflow: bool = False,
    host: "Optional[Tuple[int, int]]" = None,
    with_global_meta: bool = False,
    shape_series: Optional[str] = "train",
) -> Iterable[GraphBatch]:
    """Pack examples into padded batches.

    With ``n_shards > 1`` the batch is assembled from ``n_shards``
    equal-budget sub-batches via ``shard_concat`` so that shard boundaries
    coincide with graph boundaries — message passing then needs no
    cross-device collectives (the mesh alignment contract in
    ``parallel/mesh.py``). Trailing groups are padded with empty sub-batches.

    ``host=(process_index, process_count)`` (multi-controller JAX): every
    host runs the same deterministic packing over the same global index
    sequence, but concatenates and yields only its own slice of each shard
    group — the caller lifts it to a global array with
    ``assemble_global_batch``. Packing all groups on all hosts keeps the
    shard boundaries globally agreed without communication, the same
    contract as the reference's seeded DistributedSampler
    (CodeT5/run_defect.py:274-277).

    ``with_global_meta``: yield ``(local_batch, meta)`` where ``meta`` holds
    host-side numpy copies of the FULL group's bookkeeping
    (graph_ids/graph_mask/node_graph/node_mask) — per-example evaluation
    outputs are replicated across hosts, but their id stream lives on the
    input side, which each host only feeds a slice of.
    """
    from deepdfa_tpu.parallel.mesh import local_shard_slice, shard_concat

    chosen = [examples[i] for i in indices]
    per_shard = max(batch_size // n_shards, 1)
    budget_nodes = per_shard * data_cfg.max_nodes_per_graph
    budget_edges = budget_nodes * data_cfg.max_edges_per_node
    if build_tile_adj or build_band_adj:
        # Per-shard node budget must be a tile multiple; shard_concat stacks
        # the per-shard adjacencies along a device axis for the sharded path.
        from deepdfa_tpu.ops.tile_spmm import align_to_tile

        budget_nodes = align_to_tile(budget_nodes)
    # Multi-controller adjacency batches: every host packs the full shard
    # group, but dense tiles/bands are only materialized for the LOCAL
    # shards — remote shards contribute just their budget (pow2 tile count /
    # bucketed bandwidth) and vals dtype, computed from edge lists alone, so
    # all hosts stack to one agreed leaf shape+dtype.
    build_dense_tile = build_tile_adj and host is None
    build_dense_band = build_band_adj and host is None
    # Tile counts pad to powers of two inside build_tile_adjacency (and
    # bandwidths inside build_band_adjacency), so the jitted step sees a
    # handful of distinct adjacency shapes (the same bucket-ladder
    # compromise as the node/edge budgets), not one per batch.
    sub_iter = batch_iterator(
        chosen, per_shard, budget_nodes, budget_edges, subkeys,
        build_tile_adj=build_dense_tile, build_band_adj=build_dense_band,
        with_dataflow=with_dataflow,
        # Traffic observatory (ISSUE 20): training admission records raw
        # pre-bucket shapes + the pad ledger; warmup/init packs pass
        # shape_series=None so throwaway batches don't skew the series.
        shape_series=shape_series,
    )
    if n_shards == 1:
        # with_global_meta is a multi-controller (n_shards > 1) concern;
        # honor it anyway so callers don't need to branch.
        for sub in sub_iter:
            if with_global_meta:
                yield sub, {
                    "graph_ids": np.asarray(sub.graph_ids),
                    "graph_mask": np.asarray(sub.graph_mask),
                    "node_mask": np.asarray(sub.node_mask),
                    "node_graph": np.asarray(sub.node_graph),
                }
            else:
                yield sub
        return
    empty = batch_graphs(
        [], per_shard, budget_nodes, budget_edges, subkeys,
        build_tile_adj=build_dense_tile, build_band_adj=build_dense_band,
        with_dataflow=with_dataflow,
    )
    sel = (
        local_shard_slice(n_shards, host[0], host[1]) if host is not None
        else slice(None)
    )
    base = sel.start or 0

    def group_meta(group: List[GraphBatch]) -> Dict[str, np.ndarray]:
        g0 = group[0]
        return {
            "graph_ids": np.concatenate(
                [np.asarray(b.graph_ids) for b in group]
            ),
            "graph_mask": np.concatenate(
                [np.asarray(b.graph_mask) for b in group]
            ),
            "node_mask": np.concatenate(
                [np.asarray(b.node_mask) for b in group]
            ),
            "node_graph": np.concatenate(
                [np.asarray(b.node_graph) + i * g0.n_graphs
                 for i, b in enumerate(group)]
            ),
        }

    def concat(group: List[GraphBatch]) -> GraphBatch:
        if host is None or not (build_tile_adj or build_band_adj):
            return shard_concat(group[sel], base_shard=base)
        local = list(group[sel])
        kw: Dict[str, Any] = {}
        if build_tile_adj:
            from deepdfa_tpu.ops.tile_spmm import (
                build_tile_adjacency,
                combine_tile_stats,
                tile_nz_budget,
                tile_vals_dtype,
            )

            def stat(b: GraphBatch):
                m = np.asarray(b.edge_mask)
                s, r = np.asarray(b.senders)[m], np.asarray(b.receivers)[m]
                return tile_nz_budget(s, r, b.max_nodes), tile_vals_dtype(s, r)

            tile_nz, tile_dt = combine_tile_stats([stat(b) for b in group])
            local = [
                b.replace(
                    tile_adj=build_tile_adjacency(
                        np.asarray(b.senders), np.asarray(b.receivers),
                        np.asarray(b.edge_mask), b.max_nodes, pad_nz=tile_nz,
                    )
                )
                for b in local
            ]
            kw.update(tile_nz=tile_nz, tile_dtype=tile_dt)
        if build_band_adj:
            from deepdfa_tpu.ops.band_spmm import (
                band_width_for,
                build_band_adjacency,
                combine_band_stats,
            )
            from deepdfa_tpu.ops.tile_spmm import tile_vals_dtype

            def bstat(b: GraphBatch):
                m = np.asarray(b.edge_mask)
                s, r = np.asarray(b.senders)[m], np.asarray(b.receivers)[m]
                return band_width_for(s, r), tile_vals_dtype(s, r)

            band_bw, band_dt = combine_band_stats([bstat(b) for b in group])
            local = [
                b.replace(
                    band_adj=build_band_adjacency(
                        np.asarray(b.senders), np.asarray(b.receivers),
                        np.asarray(b.edge_mask), b.max_nodes,
                        bandwidth=band_bw,
                    )
                )
                for b in local
            ]
            kw.update(band_bandwidth=band_bw, band_dtype=band_dt)
        return shard_concat(local, base_shard=base, **kw)

    def emit(group: List[GraphBatch]):
        batch = concat(group)
        return (batch, group_meta(group)) if with_global_meta else batch

    group: List[GraphBatch] = []
    for sub in sub_iter:
        group.append(sub)
        if len(group) == n_shards:
            yield emit(group)
            group = []
    if group:
        group.extend([empty] * (n_shards - len(group)))
        yield emit(group)


def evaluate(
    eval_step: Callable,
    state: TrainState,
    examples: List[dict],
    indices: np.ndarray,
    data_cfg: DataConfig,
    subkeys,
    n_shards: int = 1,
    build_tile_adj: bool = False,
    with_dataflow: bool = False,
    host: "Optional[Tuple[int, int]]" = None,
    mesh=None,
    build_band_adj: bool = False,
) -> EvalResult:
    """``host``/``mesh``: multi-controller mode — each host feeds its local
    shard slice, lifted to global arrays. The jitted eval outputs replicate
    across hosts (out_shardings), so per-example probs/labels come straight
    off the device on every host; the id stream (an input each host only
    feeds a slice of) rides the packer's host-side global meta. Every host
    therefore returns the same full EvalResult — PR curves,
    export_predictions, and the DbgBench protocol work on a pod."""
    from deepdfa_tpu.parallel.mesh import assemble_global_batch

    # Loss accumulates on device and transfers once at the end — a
    # float(loss) per batch would serialize host and device.
    loss_sum, n_batches = jnp.zeros(()), 0
    stats = BinaryStats.zeros()
    probs_all, labels_all, ids_all = [], [], []
    for item in _batches(
        examples, indices, data_cfg, subkeys, data_cfg.eval_batch_size, n_shards,
        build_tile_adj, build_band_adj, with_dataflow, host,
        with_global_meta=host is not None,
    ):
        if host is not None:
            batch, gmeta = item
            batch = assemble_global_batch(batch, mesh)
        else:
            batch = item
            gmeta = {
                "graph_ids": np.asarray(batch.graph_ids),
                "graph_mask": np.asarray(batch.graph_mask),
                "node_graph": np.asarray(batch.node_graph),
            }
        loss, probs, labels, mask = eval_step(state, batch)
        m = np.asarray(mask)
        probs_all.append(np.asarray(probs)[m])
        labels_all.append(np.asarray(labels)[m])
        # ids aligned 1:1 with probs: per-graph for graph labels, the owning
        # graph's id for per-node labels.
        gids = gmeta["graph_ids"]
        if m.shape == gmeta["graph_mask"].shape:
            ids_all.append(gids[m])
        else:
            ids_all.append(gids[gmeta["node_graph"]][m])
        stats = stats + binary_stats(probs, labels, mask)
        loss_sum = loss_sum + loss
        n_batches += 1
    probs_np = np.concatenate(probs_all) if probs_all else np.zeros(0)
    labels_np = np.concatenate(labels_all) if labels_all else np.zeros(0)
    ids_np = np.concatenate(ids_all) if ids_all else np.zeros(0, np.int64)
    metrics = {k: float(v) for k, v in compute_metrics(stats).items()}
    return EvalResult(
        loss=float(loss_sum) / max(n_batches, 1),
        metrics=metrics,
        probs=probs_np,
        labels=labels_np,
        graph_ids=ids_np,
    )


def fit(
    model: FlowGNN,
    examples: List[dict],
    splits: Dict[str, np.ndarray],
    train_cfg: TrainConfig = TrainConfig(),
    data_cfg: DataConfig = DataConfig(),
    mesh=None,
    checkpointer=None,
    log_every: int = 50,
    resume: bool = False,
    on_epoch_end: Optional[Callable[[int, Dict[str, Any]], bool]] = None,
) -> Tuple[TrainState, Dict[str, Any]]:
    """Train to ``max_epochs``, tracking the best state by val loss.

    Returns (best_state, history). ``mesh``: optional Mesh; inputs get
    data-axis sharding, params are replicated, XLA handles the rest.
    ``resume=True`` continues from the checkpointer's ``last`` snapshot
    (params + opt_state + epoch counter — resume_from_checkpoint,
    reference config_default.yaml:39); a no-op when no snapshot exists.

    ``on_epoch_end(epoch, record) -> bool``: called after each epoch's
    validation with the history record; returning True stops training
    (history gains ``early_stopped``). The hook behind intermediate-result
    reporting and assessor-driven trial termination (the reference's NNI
    protocol, base_module.py:346 + main_cli.py:110-121).
    """
    if train_cfg.anomaly_policy not in ("raise", "rollback"):
        raise ValueError(
            f"anomaly_policy must be 'raise' or 'rollback', "
            f"got {train_cfg.anomaly_policy!r}"
        )
    subkeys = subkeys_for(model.config.feature)
    n_shards = int(mesh.shape[DATA_AXIS]) if mesh is not None else 1
    # The band-family predicate (band AND fused consume the band
    # adjacency) lives on the config so no lane can drift — the flag
    # audit in tests/test_fused_gnn.py.
    use_tile = model.config.uses_tile_adj
    use_band = model.config.uses_band_adj
    use_df = model.config.label_style.startswith("dataflow_solution")
    # Multi-controller: every process runs this same loop; each feeds its
    # local slice of every global batch (host_shard contract, mesh.py).
    host = (jax.process_index(), jax.process_count()) if jax.process_count() > 1 else None
    if host is not None and mesh is None:
        raise ValueError("multi-process fit needs an explicit global mesh")
    if mesh is not None and model.mesh is not mesh:
        # The sharded tile kernel runs under shard_map and needs the mesh.
        model = model.clone(mesh=mesh)
    # Param shapes don't depend on the batch partitioning, so init on a
    # single-shard batch with a mesh-free model: the sharded tile kernel
    # (shard_map over a possibly multi-host mesh) must not trace over a
    # host-local batch slice, and the smaller init compiles faster.
    example_batch = next(
        _batches(examples, splits["train"][:data_cfg.batch_size], data_cfg, subkeys,
                 max(data_cfg.batch_size // n_shards, 1), 1, use_tile, use_band,
                 use_df, shape_series=None)
    )
    init_model = model.clone(mesh=None) if model.mesh is not None else model
    state, tx = make_train_state(init_model, example_batch, train_cfg)
    del example_batch

    if checkpointer is None and train_cfg.checkpoint_dir:
        # Async by default: the step loop pays only the device→host copy
        # start; serialization/fsync/checksum/meta-commit ride the writer
        # thread (DEEPDFA_ASYNC_CKPT=0 restores the synchronous manager).
        from deepdfa_tpu.train.checkpoint import make_checkpoint_manager

        checkpointer = make_checkpoint_manager(
            train_cfg.checkpoint_dir, periodic_every=train_cfg.checkpoint_every_epochs
        )
    if checkpointer is not None:
        # Snapshots record the logical DP layout so restore can detect a
        # topology change and reshard instead of refusing to resume.
        from deepdfa_tpu.parallel.mesh import snapshot_layout

        checkpointer.set_layout(snapshot_layout(mesh))
        if host is not None:
            # Multi-controller fleet: each process writes its own shard
            # of every snapshot; the primary alone commits meta.
            checkpointer.set_host(*host)

    train_step = make_train_step(model, tx, train_cfg)
    eval_step = make_eval_step(model, train_cfg)
    if mesh is not None:
        bs = batch_sharding(mesh)
        rep = replicated(mesh)
        train_step = jax.jit(
            train_step,
            in_shardings=(rep, bs),
            out_shardings=(rep, rep, rep),
        )
        eval_step = jax.jit(
            eval_step, in_shardings=(rep, bs), out_shardings=(rep, rep, rep, rep)
        )
    else:
        train_step = jax.jit(train_step)
        eval_step = jax.jit(eval_step)

    labels = [int(ex["label"]) for ex in examples]
    history: Dict[str, Any] = {"epochs": [], "best_epoch": -1, "best_val_loss": float("inf")}
    best_state = state
    start_epoch = 0
    resume_mid: Optional[Dict[str, Any]] = None
    candidate = checkpointer.resume_candidate() if (
        resume and checkpointer is not None) else None
    if candidate is not None:
        from deepdfa_tpu.parallel.mesh import (
            RESUME_REDISTRIBUTE_CONSOLIDATE,
            RESUME_REDISTRIBUTE_FAST,
            ProcessCountMismatchError,
            check_layout_compatible,
            reshard_state,
            snapshot_layout,
        )
        from deepdfa_tpu.train.checkpoint import CheckpointError

        if checkpointer.preempt_info(candidate) is not None:
            # A step-granular skip count is only meaningful under the DP
            # packing that wrote it: across a reshape, fall back to the
            # newest epoch-granular snapshot (the partial epoch is lost
            # there — loudly — instead of silently sheared).
            prev = checkpointer.snapshot_layout(candidate) or {}
            if prev and prev.get("n_shards") != (
                    int(mesh.shape[DATA_AXIS]) if mesh is not None else 1):
                logger.warning(
                    "resume: preempt snapshot %s was written under DP "
                    "layout %s; step-granular mid-epoch resume does not "
                    "survive a reshape — resuming from the newest "
                    "epoch-granular snapshot instead", candidate, prev,
                )
                candidate = checkpointer.resume_candidate(
                    include_preempt=False)
    if candidate is not None:
        meta = checkpointer.best_meta
        # Elastic resume (ISSUE 18): a snapshot written under a different
        # process count is rewritten on disk BEFORE the restore — the
        # primary redistributes (hardlink re-home when the shard sets
        # nest, consolidate+re-shard otherwise), peers rendezvous on the
        # rewritten layout. check_layout_compatible routes; only a
        # genuinely broken shard set still raises the typed error.
        prev0 = checkpointer.snapshot_layout(candidate) or {}
        plan0 = check_layout_compatible(prev0, snapshot_layout(mesh))
        if plan0 in (RESUME_REDISTRIBUTE_FAST, RESUME_REDISTRIBUTE_CONSOLIDATE):
            cur_pc = host[1] if host is not None else 1
            telemetry.event(
                "ckpt.redistribute_plan", snapshot=candidate, plan=plan0,
                from_processes=int(prev0.get("process_count", 1)),
                to_processes=cur_pc,
            )
            if host is None or host[0] == 0:
                try:
                    checkpointer.redistribute(candidate, cur_pc, target=state)
                except ProcessCountMismatchError:
                    # Unrecoverable shard set (missing shard/leaf files):
                    # leave the snapshot alone — the verified-restore
                    # fallback below skips it, or surfaces the typed
                    # error if nothing intact remains.
                    logger.exception(
                        "resume: snapshot %s could not be redistributed; "
                        "the restore fallback decides what happens next",
                        candidate,
                    )
            else:
                try:
                    checkpointer.wait_redistributed(candidate, cur_pc)
                except CheckpointError:
                    logger.exception(
                        "resume: primary never published the redistributed "
                        "%s; continuing into the restore fallback", candidate,
                    )
        try:
            state = checkpointer.restore(candidate, state)
        except CheckpointError:
            # Every snapshot is damaged: the self-healing posture is to
            # retrain from scratch (loudly), not to refuse to run.
            logger.exception(
                "resume: no intact snapshot under %s; restarting from "
                "scratch", checkpointer.directory,
            )
        else:
            restored = checkpointer.last_restored or {}
            if candidate != "last" and checkpointer.preempt_info(
                    candidate) is None:
                # The 'last' snapshot never landed (a writer killed between
                # deleting the old bytes and committing the new): resume
                # from the newest intact snapshot instead of from scratch.
                # (A preempt candidate is the NORMAL mid-epoch path and
                # logs its own message below.)
                logger.warning(
                    "resume: no 'last' snapshot on disk; resuming from "
                    "%s (epoch %d)", candidate, int(restored.get("epoch", -1)),
                )
            restored_epoch = int(restored.get("epoch", -1))
            if restored_epoch < 0 and (
                    "last_epoch" not in meta or int(meta["last_epoch"]) < 0):
                logger.warning(
                    "resume: checkpoint dir has a snapshot but no epoch "
                    "record in meta.json (written by an older version?) "
                    "— restarting the epoch schedule at 0 on top of the "
                    "restored weights"
                )
            # The VERIFIED snapshot that actually loaded decides where the
            # epoch schedule restarts — never the one that was asked for
            # (a damaged 'last' must not skip the epochs between the
            # surviving fallback and itself).
            start_epoch = restored_epoch + 1
            if restored.get("fallback"):
                logger.warning(
                    "resume: restored fallback snapshot %s; restarting at "
                    "epoch %d", restored.get("name"), start_epoch,
                )
            resume_mid = checkpointer.preempt_info(
                restored.get("name", candidate))
            if resume_mid is not None:
                # Mid-epoch restart (ISSUE 10): the preempt snapshot's
                # epoch is IN PROGRESS — re-enter it at the recorded
                # step, with the saved accumulators, skipping the batches
                # the preempted process already trained on (the
                # data-order cursor is (seed, epoch, step): the packer is
                # deterministic, so skip-by-count is exact).
                resume_mid["snapshot"] = restored.get("name", candidate)
                start_epoch = int(resume_mid["epoch"])
                logger.warning(
                    "resume: mid-epoch restart from preempt snapshot %s "
                    "(epoch %d, %d step(s) already trained)",
                    resume_mid["snapshot"], start_epoch,
                    int(resume_mid["step"]),
                )
                telemetry.event("lifecycle.resume",
                                snapshot=resume_mid["snapshot"],
                                epoch=start_epoch,
                                step=int(resume_mid["step"]))
            # Topology-independent restore: compare the snapshot's
            # recorded DP layout with the resuming mesh and reshard. Same
            # shard count => bit-tracked metrics; a reshape moves the
            # per-shard packing (FP reduction order), tolerance-documented
            # in README "Elastic training & async checkpoints".
            prev_layout = checkpointer.snapshot_layout(
                restored.get("name", candidate)) or {}
            cur_layout = snapshot_layout(mesh)
            # By here any process-count change was already rewritten on
            # disk (or the restore fell back to a snapshot the sharded
            # reader consolidates host-side regardless of its count), so
            # what remains is at most a device-level reshard.
            check_layout_compatible(prev_layout, cur_layout)
            if prev_layout and prev_layout.get("n_shards") != cur_layout["n_shards"]:
                logger.warning(
                    "resume: resharding from DP layout %s to %s "
                    "(metrics tolerance-bounded across the reshape)",
                    prev_layout, cur_layout,
                )
                telemetry.event(
                    "ckpt.reshape",
                    from_shards=int(prev_layout.get("n_shards", -1)),
                    to_shards=cur_layout["n_shards"],
                    from_devices=int(prev_layout.get("device_count", -1)),
                    to_devices=cur_layout["device_count"],
                )
            with telemetry.span("ckpt.reshard"):
                state = reshard_state(state, mesh)
            history["best_epoch"] = int(meta.get("best_epoch", -1))
            history["best_val_loss"] = float(meta.get("best_val_loss",
                                                      float("inf")))
            try:
                best_state = (
                    checkpointer.restore("best", state)
                    if checkpointer.has("best") else state
                )
            except CheckpointError:
                logger.exception("resume: no intact 'best' snapshot; "
                                 "tracking best from the restored state")
                best_state = state
            else:
                if checkpointer.has("best"):
                    best_state = reshard_state(best_state, mesh)
            logger.info("resuming from epoch %d (best val_loss %.4f @ epoch %d)",
                        start_epoch, history["best_val_loss"],
                        history["best_epoch"])

    tb_writer = None
    if train_cfg.tensorboard_dir:
        try:
            from torch.utils.tensorboard import SummaryWriter

            tb_writer = SummaryWriter(train_cfg.tensorboard_dir)
        except ImportError:  # tensorboard is optional
            logger.warning("tensorboard unavailable; skipping event logging")

    try:
        return _fit_epochs(
            model, examples, splits, train_cfg, data_cfg, subkeys, n_shards,
            use_tile, use_band, use_df, state, train_step, eval_step, labels,
            history, best_state, checkpointer, tb_writer, log_every,
            start_epoch, host, mesh, on_epoch_end, resume_mid,
        )
    finally:
        # close on every exit path: a diverging run (detect_anomaly raise)
        # is exactly when the buffered loss curve matters
        if tb_writer is not None:
            tb_writer.close()
        if checkpointer is not None:
            # The fit-exit drain barrier: every submitted snapshot commits
            # (or records its failure) before the caller can act on the
            # run — including the preempted path, where the pending 'last'
            # is exactly what the resume needs.
            checkpointer.drain()


class _AnomalyGuard:
    """Non-finite-loss handling at window granularity (one window =
    ``log_every`` steps, where the rate-limited host sync already happens).

    ``anomaly_policy="raise"`` keeps Lightning detect_anomaly parity: fail
    at (the first) step that produced a non-finite loss, identified by the
    device-accumulated index. ``"rollback"`` self-heals instead: restore
    the window-start state and accumulators (dropping the poisoned window's
    updates — the batches themselves are skipped, not replayed) and keep
    training, at most ``anomaly_retry_budget`` times per fit.
    """

    def __init__(self, train_cfg):
        self.policy = train_cfg.anomaly_policy
        self.active = train_cfg.detect_anomaly or self.policy == "rollback"
        self.budget = train_cfg.anomaly_retry_budget

    def check(self, epoch, bad_step, snapshot, current, history):
        """At a window boundary (modulo-guarded call sites — the one host
        read per window). Returns (rolled_back, window_state) where
        window_state is ``current`` advanced or ``snapshot`` restored."""
        if not self.active:
            return False, current
        first = int(bad_step)
        if first < 0:
            return False, current
        if self.policy != "rollback":
            raise FloatingPointError(
                f"non-finite loss at epoch {epoch} step {first}"
            )
        if self.budget <= 0:
            raise FloatingPointError(
                f"non-finite loss at epoch {epoch} step {first} "
                "(anomaly retry budget exhausted)"
            )
        self.budget -= 1
        history["anomaly_rollbacks"] = history.get("anomaly_rollbacks", 0) + 1
        logger.warning(
            "non-finite loss at epoch %d step %d: rolling back to the last "
            "good state and skipping the window (%d retries left)",
            epoch, first, self.budget,
        )
        return True, snapshot


def _resume_payload(epoch, seen, n_batches, loss_sum, stats, bad_step,
                    data_cfg, train_cfg) -> Dict[str, Any]:
    """The step-level resume state a ``preempt_*`` snapshot records.

    Host reads (``float()``) here are the one-time preemption cost; the
    values are JSON-safe and round-trip bit-exactly (f32 -> f64 -> f32),
    so the resumed accumulators are bitwise the preempted ones. The
    data-order cursor is just ``(seed, epoch, step)``: ``epoch_indices``
    and the packer are deterministic, so skip-by-count replays the exact
    batch sequence."""
    return {
        "seen": int(seen),
        "n_batches": int(n_batches),
        "loss_sum": float(loss_sum),
        "stats": [float(stats.tp), float(stats.fp), float(stats.tn),
                  float(stats.fn)],
        "bad_step": int(bad_step),
        "data_cursor": {"seed": int(data_cfg.seed), "epoch": int(epoch)},
        "prng_seed": int(train_cfg.seed),
    }


def _preempt_exit(notice, checkpointer, state, epoch, seen, n_batches,
                  loss_sum, stats, bad_step, data_cfg, train_cfg, history,
                  participant=None):
    """The graph fit's preemption drain (ISSUE 10): the shared
    snapshot-drain-exit path carrying THIS loop's step-level resume
    payload (the one :func:`fit` knows how to restart mid-epoch from).
    Never returns."""
    lifecycle.preempt_snapshot_exit(
        notice, checkpointer, state, epoch, seen, history=history,
        resume=_resume_payload(epoch, seen, n_batches, loss_sum, stats,
                               bad_step, data_cfg, train_cfg),
        participant=participant,
    )


def _fit_epochs(
    model, examples, splits, train_cfg, data_cfg, subkeys, n_shards,
    use_tile, use_band, use_df, state, train_step, eval_step, labels, history,
    best_state, checkpointer, tb_writer, log_every, start_epoch=0, host=None,
    mesh=None, on_epoch_end=None, resume_mid=None,
):
    from deepdfa_tpu.parallel.mesh import assemble_global_batch

    guard = _AnomalyGuard(train_cfg)
    # The hung-step watchdog's emergency hook: references to the last
    # COMPLETED step's state/accumulators (updated per step — references
    # only, no host reads). A wedged step can then still leave a durable
    # snapshot behind before the forced exit.
    published: Dict[str, Any] = {}

    def _on_hang(notice):
        if checkpointer is None or not published:
            return
        p = dict(published)
        payload = _resume_payload(p["epoch"], p["seen"], p["n_batches"],
                                  p["loss_sum"], p["stats"], p["bad_step"],
                                  data_cfg, train_cfg)
        snapshot = checkpointer.save_preempt(p["state"], p["epoch"],
                                             p["seen"], resume=payload)
        try:
            checkpointer.drain(timeout=max(notice.remaining(), 1.0))
        except TimeoutError:
            logger.error("lifecycle: emergency snapshot drain overran the "
                         "grace budget")
        telemetry.event("lifecycle.preempted", epoch=int(p["epoch"]),
                        step=int(p["seen"]), snapshot=snapshot,
                        reason=notice.reason, forced=True)

    participant = lifecycle.coordinator().register("train",
                                                   on_hang=_on_hang)
    try:
        return _fit_epochs_inner(
            model, examples, splits, train_cfg, data_cfg, subkeys, n_shards,
            use_tile, use_band, use_df, state, train_step, eval_step, labels,
            history, best_state, checkpointer, tb_writer, log_every,
            start_epoch, host, mesh, on_epoch_end, resume_mid, guard,
            published, participant,
        )
    finally:
        lifecycle.coordinator().unregister(participant)


def _fit_epochs_inner(
    model, examples, splits, train_cfg, data_cfg, subkeys, n_shards,
    use_tile, use_band, use_df, state, train_step, eval_step, labels, history,
    best_state, checkpointer, tb_writer, log_every, start_epoch, host,
    mesh, on_epoch_end, resume_mid, guard, published, participant,
):
    from deepdfa_tpu.parallel.mesh import assemble_global_batch

    # Coordinated fleet drain (ISSUE 18): in a multi-process fit with a
    # shared run dir, one host's preemption notice becomes a drain
    # barrier everyone reaches at the SAME (epoch, step) — so every
    # preempt shard describes one state and nobody is stranded in a
    # collective the rest of the fleet left. Single-process fits keep
    # the immediate-drain path (fleet is None).
    fleet = lifecycle.fleet_drain(
        checkpointer.directory if checkpointer is not None else None, host)
    if fleet is not None:
        fleet.clear()

    for epoch in range(start_epoch, train_cfg.max_epochs):
        # Fault hook: a `raise` fault here is a simulated preemption — the
        # kill-and-resume determinism gate (tests/test_resilience.py) and
        # the `cli chaos` soak drive it. No-op without an armed plan.
        inject.fire("train.epoch_start", index=epoch)
        # Fresh undersample + reshuffle per epoch (reload_dataloaders_every_
        # n_epochs: 1 semantics).
        train_idx = splits["train"]
        idx = epoch_indices(
            [labels[i] for i in train_idx],
            epoch,
            seed=data_cfg.seed,
            undersample_factor=data_cfg.undersample_factor,
            oversample_factor=data_cfg.oversample_factor,
        )
        epoch_sel = train_idx[idx]
        t0 = time.time()
        stats = BinaryStats.zeros()
        # Loss accumulates on-device; transferring once per epoch (and per
        # log line) keeps host dispatch running ahead of device execution.
        loss_sum = jnp.zeros(())
        # detect_anomaly without a per-step host sync: the first offending
        # step index accumulates ON DEVICE (eager jnp ops dispatch async)
        # and is read back once per epoch/log window — a float(loss) here
        # would serialize host and device every step, the pattern that
        # kills 10-hour transformer runs.
        bad_step = jnp.asarray(-1, jnp.int32)
        # `seen` counts iterated batches (log cadence, anomaly indices);
        # `n_batches` counts KEPT batches — a rollback rewinds it with the
        # accumulators so the epoch averages cover only surviving windows.
        n_batches = seen = 0
        epoch_rolled = False
        # Window-start snapshot for rollback: references to the functional
        # state/accumulator values, so holding it costs nothing.
        window = (state, loss_sum, stats, n_batches)
        # Mid-epoch resume (ISSUE 10): re-enter the preempted epoch at
        # the recorded step — accumulators restored bitwise from the
        # preempt snapshot's payload, the already-trained batches skipped
        # by count (the packer is deterministic per (seed, epoch)).
        skip = 0
        if resume_mid is not None and epoch == int(resume_mid["epoch"]):
            skip = int(resume_mid["step"])
            loss_sum = jnp.asarray(resume_mid["loss_sum"], jnp.float32)
            stats = BinaryStats(*(jnp.asarray(v, jnp.float32)
                                  for v in resume_mid["stats"]))
            n_batches = int(resume_mid["n_batches"])
            seen = skip
            bad_step = jnp.asarray(int(resume_mid.get("bad_step", -1)),
                                   jnp.int32)
            window = (state, loss_sum, stats, n_batches)
        # Preemption check at the epoch boundary too: a notice that
        # landed during eval/checkpointing must not cost one more full
        # step before the drain starts.
        notice = lifecycle.poll()
        if notice is not None:
            if fleet is None:
                _preempt_exit(notice, checkpointer, state, epoch, seen,
                              n_batches, loss_sum, stats, bad_step, data_cfg,
                              train_cfg, history, participant)
            # Fleet: announce the drain target instead of exiting — peers
            # may already be dispatching into this epoch, and leaving now
            # would strand them in a collective. The announce-before-
            # dispatch ordering guarantees everyone sees the target
            # before they can pass it; this process drains at the
            # target's step-boundary check below like everyone else.
            fleet.announce(epoch, seen + 1, notice.reason)
        # Epoch span, FENCED on the device loss accumulator: its duration
        # covers dispatch AND device execution (the honest wall time the
        # GL011 rule exists to enforce), while the per-step spans inside
        # it measure host-dispatch only — the report derives the
        # host/device split from exactly this pairing. window_steps
        # counts the steps the fenced span covers.
        window_steps = 0
        with telemetry.span("train.epoch", epoch=epoch) as ep:
            raw_batches = 0
            for batch in _batches(examples, epoch_sel, data_cfg, subkeys,
                                  data_cfg.batch_size, n_shards, use_tile,
                                  use_band, use_df, host):
                raw_batches += 1
                if raw_batches <= skip:
                    continue  # already trained before the preemption
                # The fleet drain barrier's step-boundary check, BEFORE
                # dispatch: at or past the announced target, stop here —
                # every process reaches this exact (epoch, step) because
                # the target is durable before its step can complete
                # anywhere. Survivors synthesize their notice.
                if fleet is not None:
                    tgt = fleet.reached(epoch, seen)
                    if tgt is not None:
                        notice = lifecycle.poll()
                        if notice is None:
                            notice = lifecycle.coordinator().notify(
                                "fleet_drain")
                        fleet.mark_draining(epoch, seen)
                        _preempt_exit(notice, checkpointer, state, epoch,
                                      seen, n_batches, loss_sum, stats,
                                      bad_step, data_cfg, train_cfg, history,
                                      participant)
                if host is not None:
                    batch = assemble_global_batch(batch, mesh)
                with telemetry.span("train.step", epoch=epoch, step=seen):
                    state, loss, bstats = train_step(state, batch)
                if fleet is not None:
                    # Dispatch fence: with at most ONE step in flight, a
                    # peer can be at most one step past the announcer's
                    # completed step — the bound the "+1" drain target
                    # relies on. Single-process runs keep free-running
                    # async dispatch.
                    jax.block_until_ready(loss)
                loss = inject.corrupt_loss(loss)
                if guard.active:
                    bad_step = jnp.where(
                        (bad_step < 0) & ~jnp.isfinite(loss), seen, bad_step
                    )
                loss_sum = loss_sum + loss
                stats = stats + bstats
                n_batches += 1
                seen += 1
                window_steps += 1
                published.update(state=state, epoch=epoch, seen=seen,
                                 n_batches=n_batches, loss_sum=loss_sum,
                                 stats=stats, bad_step=bad_step)
                # THE step-granularity preemption check: one flag read
                # (plus the lifecycle.preempt fault site) per step.
                notice = lifecycle.poll()
                if notice is not None:
                    if fleet is None:
                        _preempt_exit(notice, checkpointer, state, epoch,
                                      seen, n_batches, loss_sum, stats,
                                      bad_step, data_cfg, train_cfg, history,
                                      participant)
                    # Fleet drain: target the NEXT boundary — a peer may
                    # already be blocked inside step `seen + 1`'s
                    # collective (dispatch runs one step ahead of this
                    # poll), so this process must participate in it too.
                    fleet.announce(epoch, seen + 1, notice.reason)
                if seen % log_every == 0:
                    rolled, (state, loss_sum, stats, n_batches) = guard.check(
                        epoch, bad_step, window,
                        (state, loss_sum, stats, n_batches), history,
                    )
                    if rolled:
                        bad_step = jnp.asarray(-1, jnp.int32)
                        epoch_rolled = True
                        telemetry.event("train.rollback", epoch=epoch,
                                        step=seen)
                    else:
                        logger.info("epoch %d step %d loss %.4f", epoch, seen,
                                    float(loss))
                    window = (state, loss_sum, stats, n_batches)
            ep.fence(loss_sum)
            ep.set(steps=window_steps)
        rolled, (state, loss_sum, stats, n_batches) = guard.check(
            epoch, bad_step, window, (state, loss_sum, stats, n_batches),
            history,
        )
        epoch_rolled = epoch_rolled or rolled
        # An epoch whose every window rolled back kept no batches; nan is
        # honest where 0/1 would fabricate a perfect-loss datapoint.
        epoch_loss = (float("nan") if epoch_rolled and n_batches == 0
                      else float(loss_sum))
        train_metrics = {k: float(v) for k, v in compute_metrics(stats).items()}

        with telemetry.span("train.eval", epoch=epoch):
            val = evaluate(eval_step, state, examples, splits["val"],
                           data_cfg, subkeys, n_shards, use_tile, use_df,
                           host, mesh, build_band_adj=use_band)
        if epoch == start_epoch:
            # Cost-model capture (the roofline report's input): re-lower
            # the already-warm step once and record XLA's FLOPs/bytes +
            # HBM footprint. Instrumented runs only (an active telemetry
            # run), single-controller only, and BEFORE the warmup marker
            # — the extra compile must never read as a silent recompile.
            if host is None and telemetry.current_run() is not None \
                    and window_steps:
                from deepdfa_tpu.telemetry import costmodel

                # The fused/persistent megakernels are Pallas custom
                # calls — zero in XLA's cost model — so their
                # hand-counted FLOPs join the roofline capture
                # analytically. ONE helper owns every eligibility leg
                # (band adjacency, backend, the persistent VMEM budget),
                # so the accounting tracks the program the model
                # dispatch actually runs (ops/fused_gnn).
                extra: Dict[str, Any] = {}
                from deepdfa_tpu.ops.fused_gnn import analytic_extra_cost

                ef, eb = analytic_extra_cost(
                    model.config.message_impl, batch.band_adj,
                    model.config.ggnn_hidden, model.config.n_steps,
                    model.config.dtype, include_bwd=True)
                if ef or eb:
                    extra["extra_flops"] = ef
                    extra["extra_bytes"] = eb
                costmodel.capture_jitted("train.step", train_step, state,
                                         batch, use_fenced_window=True,
                                         **extra)
            # Every jitted shape this fit dispatches has now compiled
            # (train step + eval step); any jax.compile event after this
            # marker is a silent recompile the trace report must surface.
            telemetry.event("train.warmup_done", epoch=epoch)
        record = {
            "epoch": epoch,
            "train_loss": epoch_loss / max(n_batches, 1),
            "train_metrics": train_metrics,
            "val_loss": val.loss,
            "val_metrics": val.metrics,
            "seconds": time.time() - t0,
        }
        if epoch_rolled:
            # Parity with text_loop/gen_loop: per-epoch consumers must be
            # able to tell a healed epoch from a healthy one.
            record["rolled_back"] = True
        history["epochs"].append(record)
        telemetry.event("train.epoch_end", epoch=epoch,
                        train_loss=record["train_loss"], val_loss=val.loss,
                        val_f1=val.metrics["f1"],
                        seconds=record["seconds"],
                        rolled_back=epoch_rolled)
        # Live HBM watermark where the backend exposes allocator stats
        # (no-op on CPU; the sampler is globally rate-limited).
        from deepdfa_tpu.telemetry.memory import SAMPLER

        SAMPLER.sample()
        # Epoch-cadence flush: long runs must not ride the ring buffer
        # until close (a >ring-capacity fit would drop its whole tail).
        telemetry.flush()
        logger.info(
            "epoch %d train_loss %.4f val_loss %.4f val_f1 %.4f (%.1fs)",
            epoch, record["train_loss"], val.loss, val.metrics["f1"], record["seconds"],
        )
        if tb_writer is not None:
            tb_writer.add_scalar("train/loss", record["train_loss"], epoch)
            tb_writer.add_scalar("val/loss", val.loss, epoch)
            for k, v in val.metrics.items():
                tb_writer.add_scalar(f"val/{k}", v, epoch)
        if val.loss < history["best_val_loss"]:
            history["best_val_loss"] = val.loss
            history["best_epoch"] = epoch
            best_state = state
            if checkpointer is not None:
                checkpointer.save_best(state, epoch, val.loss)
        if checkpointer is not None:
            checkpointer.save_last(state, epoch)
            checkpointer.maybe_save_periodic(state, epoch)
            if resume_mid is not None and epoch == int(resume_mid["epoch"]):
                # The preempted epoch completed and this 'last' covers it
                # (and wins the fallback tie): the consumed preempt
                # snapshot is garbage now — and stale step counts must
                # never be resumable once the schedule moved past them.
                checkpointer.remove(resume_mid["snapshot"])
        if (
            on_epoch_end is not None
            and on_epoch_end(epoch, record)
            and epoch < train_cfg.max_epochs - 1  # stopping after the last
            # epoch saves nothing and would mislabel a full run as cut short
        ):
            history["early_stopped"] = True
            logger.info("assessor stopped the run at epoch %d", epoch)
            break

    return best_state, history

"""Clone-detection fine-tuning (reference CodeT5/run_clone.py): pair-
concatenated source ids -> CloneModel -> CE, AdamW + warmup, best-F1
tracking. The batching/eval skeleton mirrors gen_loop (fixed [N, 2L]
arrays, padded tail batches)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepdfa_tpu.core.prng import fold_in_dropout
from flax import struct

from deepdfa_tpu.core.config import TransformerTrainConfig
from deepdfa_tpu.core.metrics import binary_stats, BinaryStats, compute_metrics
from deepdfa_tpu.models.t5 import CloneModel
from deepdfa_tpu.train.text_loop import make_text_optimizer


@struct.dataclass
class CloneTrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any
    dropout_rng: jnp.ndarray


def encode_clone_pairs(
    pairs, tokenize: Callable, max_source_length: int, pad_id: int = 0,
    eos_id: int = 2,
) -> Dict[str, np.ndarray]:
    """(code1, code2, label) triples -> {"source_ids" [N, 2L], "labels"}.
    Each half is tokenized/padded to max_source_length with one eos
    (CodeT5/_utils.py:64-72 ``code1 + code2``)."""

    def fit(text):
        ids = list(tokenize(text))[: max_source_length - 1] + [eos_id]
        return ids + [pad_id] * (max_source_length - len(ids))

    n = len(pairs)
    src = np.zeros((n, 2 * max_source_length), np.int32)
    labels = np.zeros(n, np.int32)
    for i, (c1, c2, label) in enumerate(pairs):
        src[i, :max_source_length] = fit(c1)
        src[i, max_source_length:] = fit(c2)
        labels[i] = int(label)
    return {"source_ids": src, "labels": labels}


def clone_loss(model: CloneModel, params, source_ids, labels, example_mask,
               dropout_rng=None, deterministic: bool = True):
    rngs = {"dropout": dropout_rng} if dropout_rng is not None else None
    logits = model.apply(params, source_ids, deterministic=deterministic,
                         rngs=rngs)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    m = example_mask.astype(jnp.float32)
    return (nll * m).sum() / jnp.maximum(m.sum(), 1.0), logits


def make_clone_train_step(model: CloneModel, tx, cfg: TransformerTrainConfig):
    def step(state: CloneTrainState, source_ids, labels, example_mask):
        dropout_rng = fold_in_dropout(state.dropout_rng, state.step)

        def loss_fn(params):
            return clone_loss(model, params, source_ids, labels, example_mask,
                              dropout_rng=dropout_rng, deterministic=False)

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        probs = jax.nn.softmax(logits, axis=-1)[:, 1]
        stats = binary_stats(probs, labels.astype(jnp.float32), example_mask)
        return (
            CloneTrainState(state.step + 1, params, opt_state, state.dropout_rng),
            loss,
            stats,
        )

    return step


def fit_clone(
    model: CloneModel,
    train_data: Dict[str, np.ndarray],
    eval_data: Dict[str, np.ndarray],
    cfg: TransformerTrainConfig,
    init_params: Optional[Any] = None,
    log: Optional[Callable[[str], None]] = None,
    mesh=None,
) -> Dict[str, Any]:
    """Train, tracking best eval F1 (run_clone.py keeps checkpoint-best-f1).
    Returns {"state", "best_f1", "eval_metrics"}.

    Multi-controller: hosts run the same deterministic batch sequence and
    feed local row slices (the _batches/host contract of train/loop.py);
    eval logits replicate, so best-F1 tracking agrees on every host."""
    from deepdfa_tpu.train.gen_loop import (
        _check_host_batch_sizes,
        _host_of,
        _lift_rows,
    )

    host = _host_of()
    if host is not None and mesh is None:
        raise ValueError("multi-process fit_clone needs an explicit global mesh")
    _check_host_batch_sizes(cfg, host)
    n = len(train_data["source_ids"])
    steps_per_epoch = max(-(-n // cfg.batch_size), 1)
    max_steps = steps_per_epoch * cfg.max_epochs

    rng = jax.random.PRNGKey(cfg.seed)
    params_rng, dropout_rng = jax.random.split(rng)
    params = model.init(
        {"params": params_rng, "dropout": dropout_rng},
        jnp.asarray(train_data["source_ids"][: cfg.batch_size]),
    )
    if init_params is not None:
        # Graft (don't replace): a pretrained tree may cover only the "t5"
        # subtree while the clone head trains fresh (run_clone.py
        # from_pretrained); the merge validates keys/shapes.
        from deepdfa_tpu.train.text_loop import _merge_params

        params = _merge_params(params, init_params)
    tx = make_text_optimizer(cfg, max_steps)
    state = CloneTrainState(jnp.zeros((), jnp.int32), params, tx.init(params),
                            dropout_rng)
    # No donation: best_state is retained across later epochs' steps, and a
    # donated state argument would delete its buffers (the fit_text
    # pattern; donating here crashes the post-training test eval whenever
    # the best epoch is not the last).
    if mesh is None:
        step = jax.jit(make_clone_train_step(model, tx, cfg))
    else:
        # dp over the mesh's data axis (the DataParallel analog for the
        # clone task, reference run_clone.py).
        from deepdfa_tpu.parallel.mesh import jit_dp_step

        step = jit_dp_step(make_clone_train_step(model, tx, cfg), mesh,
                           n_batch_args=3, n_out=3,
                           batch_sizes=(cfg.batch_size,), donate=())
    eval_fn = make_clone_eval_fn(model, mesh)

    np_rng = np.random.RandomState(cfg.seed)
    best_f1, best_state = -1.0, state
    best_metrics: dict = {}
    for epoch in range(cfg.max_epochs):
        order = np_rng.permutation(n)
        for src, labels, mask in _clone_batches(train_data, cfg.batch_size, order):
            state, loss, _ = step(
                state, _lift_rows(src, mesh, host), _lift_rows(labels, mesh, host),
                _lift_rows(mask, mesh, host),
            )

        metrics = evaluate_clone(model, state.params, eval_data, cfg,
                                 mesh=mesh, host=host, eval_fn=eval_fn)
        if log:
            log(f"epoch {epoch}: eval_f1={metrics['f1']:.4f}")
        if metrics["f1"] > best_f1:
            best_f1, best_state, best_metrics = metrics["f1"], state, metrics

    # eval_metrics describe the returned (best) state, not the last epoch.
    return {"state": best_state, "best_f1": best_f1, "eval_metrics": best_metrics}


def make_clone_eval_fn(model: "CloneModel", mesh=None):
    def eval_forward(params, s, l, m):
        loss, logits = clone_loss(model, params, s, l, m)
        # softmax on device, inside the jitted program — the host should
        # only ever see the final probs (one transfer, replicated).
        return loss, jax.nn.softmax(logits, axis=-1)[:, 1]

    if mesh is None:
        return jax.jit(eval_forward)
    from deepdfa_tpu.parallel.mesh import batch_sharding, replicated

    rep, dsh = replicated(mesh), batch_sharding(mesh)
    return jax.jit(
        eval_forward,
        in_shardings=(rep, dsh, dsh, dsh), out_shardings=(rep, rep),
    )


def _clone_batches(data, batch_size, order=None):
    """Padded tail batch with an example mask: no rows dropped, and
    small datasets still train (the gen_loop._batches contract)."""
    idx = np.arange(len(data["source_ids"])) if order is None else order
    for start in range(0, len(idx), batch_size):
        sel = idx[start : start + batch_size]
        src, labels = data["source_ids"][sel], data["labels"][sel]
        n_valid = len(sel)
        if n_valid < batch_size:
            pad = batch_size - n_valid
            src = np.concatenate(
                [src, np.zeros((pad, src.shape[1]), src.dtype)]
            )
            labels = np.concatenate([labels, np.zeros(pad, labels.dtype)])
        mask = np.arange(batch_size) < n_valid
        yield src, labels, mask


def evaluate_clone(model, params, data, cfg, mesh=None, host=None,
                   eval_fn=None) -> dict:
    """Binary clone metrics over ``data`` — usable on the dev set per epoch
    (fit_clone) or on the test split from the selected state (the
    reference's post-training test eval, run_clone.py)."""
    from deepdfa_tpu.train.gen_loop import _lift_rows

    eval_fn = eval_fn or make_clone_eval_fn(model, mesh)
    stats = BinaryStats.zeros()
    for src, labels, mask in _clone_batches(data, cfg.eval_batch_size):
        _, probs = eval_fn(
            params, _lift_rows(src, mesh, host),
            _lift_rows(labels, mesh, host), _lift_rows(mask, mesh, host),
        )
        # probs replicate; stats from host-side global labels/mask are
        # identical on every host.
        stats = stats + binary_stats(
            jnp.asarray(np.asarray(probs)), jnp.asarray(labels, jnp.float32),
            jnp.asarray(mask),
        )
    return {k: float(v) for k, v in compute_metrics(stats).items()}

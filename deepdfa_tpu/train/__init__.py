from deepdfa_tpu.train.loop import (
    EvalResult,
    TrainState,
    evaluate,
    fit,
    make_eval_step,
    make_train_step,
    make_train_state,
)

__all__ = [
    "EvalResult",
    "TrainState",
    "evaluate",
    "fit",
    "make_eval_step",
    "make_train_step",
    "make_train_state",
]

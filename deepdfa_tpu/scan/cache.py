"""Incremental scan cache: normalized-content hash -> verdict.

The property the streaming scan service sells: a PR-diff scan
re-analyzes only *changed* functions, and a whole-repo re-sweep after a
one-line edit costs ~one Joern invocation. Keys are content hashes of
the **normalized** source text (:func:`normalize_source` — the rule is
documented in the README and must never drift silently: CRLF→LF,
per-line trailing whitespace stripped, leading/trailing blank lines
dropped, exactly one trailing newline), so formatting-only churn that
the parser cannot see does not defeat the cache, while any token change
does.

Persistence follows the ``etl/cache.py`` checksummed-JSONL discipline:
append-only rows carrying a per-row ``__sha1__`` digest, read back
through ``contracts.validate_cache_row`` with skip-and-count — a torn or
bit-rotted row costs that row (quarantined into the cache's
``quarantine/`` sibling), never the store. Verdict values hold only
content-derived fields (prob, model, key), mirroring the serve result
cache's rule: per-request metadata must never ride a shared cache line.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
from collections import OrderedDict
from pathlib import Path
from typing import Dict, Optional

logger = logging.getLogger(__name__)


def normalize_source(source: str) -> str:
    """THE cache-key normalization rule (README "Streaming scan
    service"): CRLF/CR to LF, trailing whitespace stripped per line,
    leading/trailing blank lines dropped, one trailing newline."""
    lines = [line.rstrip()
             for line in source.replace("\r\n", "\n").replace("\r", "\n")
             .split("\n")]
    while lines and not lines[0]:
        lines.pop(0)
    while lines and not lines[-1]:
        lines.pop()
    return "\n".join(lines) + "\n"


def source_key(source: str) -> str:
    """Stable digest of one function's normalized source text."""
    return hashlib.blake2b(normalize_source(source).encode("utf-8"),
                           digest_size=16).hexdigest()


class ScanCache:
    """Thread-safe LRU of ``source_key -> verdict`` with optional
    checksummed-JSONL persistence.

    ``path=None`` keeps the cache in-memory (tests, one-shot sweeps);
    with a path, rows append on every put and load back last-wins, so a
    restarted scan service resumes warm. ``capacity`` bounds memory; the
    on-disk log is append-only (compaction is a re-write of live
    entries, done only at :meth:`compact`).
    """

    def __init__(self, path: "str | Path | None" = None,
                 capacity: int = 65536):
        self.path = Path(path) if path is not None else None
        self.capacity = int(capacity)
        self._entries: "OrderedDict[str, Dict]" = OrderedDict()
        self._lock = threading.Lock()
        self.loaded_rows = 0
        self.corrupt_rows = 0
        if self.path is not None and self.path.exists():
            self._load()

    def _load(self) -> None:
        from deepdfa_tpu.contracts import ContractError, Quarantine
        from deepdfa_tpu.contracts.quarantine import quarantine_dir
        from deepdfa_tpu.contracts.schema import validate_cache_row

        sink: Optional[Quarantine] = None

        def quarantine(err: ContractError, raw) -> None:
            nonlocal sink
            if sink is None:
                sink = Quarantine(quarantine_dir(self.path))
            sink.put(err, raw=raw)

        with open(self.path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                if not line.strip():
                    continue
                try:
                    row = validate_cache_row(json.loads(line),
                                             boundary="scan_cache",
                                             item_id=i)
                    key = row["key"]
                    verdict = row["verdict"]
                    if not isinstance(key, str) \
                            or not isinstance(verdict, dict):
                        raise ContractError(
                            "mistyped_field",
                            "scan cache row lacks key/verdict",
                            boundary="scan_cache", item_id=i)
                except json.JSONDecodeError as e:
                    self.corrupt_rows += 1
                    quarantine(ContractError(
                        "truncated_json", f"row {i}: {e}",
                        boundary="scan_cache", item_id=i), raw=line)
                    continue
                except (ContractError, KeyError) as e:
                    self.corrupt_rows += 1
                    err = e if isinstance(e, ContractError) else \
                        ContractError("missing_field",
                                      f"scan cache row {i}: missing {e}",
                                      boundary="scan_cache", item_id=i)
                    quarantine(err, raw=line)
                    continue
                self._entries[key] = verdict
                self._entries.move_to_end(key)
                self.loaded_rows += 1
        self._evict()
        if self.corrupt_rows:
            logger.warning("scan cache %s: %d corrupt row(s) quarantined",
                           self.path, self.corrupt_rows)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: str) -> Optional[Dict]:
        with self._lock:
            value = self._entries.get(key)
            if value is not None:
                self._entries.move_to_end(key)
            return value

    def put(self, key: str, verdict: Dict) -> None:
        from deepdfa_tpu.contracts.schema import CHECKSUM_KEY, row_checksum

        with self._lock:
            self._entries[key] = verdict
            self._entries.move_to_end(key)
            self._evict()
        if self.path is None:
            return
        self.path.parent.mkdir(parents=True, exist_ok=True)
        row = {"key": key, "verdict": verdict}
        row[CHECKSUM_KEY] = row_checksum(row)
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json.dumps(row) + "\n")

    def _evict(self) -> None:
        # caller holds the lock
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)

    def compact(self) -> int:
        """Rewrite the log to the live entries only (atomic rename);
        returns rows written."""
        from deepdfa_tpu.contracts.schema import CHECKSUM_KEY, row_checksum

        if self.path is None:
            return 0
        import os

        with self._lock:
            items = list(self._entries.items())
            tmp = self.path.with_suffix(".tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                for key, verdict in items:
                    row = {"key": key, "verdict": verdict}
                    row[CHECKSUM_KEY] = row_checksum(row)
                    f.write(json.dumps(row) + "\n")
            os.replace(tmp, self.path)
        return len(items)

"""Streaming scan service: raw C source -> pooled Joern -> DDFA verdicts.

The subsystem that closes the loop a real user hits (ISSUE 8 / ROADMAP
"End-to-end streaming scan service"):

* :mod:`~deepdfa_tpu.scan.pool` — N pooled persistent Joern sessions
  with health-checking, per-item deadlines, retry-with-restart, and a
  typed give-up when every worker is gone;
* :mod:`~deepdfa_tpu.scan.cache` — the incremental verdict cache keyed
  by normalized function content hash (checksummed-JSONL persistence);
* :mod:`~deepdfa_tpu.scan.featurize` — on-demand CPG -> abstract-
  dataflow features for a single function, shaped for the warmed serve
  engine (zero new compiles after warmup);
* :mod:`~deepdfa_tpu.scan.service` — the composition behind
  ``POST /scan`` and ``cli scan``;
* :mod:`~deepdfa_tpu.scan.fake_joern` — the hermetic fake-Joern
  transport (a scripted subprocess speaking the real session protocol),
  so every tier-1 test and the smoke path run without a JVM.
"""

from deepdfa_tpu.scan.cache import ScanCache, normalize_source, source_key
from deepdfa_tpu.scan.fake_joern import fake_joern_command, seeded_sources
from deepdfa_tpu.scan.pool import JoernPool, PoolExhaustedError
from deepdfa_tpu.scan.service import (
    ScanConfig,
    ScanService,
    changed_paths_from_diff,
)

__all__ = [
    "JoernPool",
    "PoolExhaustedError",
    "ScanCache",
    "ScanConfig",
    "ScanService",
    "changed_paths_from_diff",
    "fake_joern_command",
    "normalize_source",
    "seeded_sources",
    "source_key",
]

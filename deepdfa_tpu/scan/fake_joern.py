"""Hermetic fake-Joern transport: a scripted subprocess speaking the real
session protocol.

The streaming scan service drives CPG extraction through
:class:`~deepdfa_tpu.etl.joern_session.JoernSession` — a pty REPL that
expects a ``joern>`` prompt, an ``import $file.`...``` line, and a
``<stem>.exec(filename="...")`` call that leaves ``<file>.nodes.json`` /
``<file>.edges.json`` next to the input. This module is a stdlib-only
stand-in for the JVM side of that conversation: spawned as a child
process (``fake_joern_command()``), it answers the same protocol and
emits a *canned but content-derived* CPG, so every tier-1 test, the
``cli scan --smoke`` path, and the chaos soak run the full pool /
session / retry / cache machinery with no Joern install, single-device,
in milliseconds per function.

Determinism: the emitted graph is a pure function of the source text
(same bytes -> same nodes/edges -> same features -> same verdict), which
is what makes the incremental-cache headline test exact. Two scripted
behaviors support fault testing without a fault plan:

* a source containing :data:`POISON_TOKEN` exports a graph with no
  METHOD node — the ingestion contract quarantines it deterministically
  (reason ``no_method_node``);
* ``FAKE_JOERN_STARTUP_FAIL=1`` in the environment makes the child exit
  before printing its first prompt — the all-workers-dead scenario.

IMPORTANT: this file must stay importable/runnable with the stdlib alone
(it is executed by *path*, never via ``-m``), so the child process never
pays the package/jax import cost — session startup is what the pool
tests time against their deadlines.
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Dict, List, Tuple

PROMPT = "joern>"

#: Magic token: a "function" carrying it exports a METHOD-less graph (the
#: deterministic quarantine victim for chaos/death scenarios).
POISON_TOKEN = "__JOERN_POISON__"

# The fake keeps graphs comfortably inside the serve admission caps
# (ServeConfig.max_nodes_per_graph=64 at 3 nodes per statement + METHOD).
MAX_STATEMENTS = 12

_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_EXEC_RE = re.compile(r'\.exec\(\s*filename="((?:[^"\\]|\\.)*)"')

# Deterministic type assignment: hash of the statement's first identifier
# picks from a tiny C-type palette, so edits that rename variables move
# the datatype feature too (content-sensitive features, fixed vocab).
_TYPES = ("int", "char *", "size_t", "float")


def stable_hash(text: str) -> int:
    """hashlib-free FNV-1a: stable across processes and PYTHONHASHSEED.

    Shared with :mod:`~deepdfa_tpu.scan.featurize` (hashing-vocab bucket
    assignment) — the one content-hash both sides of the fake transport
    derive from. It lives here, not there, because this file must stay
    importable with the stdlib alone (it runs as the child by path).
    """
    h = 2166136261
    for b in text.encode("utf-8", "replace"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


def fake_cpg(source: str) -> Tuple[List[Dict], List[List]]:
    """(nodes_json, edges_json) in the Joern v1.1.107 export shape.

    One METHOD node plus, per statement line (non-empty, not brace-only,
    capped at :data:`MAX_STATEMENTS`), an assignment CALL with an
    IDENTIFIER and a LITERAL child — enough structure for the abstract-
    dataflow feature miner (``etl.absdf``) to produce per-node features
    that vary with the text. Edge rows are ``[inNode, outNode, label,
    ""]`` (TinkerPop order, exactly what ``etl.cpg.from_joern_json``
    parses).
    """
    lines = source.splitlines()
    poisoned = POISON_TOKEN in source

    def node(nid, label, name="", code="", line=None, order=0, tfn=""):
        return {"id": nid, "_label": label, "name": name, "code": code,
                "lineNumber": line, "order": order, "typeFullName": tfn}

    def edge(src, dst, etype):
        return [dst, src, etype, ""]

    nodes: List[Dict] = []
    edges: List[List] = []

    first_line = lines[0].strip() if lines else "int fn(void)"
    m = _IDENT_RE.search(first_line.split("(")[0].split()[-1]
                         if first_line.split("(")[0].split() else "fn")
    method_name = m.group(0) if m else "fn"
    if not poisoned:
        nodes.append(node(1, "METHOD", name=method_name, code=first_line,
                          line=1))

    stmts: List[int] = []
    nid = 10
    for i, raw in enumerate(lines, start=1):
        text = raw.strip()
        if not text or text in ("{", "}", "};"):
            continue
        if len(stmts) >= MAX_STATEMENTS:
            break
        idents = _IDENT_RE.findall(text)
        var = idents[0] if idents else f"v{i}"
        lit = str(stable_hash(text) % 997)
        call, ident, literal = nid, nid + 1, nid + 2
        nid += 10
        nodes.append(node(call, "CALL", name="<operator>.assignment",
                          code=text, line=i))
        nodes.append(node(ident, "IDENTIFIER", name=var, code=var, line=i,
                          order=1, tfn=_TYPES[stable_hash(var) % len(_TYPES)]))
        nodes.append(node(literal, "LITERAL", name=lit, code=lit, line=i,
                          order=2))
        edges.append(edge(call, ident, "AST"))
        edges.append(edge(call, literal, "AST"))
        edges.append(edge(call, ident, "ARGUMENT"))
        edges.append(edge(call, literal, "ARGUMENT"))
        if not poisoned:
            edges.append(edge(1, call, "AST"))
        stmts.append(call)

    if not stmts:
        # Whitespace/brace-only bodies still need one statement so the
        # exported graph batches (empty graphs fail the example contract).
        call, ident, literal = 10, 11, 12
        nodes.append(node(call, "CALL", name="<operator>.assignment",
                          code="x = 0", line=1))
        nodes.append(node(ident, "IDENTIFIER", name="x", code="x", line=1,
                          order=1, tfn="int"))
        nodes.append(node(literal, "LITERAL", name="0", code="0", line=1,
                          order=2))
        edges += [edge(call, ident, "AST"), edge(call, literal, "AST"),
                  edge(call, ident, "ARGUMENT"),
                  edge(call, literal, "ARGUMENT")]
        if not poisoned:
            edges.append(edge(1, call, "AST"))
        stmts = [call]

    prev = 1 if not poisoned else None
    for call in stmts:
        if prev is not None:
            edges.append(edge(prev, call, "CFG"))
            if prev != 1:
                edges.append(edge(prev, call, "REACHING_DEF"))
        prev = call
    return nodes, edges


def export_file(filename: str) -> int:
    """Write ``<filename>.nodes.json``/``.edges.json`` from the file's
    text; returns the node count (the REPL's reply payload)."""
    with open(filename, encoding="utf-8", errors="replace") as f:
        source = f.read()
    nodes, edges = fake_cpg(source)
    with open(filename + ".nodes.json", "w", encoding="utf-8") as f:
        json.dump(nodes, f)
    with open(filename + ".edges.json", "w", encoding="utf-8") as f:
        json.dump(edges, f)
    return len(nodes)


def seeded_sources(n: int, seed: int = 0) -> List[str]:
    """A deterministic mini-corpus of single-function C sources — the
    seeded corpus behind ``cli scan --smoke``, the bench scan lane, and
    the replay harness's edit/repeat traffic mix."""
    import random

    rng = random.Random(seed)
    out: List[str] = []
    for i in range(n):
        n_stmts = rng.randint(2, 6)
        body = [f"int fn_{seed}_{i}(int a, char *p) {{"]
        for s in range(n_stmts):
            var = rng.choice(("x", "y", "len", "count", "acc"))
            body.append(f"  int {var}_{s} = a + {rng.randint(0, 99)};")
        body.append(f"  return {rng.randint(0, 9)};")
        body.append("}")
        out.append("\n".join(body) + "\n")
    return out


def edit_source(source: str, salt: int = 1) -> str:
    """A deterministic one-line edit (the PR-diff shape: one changed
    function) that changes the content hash AND the canned graph."""
    lines = source.splitlines()
    for i, line in enumerate(lines):
        if "=" in line:
            lines[i] = line.rstrip(";") + f" + {1000 + salt};"
            break
    else:
        lines.insert(len(lines) - 1 if lines else 0,
                     f"  int edited = {1000 + salt};")
    return "\n".join(lines) + "\n"


def fake_joern_command() -> List[str]:
    """The argv that spawns this module as the session child — by file
    path, so the subprocess never imports the package (or jax)."""
    return [sys.executable, os.path.abspath(__file__)]


def main() -> int:
    if os.environ.get("FAKE_JOERN_STARTUP_FAIL"):
        # The pool's "factory keeps failing" scenario: die before the
        # first prompt so session construction raises.
        sys.stderr.write("fake-joern: injected startup failure\n")
        return 3
    die_after = int(os.environ.get("FAKE_JOERN_DIE_AFTER", "0"))
    sys.stdout.write("fake joern v0 (hermetic transport)\n")
    sys.stdout.write(PROMPT + " ")
    sys.stdout.flush()
    exports = 0
    for line in sys.stdin:
        line = line.strip()
        if line == "exit":
            break
        m = _EXEC_RE.search(line)
        if m:
            filename = m.group(1).replace('\\"', '"').replace("\\\\", "\\")
            try:
                n = export_file(filename)
                sys.stdout.write(f"exported {n} nodes\n")
            except OSError as e:
                sys.stdout.write(f"export failed: {e}\n")
            exports += 1
            if die_after and exports >= die_after:
                # Mid-protocol death: exit WITHOUT a prompt — the driver
                # sees EOF (JoernDiedError), exactly like a crashed JVM.
                sys.stdout.flush()
                return 4
        sys.stdout.write(PROMPT + " ")
        sys.stdout.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Pooled persistent Joern workers for the streaming scan service.

Generalizes the one-REPL-per-ETL-worker driver
(``etl/joern_session.extract_cpg_batch``) into a long-lived pool: N
worker threads, each owning one persistent :class:`JoernSession` (real
JVM or the hermetic fake transport — same protocol), draining a shared
work queue of single-function ``.c`` files. The pool holds the scan
service's availability invariants:

* **A killed Joern costs one restart, never the pool.** Each item runs
  under ``core/retry`` (jittered backoff, per-item attempt cap); a dead
  JVM (:class:`JoernDiedError`) or a hung REPL (the session read
  deadline's ``TimeoutError``) restarts that worker's session between
  attempts and re-runs the item on the fresh one.
* **Per-item wall deadline.** Futures are waited with a budget derived
  from the session timeout and attempt cap, so a pathological item can
  never wedge a caller — it resolves to a typed failure instead.
* **Typed give-up when the pool is gone.** A worker whose session
  *factory* fails (binary vanished, startup crash-loop) dies and hands
  its item to a surviving worker; when the last worker dies, everything
  still queued resolves to :class:`PoolExhaustedError` — partial results
  plus typed failures, never a hang.

Fault sites: items fire ``scan.item`` before dispatch, and every REPL
command inside the session fires the existing ``joern.send`` site — the
``kill``/``hang`` fault kinds drive the restart/deadline paths without a
real JVM. Restarts count into the shared registry
(``scan_pool_restarts_total``) and emit ``scan.pool_restart`` events;
per-item work is a ``scan.joern`` span.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from concurrent.futures import Future
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence

from deepdfa_tpu import telemetry
from deepdfa_tpu.core.retry import GiveUp, RetryPolicy, retry_call
from deepdfa_tpu.etl.joern_session import JoernDiedError, JoernSession
from deepdfa_tpu.resilience import inject

logger = logging.getLogger(__name__)

_EXPORT_SCRIPT = (Path(__file__).parent.parent / "etl" / "scripts"
                  / "export_cpg.sc")
_SESSION_FATAL = (TimeoutError, JoernDiedError, OSError)


class PoolExhaustedError(RuntimeError):
    """Every pooled worker is dead (session factory keeps failing): the
    typed give-up for items the pool can no longer run."""


class _WorkerDeath(Exception):
    """Internal: the session FACTORY failed — the worker cannot continue.
    Distinct from an item failure (which costs the item, not the worker)."""

    def __init__(self, cause: BaseException):
        super().__init__(f"session factory failed: {cause}")
        self.cause = cause


@dataclasses.dataclass
class _Job:
    path: Path
    future: Future
    index: int
    requeues: int = 0


class JoernPool:
    """N persistent Joern sessions behind one work queue.

    ``session_factory(worker_id, workspace_root)`` builds one session
    (default: :class:`JoernSession` on ``command`` — pass
    ``fake_joern_command()`` for the hermetic transport). ``submit``
    returns a Future resolving to the export stem (the ``.c`` path whose
    ``.nodes.json``/``.edges.json`` now exist) or failing with the
    terminal error. Thread-safe: transport threads may submit
    concurrently.
    """

    def __init__(
        self,
        size: int = 2,
        command: "str | Sequence[str]" = "joern",
        session_factory: Optional[Callable[..., JoernSession]] = None,
        workspace_root: "str | Path" = "runs/scan_ws",
        timeout_s: float = 120.0,
        attempts: int = 3,
        script: "str | Path" = _EXPORT_SCRIPT,
    ):
        if size < 1:
            raise ValueError("pool size must be >= 1")
        self.size = size
        self.timeout_s = timeout_s
        self.attempts = max(int(attempts), 1)
        self.script = Path(script)
        self.workspace_root = Path(workspace_root)
        self._factory = session_factory or (
            lambda wid, root: JoernSession(wid, root, timeout_s=timeout_s,
                                           binary=command)
        )
        self._queue: "queue.Queue[Optional[_Job]]" = queue.Queue()
        self._lock = threading.Lock()
        self._alive = 0
        self._closed = False
        self._restarts = 0
        self._sessions: Dict[int, Optional[JoernSession]] = {}
        self._threads: List[threading.Thread] = []
        self._item_ordinal = 0
        for wid in range(size):
            t = threading.Thread(target=self._worker, args=(wid,),
                                 name=f"joern-pool-{wid}", daemon=True)
            self._alive += 1
            self._threads.append(t)
            t.start()

    # -- introspection -------------------------------------------------------

    @property
    def alive_workers(self) -> int:
        with self._lock:
            return self._alive

    @property
    def restarts(self) -> int:
        with self._lock:
            return self._restarts

    def health(self) -> List[bool]:
        """Per-worker liveness: the worker thread runs AND its current
        session's child process has not exited (workers with no session
        yet — lazy start — count as healthy). Non-invasive by design: a
        protocol-level probe would race the owning worker thread."""
        out: List[bool] = []
        with self._lock:
            for wid, thread in enumerate(self._threads):
                session = self._sessions.get(wid)
                up = thread.is_alive() and (
                    session is None or _session_alive(session))
                out.append(up)
        return out

    def item_deadline_s(self) -> float:
        """Wall budget for one item: every attempt may burn the session
        read deadline, plus restart/backoff slack."""
        return self.attempts * (self.timeout_s + 5.0) + 15.0

    def _session_pid(self, wid: int) -> Optional[int]:
        """The child pid behind a worker's current session (None for
        test doubles without a process) — trace-plane bookkeeping."""
        with self._lock:
            session = self._sessions.get(wid)
        proc = getattr(session, "_proc", None)
        return getattr(proc, "pid", None)

    # -- submission ----------------------------------------------------------

    def submit(self, path: "str | Path") -> Future:
        """Queue one ``.c`` file for export; resolves to its Path."""
        future: Future = Future()
        with self._lock:
            if self._closed:
                raise RuntimeError("pool is closed")
            dead = self._alive == 0
            index = self._item_ordinal
            self._item_ordinal += 1
        if dead:
            future.set_exception(PoolExhaustedError(
                "all pooled Joern workers are dead"))
            return future
        self._queue.put(_Job(Path(path), future, index))
        return future

    def extract(self, paths: Sequence["str | Path"],
                ) -> List["Path | BaseException"]:
        """Run a batch through the pool; one entry per input, in order —
        the Path on success, the terminal exception on failure. Bounded:
        every wait carries the per-item deadline, so a wedged pool
        surfaces as typed timeouts, not a hang."""
        futures = [self.submit(p) for p in paths]
        out: List["Path | BaseException"] = []
        deadline = self.item_deadline_s()
        for fut in futures:
            try:
                out.append(fut.result(timeout=deadline))
            except BaseException as exc:  # typed per-item failure
                out.append(exc)
        return out

    def close(self, deadline_s: Optional[float] = None) -> None:
        """Drain and shut the pool down with close→wait→kill escalation
        under one overall deadline.

        Phase 1 — stop dispatch (``_closed``: no new submissions, no new
        sessions) and let workers finish everything already queued (the
        sentinels land BEHIND the in-flight items). Phase 2 — workers
        that outlive the deadline are mid-item on a wedged/hung child:
        force-kill their children so the blocked REPL read sees EOF and
        the thread exits, instead of leaking live JVMs behind an
        "closed" pool. Phase 3 — leftover sessions shut down via the
        session protocol (``exit`` + bounded wait, kill only as the
        escalation terminus).

        ``deadline_s`` bounds the whole drain (default: one item budget —
        the legacy behavior); the lame-duck path passes the lifecycle
        notice's remaining grace."""
        import time as _time

        with self._lock:
            if self._closed:
                return
            self._closed = True
        for _ in self._threads:
            self._queue.put(None)
        budget = (self.timeout_s + 10.0 if deadline_s is None
                  else max(float(deadline_s), 0.1))
        deadline = _time.monotonic() + budget
        for t in self._threads:
            t.join(timeout=max(deadline - _time.monotonic(), 0.05))
        stuck = [t for t in self._threads if t.is_alive()]
        if stuck:
            # Escalation: a worker is wedged mid-item (hung child, dead
            # pty). Kill the children outright — EOF unblocks the reader
            # — and give the threads one short grace to unwind.
            logger.error("pool close: %d worker(s) still busy at the "
                         "deadline; killing their children", len(stuck))
            telemetry.event("scan.pool_close_escalated", stuck=len(stuck))
            with self._lock:
                sessions = dict(self._sessions)
            for wid, session in sessions.items():
                if session is not None:
                    _kill_session_child(wid, session)
            for t in stuck:
                t.join(timeout=5.0)
        with self._lock:
            leftovers = list(self._sessions.values())
            self._sessions.clear()
        for session in leftovers:
            if session is not None:
                try:
                    session.close()
                except Exception:
                    logger.warning("pool: session close failed",
                                   exc_info=True)
        self._drain_dead()  # anything still queued resolves typed, never hangs

    def __enter__(self) -> "JoernPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker internals ----------------------------------------------------

    def _new_session(self, wid: int) -> JoernSession:
        with self._lock:
            if self._closed:
                # A restart racing close() must not mint a session nobody
                # will ever shut down (the leaked-child shape).
                raise _WorkerDeath(RuntimeError("pool is closed"))
        try:
            session = self._factory(wid, self.workspace_root)
        except Exception as exc:
            raise _WorkerDeath(exc) from exc
        with self._lock:
            if self._closed:
                try:
                    session.close()
                except Exception:
                    pass
                raise _WorkerDeath(RuntimeError("pool is closed"))
            self._sessions[wid] = session
        return session

    def _drop_session(self, wid: int) -> None:
        with self._lock:
            session = self._sessions.pop(wid, None)
        if session is not None:
            try:
                session.close()
            except Exception:
                logger.warning("pool worker %d: close of the dead session "
                               "failed", wid, exc_info=True)

    def _restart(self, wid: int, exc: BaseException) -> None:
        """Replace a dead/hung session (raises :class:`_WorkerDeath` when
        the factory itself fails — the worker-death path)."""
        logger.warning("pool worker %d: %s: %s — restarting the session",
                       wid, type(exc).__name__, exc)
        self._drop_session(wid)
        self._new_session(wid)
        with self._lock:
            self._restarts += 1
        telemetry.REGISTRY.counter("scan_pool_restarts_total").inc()
        telemetry.event("scan.pool_restart", worker=wid,
                        error=type(exc).__name__)

    def _run_item(self, wid: int, job: _Job) -> Path:
        with self._lock:
            session = self._sessions.get(wid)
        if session is None:
            session = self._new_session(wid)
        session.run_script(self.script,
                           {"filename": str(job.path.resolve())})
        nodes = job.path.with_suffix(job.path.suffix + ".nodes.json")
        if not nodes.exists():
            raise RuntimeError(f"export produced no {nodes.name}")
        return job.path

    def _worker(self, wid: int) -> None:
        policy = RetryPolicy(max_attempts=self.attempts, base_delay_s=0.05,
                             retry_on=_SESSION_FATAL,
                             giveup_on=(_WorkerDeath,))
        while True:
            job = self._queue.get()
            if job is None:
                break
            if job.future.cancelled():
                continue
            try:
                # Fault site: per-item hook, index = global submission
                # ordinal (position-derived, so plans replay across pool
                # sizes). A `hang` here surfaces as the item's failure.
                inject.fire("scan.item", index=job.index)
                # Worker bookkeeping for the cross-process trace plane
                # (ISSUE 14): the span records the session child's pid
                # AFTER the item ran, so the merged timeline attributes
                # each item to the exact Joern process that served it —
                # across restarts, one worker slot's items join to
                # different pids.
                with telemetry.span("scan.joern", worker=wid,
                                    item=job.path.name) as jsp:
                    result = retry_call(
                        self._run_item, (wid, job), policy=policy,
                        on_retry=lambda a, e, d: self._restart(wid, e))
                    jsp.set(child_pid=self._session_pid(wid))
                job.future.set_result(result)
            except _WorkerDeath as death:
                self._die(wid, job, death)
                return
            except GiveUp as exc:
                job.future.set_exception(exc)
                if isinstance(exc.last, _SESSION_FATAL):
                    # retry_call only restarts BETWEEN attempts: the final
                    # failure leaves the corpse in the slot and must not
                    # poison the next item's budget.
                    try:
                        self._restart(wid, exc.last)
                    except _WorkerDeath as death:
                        self._die(wid, None, death)
                        return
            except Exception as exc:  # per-item fault tolerance
                job.future.set_exception(exc)
        self._drop_session(wid)
        self._retire()

    def _die(self, wid: int, job: Optional[_Job],
             death: _WorkerDeath) -> None:
        """Session factory failed: retire this worker, hand its item to a
        survivor (or fail it typed when none remain)."""
        logger.error("pool worker %d: dying (%s)", wid, death)
        telemetry.event("scan.pool_worker_dead", worker=wid,
                        error=type(death.cause).__name__)
        self._drop_session(wid)
        with self._lock:
            self._alive -= 1
            survivors = self._alive > 0
        if job is not None and not job.future.done():
            if survivors and job.requeues < self.size:
                job.requeues += 1
                self._queue.put(job)
            else:
                job.future.set_exception(PoolExhaustedError(
                    f"all pooled Joern workers are dead "
                    f"(last factory error: {death.cause})"))
        if not survivors:
            self._drain_dead()

    def _retire(self) -> None:
        """Clean shutdown bookkeeping (sentinel path)."""
        with self._lock:
            self._alive -= 1
            last = self._alive == 0
        if last:
            self._drain_dead()

    def _drain_dead(self) -> None:
        """No workers remain: everything still queued resolves typed."""
        while True:
            try:
                job = self._queue.get_nowait()
            except queue.Empty:
                return
            if job is not None and not job.future.done():
                job.future.set_exception(PoolExhaustedError(
                    "all pooled Joern workers are dead"))


def _kill_session_child(wid: int, session) -> None:
    """Escalation terminus for a wedged worker: SIGKILL the session's
    child process directly (``session.kill()`` when the transport
    provides it, the raw ``_proc`` otherwise) so the blocked read sees
    EOF. Test doubles without a child are a no-op."""
    try:
        killer = getattr(session, "kill", None)
        if callable(killer):
            killer()
            return
        proc = getattr(session, "_proc", None)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=5)
    except Exception:
        logger.warning("pool close: killing worker %d's child failed",
                       wid, exc_info=True)


def _session_alive(session) -> bool:
    probe = getattr(session, "alive", None)
    if probe is None:
        return True  # test doubles without a child process
    try:
        return bool(probe())
    except Exception:
        return False

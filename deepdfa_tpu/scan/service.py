"""The streaming scan service: raw C source -> DDFA verdict.

Closes the loop the ROADMAP names as the missing scenario: POST raw
source (or sweep files offline), extract a CPG through the pooled
persistent Joern workers (:mod:`~deepdfa_tpu.scan.pool`), featurize on
demand (:mod:`~deepdfa_tpu.scan.featurize`), and score through the
existing warmed serve engine — zero new compiles after warmup, because
the scan path reuses the engine's ``(lane, slot-bucket)`` executables
unchanged.

Contracts at every boundary: the source text itself is validated at the
API edge (``contracts.validate_scan_source`` — attacker-controlled input
enters here), Joern exports pass the Joern ingestion contract inside
``featurize_export``, and the featurized graph passes the serve
admission contract inside ``engine.submit``. Anything that fails lands
in the scan quarantine (reason-coded manifest) and comes back as an
inline error verdict — one poisoned function never aborts a sweep.

Incrementality: verdicts cache by normalized content hash
(:mod:`~deepdfa_tpu.scan.cache`), so a re-scan after a one-line edit
re-runs Joern for exactly the changed function. Cache hits/misses, pool
restarts, and featurize counts publish into the shared registry;
``scan.request`` / ``scan.joern`` / ``scan.featurize`` / ``scan.score``
spans thread the run trace.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from deepdfa_tpu import contracts, telemetry
from deepdfa_tpu.contracts.schema import MAX_SOURCE_BYTES
from deepdfa_tpu.scan.cache import ScanCache, normalize_source, source_key
from deepdfa_tpu.scan.featurize import featurize_export, hashing_vocabs
from deepdfa_tpu.scan.pool import JoernPool
from deepdfa_tpu.serve.batcher import OversizedError, RejectedError
from deepdfa_tpu.serve.engine import BadRequestError, ServeEngine

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ScanConfig:
    pool_size: int = 2          # persistent Joern workers
    timeout_s: float = 120.0    # per-REPL-command read deadline
    attempts: int = 3           # per-item tries (restart between)
    gtype: str = "cfg"          # graph reduction fed to the model
    max_source_bytes: int = MAX_SOURCE_BYTES
    cache_capacity: int = 65536

    def __post_init__(self):
        if self.pool_size < 1:
            raise ValueError("pool_size must be >= 1")


def changed_paths_from_diff(diff_text: str) -> List[str]:
    """Post-image file paths named by a unified diff (``+++ b/...``
    lines; ``/dev/null`` — deletions — skipped). The PR-diff scan's
    work-list: scan only what changed."""
    out: List[str] = []
    for line in diff_text.splitlines():
        if not line.startswith("+++ "):
            continue
        target = line[4:].split("\t")[0].strip()
        if target in ("/dev/null", ""):
            continue
        if target.startswith(("a/", "b/")):
            target = target[2:]
        if target not in out:
            out.append(target)
    return out


class ScanService:
    """Pool + cache + featurize + warmed engine, behind one call.

    ``engine`` must already be constructed (and ideally warmed);
    ``feature`` is the graph model's FeatureSpec — it sizes the hashing
    vocabulary to the embedding table. ``command``/``session_factory``
    pick the transport (real ``joern`` or
    ``fake_joern.fake_joern_command()``); tests may inject a prebuilt
    ``pool``.
    """

    def __init__(
        self,
        engine: ServeEngine,
        feature,
        workdir: "str | Path" = "runs/scan",
        config: Optional[ScanConfig] = None,
        command: "str | Sequence[str]" = "joern",
        session_factory=None,
        pool: Optional[JoernPool] = None,
        cache: Optional[ScanCache] = None,
        cache_path: "str | Path | None" = None,
        vocabs: Optional[Mapping] = None,
    ):
        self.engine = engine
        self.config = config or ScanConfig()
        self.workdir = Path(workdir)
        (self.workdir / "functions").mkdir(parents=True, exist_ok=True)
        self.pool = pool or JoernPool(
            size=self.config.pool_size, command=command,
            session_factory=session_factory,
            workspace_root=self.workdir / "ws",
            timeout_s=self.config.timeout_s,
            attempts=self.config.attempts,
        )
        if cache is None and cache_path is None:
            cache_path = self.workdir / "verdicts.jsonl"
        self.cache = cache or ScanCache(cache_path,
                                        capacity=self.config.cache_capacity)
        self.quarantine = contracts.Quarantine(self.workdir / "quarantine")
        if vocabs is not None:
            # Checkpoint-faithful mode: the ETL export's persisted vocabs
            # (etl/export.load_vocabs) — scan indices then match what the
            # model trained on exactly. A vocab set missing one of the
            # engine's subkeys would silently zero a whole embedding
            # table's features; fail loudly instead.
            missing = [k for k in engine.required_subkeys if k not in vocabs]
            if missing:
                raise ValueError(
                    f"scan vocabs missing subkeys {missing} (engine lanes "
                    f"need {engine.required_subkeys})")
            # The embedding table is sized input_dim == limit_all + 2; a
            # vocab exported under a bigger limit would hand index_for
            # results past the table (silent clamp/wrap on gather — wrong
            # features, no error). Same fail-loud contract as above.
            bad = {k: v.limit_all for k, v in vocabs.items()
                   if k in engine.required_subkeys
                   and v.limit_all > feature.limit_all}
            if bad:
                raise ValueError(
                    f"scan vocabs exported with limit_all {bad} exceed the "
                    f"model's feature limit_all={feature.limit_all} "
                    f"(embedding input_dim={feature.limit_all + 2}) — "
                    "re-export with the checkpoint's FeatureSpec")
            self.vocabs = vocabs
        else:
            # Fallback: deterministic hashing vocabulary (same index_for
            # contract, no train split needed) — reproducible across
            # restarts but NOT the mapping the checkpoint trained on.
            self.vocabs = hashing_vocabs(engine.required_subkeys,
                                         feature.limit_all)

    # -- metrics -------------------------------------------------------------

    @staticmethod
    def _count(name: str, by: int = 1) -> None:
        telemetry.REGISTRY.counter(name).inc(by)

    def snapshot(self) -> Dict[str, Any]:
        reg = telemetry.REGISTRY
        return {
            "cache_entries": len(self.cache),
            "cache_hits": reg.counter("scan_cache_hits_total").value,
            "cache_misses": reg.counter("scan_cache_misses_total").value,
            "featurized": reg.counter("scan_featurized_total").value,
            "errors": reg.counter("scan_errors_total").value,
            "pool_restarts": self.pool.restarts,
            "pool_alive": self.pool.alive_workers,
            "pool_health": self.pool.health(),
            "quarantined": self.quarantine.total,
        }

    # -- the scan ------------------------------------------------------------

    def scan_sources(self, items: Sequence[Mapping], *,
                     wait: str = "drain",
                     trace_id: Optional[str] = None,
                     trace_continued: bool = False) -> List[Dict]:
        """Score a batch of raw-source items, returning one verdict per
        item in order.

        Items are ``{"id"?: any, "source": str}``. ``wait="drain"`` is
        the offline mode (this thread pumps the engine);
        ``wait="event"`` is the transport mode (an external pump thread
        flushes; this thread blocks on each request's event with a
        bounded timeout). Verdicts are ``{"id", "key", "prob", "model",
        "cached", "featurized"}`` or inline ``{"id", "error", "detail"}``
        — a bad item costs itself, never the sweep.

        ``trace_id``/``trace_continued`` (ISSUE 14): the distributed
        trace this sweep rides — ``POST /scan`` passes its traceparent
        continuation, and every ``scan.request`` span plus the engine
        submissions carry it for the offline client↔server join.
        Threaded as locals: concurrent transport threads sweep with
        their own trace ids, so none of this lives on ``self``.
        """
        tattrs = ({"trace_id": trace_id, "trace_continued": trace_continued}
                  if trace_id is not None else {})
        results: List[Optional[Dict]] = [None] * len(items)
        pending: List[Tuple[int, Any, str, Path, float]] = []
        for i, item in enumerate(items):
            item_id = item.get("id", i) if isinstance(item, Mapping) else i
            raw = item.get("source") if isinstance(item, Mapping) else item
            t0 = telemetry.now()
            try:
                source = contracts.validate_scan_source(
                    raw, item_id=item_id,
                    max_bytes=self.config.max_source_bytes,
                    stats=contracts.STATS)
            except contracts.ContractError as e:
                results[i] = self._fail(item_id, e, raw, t0, tattrs)
                continue
            # Traffic observatory (ISSUE 20): raw validated function size
            # at the scan admission edge (cached or not — every admitted
            # source is demand the extraction ladder must cover).
            telemetry.observe_shape("traffic_shape_scan_source_bytes",
                                    len(source))
            key = source_key(source)
            cached = self.cache.get(key)
            if cached is not None:
                self._count("scan_cache_hits_total")
                results[i] = {"id": item_id, "key": key, **cached,
                              "cached": True, "featurized": False}
                telemetry.record_span("scan.request", t0, id=str(item_id),
                                      cached=True, **tattrs)
                continue
            self._count("scan_cache_misses_total")
            path = self.workdir / "functions" / f"{key}.c"
            path.write_text(normalize_source(source), encoding="utf-8")
            pending.append((i, item_id, key, path, t0))

        outcomes = self.pool.extract([p for _, _, _, p, _ in pending]) \
            if pending else []

        scored: List[Tuple[int, Any, str, float, Any]] = []
        for (i, item_id, key, path, t0), outcome in zip(pending, outcomes):
            if isinstance(outcome, BaseException):
                err = contracts.ContractError(
                    "joern_failure",
                    f"CPG extraction failed: {type(outcome).__name__}: "
                    f"{outcome}",
                    boundary="scan", item_id=item_id)
                results[i] = self._fail(item_id, err, key, t0, tattrs)
                continue
            try:
                with telemetry.span("scan.featurize", item=key):
                    graph = featurize_export(path, self.vocabs,
                                             gtype=self.config.gtype)
                self._count("scan_featurized_total")
                req = self._submit(graph, wait, tattrs)
            except contracts.ContractError as e:
                results[i] = self._fail(item_id, e, key, t0, tattrs)
                continue
            except (BadRequestError, OversizedError, RejectedError,
                    ValueError) as e:
                err = contracts.ContractError(
                    "joern_failure",
                    f"featurized graph not admissible: "
                    f"{type(e).__name__}: {e}",
                    boundary="scan", item_id=item_id)
                results[i] = self._fail(item_id, err, key, t0, tattrs)
                continue
            scored.append((i, item_id, key, t0, req))

        # The .c files and their Joern exports are one-shot featurize
        # inputs; the verdict cache (and, for bad items, the quarantine's
        # raw payload) is the durable artifact. A long-lived serve fed
        # attacker-controlled sources must not grow workdir/functions
        # without bound. Deduped: same-source items share one path.
        for path in {p for _, _, _, p, _ in pending}:
            self._discard_scratch(path)

        with telemetry.span("scan.score", n=len(scored)):
            if scored and wait == "drain":
                self.engine.drain()
            for i, item_id, key, t0, req in scored:
                results[i] = self._collect(item_id, key, t0, req, wait,
                                           tattrs)
        return [r for r in results if r is not None]

    def _submit(self, graph: Dict, wait: str,
                tattrs: Optional[Dict] = None):
        kw = {"trace_id": tattrs["trace_id"],
              "trace_continued": tattrs["trace_continued"]} if tattrs else {}
        try:
            return self.engine.submit(graph, **kw)
        except RejectedError as e:
            # Offline: drain and retry (nowhere to shed load to).
            # Transport mode: the pump thread is flushing — wait out one
            # flush window and retry once.
            if wait == "drain":
                self.engine.drain()
            else:
                time.sleep(max(e.retry_after_s, 0.01))
            return self.engine.submit(graph, **kw)

    def _collect(self, item_id, key: str, t0: float, req, wait: str,
                 tattrs: Optional[Dict] = None) -> Dict:
        tattrs = tattrs or {}
        if wait != "drain":
            wait_s = self.engine.config.deadline_ms / 1000.0 * 10 + 30.0
            req.event.wait(timeout=wait_s)
        res = req.result
        if res is None or "error" in (res or {}):
            self._count("scan_errors_total")
            detail = (res or {}).get("detail", "scoring timed out")
            telemetry.record_span("scan.request", t0, id=str(item_id),
                                  cached=False, error="internal", **tattrs)
            return {"id": item_id, "key": key, "error": "internal",
                    "detail": detail}
        verdict = {"prob": res["prob"], "model": res["model"]}
        self.cache.put(key, verdict)
        telemetry.record_span("scan.request", t0, id=str(item_id),
                              cached=False, **tattrs)
        return {"id": item_id, "key": key, **verdict, "cached": False,
                "featurized": True}

    @staticmethod
    def _discard_scratch(path: Path) -> None:
        for p in (path, Path(f"{path}.nodes.json"),
                  Path(f"{path}.edges.json")):
            try:
                p.unlink(missing_ok=True)
            except OSError:
                pass

    def _fail(self, item_id, err: contracts.ContractError, raw,
              t0: float, tattrs: Optional[Dict] = None) -> Dict:
        self._count("scan_errors_total")
        self.quarantine.put(err, raw=raw)
        logger.warning("scan: item %r quarantined (%s: %s)", item_id,
                       err.reason, err)
        telemetry.record_span("scan.request", t0, id=str(item_id),
                              cached=False, error=err.reason,
                              **(tattrs or {}))
        return {"id": item_id, "error": err.reason, "detail": str(err)}

    # -- offline sweep helpers (cli scan) ------------------------------------

    def scan_files(self, paths: Sequence["str | Path"], *,
                   wait: str = "drain") -> List[Dict]:
        """One verdict per file — each file is one function's source (the
        ETL ``prepare`` layout: functions/<id>.c). Unreadable files come
        back as inline errors without aborting the sweep."""
        slots: List[Optional[Dict]] = []
        items: List[Dict] = []
        for p in paths:
            p = Path(p)
            try:
                items.append({"id": str(p),
                              "source": p.read_text(encoding="utf-8",
                                                    errors="replace")})
                slots.append(None)
            except OSError as e:
                self._count("scan_errors_total")
                slots.append({"id": str(p), "error": "bad_source",
                              "detail": f"unreadable: {e}"})
        verdicts = iter(self.scan_sources(items, wait=wait))
        return [next(verdicts) if pre is None else pre for pre in slots]

    def drain(self, deadline_s: Optional[float] = None) -> None:
        """The lame-duck drain (ISSUE 10): stop dispatch, finish
        in-flight Joern items, shut workers down via the session protocol
        (close→wait→kill escalation under ``deadline_s``), and flush the
        verdict cache to its persisted live set — after this returns, a
        restarted service resumes warm from exactly the verdicts this
        process computed. Idempotent; audited as ``lifecycle.drain``
        events by the caller's participant plus a ``scan.drained``
        marker here."""
        with telemetry.span("lifecycle.drain_scan"):
            self.pool.close(deadline_s=deadline_s)
            compacted = self.cache.compact()
        telemetry.event("scan.drained", cache_rows=compacted,
                        pool_restarts=self.pool.restarts)

    def close(self) -> None:
        self.pool.close()

    def __enter__(self) -> "ScanService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

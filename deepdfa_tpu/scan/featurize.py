"""On-demand featurization: one Joern export -> one serve-ready graph.

The offline ETL builds abstract-dataflow vocabularies over a whole train
split (``etl/absdf.build_all_vocabs``) and exports a corpus; the scan
path needs the same CPG -> features transform for a *single* function,
milliseconds after Joern produced its export, shaped exactly like a
``POST /score`` graph so the warmed serve engine scores it with zero new
compiles.

Vocabulary: the ETL export stage does not persist its vocabs (ROADMAP
notes this as remaining work for checkpoint-faithful scan verdicts), so
the scan path ships a **deterministic hashing vocabulary** with the same
index contract (0 = not a definition, 1 = reserved UNKNOWN, else
``2 + stable_hash % limit_all`` — always inside the model's
``input_dim == limit_all + 2`` embedding table). Hashing is
content-derived and process-independent (FNV over the canonical feature
hash string, never Python's seeded ``hash``), which is what makes scan
verdicts reproducible across service restarts — the incremental-cache
headline property.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Mapping, Sequence

import numpy as np

from deepdfa_tpu.etl.absdf import (
    SINGLE_SUBKEYS,
    extract_decl_features,
    node_feature_indices,
    node_subkey_values,
)
from deepdfa_tpu.etl.cpg import CPG, load_joern_export, reduce_graph
from deepdfa_tpu.scan.fake_joern import stable_hash


@dataclasses.dataclass(frozen=True)
class HashingDataflowVocab:
    """Drop-in for ``AbstractDataflowVocab`` (same ``index_for``
    contract) that needs no train split: feature hashes map to a stable
    bucket in ``[2, limit_all + 1]``."""

    subkey: str
    limit_all: int

    def index_for(self, fields) -> int:
        if not fields:
            return 0  # not a definition — the per-node zero-set contract
        values = node_subkey_values(fields, self.subkey)
        if self.subkey in SINGLE_SUBKEYS:
            values = values[:1]
        canon = json.dumps({self.subkey: sorted(set(values))})
        return 2 + stable_hash(canon) % max(self.limit_all, 1)


def hashing_vocabs(subkeys: Sequence[str],
                   limit_all: int) -> Dict[str, HashingDataflowVocab]:
    return {sk: HashingDataflowVocab(sk, limit_all) for sk in subkeys}


def featurize_cpg(cpg: CPG, vocabs: Mapping, gtype: str = "cfg") -> Dict:
    """CPG -> the serve-admission graph shape (``num_nodes`` / ``senders``
    / ``receivers`` / ``feats``), dense-indexed by sorted Joern id like
    ``etl/export.cpg_to_example`` — but WITHOUT label/line fields: a scan
    request has no ground truth, and the serve contract
    (``contracts.validate_example(with_label=False)``) is the gate it
    must pass next."""
    node_ids = sorted(cpg.nodes)
    dense = {nid: i for i, nid in enumerate(node_ids)}
    edges = reduce_graph(cpg, gtype).edges
    features = extract_decl_features(cpg)
    feats = {
        subkey: np.asarray(idxs, np.int64)
        for subkey, idxs in node_feature_indices(cpg, features,
                                                 vocabs).items()
    }
    return {
        "num_nodes": len(node_ids),
        "senders": np.asarray([dense[s] for s, _, _ in edges], np.int32),
        "receivers": np.asarray([dense[d] for _, d, _ in edges], np.int32),
        "feats": feats,
    }


def featurize_export(stem: "str | Path", vocabs: Mapping,
                     gtype: str = "cfg") -> Dict:
    """``<stem>.nodes.json``/``.edges.json`` (a pool worker's output) ->
    serve-ready graph. Raises ``ContractError``/``JSONDecodeError`` on a
    malformed export — the scan service quarantines those per item."""
    return featurize_cpg(load_joern_export(stem), vocabs, gtype=gtype)

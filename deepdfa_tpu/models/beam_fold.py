"""Beam-deduped cross-attention query folding, shared by the T5 and
RoBERTa-seq2seq attention modules.

During beam decoding (models/t5_generate.py) decoder rows are beam-major
``b*K + beam`` while the encoder K/V are stored ONCE per batch row — every
beam of a row attends over identical K/V, so replicating them would
multiply the biggest HBM reads of the decode step by the beam width.
Instead the beam factor folds into the query-length axis for the attention
einsums (masks shaped [B, 1, 1, S] broadcast over it), and the output
unfolds back to beam-major rows. This invariant is layout-critical: it
assumes the beam-major flatten used by beam_search's ``reshape(b*k, 1)``.
"""

from __future__ import annotations

from typing import Optional, Tuple


def fold_beam_queries(q, k) -> Tuple[object, Optional[Tuple[int, int]]]:
    """Fold q [B*K, T, ...] to [B, K*T, ...] when k has B rows. Returns
    (q, fold) where fold is None (no-op) or the original (rows, q_len) for
    unfold_beam_out."""
    if k.shape[0] == q.shape[0]:
        return q, None
    if q.shape[0] % k.shape[0]:
        raise ValueError(
            f"cross-attention query rows {q.shape[0]} must be a multiple "
            f"of K/V rows {k.shape[0]}"
        )
    beams = q.shape[0] // k.shape[0]
    fold = (q.shape[0], q.shape[1])
    return q.reshape(k.shape[0], beams * q.shape[1], *q.shape[2:]), fold


def unfold_beam_out(out, fold: Optional[Tuple[int, int]]):
    """Undo fold_beam_queries on the attention output [B, K*T, H, D]."""
    if fold is None:
        return out
    return out.reshape(*fold, *out.shape[2:])

"""RoBERTa-compatible transformer encoder, written natively in Flax.

The reference rides HuggingFace PyTorch ``RobertaForSequenceClassification``
(LineVul/linevul/linevul_model.py:26-69, codebert/unixcoder backbones). Here
the encoder is our own module so the stack stays JAX-native end to end:
bfloat16-friendly, fusable by XLA, no dependency on transformers' Flax
classes. Weights convert 1:1 from any HF RoBERTa-family checkpoint via
:func:`convert_hf_roberta` (codebert-base and unixcoder-base share this
architecture).

Architectural parity (post-LN BERT encoder):
  embeddings = word + learned positions (offset by pad_id+1, RoBERTa
  convention) + token type, then LayerNorm; N layers of MHA + FFN(gelu),
  residual + post-LayerNorm each.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    """codebert-base / unixcoder-base shape by default."""

    vocab_size: int = 50265
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 514
    type_vocab_size: int = 1
    # FFN activation: the tanh-approximate gelu is the measured TPU
    # champion — the exact erf runs on the VPU's transcendental path in
    # fwd AND bwd, a whole-step A/B'd 227 -> 269 ex/s (+18.5%) at the
    # combined 512-token shape (bench.py round-5 notes). |tanh - erf|
    # < 1e-3 absolute; HF RoBERTa numerics (the golden-parity tests and
    # converted checkpoints, models/pretrained.py) need False.
    gelu_approximate: bool = True
    pad_token_id: int = 1
    layer_norm_eps: float = 1e-5
    dropout_rate: float = 0.1
    dtype: str = "float32"
    # Attention implementation: "dense" (O(T^2), returns weights — required
    # for line-level localization), "blockwise" (streaming-softmax lax.scan,
    # O(T) memory), "flash" (Pallas TPU fwd+bwd kernels), "auto" (flash on
    # TPU, blockwise elsewhere), or "ring" (sequence-parallel over the
    # mesh's seq axis — the long-context path the reference lacks,
    # SURVEY §5). Non-dense impls compute exact attention but apply no
    # attention-probability dropout (standard for fused kernels).
    attention_impl: str = "dense"
    seq_axis: str = "seq"
    # Rematerialize each transformer layer in the backward pass. The layers
    # are matmul-bound, so recomputing activations costs little and frees
    # the per-layer activation memory — what lets the combined model train
    # at batch 64 / 512 tokens (and long context) on one 16G chip.
    remat_layers: bool = False

    @classmethod
    def tiny(cls, vocab_size: int = 128) -> "EncoderConfig":
        return cls(
            vocab_size=vocab_size,
            hidden_size=32,
            num_layers=2,
            num_heads=4,
            intermediate_size=64,
            max_position_embeddings=66,
        )

    @classmethod
    def codebert_base(cls) -> "EncoderConfig":
        """microsoft/codebert-base — the LineVul backbone
        (LineVul/linevul/scripts/msr_train_linevul.sh)."""
        return cls()

    @classmethod
    def unixcoder_base(cls) -> "EncoderConfig":
        """microsoft/unixcoder-base — the UniXcoder variant backbone
        (LineVul/unixcoder/rq1_train_uxc.sh:10-28); same RoBERTa encoder
        with a longer position table."""
        return cls(vocab_size=51416, max_position_embeddings=1026)


class SelfAttention(nn.Module):
    cfg: EncoderConfig
    mesh: Any = None  # required for attention_impl="ring" under a dp×sp mesh

    @nn.compact
    def __call__(self, x, attn_mask, deterministic):
        c = self.cfg
        d = jnp.dtype(c.dtype)
        head_dim = c.hidden_size // c.num_heads
        q = nn.Dense(c.hidden_size, dtype=d, name="query")(x)
        k = nn.Dense(c.hidden_size, dtype=d, name="key")(x)
        v = nn.Dense(c.hidden_size, dtype=d, name="value")(x)

        def split(t):
            return t.reshape(t.shape[0], t.shape[1], c.num_heads, head_dim)

        q, k, v = split(q), split(k), split(v)

        if c.attention_impl == "dense":
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(head_dim)
            bias = jnp.where(attn_mask[:, None, None, :], 0.0, -1e9)
            weights = jax.nn.softmax(scores + bias, axis=-1)
            weights = nn.Dropout(c.dropout_rate)(weights, deterministic=deterministic)
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        elif c.attention_impl in ("blockwise", "flash", "auto"):
            from deepdfa_tpu.ops.attention import attention as attn_fn

            out = attn_fn(q, k, v, kv_mask=attn_mask, impl=c.attention_impl)
            weights = None
        elif c.attention_impl == "ring":
            from deepdfa_tpu.parallel.ring import ring_attention_sharded

            out = ring_attention_sharded(
                q, k, v, kv_mask=attn_mask, mesh=self.mesh,
                axis_name=c.seq_axis,
            )
            weights = None
        else:
            raise ValueError(f"unknown attention_impl {c.attention_impl!r}")
        out = out.astype(d)
        out = out.reshape(out.shape[0], out.shape[1], c.hidden_size)
        return out, weights


class EncoderLayer(nn.Module):
    cfg: EncoderConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, x, attn_mask, deterministic):
        c = self.cfg
        d = jnp.dtype(c.dtype)
        attn_out, attn_weights = SelfAttention(c, mesh=self.mesh, name="attention")(
            x, attn_mask, deterministic
        )
        attn_out = nn.Dense(c.hidden_size, dtype=d, name="attention_output")(attn_out)
        attn_out = nn.Dropout(c.dropout_rate)(attn_out, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, name="attention_ln")(x + attn_out)
        ff = nn.Dense(c.intermediate_size, dtype=d, name="intermediate")(x)
        ff = nn.gelu(ff, approximate=c.gelu_approximate)
        ff = nn.Dense(c.hidden_size, dtype=d, name="output")(ff)
        ff = nn.Dropout(c.dropout_rate)(ff, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, name="output_ln")(x + ff)
        return x, attn_weights


class RobertaEncoder(nn.Module):
    """Returns (last_hidden_state, attentions tuple). ``output_attentions``
    requires ``attention_impl="dense"`` (fused/ring impls never materialize
    the T×T weights)."""

    cfg: EncoderConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, input_ids, attn_mask=None, deterministic: bool = True,
                 output_attentions: bool = False, input_embeds=None):
        """``input_embeds`` (optional [B, T, H]) replaces the word-embedding
        lookup — the hook for gradient-based attribution (saliency /
        integrated gradients differentiate wrt the embedding, the captum
        pattern in the reference, unixcoder/linevul_main.py:1052-1078)."""
        c = self.cfg
        if attn_mask is None:
            attn_mask = input_ids != c.pad_token_id
        if input_embeds is None:
            word = nn.Embed(c.vocab_size, c.hidden_size, name="word_embeddings")(
                input_ids
            )
        else:
            word = input_embeds
        # RoBERTa position ids: pad positions stay at pad_id; real tokens
        # count up from pad_id+1. Ids past the table CLAMP to the last
        # entry — explicitly, because JAX's out-of-bounds gather fills NaN
        # under jit, which silently poisoned training (tiny table vs
        # 512-token inputs, round 5). Clamping keeps sequences longer than
        # the table trainable (the long-context perf shape at 4096 rides
        # the 514-entry table by design — bench.py).
        positions = jnp.cumsum(attn_mask.astype(jnp.int32), axis=1) * attn_mask + c.pad_token_id
        positions = jnp.minimum(positions, c.max_position_embeddings - 1)
        pos = nn.Embed(
            c.max_position_embeddings, c.hidden_size, name="position_embeddings"
        )(positions)
        tok_type = nn.Embed(
            c.type_vocab_size, c.hidden_size, name="token_type_embeddings"
        )(jnp.zeros_like(input_ids))
        x = word + pos + tok_type
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, name="embeddings_ln")(x)
        x = nn.Dropout(c.dropout_rate)(x, deterministic=deterministic)

        if output_attentions and c.attention_impl != "dense":
            raise ValueError(
                "output_attentions needs attention_impl='dense'; "
                f"got {c.attention_impl!r}"
            )
        layer_cls = EncoderLayer
        if c.remat_layers and not output_attentions:
            # static_argnums counts self: (self, x, attn_mask, deterministic)
            layer_cls = nn.remat(EncoderLayer, static_argnums=(3,))
        attentions = []
        for i in range(c.num_layers):
            x, attn = layer_cls(c, mesh=self.mesh, name=f"layer_{i}")(
                x, attn_mask, deterministic
            )
            if output_attentions:
                attentions.append(attn)
        return x, tuple(attentions)


def convert_hf_roberta(state_dict: Dict[str, Any], cfg: EncoderConfig) -> Dict:
    """Map a HuggingFace PyTorch RoBERTa ``state_dict`` (codebert-base,
    unixcoder-base, roberta-base) onto :class:`RobertaEncoder` params.

    Accepts either ``roberta.``-prefixed keys (ForSequenceClassification
    checkpoints) or bare ``embeddings./encoder.`` keys (base models).
    """

    def get(key):
        for prefix in ("roberta.", ""):
            k = prefix + key
            if k in state_dict:
                return np.asarray(state_dict[k].detach().cpu().numpy()
                                  if hasattr(state_dict[k], "detach")
                                  else state_dict[k])
        raise KeyError(key)

    p: Dict[str, Any] = {
        "word_embeddings": {"embedding": get("embeddings.word_embeddings.weight")},
        "position_embeddings": {"embedding": get("embeddings.position_embeddings.weight")},
        "token_type_embeddings": {"embedding": get("embeddings.token_type_embeddings.weight")},
        "embeddings_ln": {
            "scale": get("embeddings.LayerNorm.weight"),
            "bias": get("embeddings.LayerNorm.bias"),
        },
    }

    def dense(key):
        return {"kernel": get(key + ".weight").T, "bias": get(key + ".bias")}

    for i in range(cfg.num_layers):
        b = f"encoder.layer.{i}."
        p[f"layer_{i}"] = {
            "attention": {
                "query": dense(b + "attention.self.query"),
                "key": dense(b + "attention.self.key"),
                "value": dense(b + "attention.self.value"),
            },
            "attention_output": dense(b + "attention.output.dense"),
            "attention_ln": {
                "scale": get(b + "attention.output.LayerNorm.weight"),
                "bias": get(b + "attention.output.LayerNorm.bias"),
            },
            "intermediate": dense(b + "intermediate.dense"),
            "output": dense(b + "output.dense"),
            "output_ln": {
                "scale": get(b + "output.LayerNorm.weight"),
                "bias": get(b + "output.LayerNorm.bias"),
            },
        }
    return {"params": p}

"""T5-compatible encoder-decoder, written natively in Flax.

The reference rides HuggingFace PyTorch ``T5ForConditionalGeneration``
(CodeT5/run_defect.py:155-158, Salesforce codet5-{small,base,large}). Here the
stack is our own module so it stays JAX-native end to end — bfloat16-friendly
matmuls for the MXU, static shapes, XLA-fusable — with a 1:1 weight converter
from HF T5-family checkpoints (:func:`convert_hf_t5`).

Architectural parity with T5 v1.0 (the codet5 architecture):
  - RMS LayerNorm (no mean subtraction, no bias), pre-LN residual blocks.
  - Attention projections without bias; no 1/sqrt(d) score scaling (folded
    into initialization, as in T5).
  - Bucketed relative position bias, computed by the first layer of each
    stack and shared across its other layers; bidirectional buckets in the
    encoder, unidirectional in the decoder; none on cross-attention.
  - FFN relu (``wi``/``wo``) or gated-gelu (``wi_0``/``wi_1``, v1.1).
  - Tied input/output embedding with ``d_model**-0.5`` logit scaling.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models.beam_fold import fold_beam_queries, unfold_beam_out


def ancestry_gather(x, anc, impl: str = "take_along"):
    """Resolve a beam-ancestry index into a beam-major cache read.

    ``x``: a decode-cache buffer ``[B*K, T, ...]`` whose rows were written
    in PHYSICAL order (row k always holds whatever logical beam occupied
    slot k when each position was written — the batched-beam layout of
    models/t5_generate.py, which never reorders the cache itself).
    ``anc``: ``[B, K, T]`` int32 — for logical beam k of batch row b, the
    physical row holding its position-p K/V. The gather runs at READ time,
    fused into the attention score computation, so the per-step cost is
    one indexed read of the bytes attention was going to read anyway —
    never a separate gather+write round trip of the whole cache through
    HBM between steps (the reorder that made beam-10 12x slower than
    greedy).

    ``impl``: "take_along" (default) or "onehot" — the one-hot einsum
    reads K× the cache per step (measured a LOSS on v5e, ISSUE 13), kept
    only so bench.py can A/B the choice per backend.
    """
    b, k, t = anc.shape
    xr = x.reshape(b, k, *x.shape[1:])
    if impl == "onehot":
        hot = jax.nn.one_hot(anc, k, dtype=x.dtype)  # [B, K, T, K]
        return jnp.einsum("bptj,bjt...->bpt...", hot, xr).reshape(x.shape)
    if impl != "take_along":
        raise ValueError(
            f"ancestry gather impl {impl!r}: expected 'take_along' or "
            "'onehot'")
    idx = anc.reshape(b, k, t, *([1] * (x.ndim - 2)))
    return jnp.take_along_axis(xr, idx, axis=1).reshape(x.shape)


@dataclasses.dataclass(frozen=True)
class T5Config:
    """Salesforce codet5-base shape by default (CodeT5/sh/exp_with_args.sh
    model zoo tag ``codet5-base``)."""

    vocab_size: int = 32100
    d_model: int = 768
    d_kv: int = 64
    d_ff: int = 3072
    num_layers: int = 12
    num_decoder_layers: int = 12
    num_heads: int = 12
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_epsilon: float = 1e-6
    gated_ffn: bool = False  # False = relu (v1.0 / codet5), True = gated gelu
    pad_token_id: int = 0
    eos_token_id: int = 2
    decoder_start_token_id: int = 0
    tie_word_embeddings: bool = True
    dtype: str = "float32"
    # Decode-cache storage layout: "split" [B, T, H, d_kv] is the layout
    # the attention einsum consumes directly (pads (12, 64) minor dims to
    # (16, 128), 2.7x memory); "merged" [B, T, H*d_kv] tiles cleanly but
    # relayouts on every read. Measured on v5e at the codet5-base decode
    # shape (bench.py A/B): split wins greedy 13.9k vs 10.0k tok/s and
    # beam-10 1007 vs 718 — the per-step relayout costs more than the
    # padded reads. "merged" stays as the memory-tight escape hatch.
    decode_cache_layout: str = "split"

    @classmethod
    def tiny(cls, vocab_size: int = 128) -> "T5Config":
        return cls(
            vocab_size=vocab_size,
            d_model=32,
            d_kv=8,
            d_ff=64,
            num_layers=2,
            num_decoder_layers=2,
            num_heads=4,
        )

    @classmethod
    def codet5_small(cls) -> "T5Config":
        return cls(d_model=512, d_kv=64, d_ff=2048, num_layers=6,
                   num_decoder_layers=6, num_heads=8)

    @classmethod
    def codet5_base(cls) -> "T5Config":
        return cls()

    @classmethod
    def codet5_large(cls) -> "T5Config":
        return cls(d_model=1024, d_kv=64, d_ff=4096, num_layers=24,
                   num_decoder_layers=24, num_heads=16)


class T5LayerNorm(nn.Module):
    """RMS norm: x / sqrt(mean(x^2) + eps) * weight. No bias, no centering."""

    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("weight", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.epsilon)).astype(x.dtype) * scale


def relative_position_bucket(
    relative_position: jnp.ndarray,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jnp.ndarray:
    """T5's log-bucketed relative positions (memory_pos - query_pos)."""
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5Attention(nn.Module):
    cfg: T5Config
    causal: bool = False
    has_relative_bias: bool = False

    def _bias_table(self):
        c = self.cfg
        return self.param(
            "relative_attention_bias",
            nn.initializers.normal(1.0 / np.sqrt(c.d_model)),
            (c.relative_attention_num_buckets, c.num_heads),
        )

    def _rel_bias(self, q_len: int, k_len: int) -> jnp.ndarray:
        c = self.cfg
        table = self._bias_table()
        ctx = jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        buckets = relative_position_bucket(
            mem - ctx,
            bidirectional=not self.causal,
            num_buckets=c.relative_attention_num_buckets,
            max_distance=c.relative_attention_max_distance,
        )
        return jnp.take(table, buckets, axis=0).transpose(2, 0, 1)[None]

    def _rel_bias_row(self, q_pos: jnp.ndarray, k_len: int) -> jnp.ndarray:
        """Bias for one (traced) query position over k_len keys — the
        incremental-decode analogue of :meth:`_rel_bias`."""
        c = self.cfg
        table = self._bias_table()
        mem = jnp.arange(k_len)[None, :]
        buckets = relative_position_bucket(
            mem - q_pos,
            bidirectional=not self.causal,
            num_buckets=c.relative_attention_num_buckets,
            max_distance=c.relative_attention_max_distance,
        )
        return jnp.take(table, buckets, axis=0).transpose(2, 0, 1)[None]

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        kv: Optional[jnp.ndarray],
        mask: jnp.ndarray,
        position_bias: Optional[jnp.ndarray],
        deterministic: bool,
        decode: bool = False,
        beam_anc: Optional[jnp.ndarray] = None,
        beam_gather_impl: str = "take_along",
    ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        c = self.cfg
        d = jnp.dtype(c.dtype)
        inner = c.num_heads * c.d_kv
        is_cross = kv is not None
        kv = x if kv is None else kv
        # T5's factor-1.0 init compensates for the missing 1/sqrt(d_kv)
        # score scaling; with default lecun init the softmax saturates at
        # init and gradients vanish.
        init_q = nn.initializers.normal((c.d_model * c.d_kv) ** -0.5)
        init_kv = nn.initializers.normal(c.d_model**-0.5)
        q = nn.Dense(inner, use_bias=False, dtype=d, kernel_init=init_q, name="q")(x)

        def split(t):
            return t.reshape(t.shape[0], t.shape[1], c.num_heads, c.d_kv)

        q = split(q)

        # Cache storage layout (decode_cache_layout): "merged" [B, T,
        # inner] tiles cleanly (inner is a multiple of 128 lanes) where
        # "split" [B, T, H, d_kv] pads (12, 64) minor dims to (16, 128) —
        # measured 2.7x memory expansion at the codet5-base decode shape
        # (beam-10 at batch 48 OOMs on a 16G chip split, fits merged).
        # The flip side: the attention einsum consumes the split shape, so
        # merged storage may relayout on read — bench.py A/Bs both.
        if c.decode_cache_layout not in ("merged", "split"):
            raise ValueError(
                f"decode_cache_layout {c.decode_cache_layout!r}: "
                "expected 'merged' or 'split'"
            )
        merged_layout = c.decode_cache_layout == "merged"
        merge = (
            (lambda t: t.reshape(t.shape[0], t.shape[1], inner))
            if merged_layout else (lambda t: t)
        )
        unmerge = split if merged_layout else (lambda t: t)
        cross_cached = (
            decode and is_cross and self.has_variable("cache", "cross_k")
        )
        if cross_cached:
            # Encoder K/V are step-invariant: projected once at cache
            # priming, reused every decode step.
            k = unmerge(self.get_variable("cache", "cross_k"))
            v = unmerge(self.get_variable("cache", "cross_v"))
        else:
            k = split(
                nn.Dense(inner, use_bias=False, dtype=d, kernel_init=init_kv,
                         name="k")(kv)
            )
            v = split(
                nn.Dense(inner, use_bias=False, dtype=d, kernel_init=init_kv,
                         name="v")(kv)
            )
            if decode and is_cross:
                self.variable("cache", "cross_k", lambda: merge(k))
                self.variable("cache", "cross_v", lambda: merge(v))

        if decode and not is_cross:
            # Incremental decoding (self-attention only): the cache is
            # created at full target length by a priming call (init_cache);
            # step calls write this token's K/V at cache_index and attend
            # over the whole buffer with positions > index masked.
            assert self.causal, "decode cache is for the causal self-attention"
            is_init = not self.has_variable("cache", "cached_k")
            cshape = merge(k).shape
            ck = self.variable("cache", "cached_k", jnp.zeros, cshape, k.dtype)
            cv = self.variable("cache", "cached_v", jnp.zeros, cshape, k.dtype)
            ci = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            if not is_init:
                idx = ci.value
                zeros = (0,) * (len(cshape) - 2)
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, merge(k), (0, idx) + zeros
                )
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, merge(v), (0, idx) + zeros
                )
                ci.value = idx + 1
                k, v = unmerge(ck.value), unmerge(cv.value)
                if beam_anc is not None:
                    # Batched-beam decode (models/t5_generate.py): the
                    # cache rows are physical — never reordered between
                    # steps — and the ancestry index resolves each
                    # logical beam's history here, fused into the read
                    # attention performs anyway.
                    k = ancestry_gather(k, beam_anc, beam_gather_impl)
                    v = ancestry_gather(v, beam_anc, beam_gather_impl)
                max_len = k.shape[1]
                mask = (jnp.arange(max_len) <= idx)[None, None, None, :]
                if self.has_relative_bias:
                    position_bias = self._rel_bias_row(idx, max_len)

        # Beam-deduped cross K/V (models/beam_fold.py): the beam factor
        # folds into the query axis when K/V are stored once per batch row.
        fold = None
        if is_cross:
            q, fold = fold_beam_queries(q, k)

        # No sqrt(d_kv) scaling — T5 folds it into the init.
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
        if position_bias is None and self.has_relative_bias:
            position_bias = self._rel_bias(x.shape[1], kv.shape[1])
        if position_bias is not None:
            scores = scores + position_bias
        scores = scores + jnp.where(mask, 0.0, -1e9)
        weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(d)
        weights = nn.Dropout(c.dropout_rate)(weights, deterministic=deterministic)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        out = unfold_beam_out(out, fold)
        out = out.reshape(out.shape[0], out.shape[1], inner)
        init_o = nn.initializers.normal((c.num_heads * c.d_kv) ** -0.5)
        return (
            nn.Dense(c.d_model, use_bias=False, dtype=d, kernel_init=init_o,
                     name="o")(out),
            position_bias,
        )


class T5FFN(nn.Module):
    cfg: T5Config

    @nn.compact
    def __call__(self, x, deterministic):
        c = self.cfg
        d = jnp.dtype(c.dtype)
        init_in = nn.initializers.normal(c.d_model**-0.5)
        init_out = nn.initializers.normal(c.d_ff**-0.5)
        if c.gated_ffn:
            gate = nn.gelu(
                nn.Dense(c.d_ff, use_bias=False, dtype=d, kernel_init=init_in,
                         name="wi_0")(x)
            )
            lin = nn.Dense(c.d_ff, use_bias=False, dtype=d, kernel_init=init_in,
                           name="wi_1")(x)
            h = gate * lin
        else:
            h = nn.relu(
                nn.Dense(c.d_ff, use_bias=False, dtype=d, kernel_init=init_in,
                         name="wi")(x)
            )
        h = nn.Dropout(c.dropout_rate)(h, deterministic=deterministic)
        return nn.Dense(c.d_model, use_bias=False, dtype=d, kernel_init=init_out,
                        name="wo")(h)


class T5Block(nn.Module):
    cfg: T5Config
    causal: bool = False
    has_relative_bias: bool = False
    has_cross_attention: bool = False

    @nn.compact
    def __call__(
        self,
        x,
        self_mask,
        position_bias,
        enc_out=None,
        cross_mask=None,
        deterministic: bool = True,
        decode: bool = False,
        beam_anc: Optional[jnp.ndarray] = None,
        beam_gather_impl: str = "take_along",
    ):
        c = self.cfg
        h = T5LayerNorm(c.layer_norm_epsilon, name="self_attn_ln")(x)
        attn, position_bias = T5Attention(
            c, causal=self.causal, has_relative_bias=self.has_relative_bias,
            name="self_attn",
        )(h, None, self_mask, position_bias, deterministic, decode=decode,
          beam_anc=beam_anc, beam_gather_impl=beam_gather_impl)
        x = x + nn.Dropout(c.dropout_rate)(attn, deterministic=deterministic)

        if self.has_cross_attention:
            h = T5LayerNorm(c.layer_norm_epsilon, name="cross_attn_ln")(x)
            attn, _ = T5Attention(c, name="cross_attn")(
                h, enc_out, cross_mask, None, deterministic, decode=decode
            )
            x = x + nn.Dropout(c.dropout_rate)(attn, deterministic=deterministic)

        h = T5LayerNorm(c.layer_norm_epsilon, name="ffn_ln")(x)
        ff = T5FFN(c, name="ffn")(h, deterministic)
        x = x + nn.Dropout(c.dropout_rate)(ff, deterministic=deterministic)
        return x, position_bias


class T5Stack(nn.Module):
    cfg: T5Config
    causal: bool = False
    num_layers: int = 12

    @nn.compact
    def __call__(
        self,
        embeds: jnp.ndarray,
        attn_mask: jnp.ndarray,
        enc_out: Optional[jnp.ndarray] = None,
        enc_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
        decode: bool = False,
        beam_anc: Optional[jnp.ndarray] = None,
        beam_gather_impl: str = "take_along",
    ) -> jnp.ndarray:
        c = self.cfg
        q_len = embeds.shape[1]
        # [B, 1, Q, K] self-attention mask; decoder adds the causal triangle.
        # In decode mode the cache supplies the causal structure instead.
        self_mask = attn_mask[:, None, None, :]
        if self.causal and not decode:
            causal = jnp.tril(jnp.ones((q_len, q_len), bool))
            self_mask = self_mask & causal[None, None]
        cross_mask = None
        if enc_out is not None and enc_mask is not None:
            cross_mask = enc_mask[:, None, None, :]

        x = nn.Dropout(c.dropout_rate)(embeds, deterministic=deterministic)
        position_bias = None
        for i in range(self.num_layers):
            x, position_bias = T5Block(
                c,
                causal=self.causal,
                has_relative_bias=(i == 0),
                has_cross_attention=enc_out is not None,
                name=f"block_{i}",
            )(x, self_mask, position_bias, enc_out, cross_mask, deterministic,
              decode=decode, beam_anc=beam_anc,
              beam_gather_impl=beam_gather_impl)
        x = T5LayerNorm(c.layer_norm_epsilon, name="final_ln")(x)
        return nn.Dropout(c.dropout_rate)(x, deterministic=deterministic)


def shift_right(ids: jnp.ndarray, decoder_start_token_id: int) -> jnp.ndarray:
    """HF semantics for ``labels=source_ids``: decoder inputs are the labels
    shifted right with the start token prepended."""
    return jnp.concatenate(
        [jnp.full_like(ids[:, :1], decoder_start_token_id), ids[:, :-1]], axis=1
    )


class T5Model(nn.Module):
    """Encoder-decoder returning the last decoder hidden state (and
    optionally lm logits via the tied embedding)."""

    cfg: T5Config

    def setup(self):
        c = self.cfg
        self.shared = nn.Embed(c.vocab_size, c.d_model, name="shared")
        self.encoder = T5Stack(c, causal=False, num_layers=c.num_layers, name="encoder")
        self.decoder = T5Stack(
            c, causal=True, num_layers=c.num_decoder_layers, name="decoder"
        )
        if not c.tie_word_embeddings:
            self.lm_head = nn.Dense(c.vocab_size, use_bias=False, name="lm_head")

    def encode(self, input_ids, attn_mask, deterministic: bool = True):
        return self.encoder(self.shared(input_ids), attn_mask, deterministic=deterministic)

    def decode(
        self, decoder_input_ids, decoder_mask, enc_out, enc_mask,
        deterministic: bool = True, decode: bool = False,
        beam_anc=None, beam_gather_impl: str = "take_along",
    ):
        return self.decoder(
            self.shared(decoder_input_ids), decoder_mask, enc_out, enc_mask,
            deterministic=deterministic, decode=decode, beam_anc=beam_anc,
            beam_gather_impl=beam_gather_impl,
        )

    def decode_logits(
        self, decoder_input_ids, decoder_mask, enc_out, enc_mask,
        deterministic: bool = True, decode: bool = False,
        beam_anc=None, beam_gather_impl: str = "take_along",
    ):
        """decode() + lm logits in one apply (generation step fn)."""
        hidden = self.decode(
            decoder_input_ids, decoder_mask, enc_out, enc_mask,
            deterministic=deterministic, decode=decode, beam_anc=beam_anc,
            beam_gather_impl=beam_gather_impl,
        )
        return self.logits(hidden)

    def logits(self, decoder_hidden):
        c = self.cfg
        if c.tie_word_embeddings:
            # T5 scales tied-embedding logits by d_model**-0.5.
            return (decoder_hidden * (c.d_model ** -0.5)) @ self.shared.embedding.T
        return self.lm_head(decoder_hidden)

    def __call__(
        self,
        input_ids: jnp.ndarray,
        decoder_input_ids: jnp.ndarray,
        attn_mask: Optional[jnp.ndarray] = None,
        decoder_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        c = self.cfg
        if attn_mask is None:
            attn_mask = input_ids != c.pad_token_id
        if decoder_mask is None:
            decoder_mask = jnp.ones_like(decoder_input_ids, bool)
        enc_out = self.encode(input_ids, attn_mask, deterministic)
        return self.decode(
            decoder_input_ids, decoder_mask, enc_out, attn_mask, deterministic
        )


def last_eos_vector(
    hidden: jnp.ndarray, source_ids: jnp.ndarray, eos_token_id: int
) -> jnp.ndarray:
    """Hidden state at each row's LAST eos position (models.py:143-148).

    The reference asserts every row has the same eos count and indexes the
    final one; with static shapes we take the max position where
    ``source_ids == eos`` (rows with no eos fall back to position 0, matching
    the reference's hard failure domain — such rows are filtered upstream,
    CodeT5/_utils.py:34).
    """
    eos = source_ids == eos_token_id
    positions = jnp.arange(source_ids.shape[1])[None, :]
    last = jnp.max(jnp.where(eos, positions, 0), axis=1)
    return jnp.take_along_axis(hidden, last[:, None, None], axis=1)[:, 0, :]


class DefectModel(nn.Module):
    """CodeT5 defect classifier, optionally combined with FlowGNN.

    Parity with the reference ``DefectModel`` (CodeT5/models.py:125-191):
    run the full encoder-decoder with ``decoder_input_ids =
    shift_right(source_ids)`` and the *source* mask as decoder attention
    mask (the reference passes ``decoder_attention_mask=attention_mask``),
    pool the last decoder hidden state at the final ``<eos>``, concat the
    pooled FlowGNN embedding when combined, then Linear -> 2 logits.
    """

    cfg: T5Config
    graph_config: Optional[Any] = None  # FlowGNNConfig with encoder_mode=True

    @nn.compact
    def __call__(
        self,
        source_ids: jnp.ndarray,
        graphs=None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        c = self.cfg
        attn_mask = source_ids != c.pad_token_id
        t5 = T5Model(c, name="t5")
        dec_in = shift_right(source_ids, c.decoder_start_token_id)
        hidden = t5(
            source_ids, dec_in, attn_mask=attn_mask, decoder_mask=attn_mask,
            deterministic=deterministic,
        )
        vec = last_eos_vector(hidden, source_ids, c.eos_token_id)

        if self.graph_config is not None:
            assert graphs is not None, "combined model needs a GraphBatch"
            from deepdfa_tpu.models.flowgnn import FlowGNN

            assert self.graph_config.encoder_mode
            graph_embed = FlowGNN(self.graph_config, name="flowgnn")(graphs)
            vec = jnp.concatenate([vec, graph_embed], axis=-1)

        return nn.Dense(2, name="classifier")(vec)


class CloneModel(nn.Module):
    """Clone detection (CodeT5/models.py:64-122): ``source_ids`` holds the
    token ids of BOTH snippets back to back ([B, 2L], CodeT5/_utils.py:71
    ``code1 + code2``); each snippet is eos-pooled *separately* (the
    reference's ``view(-1, max_source_length)``), the two vectors concat to
    [B, 2d], then dense(2d→d) → tanh → proj(2). (The reference's clone head,
    CodeT5/models.py:48-61, applies no dropout — unlike LineVul's.)"""

    cfg: T5Config

    @nn.compact
    def __call__(self, source_ids: jnp.ndarray, deterministic: bool = True):
        c = self.cfg
        b, two_l = source_ids.shape
        assert two_l % 2 == 0, "clone input must concatenate two equal halves"
        rows = source_ids.reshape(b * 2, two_l // 2)
        attn_mask = rows != c.pad_token_id
        t5 = T5Model(c, name="t5")
        dec_in = shift_right(rows, c.decoder_start_token_id)
        hidden = t5(rows, dec_in, attn_mask=attn_mask, decoder_mask=attn_mask,
                    deterministic=deterministic)
        vec = last_eos_vector(hidden, rows, c.eos_token_id)  # [2B, d]
        x = vec.reshape(b, 2 * c.d_model)
        x = jnp.tanh(nn.Dense(c.d_model, name="dense")(x))
        return nn.Dense(2, name="out_proj")(x)


def convert_hf_t5(state_dict: Dict[str, Any], cfg: T5Config) -> Dict:
    """Map a HuggingFace PyTorch T5 ``state_dict`` (t5-*, Salesforce/codet5-*)
    onto :class:`T5Model` params."""

    def get(key):
        v = state_dict[key]
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)

    def dense(key):
        return {"kernel": get(key + ".weight").T}

    def ln(key):
        return {"weight": get(key + ".weight")}

    def attn(prefix, has_bias):
        p = {
            "q": dense(prefix + ".q"),
            "k": dense(prefix + ".k"),
            "v": dense(prefix + ".v"),
            "o": dense(prefix + ".o"),
        }
        if has_bias:
            p["relative_attention_bias"] = get(
                prefix + ".relative_attention_bias.weight"
            )
        return p

    def ffn(prefix):
        if cfg.gated_ffn:
            return {
                "wi_0": dense(prefix + ".wi_0"),
                "wi_1": dense(prefix + ".wi_1"),
                "wo": dense(prefix + ".wo"),
            }
        return {"wi": dense(prefix + ".wi"), "wo": dense(prefix + ".wo")}

    def stack(side, n_layers, causal):
        p: Dict[str, Any] = {}
        for i in range(n_layers):
            b = f"{side}.block.{i}.layer"
            blk = {
                "self_attn_ln": ln(f"{b}.0.layer_norm"),
                "self_attn": attn(f"{b}.0.SelfAttention", has_bias=(i == 0)),
            }
            if causal:
                blk["cross_attn_ln"] = ln(f"{b}.1.layer_norm")
                blk["cross_attn"] = attn(f"{b}.1.EncDecAttention", has_bias=False)
                blk["ffn_ln"] = ln(f"{b}.2.layer_norm")
                blk["ffn"] = ffn(f"{b}.2.DenseReluDense")
            else:
                blk["ffn_ln"] = ln(f"{b}.1.layer_norm")
                blk["ffn"] = ffn(f"{b}.1.DenseReluDense")
            p[f"block_{i}"] = blk
        p["final_ln"] = ln(f"{side}.final_layer_norm")
        return p

    params: Dict[str, Any] = {
        "shared": {"embedding": get("shared.weight")},
        "encoder": stack("encoder", cfg.num_layers, causal=False),
        "decoder": stack("decoder", cfg.num_decoder_layers, causal=True),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense("lm_head")
    return {"params": params}

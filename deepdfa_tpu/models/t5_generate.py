"""Autoregressive generation: greedy and beam search with a KV cache.

Works over any encoder-decoder implementing the decode protocol —
``encode(input_ids, attn_mask)``, ``decode(ids, mask, enc_out, enc_mask,
deterministic=..., decode=...)``, ``decode_logits(...)`` — with a config
exposing ``pad_token_id`` / ``eos_token_id`` / ``decoder_start_token_id``:
models/t5.py's T5Model and models/seq2seq.py's RobertaSeq2Seq both qualify.

The reference generates with HF ``model.generate(num_beams=args.beam_size,
early_stopping=..., max_length=...)`` (CodeT5/run_gen.py:104-112) on the
CUDA stack, and hand-rolls a ``Beam`` class for the RoBERTa path
(CodeT5/models.py:195-408). Here decoding is a jitted scan over steps with
a KV cache (models/t5.py decode path): static shapes, no host round-trips
— the XLA-native shape of a decode loop. Beam search follows the standard
alive/finished formulation (score = logprob / length**length_penalty, HF
semantics).

**Batched-beam cache layout (ISSUE 13).** All ``batch*beams`` hypotheses
ride ONE KV cache ``[B*K, ...]`` whose rows are *physical*: row k writes
its step-t K/V at position t and the buffer is NEVER reordered between
steps. Beam reorders touch only a ``[B, K, T]`` int32 *ancestry* index —
gathered at the beam-select point inside the scan body (a few hundred KB)
— and the attention read resolves ancestry in place
(:func:`deepdfa_tpu.models.t5.ancestry_gather`), fused into the read the
score einsum performs anyway. The previous formulation
(:func:`beam_search_reference`, kept as the parity oracle)
``take_along_axis``-gathered the WHOLE cache through HBM every step —
read + gather + write ≈ 3× the cache bytes per step, the dominant term in
the measured 12× beam-10-vs-greedy cliff at the codet5-base bench shape.
Cross-attention K/V stay deduped per request (primed once with
unreplicated encoder outputs; the beam factor folds into the query axis —
models/beam_fold.py) exactly as before.

**Length-bucketed early exit.** The scan runs in fixed-length segments
under a ``lax.while_loop``; after each segment the device checks the
flax/t5x termination bound — the best alive hypothesis, brevity-optimally
extended to ``max_len``, can no longer beat the worst kept finished score
— and a batch whose every row is decided stops paying the remaining
``max_len`` steps. The bound is exact, so early-exit outputs are bitwise
identical to the full-length run (asserted in tests/test_t5_generate.py).

All functions take ``model``/``params`` explicitly and are jit-compatible;
wrap in ``jax.jit`` (or pjit with a sharded batch) at the call site.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models.t5 import T5Config, T5Model

NEG_INF = -1.0e7


def _init_cache(model: T5Model, params, batch: int, max_len: int, enc_out, enc_mask):
    """Prime the decode cache at full target length (flax idiom: run the
    decoder once in decode mode with a dummy of the final shape)."""
    dummy = jnp.zeros((batch, max_len), jnp.int32)
    _, variables = model.apply(
        {"params": params["params"]},
        dummy,
        jnp.ones_like(dummy, bool),
        enc_out,
        enc_mask,
        decode=True,
        method=type(model).decode,
        mutable=["cache"],
    )
    return variables["cache"]


def _is_cross_path(path) -> bool:
    return any(getattr(e, "key", None) in ("cross_k", "cross_v")
               for e in path)


def _partition_cache(cache):
    """Split the decode cache into (cross, dynamic) trees. Cross-attention
    K/V are projected once at priming and never written again, so carrying
    them through the decode ``lax.scan`` only risks per-step copies of the
    largest buffers in the program (at codet5-base/beam-10 they are ~4.5 GB
    that the scan carry cannot donate in place); they become closed-over
    constants instead. The two trees keep the full structure with ``None``
    holes so they re-merge positionally."""
    tm = jax.tree_util.tree_map_with_path
    cross = tm(lambda p, x: x if _is_cross_path(p) else None, cache)
    dyn = tm(lambda p, x: None if _is_cross_path(p) else x, cache)
    return cross, dyn


def _merge_cache(cross, dyn):
    return jax.tree_util.tree_map(
        lambda c, d: d if c is None else c, cross, dyn,
        is_leaf=lambda x: x is None,
    )


def _step_logits(model: T5Model, params, cache, token, enc_out, enc_mask,
                 beam_anc=None, gather_impl: str = "take_along"):
    """One cached decode step. token: [B, 1] -> logits [B, V], new cache.

    ``beam_anc`` [B, K, T]: batched-beam ancestry — the self-attention
    cache rows are physical and the read resolves each logical beam's
    history through this index (models/t5.py ancestry_gather)."""
    kwargs = {}
    if beam_anc is not None:
        kwargs = dict(beam_anc=beam_anc, beam_gather_impl=gather_impl)
    logits, variables = model.apply(
        {"params": params["params"], "cache": cache},
        token,
        jnp.ones_like(token, bool),
        enc_out,
        enc_mask,
        decode=True,
        method=type(model).decode_logits,
        mutable=["cache"],
        **kwargs,
    )
    return logits[:, -1, :], variables["cache"]


def greedy_decode(
    model: T5Model,
    params,
    input_ids: jnp.ndarray,
    max_len: int,
    attn_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Greedy generation; returns [B, max_len] padded with pad_token after
    each row's eos."""
    c = model.cfg
    if attn_mask is None:
        attn_mask = input_ids != c.pad_token_id
    enc_out = model.apply(
        {"params": params["params"]}, input_ids, attn_mask, method=type(model).encode
    )
    b = input_ids.shape[0]
    cross, dyn = _partition_cache(
        _init_cache(model, params, b, max_len, enc_out, attn_mask)
    )

    def body(carry, _):
        dyn, token, finished = carry
        logits, cache = _step_logits(
            model, params, _merge_cache(cross, dyn), token, enc_out, attn_mask
        )
        dyn = _partition_cache(cache)[1]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(finished, c.pad_token_id, nxt)
        finished = finished | (nxt == c.eos_token_id)
        return (dyn, nxt[:, None], finished), nxt

    start = jnp.full((b, 1), c.decoder_start_token_id, jnp.int32)
    (_, _, _), tokens = jax.lax.scan(
        body, (dyn, start, jnp.zeros(b, bool)), None, length=max_len
    )
    return tokens.T  # [max_len, B] -> [B, max_len]


def _gather_beams(tree, beam_idx, batch: int, beams: int):
    """Reorder the beam-flattened leading axis of every array leaf by
    ``beam_idx`` [batch, new_beams]. Only the dynamic (self-attention)
    cache ever reaches this — cross K/V are removed by _partition_cache,
    which is what keeps the beam step free of the 18 x 480 MB gather
    temporaries that OOMed beam-10 before round 5."""

    def gather(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x  # cache_index scalars are shared across beams
        shaped = x.reshape(batch, beams, *x.shape[1:])
        out = jnp.take_along_axis(
            shaped,
            beam_idx.reshape(batch, -1, *([1] * (x.ndim - 1))),
            axis=1,
        )
        return out.reshape(-1, *x.shape[1:])

    return jax.tree_util.tree_map(gather, tree)


def beam_search_reference(
    model: T5Model,
    params,
    input_ids: jnp.ndarray,
    max_len: int,
    beam_size: int = 10,
    length_penalty: float = 1.0,
    attn_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The pre-ISSUE-13 beam search, kept verbatim as the parity oracle:
    same alive/finished bookkeeping as :func:`beam_search`, but the whole
    self-attention cache is physically ``take_along_axis``-gathered along
    the beam axis every step — a read+gather+write of the full cache
    through HBM per token, which is exactly the traffic the batched
    ancestry layout removes. Returns (sequences [B, max_len], scores [B])
    — the best finished hypothesis per row (falling back to the best alive
    one if none finished). Score = sum logprob / len**length_penalty (HF
    convention)."""
    c = model.cfg
    if attn_mask is None:
        attn_mask = input_ids != c.pad_token_id
    b = input_ids.shape[0]
    k = beam_size

    enc_out = model.apply(
        {"params": params["params"]}, input_ids, attn_mask, method=type(model).encode
    )
    # Decoder rows expand to B*K (beam-major flatten) but the encoder side
    # does NOT: cross K/V are identical for every beam of a row, so the
    # cache is primed with the unreplicated encoder outputs and the
    # attention modules fold the beam factor into the query axis
    # (T5Attention's beam-deduped cross path). At codet5-base/beam-10 the
    # replicated alternative reads 10 identical copies of ~0.45 GB of
    # encoder K/V per decode step.
    cross, dyn = _partition_cache(
        _init_cache(model, params, b * k, max_len, enc_out, attn_mask)
    )

    # Alive state: only beam 0 starts live so the first step's top-k is not
    # k copies of the same hypothesis.
    alive_logp = jnp.tile(jnp.array([0.0] + [NEG_INF] * (k - 1)), (b, 1))
    alive_seq = jnp.full((b, k, max_len), c.pad_token_id, jnp.int32)
    fin_seq = jnp.full((b, k, max_len), c.pad_token_id, jnp.int32)
    fin_score = jnp.full((b, k), NEG_INF)
    token = jnp.full((b * k, 1), c.decoder_start_token_id, jnp.int32)

    def body(carry, t):
        dyn, token, alive_logp, alive_seq, fin_seq, fin_score = carry
        logits, cache = _step_logits(
            model, params, _merge_cache(cross, dyn), token, enc_out, attn_mask
        )
        dyn = _partition_cache(cache)[1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))  # [B*K, V]
        v = logp.shape[-1]
        total = alive_logp[:, :, None] + logp.reshape(b, k, v)  # [B, K, V]

        # Top 2K candidates over (beam, token): enough survive even if K of
        # them are eos.
        flat = total.reshape(b, k * v)
        cand_logp, cand_idx = jax.lax.top_k(flat, 2 * k)
        cand_beam = cand_idx // v  # [B, 2K]
        cand_tok = (cand_idx % v).astype(jnp.int32)

        cand_seq = jnp.take_along_axis(alive_seq, cand_beam[:, :, None], axis=1)
        cand_seq = jax.lax.dynamic_update_slice_in_dim(
            cand_seq, cand_tok[:, :, None], t, axis=2
        )
        is_eos = cand_tok == c.eos_token_id

        # Finished pool: merge newly-eos candidates (length-normalized).
        cand_score = cand_logp / ((t + 1).astype(jnp.float32) ** length_penalty)
        new_fin_score = jnp.where(is_eos, cand_score, NEG_INF)
        all_fin_score = jnp.concatenate([fin_score, new_fin_score], axis=1)
        all_fin_seq = jnp.concatenate([fin_seq, cand_seq], axis=1)
        fin_score, fin_top = jax.lax.top_k(all_fin_score, k)
        fin_seq = jnp.take_along_axis(all_fin_seq, fin_top[:, :, None], axis=1)

        # Alive pool: best K non-eos candidates.
        alive_cand = jnp.where(is_eos, NEG_INF, cand_logp)
        alive_logp, alive_top = jax.lax.top_k(alive_cand, k)
        alive_seq = jnp.take_along_axis(cand_seq, alive_top[:, :, None], axis=1)
        chosen_beam = jnp.take_along_axis(cand_beam, alive_top, axis=1)  # [B, K]
        chosen_tok = jnp.take_along_axis(cand_tok, alive_top, axis=1)

        dyn = _gather_beams(dyn, chosen_beam, b, k)
        token = chosen_tok.reshape(b * k, 1)
        return (dyn, token, alive_logp, alive_seq, fin_seq, fin_score), None

    carry = (dyn, token, alive_logp, alive_seq, fin_seq, fin_score)
    (dyn, token, alive_logp, alive_seq, fin_seq, fin_score), _ = jax.lax.scan(
        body, carry, jnp.arange(max_len)
    )

    # Prefer finished hypotheses; fall back to the best alive (unterminated)
    # beam when nothing finished within max_len.
    alive_score = alive_logp / (float(max_len) ** length_penalty)
    none_fin = fin_score[:, 0] <= NEG_INF / 2
    best_seq = jnp.where(none_fin[:, None], alive_seq[:, 0], fin_seq[:, 0])
    best_score = jnp.where(none_fin, alive_score[:, 0], fin_score[:, 0])
    return best_seq, best_score


def default_segment_len(max_len: int) -> int:
    """The early-exit check cadence: the largest divisor of ``max_len``
    that is <= max_len // 4 (floored at 1) — four decision points along
    the length ladder, every segment the same compiled shape."""
    target = max(max_len // 4, 1)
    for s in range(target, 0, -1):
        if max_len % s == 0:
            return s
    return 1


def beam_search(
    model: T5Model,
    params,
    input_ids: jnp.ndarray,
    max_len: int,
    beam_size: int = 10,
    length_penalty: float = 1.0,
    attn_mask: Optional[jnp.ndarray] = None,
    gather_impl: str = "take_along",
    early_exit: bool = True,
    segment_len: Optional[int] = None,
    with_aux: bool = False,
):
    """Batched beam search on one physical KV cache (module docstring).

    Returns (sequences [B, max_len], scores [B]) — the best finished
    hypothesis per row, falling back to the best alive one if none
    finished; score = sum logprob / len**length_penalty (HF convention).
    Bit-for-bit the same outputs as :func:`beam_search_reference` — the
    per-step math is identical, only the cache movement changed.

    ``gather_impl``: how the attention read resolves ancestry —
    "take_along" (default) or "onehot" (the bmm variant; measured a LOSS
    on v5e, kept A/B-able per backend via bench.py).
    ``early_exit``: stop at the next segment boundary once no future
    hypothesis can alter the result (the exact flax/t5x bound: best alive
    logprob, brevity-optimally normalized, vs the worst kept finished
    score). Exact, so outputs are bitwise identical either way.
    ``segment_len``: steps per early-exit check (must divide ``max_len``;
    default :func:`default_segment_len`).
    ``with_aux``: also return ``{"steps": <int32 scalar>}`` — decode steps
    actually executed (a segment multiple; ``max_len`` when never exited).
    """
    c = model.cfg
    if attn_mask is None:
        attn_mask = input_ids != c.pad_token_id
    b = input_ids.shape[0]
    k = beam_size
    if segment_len is None:
        segment_len = default_segment_len(max_len)
    if max_len % segment_len:
        raise ValueError(
            f"segment_len {segment_len} must divide max_len {max_len}")

    enc_out = model.apply(
        {"params": params["params"]}, input_ids, attn_mask, method=type(model).encode
    )
    # Cross K/V deduped exactly as the reference: primed once per request
    # row, beam factor folded into the query axis (models/beam_fold.py).
    cross, dyn = _partition_cache(
        _init_cache(model, params, b * k, max_len, enc_out, attn_mask)
    )

    alive_logp = jnp.tile(jnp.array([0.0] + [NEG_INF] * (k - 1)), (b, 1))
    alive_seq = jnp.full((b, k, max_len), c.pad_token_id, jnp.int32)
    fin_seq = jnp.full((b, k, max_len), c.pad_token_id, jnp.int32)
    fin_score = jnp.full((b, k), NEG_INF)
    token = jnp.full((b * k, 1), c.decoder_start_token_id, jnp.int32)
    # Ancestry: anc[b, j, p] = physical cache row of logical beam j's
    # position-p K/V. Row j writes position t in place, so at every step
    # column t is pinned to identity before the model call; the
    # beam-select point then gathers this [B, K, T] int32 index — a few
    # hundred KB — instead of the multi-GB cache.
    own_row = jnp.broadcast_to(
        jnp.arange(k, dtype=jnp.int32)[None, :, None], (b, k, 1))
    anc = jnp.broadcast_to(own_row, (b, k, max_len)).astype(jnp.int32)

    def step(carry, t):
        dyn, anc, token, alive_logp, alive_seq, fin_seq, fin_score = carry
        anc = jax.lax.dynamic_update_slice_in_dim(anc, own_row, t, axis=2)
        logits, cache = _step_logits(
            model, params, _merge_cache(cross, dyn), token, enc_out,
            attn_mask, beam_anc=anc, gather_impl=gather_impl,
        )
        dyn = _partition_cache(cache)[1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))  # [B*K, V]
        v = logp.shape[-1]
        total = alive_logp[:, :, None] + logp.reshape(b, k, v)  # [B, K, V]

        # Top 2K candidates over (beam, token): enough survive even if K
        # of them are eos.
        flat = total.reshape(b, k * v)
        cand_logp, cand_idx = jax.lax.top_k(flat, 2 * k)
        cand_beam = cand_idx // v  # [B, 2K]
        cand_tok = (cand_idx % v).astype(jnp.int32)

        cand_seq = jnp.take_along_axis(alive_seq, cand_beam[:, :, None], axis=1)
        cand_seq = jax.lax.dynamic_update_slice_in_dim(
            cand_seq, cand_tok[:, :, None], t, axis=2
        )
        is_eos = cand_tok == c.eos_token_id

        # Finished pool: merge newly-eos candidates (length-normalized).
        cand_score = cand_logp / ((t + 1).astype(jnp.float32) ** length_penalty)
        new_fin_score = jnp.where(is_eos, cand_score, NEG_INF)
        all_fin_score = jnp.concatenate([fin_score, new_fin_score], axis=1)
        all_fin_seq = jnp.concatenate([fin_seq, cand_seq], axis=1)
        fin_score, fin_top = jax.lax.top_k(all_fin_score, k)
        fin_seq = jnp.take_along_axis(all_fin_seq, fin_top[:, :, None], axis=1)

        # Alive pool: best K non-eos candidates.
        alive_cand = jnp.where(is_eos, NEG_INF, cand_logp)
        alive_logp, alive_top = jax.lax.top_k(alive_cand, k)
        alive_seq = jnp.take_along_axis(cand_seq, alive_top[:, :, None], axis=1)
        chosen_beam = jnp.take_along_axis(cand_beam, alive_top, axis=1)  # [B, K]
        chosen_tok = jnp.take_along_axis(cand_tok, alive_top, axis=1)

        # THE beam-select reorder: compose the ancestry, not the cache.
        anc = jnp.take_along_axis(anc, chosen_beam[:, :, None], axis=1)
        token = chosen_tok.reshape(b * k, 1)
        return (dyn, anc, token, alive_logp, alive_seq, fin_seq, fin_score), None

    def decided(alive_logp, fin_score, t_next):
        # Exact termination: the best alive hypothesis's best achievable
        # future score vs the worst kept finished score. Log-probs are
        # <= 0, so with length_penalty >= 0 the most favorable future
        # normalization is the longest (max_len); with a negative penalty
        # it is the earliest possible finish (t_next + 1).
        if length_penalty >= 0:
            denom = float(max_len) ** length_penalty
        else:
            denom = (t_next + 1.0).astype(jnp.float32) ** length_penalty
        bound = alive_logp[:, 0] / denom
        return jnp.all(fin_score[:, -1] >= bound)

    def seg_cond(state):
        t0, done = state[0], state[1]
        in_range = t0 < max_len
        if not early_exit:
            return in_range
        return in_range & jnp.logical_not(done)

    def seg_body(state):
        t0 = state[0]
        carry, _ = jax.lax.scan(step, state[2:],
                                t0 + jnp.arange(segment_len))
        done = decided(carry[3], carry[6], t0 + segment_len)
        return (t0 + segment_len, done) + carry

    state = jax.lax.while_loop(
        seg_cond, seg_body,
        (jnp.zeros((), jnp.int32), jnp.zeros((), bool),
         dyn, anc, token, alive_logp, alive_seq, fin_seq, fin_score))
    steps, alive_logp, alive_seq = state[0], state[5], state[6]
    fin_seq, fin_score = state[7], state[8]

    # Prefer finished hypotheses; fall back to the best alive
    # (unterminated) beam when nothing finished within max_len.
    alive_score = alive_logp / (float(max_len) ** length_penalty)
    none_fin = fin_score[:, 0] <= NEG_INF / 2
    best_seq = jnp.where(none_fin[:, None], alive_seq[:, 0], fin_seq[:, 0])
    best_score = jnp.where(none_fin, alive_score[:, 0], fin_score[:, 0])
    if with_aux:
        return best_seq, best_score, {"steps": steps}
    return best_seq, best_score


def generate(
    model: T5Model,
    params,
    input_ids: jnp.ndarray,
    max_len: int = 128,
    beam_size: int = 1,
    length_penalty: float = 1.0,
    gather_impl: str = "take_along",
    early_exit: bool = True,
) -> jnp.ndarray:
    """HF-generate-shaped convenience: beam_size 1 → greedy."""
    if beam_size <= 1:
        return greedy_decode(model, params, input_ids, max_len)
    seq, _ = beam_search(
        model, params, input_ids, max_len, beam_size, length_penalty,
        gather_impl=gather_impl, early_exit=early_exit,
    )
    return seq

"""Autoregressive generation: greedy and beam search with a KV cache.

Works over any encoder-decoder implementing the decode protocol —
``encode(input_ids, attn_mask)``, ``decode(ids, mask, enc_out, enc_mask,
deterministic=..., decode=...)``, ``decode_logits(...)`` — with a config
exposing ``pad_token_id`` / ``eos_token_id`` / ``decoder_start_token_id``:
models/t5.py's T5Model and models/seq2seq.py's RobertaSeq2Seq both qualify.

The reference generates with HF ``model.generate(num_beams=args.beam_size,
early_stopping=..., max_length=...)`` (CodeT5/run_gen.py:104-112) on the
CUDA stack, and hand-rolls a ``Beam`` class for the RoBERTa path
(CodeT5/models.py:195-408). Here decoding is a single jitted ``lax.scan``
over steps with a KV cache (models/t5.py decode path): static trip count,
static shapes, no host round-trips — the XLA-native shape of a decode loop.
Beam search follows the standard alive/finished formulation (score =
logprob / length**length_penalty, HF semantics) with the cache gathered
along the beam axis at every reorder.

All functions take ``model``/``params`` explicitly and are jit-compatible;
wrap in ``jax.jit`` (or pjit with a sharded batch) at the call site.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models.t5 import T5Config, T5Model

NEG_INF = -1.0e7


def _init_cache(model: T5Model, params, batch: int, max_len: int, enc_out, enc_mask):
    """Prime the decode cache at full target length (flax idiom: run the
    decoder once in decode mode with a dummy of the final shape)."""
    dummy = jnp.zeros((batch, max_len), jnp.int32)
    _, variables = model.apply(
        {"params": params["params"]},
        dummy,
        jnp.ones_like(dummy, bool),
        enc_out,
        enc_mask,
        decode=True,
        method=type(model).decode,
        mutable=["cache"],
    )
    return variables["cache"]


def _is_cross_path(path) -> bool:
    return any(getattr(e, "key", None) in ("cross_k", "cross_v")
               for e in path)


def _partition_cache(cache):
    """Split the decode cache into (cross, dynamic) trees. Cross-attention
    K/V are projected once at priming and never written again, so carrying
    them through the decode ``lax.scan`` only risks per-step copies of the
    largest buffers in the program (at codet5-base/beam-10 they are ~4.5 GB
    that the scan carry cannot donate in place); they become closed-over
    constants instead. The two trees keep the full structure with ``None``
    holes so they re-merge positionally."""
    tm = jax.tree_util.tree_map_with_path
    cross = tm(lambda p, x: x if _is_cross_path(p) else None, cache)
    dyn = tm(lambda p, x: None if _is_cross_path(p) else x, cache)
    return cross, dyn


def _merge_cache(cross, dyn):
    return jax.tree_util.tree_map(
        lambda c, d: d if c is None else c, cross, dyn,
        is_leaf=lambda x: x is None,
    )


def _step_logits(model: T5Model, params, cache, token, enc_out, enc_mask):
    """One cached decode step. token: [B, 1] -> logits [B, V], new cache."""
    logits, variables = model.apply(
        {"params": params["params"], "cache": cache},
        token,
        jnp.ones_like(token, bool),
        enc_out,
        enc_mask,
        decode=True,
        method=type(model).decode_logits,
        mutable=["cache"],
    )
    return logits[:, -1, :], variables["cache"]


def greedy_decode(
    model: T5Model,
    params,
    input_ids: jnp.ndarray,
    max_len: int,
    attn_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Greedy generation; returns [B, max_len] padded with pad_token after
    each row's eos."""
    c = model.cfg
    if attn_mask is None:
        attn_mask = input_ids != c.pad_token_id
    enc_out = model.apply(
        {"params": params["params"]}, input_ids, attn_mask, method=type(model).encode
    )
    b = input_ids.shape[0]
    cross, dyn = _partition_cache(
        _init_cache(model, params, b, max_len, enc_out, attn_mask)
    )

    def body(carry, _):
        dyn, token, finished = carry
        logits, cache = _step_logits(
            model, params, _merge_cache(cross, dyn), token, enc_out, attn_mask
        )
        dyn = _partition_cache(cache)[1]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(finished, c.pad_token_id, nxt)
        finished = finished | (nxt == c.eos_token_id)
        return (dyn, nxt[:, None], finished), nxt

    start = jnp.full((b, 1), c.decoder_start_token_id, jnp.int32)
    (_, _, _), tokens = jax.lax.scan(
        body, (dyn, start, jnp.zeros(b, bool)), None, length=max_len
    )
    return tokens.T  # [max_len, B] -> [B, max_len]


def _gather_beams(tree, beam_idx, batch: int, beams: int):
    """Reorder the beam-flattened leading axis of every array leaf by
    ``beam_idx`` [batch, new_beams]. Only the dynamic (self-attention)
    cache ever reaches this — cross K/V are removed by _partition_cache,
    which is what keeps the beam step free of the 18 x 480 MB gather
    temporaries that OOMed beam-10 before round 5."""

    def gather(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x  # cache_index scalars are shared across beams
        shaped = x.reshape(batch, beams, *x.shape[1:])
        out = jnp.take_along_axis(
            shaped,
            beam_idx.reshape(batch, -1, *([1] * (x.ndim - 1))),
            axis=1,
        )
        return out.reshape(-1, *x.shape[1:])

    return jax.tree_util.tree_map(gather, tree)


def beam_search(
    model: T5Model,
    params,
    input_ids: jnp.ndarray,
    max_len: int,
    beam_size: int = 10,
    length_penalty: float = 1.0,
    attn_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Beam search; returns (sequences [B, max_len], scores [B]) — the best
    finished hypothesis per row (falling back to the best alive one if none
    finished). Score = sum logprob / len**length_penalty (HF convention)."""
    c = model.cfg
    if attn_mask is None:
        attn_mask = input_ids != c.pad_token_id
    b = input_ids.shape[0]
    k = beam_size

    enc_out = model.apply(
        {"params": params["params"]}, input_ids, attn_mask, method=type(model).encode
    )
    # Decoder rows expand to B*K (beam-major flatten) but the encoder side
    # does NOT: cross K/V are identical for every beam of a row, so the
    # cache is primed with the unreplicated encoder outputs and the
    # attention modules fold the beam factor into the query axis
    # (T5Attention's beam-deduped cross path). At codet5-base/beam-10 the
    # replicated alternative reads 10 identical copies of ~0.45 GB of
    # encoder K/V per decode step.
    cross, dyn = _partition_cache(
        _init_cache(model, params, b * k, max_len, enc_out, attn_mask)
    )

    # Alive state: only beam 0 starts live so the first step's top-k is not
    # k copies of the same hypothesis.
    alive_logp = jnp.tile(jnp.array([0.0] + [NEG_INF] * (k - 1)), (b, 1))
    alive_seq = jnp.full((b, k, max_len), c.pad_token_id, jnp.int32)
    fin_seq = jnp.full((b, k, max_len), c.pad_token_id, jnp.int32)
    fin_score = jnp.full((b, k), NEG_INF)
    token = jnp.full((b * k, 1), c.decoder_start_token_id, jnp.int32)

    def body(carry, t):
        dyn, token, alive_logp, alive_seq, fin_seq, fin_score = carry
        logits, cache = _step_logits(
            model, params, _merge_cache(cross, dyn), token, enc_out, attn_mask
        )
        dyn = _partition_cache(cache)[1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))  # [B*K, V]
        v = logp.shape[-1]
        total = alive_logp[:, :, None] + logp.reshape(b, k, v)  # [B, K, V]

        # Top 2K candidates over (beam, token): enough survive even if K of
        # them are eos.
        flat = total.reshape(b, k * v)
        cand_logp, cand_idx = jax.lax.top_k(flat, 2 * k)
        cand_beam = cand_idx // v  # [B, 2K]
        cand_tok = (cand_idx % v).astype(jnp.int32)

        cand_seq = jnp.take_along_axis(alive_seq, cand_beam[:, :, None], axis=1)
        cand_seq = jax.lax.dynamic_update_slice_in_dim(
            cand_seq, cand_tok[:, :, None], t, axis=2
        )
        is_eos = cand_tok == c.eos_token_id

        # Finished pool: merge newly-eos candidates (length-normalized).
        cand_score = cand_logp / ((t + 1).astype(jnp.float32) ** length_penalty)
        new_fin_score = jnp.where(is_eos, cand_score, NEG_INF)
        all_fin_score = jnp.concatenate([fin_score, new_fin_score], axis=1)
        all_fin_seq = jnp.concatenate([fin_seq, cand_seq], axis=1)
        fin_score, fin_top = jax.lax.top_k(all_fin_score, k)
        fin_seq = jnp.take_along_axis(all_fin_seq, fin_top[:, :, None], axis=1)

        # Alive pool: best K non-eos candidates.
        alive_cand = jnp.where(is_eos, NEG_INF, cand_logp)
        alive_logp, alive_top = jax.lax.top_k(alive_cand, k)
        alive_seq = jnp.take_along_axis(cand_seq, alive_top[:, :, None], axis=1)
        chosen_beam = jnp.take_along_axis(cand_beam, alive_top, axis=1)  # [B, K]
        chosen_tok = jnp.take_along_axis(cand_tok, alive_top, axis=1)

        dyn = _gather_beams(dyn, chosen_beam, b, k)
        token = chosen_tok.reshape(b * k, 1)
        return (dyn, token, alive_logp, alive_seq, fin_seq, fin_score), None

    carry = (dyn, token, alive_logp, alive_seq, fin_seq, fin_score)
    (dyn, token, alive_logp, alive_seq, fin_seq, fin_score), _ = jax.lax.scan(
        body, carry, jnp.arange(max_len)
    )

    # Prefer finished hypotheses; fall back to the best alive (unterminated)
    # beam when nothing finished within max_len.
    alive_score = alive_logp / (float(max_len) ** length_penalty)
    none_fin = fin_score[:, 0] <= NEG_INF / 2
    best_seq = jnp.where(none_fin[:, None], alive_seq[:, 0], fin_seq[:, 0])
    best_score = jnp.where(none_fin, alive_score[:, 0], fin_score[:, 0])
    return best_seq, best_score


def generate(
    model: T5Model,
    params,
    input_ids: jnp.ndarray,
    max_len: int = 128,
    beam_size: int = 1,
    length_penalty: float = 1.0,
) -> jnp.ndarray:
    """HF-generate-shaped convenience: beam_size 1 → greedy."""
    if beam_size <= 1:
        return greedy_decode(model, params, input_ids, max_len)
    seq, _ = beam_search(
        model, params, input_ids, max_len, beam_size, length_penalty
    )
    return seq

"""Pretrained-checkpoint loading: HF directory -> framework config + params.

The reference fine-tunes from pretrained checkpoints via
``from_pretrained`` (LineVul/linevul/linevul_main.py:605-621,
CodeT5/run_defect.py:155-158). The TPU-native equivalent reads an HF
checkpoint DIRECTORY (config.json + torch weights, as written by
``save_pretrained``), derives the matching framework config from the HF
config, and runs the golden-tested converters (``convert_hf_t5``,
``convert_hf_roberta``) — the result grafts onto a fresh init through the
trainers' ``init_params`` hook (text_loop._merge_params).

torch/transformers are load-time-only dependencies: everything they produce
is converted to numpy before returning, so training itself stays pure JAX.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from deepdfa_tpu.models.t5 import T5Config, convert_hf_t5
from deepdfa_tpu.models.transformer import EncoderConfig, convert_hf_roberta


def t5_config_from_hf(hf_cfg) -> T5Config:
    """Derive :class:`T5Config` from a transformers T5Config."""
    return T5Config(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.d_model,
        d_kv=hf_cfg.d_kv,
        d_ff=hf_cfg.d_ff,
        num_layers=hf_cfg.num_layers,
        num_decoder_layers=hf_cfg.num_decoder_layers or hf_cfg.num_layers,
        num_heads=hf_cfg.num_heads,
        relative_attention_num_buckets=hf_cfg.relative_attention_num_buckets,
        relative_attention_max_distance=getattr(
            hf_cfg, "relative_attention_max_distance", 128
        ),
        dropout_rate=hf_cfg.dropout_rate,
        layer_norm_epsilon=hf_cfg.layer_norm_epsilon,
        gated_ffn="gated" in hf_cfg.feed_forward_proj,
        pad_token_id=hf_cfg.pad_token_id,
        eos_token_id=hf_cfg.eos_token_id,
        decoder_start_token_id=hf_cfg.decoder_start_token_id,
        tie_word_embeddings=hf_cfg.tie_word_embeddings,
    )


def encoder_config_from_hf(hf_cfg, **overrides) -> EncoderConfig:
    """Derive :class:`EncoderConfig` from a transformers RobertaConfig.

    ``overrides`` pass through runtime choices the checkpoint doesn't fix
    (``attention_impl`` etc.). ``gelu_approximate`` defaults to False here
    — an HF checkpoint was trained with the exact erf gelu, and a
    converted model must reproduce its numerics (override to True to trade
    <1e-3 activation deviation for the measured +18% TPU training step).
    """
    overrides.setdefault("gelu_approximate", False)
    return EncoderConfig(
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        intermediate_size=hf_cfg.intermediate_size,
        max_position_embeddings=hf_cfg.max_position_embeddings,
        type_vocab_size=hf_cfg.type_vocab_size,
        pad_token_id=hf_cfg.pad_token_id,
        layer_norm_eps=hf_cfg.layer_norm_eps,
        dropout_rate=hf_cfg.hidden_dropout_prob,
        **overrides,
    )


def load_pretrained(path: str, **config_overrides) -> Tuple[str, Any, Dict]:
    """Load an HF checkpoint directory.

    Returns ``(kind, config, params)`` where ``kind`` is ``"t5"`` or
    ``"roberta"``, ``config`` the derived framework config, and ``params``
    the converted ``{"params": ...}`` tree for :class:`T5Model` /
    :class:`RobertaEncoder`. Callers nest the tree under the submodule name
    their model uses ("t5", "roberta", "encoder") before handing it to a
    trainer's ``init_params``.
    """
    try:
        import transformers
    except ImportError as exc:  # pragma: no cover - baked into the image
        raise RuntimeError(
            "loading pretrained HF checkpoints needs transformers+torch "
            "installed; they are load-time-only dependencies"
        ) from exc

    hf_cfg = transformers.AutoConfig.from_pretrained(path)
    if hf_cfg.model_type == "t5":
        hf = transformers.T5ForConditionalGeneration.from_pretrained(path)
        cfg = t5_config_from_hf(hf_cfg)
        return "t5", cfg, convert_hf_t5(hf.state_dict(), cfg)
    if hf_cfg.model_type == "roberta":
        # AutoModel (not ForSequenceClassification): the classification head
        # is task-specific and trains fresh, matching the reference's
        # from_pretrained of the base encoder.
        hf = transformers.AutoModel.from_pretrained(path)
        cfg = encoder_config_from_hf(hf_cfg, **config_overrides)
        return "roberta", cfg, convert_hf_roberta(hf.state_dict(), cfg)
    raise ValueError(
        f"unsupported model_type {hf_cfg.model_type!r} in {path} "
        "(supported: t5, roberta)"
    )

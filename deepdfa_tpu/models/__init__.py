from deepdfa_tpu.models.flowgnn import FlowGNN

__all__ = ["FlowGNN"]

"""Inference-only apply paths for the serving layer.

Training steps (train/loop.py, train/text_loop.py) carry labels, masks,
loss, and metric stats through the jitted program; serving wants the
smallest possible program per bucket shape — params + padded batch in,
per-slot probabilities out. These factories are that program. They are the
functions the serve engine AOT-compiles once per bucket at startup
(``deepdfa_tpu/serve/engine.py``), so anything added here is paid again at
every warm bucket shape.

Correctness contract: on the same (padded) inputs, ``make_gnn_infer`` must
reproduce the probabilities of the offline eval path
(``make_eval_step`` -> sigmoid) and ``make_combined_infer`` those of
``make_text_eval_step`` — pinned by tests/test_serve.py.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from deepdfa_tpu.graphs.batch import GraphBatch
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.models.linevul import LineVul


def make_gnn_infer(model: FlowGNN) -> Callable:
    """(params, GraphBatch) -> float32 probs per graph slot.

    ``label_style`` must be "graph" (one logit per graph slot); padded
    slots produce garbage probabilities that callers drop via
    ``batch.graph_mask`` — the same masking contract as evaluate().
    """
    if model.config.label_style != "graph":
        raise ValueError(
            f"serving scores functions (label_style='graph'), got "
            f"{model.config.label_style!r}"
        )

    def infer(params, batch: GraphBatch) -> jnp.ndarray:
        return jax.nn.sigmoid(model.apply(params, batch))

    return infer


def make_combined_infer(model: LineVul) -> Callable:
    """(params, input_ids, GraphBatch) -> float32 P(vulnerable) per row.

    The DeepDFA+LineVul combined forward (text row i joined with graph
    slot i), deterministic (no dropout) — the probability column of
    make_text_eval_step without loss/labels.
    """
    if model.graph_config is None:
        raise ValueError("combined inference needs LineVul(graph_config=...)")

    def infer(params, input_ids: jnp.ndarray, graphs: GraphBatch) -> jnp.ndarray:
        logits = model.apply(params, input_ids, graphs, deterministic=True)
        return jax.nn.softmax(logits, axis=-1)[:, 1]

    return infer


def make_text_infer(model: LineVul) -> Callable:
    """(params, input_ids) -> float32 P(vulnerable) per row — the pure
    LineVul path (no graph encoder), for text-only deployments."""
    if model.graph_config is not None:
        raise ValueError(
            "model has a graph encoder; use make_combined_infer (its "
            "params include the flowgnn subtree, which a text-only apply "
            "would silently skip)"
        )

    def infer(params, input_ids: jnp.ndarray) -> jnp.ndarray:
        logits = model.apply(params, input_ids, None, deterministic=True)
        return jax.nn.softmax(logits, axis=-1)[:, 1]

    return infer
